"""Web browser over the results store — the reference's `serve` command
(ring/jetty directory browser, src/jepsen/etcdemo.clj:198)."""

from .server import serve, make_handler  # noqa: F401
