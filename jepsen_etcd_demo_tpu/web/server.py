"""Tiny stdlib HTTP server over store/ — `lein run serve` equivalent.

The reference serves its store with ring/jetty + a directory browser
(src/jepsen/etcdemo.clj:198; deps jepsen.etcdemo.iml:82-99). Same capability
on http.server: an index of runs with verdicts, and static file serving of
each run dir (charts, timelines, logs, history)."""

from __future__ import annotations

import html
import urllib.parse
from functools import partial
from http.server import SimpleHTTPRequestHandler, ThreadingHTTPServer

from ..store import Store


def _run_summary(results: dict) -> str:
    """Compact why-it-failed / what-ran column: op count, rate, and for
    invalid runs the failing detail (per-key failed ops, elle anomaly
    types) pulled from the composed result tree."""
    bits = []
    perf = results.get("perf") or {}
    if perf.get("count"):
        bits.append(f"{perf['count']} ops")
    if perf.get("rate_hz"):
        bits.append(f"{perf['rate_hz']:.0f}/s")
    indep = results.get("indep") or {}
    for key, sub in (indep.get("results") or {}).items():
        lin = sub.get("linear", sub) if isinstance(sub, dict) else {}
        if isinstance(lin, dict) and lin.get("valid") is False:
            op = lin.get("failed_op")
            bits.append(f"key {key}: {op}" if op else f"key {key}: invalid")
    # Whole-history workloads (gset/mutex/multiregister) have no per-key
    # results — the failing op sits directly under indep.linear.
    whole_lin = indep.get("linear") or {}
    if whole_lin.get("valid") is False:
        op = whole_lin.get("failed_op")
        bits.append(str(op) if op else "invalid")
    elle = indep.get("elle") or {}
    if elle.get("anomaly_types"):
        bits.append("anomalies: " + ", ".join(elle["anomaly_types"]))
    if indep.get("lost_count"):   # untruncated ('lost' caps at 100)
        bits.append(f"lost adds: {indep['lost_count']}")
    return "; ".join(str(b) for b in bits[:4])


def _index_html(store: Store) -> str:
    rows = []
    for run in reversed(store.runs()):
        rel = run.path.relative_to(store.root)
        try:
            results = run.read_results()
            valid = results.get("valid")
        except Exception:
            results, valid = {}, "?"
        try:
            summary = _run_summary(results)
        except Exception:   # off-schema results must not hide the verdict
            summary = ""
        color = {True: "#2a9d43", False: "#d43a2a"}.get(valid, "#e9a820")
        href = urllib.parse.quote(f"/files/{rel}/")
        rows.append(
            f"<tr><td><a href='{href}'>"
            f"{html.escape(str(rel))}</a></td>"
            f"<td style='color:{color};font-weight:bold'>{valid}</td>"
            f"<td style='color:#666'>{html.escape(summary)}</td></tr>")
    return (
        "<!doctype html><html><head><meta charset='utf-8'>"
        "<title>jepsen-tpu store</title>"
        "<style>body{font-family:sans-serif}td{padding:4px 12px}</style>"
        "</head><body><h2>test runs</h2>"
        f"<table><tr><th>run</th><th>valid</th><th>detail</th></tr>"
        f"{''.join(rows)}</table>"
        "</body></html>")


class StoreHandler(SimpleHTTPRequestHandler):
    """/ -> run index; /files/... -> static serving rooted at the store."""

    def __init__(self, *args, store_root: str = "store", **kw):
        self.store = Store(store_root)
        super().__init__(*args, directory=str(store_root), **kw)

    def do_GET(self):
        if self.path in ("/", "/index.html"):
            body = _index_html(self.store).encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/html; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if self.path.startswith("/files/"):
            self.path = self.path[len("/files"):]
        return super().do_GET()

    def log_message(self, fmt, *args):  # quiet
        pass


def make_handler(store_root: str):
    return partial(StoreHandler, store_root=store_root)


def serve(store_root: str = "store", host: str = "127.0.0.1",
          port: int = 8080):
    httpd = ThreadingHTTPServer((host, port), make_handler(store_root))
    print(f"serving {store_root} on http://{host}:{port}")
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        httpd.server_close()
