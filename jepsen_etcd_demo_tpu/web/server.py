"""Tiny stdlib HTTP server over store/ — `lein run serve` equivalent.

The reference serves its store with ring/jetty + a directory browser
(src/jepsen/etcdemo.clj:198; deps jepsen.etcdemo.iml:82-99). Same capability
on http.server: an index of runs with verdicts, static file serving of
each run dir (charts, timelines, logs, history), and a per-run telemetry
page (/telemetry/<run>) rendering the span tree + metric table the
harness records in telemetry.jsonl / metrics.json (obs/)."""

from __future__ import annotations

import html
import urllib.parse
from functools import partial
from http.server import SimpleHTTPRequestHandler, ThreadingHTTPServer

from ..obs import METRICS_FILE, TELEMETRY_FILE, read_jsonl, read_metrics
from ..store import Store


def _run_summary(results: dict) -> str:
    """Compact why-it-failed / what-ran column: op count, rate, and for
    invalid runs the failing detail (per-key failed ops, elle anomaly
    types) pulled from the composed result tree."""
    bits = []
    perf = results.get("perf") or {}
    if perf.get("count"):
        bits.append(f"{perf['count']} ops")
    if perf.get("rate_hz"):
        bits.append(f"{perf['rate_hz']:.0f}/s")
    indep = results.get("indep") or {}
    for key, sub in (indep.get("results") or {}).items():
        lin = sub.get("linear", sub) if isinstance(sub, dict) else {}
        if isinstance(lin, dict) and lin.get("valid") is False:
            op = lin.get("failed_op")
            bits.append(f"key {key}: {op}" if op else f"key {key}: invalid")
    # Whole-history workloads (gset/mutex/multiregister) have no per-key
    # results — the failing op sits directly under indep.linear.
    whole_lin = indep.get("linear") or {}
    if whole_lin.get("valid") is False:
        op = whole_lin.get("failed_op")
        bits.append(str(op) if op else "invalid")
    elle = indep.get("elle") or {}
    if elle.get("anomaly_types"):
        bits.append("anomalies: " + ", ".join(elle["anomaly_types"]))
    if indep.get("lost_count"):   # untruncated ('lost' caps at 100)
        bits.append(f"lost adds: {indep['lost_count']}")
    return "; ".join(str(b) for b in bits[:4])


def _check_perf_columns(run) -> tuple[str, str, str, str]:
    """(throughput, padding-waste, sweep-mode, live-tile-ratio) columns
    for the run index, from the run's metrics.json (obs/): check
    throughput = encoded history events over the kernels'
    compile+execute wall, padding waste = the last launch's padded/real
    step ratio (wgl3._record_padding), sweep mode = which dense-lattice
    sweep the run's checks took (the wgl.sweep_* counters — sparse
    engine, ops/wgl3_sparse.py), live tiles = the wgl.live_tile_ratio
    occupancy gauge. Blank when the run has no telemetry or never
    launched a kernel."""
    try:
        metrics = read_metrics(run.path / METRICS_FILE)
    except Exception:
        return "", "", "", ""

    def counter(name: str) -> float:
        rec = metrics.get(name) or {}
        return float(rec.get("value", 0.0)) \
            if rec.get("type") == "counter" else 0.0

    events = counter("encode.event_bytes") / 24.0   # 6 int32 per event
    kernel_s = counter("wgl.compile_s") + counter("wgl.execute_s")
    eps = f"{events / kernel_s:,.0f}/s" if events and kernel_s else ""
    ratio = (metrics.get("wgl.step_padding_ratio") or {}).get("last")
    waste = f"{ratio:.2f}x" if isinstance(ratio, (int, float)) else ""
    sp = counter("wgl.sweep_steps_sparse")
    dn = counter("wgl.sweep_steps_dense")
    if sp and dn:
        sweep = f"mixed ({100 * sp / (sp + dn):.0f}% sp)"
    elif sp:
        sweep = "sparse"
    elif dn or counter("wgl.sweep_checks_dense") \
            or counter("wgl.sweep_checks_mixed"):
        sweep = "dense"
    else:
        sweep = ""
    lt = (metrics.get("wgl.live_tile_ratio") or {}).get("last")
    live = f"{lt:.1%}" if isinstance(lt, (int, float)) else ""
    return eps, waste, sweep, live


def _stream_columns(results: dict) -> tuple[str, str]:
    """(check mode, overlap ratio) columns for the run index, from the
    run's results.json (runner/core.py stamps check_mode + the stream
    session record). Blank for runs recorded before streaming existed;
    overlap shows only for streamed runs (a post run has none by
    definition)."""
    mode = results.get("check_mode")
    if mode not in ("post", "stream"):
        return "", ""
    if mode != "stream":
        return mode, ""
    ov = (results.get("stream") or {}).get("overlap_ratio")
    return mode, (f"{ov:.0%}" if isinstance(ov, (int, float)) else "")


def _index_html(store: Store) -> str:
    rows = []
    for run in reversed(store.runs()):
        rel = run.path.relative_to(store.root)
        try:
            results = run.read_results()
            valid = results.get("valid")
        except Exception:
            results, valid = {}, "?"
        try:
            summary = _run_summary(results)
        except Exception:   # off-schema results must not hide the verdict
            summary = ""
        color = {True: "#2a9d43", False: "#d43a2a"}.get(valid, "#e9a820")
        href = urllib.parse.quote(f"/files/{rel}/")
        tele = ""
        if (run.path / TELEMETRY_FILE).exists():
            thref = urllib.parse.quote(f"/telemetry/{rel}")
            tele = f"<a href='{thref}'>telemetry</a>"
        eps, waste, sweep, live = _check_perf_columns(run)
        mode, overlap = _stream_columns(results)
        rows.append(
            f"<tr><td><a href='{href}'>"
            f"{html.escape(str(rel))}</a></td>"
            f"<td style='color:{color};font-weight:bold'>{valid}</td>"
            f"<td style='color:#666'>{html.escape(summary)}</td>"
            f"<td>{html.escape(eps)}</td>"
            f"<td>{html.escape(waste)}</td>"
            f"<td>{html.escape(sweep)}</td>"
            f"<td>{html.escape(live)}</td>"
            f"<td>{html.escape(mode)}</td>"
            f"<td>{html.escape(overlap)}</td>"
            f"<td><code>{html.escape(_profile_column(results))}</code></td>"
            f"<td>{tele}</td></tr>")
    return (
        "<!doctype html><html><head><meta charset='utf-8'>"
        "<title>jepsen-tpu store</title>"
        "<style>body{font-family:sans-serif}td{padding:4px 12px}</style>"
        "</head><body><h2>test runs</h2>"
        f"<table><tr><th>run</th><th>valid</th><th>detail</th>"
        f"<th>check eps</th><th>pad waste</th>"
        f"<th>sweep</th><th>live tiles</th>"
        f"<th>check mode</th><th>overlap</th>"
        f"<th>profile</th>"
        f"<th>obs</th></tr>"
        f"{''.join(rows)}</table>"
        "</body></html>")


def _profile_column(results: dict) -> str:
    """Which tuning profile the run's check resolved (runner/core.py
    stamps results.json with tune/profile.run_record): the short hash,
    plus the tuned-field count when any applied. Blank for runs recorded
    before the autotuner existed."""
    prof = results.get("profile")
    if not isinstance(prof, dict) or not prof.get("hash"):
        return ""
    h = str(prof["hash"])
    n = prof.get("tuned_fields") or 0
    return f"{h} ({n} tuned)" if n else h


# -- telemetry page --------------------------------------------------------

def _perf_summary_html(run_dir) -> str:
    """Compact per-run strip on the telemetry page mirroring the index's
    perf columns (check eps / pad waste / sweep mode / live-tile ratio),
    plus the streaming check gauges (stream/engine.py) next to them —
    overlap ratio and the watermark's lag high-water mark; empty when
    the run recorded none of them."""
    class _Run:
        path = run_dir

    eps, waste, sweep, live = _check_perf_columns(_Run)
    bits = [("check eps", eps), ("pad waste", waste), ("sweep", sweep),
            ("live tiles", live)]
    bits += _stream_gauge_bits(run_dir)
    shown = [f"{name}: <b>{html.escape(val)}</b>"
             for name, val in bits if val]
    return f"<p class='a'>{' · '.join(shown)}</p>" if shown else ""


def _stream_gauge_bits(run_dir) -> list[tuple[str, str]]:
    """The stream.overlap_ratio / stream.watermark_lag gauges from the
    run's metrics.json, formatted for the telemetry strip. A post-hoc
    run records both at zero-n (pre-registered, never set) — shown
    blank."""
    try:
        metrics = read_metrics(run_dir / METRICS_FILE)
    except Exception:
        return []
    out: list[tuple[str, str]] = []
    g = metrics.get("stream.overlap_ratio") or {}
    if g.get("type") == "gauge" and g.get("n") \
            and isinstance(g.get("last"), (int, float)):
        out.append(("stream overlap", f"{g['last']:.0%}"))
    g = metrics.get("stream.watermark_lag") or {}
    if g.get("type") == "gauge" and g.get("n") \
            and g.get("max") is not None:
        out.append(("watermark lag", f"{g.get('last'):g} "
                                     f"(max {g['max']:g})"))
    return out

def _fmt_ms(ns: int) -> str:
    return f"{ns / 1e6:,.1f}"


def _fmt_attrs(attrs: dict) -> str:
    if not attrs:
        return ""
    return html.escape(", ".join(f"{k}={v}" for k, v in attrs.items()))


def _span_tree_html(records: list[dict]) -> str:
    """Nested list of spans (parent links -> tree), each with duration
    and attrs; events render under their enclosing span. Spans keep
    completion order within one parent — close enough to timeline order
    for phase-level reading, and robust to concurrent workers."""
    spans = [r for r in records if r.get("kind") == "span"]
    events = [r for r in records if r.get("kind") == "event"]
    children: dict = {}
    for s in spans:
        children.setdefault(s.get("parent"), []).append(s)
    ev_by_span: dict = {}
    for e in events:
        ev_by_span.setdefault(e.get("span"), []).append(e)
    for group in (children, ev_by_span):
        for v in group.values():
            v.sort(key=lambda r: r.get("t0_ns", r.get("t_ns", 0)))

    def render(span_id) -> str:
        out = []
        for e in ev_by_span.get(span_id, []):
            out.append(
                f"<li class='ev'>⚡ {html.escape(str(e['name']))}"
                f" <span class='t'>@{_fmt_ms(e.get('t_ns', 0))} ms</span>"
                f" <span class='a'>{_fmt_attrs(e.get('attrs') or {})}"
                f"</span></li>")
        for s in children.get(span_id, []):
            dur = s.get("t1_ns", 0) - s.get("t0_ns", 0)
            err = " class='err'" if s.get("status") == "error" else ""
            out.append(
                f"<li><span{err}><b>{html.escape(str(s['name']))}</b></span>"
                f" <span class='t'>{_fmt_ms(dur)} ms</span>"
                f" <span class='a'>{_fmt_attrs(s.get('attrs') or {})}"
                f"</span><ul>{render(s['id'])}</ul></li>")
        return "".join(out)

    # Roots: spans with no recorded parent (parent None or missing — a
    # dropped/unclosed parent must not hide its finished children).
    known = {s["id"] for s in spans}
    roots = [s for s in spans
             if s.get("parent") is None or s.get("parent") not in known]
    children[None] = sorted(roots, key=lambda s: s.get("t0_ns", 0))
    return f"<ul class='tree'>{render(None)}</ul>"


def _metrics_table_html(metrics: dict) -> str:
    rows = []
    for name, rec in sorted(metrics.items()):
        kind = rec.get("type", "?")
        if kind == "counter":
            val = f"{rec.get('value', 0):,.6g}"
        elif kind == "gauge":
            val = (f"last {rec.get('last')} / min {rec.get('min')} / "
                   f"max {rec.get('max')} (n={rec.get('n', 0)})")
        else:
            val = (f"n {rec.get('count', 0)}, sum {rec.get('sum', 0):.6g}, "
                   f"min {rec.get('min')}, max {rec.get('max')}, "
                   f"avg {round(rec['avg'], 6) if rec.get('avg') is not None else None}")
        rows.append(f"<tr><td><code>{html.escape(name)}</code></td>"
                    f"<td>{kind}</td><td>{html.escape(val)}</td></tr>")
    return (f"<table><tr><th>metric</th><th>type</th><th>value</th></tr>"
            f"{''.join(rows)}</table>")


def _telemetry_html(store: Store, rel: str) -> str | None:
    """Render <store>/<rel>'s telemetry artifacts; None -> 404 (missing
    run, no artifacts, or a path escaping the store root)."""
    root = store.root.resolve()
    run_dir = (root / rel).resolve()
    if root not in run_dir.parents or not run_dir.is_dir():
        return None
    tele = run_dir / TELEMETRY_FILE
    metr = run_dir / METRICS_FILE
    if not tele.exists() and not metr.exists():
        return None
    parts = [
        "<!doctype html><html><head><meta charset='utf-8'>",
        f"<title>telemetry — {html.escape(rel)}</title>",
        "<style>body{font-family:sans-serif;margin:2em}"
        "td{padding:2px 10px;border-bottom:1px solid #eee}"
        "ul.tree,ul.tree ul{list-style:none;border-left:1px solid #ccc;"
        "padding-left:1.2em;margin:2px 0}"
        ".t{color:#2a6db0}.a{color:#888;font-size:90%}"
        ".err{color:#d43a2a}.ev{color:#555}</style></head><body>",
        f"<h2>telemetry — {html.escape(rel)}</h2>",
        f"<p><a href='/'>index</a> · "
        f"<a href='{urllib.parse.quote(f'/files/{rel}/')}'>run files</a></p>",
        _perf_summary_html(run_dir),
    ]
    if tele.exists():
        records = read_jsonl(tele)
        meta = next((r for r in records if r.get("kind") == "meta"), {})
        n_spans = sum(1 for r in records if r.get("kind") == "span")
        n_events = sum(1 for r in records if r.get("kind") == "event")
        parts.append(
            f"<h3>span tree</h3><p class='a'>{n_spans} spans, "
            f"{n_events} events; started {html.escape(str(meta.get('wall_start', '?')))}"
            f"{'; DROPPED ' + str(meta['dropped']) + ' records' if meta.get('dropped') else ''}"
            f"</p>")
        parts.append(_span_tree_html(records))
    if metr.exists():
        try:
            parts.append("<h3>metrics</h3>")
            parts.append(_metrics_table_html(read_metrics(metr)))
        except Exception as e:   # a torn metrics.json must not 500 the page
            parts.append(f"<p class='err'>metrics.json unreadable: "
                         f"{html.escape(str(e))}</p>")
    parts.append("</body></html>")
    return "".join(parts)


class StoreHandler(SimpleHTTPRequestHandler):
    """/ -> run index; /telemetry/<run> -> span tree + metric table;
    /files/... -> static serving rooted at the store."""

    def __init__(self, *args, store_root: str = "store", **kw):
        self.store = Store(store_root)
        super().__init__(*args, directory=str(store_root), **kw)

    def _send_html(self, body: str, status: int = 200) -> None:
        payload = body.encode()
        self.send_response(status)
        self.send_header("Content-Type", "text/html; charset=utf-8")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def do_GET(self):
        if self.path in ("/", "/index.html"):
            self._send_html(_index_html(self.store))
            return
        if self.path.startswith("/telemetry/"):
            rel = urllib.parse.unquote(
                self.path[len("/telemetry/"):]).strip("/")
            try:
                body = _telemetry_html(self.store, rel)
            except Exception as e:   # never 500 on a torn artifact
                body = (f"<!doctype html><p>telemetry unreadable: "
                        f"{html.escape(str(e))}</p>")
            if body is None:
                self._send_html("<!doctype html><p>no telemetry for "
                                f"{html.escape(rel)}</p>", status=404)
            else:
                self._send_html(body)
            return
        if self.path.startswith("/files/"):
            self.path = self.path[len("/files"):]
        return super().do_GET()

    def log_message(self, fmt, *args):  # quiet
        pass


def make_handler(store_root: str):
    return partial(StoreHandler, store_root=store_root)


def serve(store_root: str = "store", host: str = "127.0.0.1",
          port: int = 8080):
    httpd = ThreadingHTTPServer((host, port), make_handler(store_root))
    print(f"serving {store_root} on http://{host}:{port}")
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        httpd.server_close()
