"""Tiny stdlib HTTP server over store/ — `lein run serve` equivalent.

The reference serves its store with ring/jetty + a directory browser
(src/jepsen/etcdemo.clj:198; deps jepsen.etcdemo.iml:82-99). Same capability
on http.server: an index of runs with verdicts, static file serving of
each run dir (charts, timelines, logs, history), and a per-run telemetry
page (/telemetry/<run>) rendering the span tree + metric table the
harness records in telemetry.jsonl / metrics.json (obs/).

Live observability plane (ISSUE 8) — three process-level endpoints on
top of the per-run artifacts:

  /metrics     Prometheus text exposition of the ACTIVE capture's
               registry (obs/export.py) + backend health series
  /healthz     the backend supervisor's state as JSON (obs/health.py);
               HTTP 503 when wedged so load balancers see it
  /live        an in-flight-run page fed by Server-Sent Events from
               /live/events (the obs subscription bus): span tree, op
               throughput, nemesis events, stream gauges, health

These observe the SERVING PROCESS — they show a run in flight when the
server shares the process with the runner (`jepsen-tpu test
--live-port N`, or the future checking-as-a-service daemon)."""

from __future__ import annotations

import html
import json
import urllib.parse
from functools import partial
from http.server import SimpleHTTPRequestHandler, ThreadingHTTPServer

from .. import obs
from ..obs import (METRICS_FILE, TELEMETRY_FILE, export, health,
                   read_jsonl, read_metrics)
from ..obs import ledger as obs_ledger
from ..store import Store


def _run_summary(results: dict) -> str:
    """Compact why-it-failed / what-ran column: op count, rate, and for
    invalid runs the failing detail (per-key failed ops, elle anomaly
    types) pulled from the composed result tree. Served checks
    (serve/daemon.py artifacts, ISSUE 13) summarize their tenant /
    batch / route instead — they are browsable history like CLI runs."""
    bits = []
    srv = results.get("serve") or {}
    if srv.get("tenant"):
        bits.append(f"tenant {srv['tenant']}")
        batch = srv.get("batch") or {}
        if batch.get("size"):
            bits.append(f"batch of {batch['size']}")
        if srv.get("route") and srv["route"] != "jax":
            bits.append(f"route {srv['route']}")
        if srv.get("op_count"):
            bits.append(f"{srv['op_count']} ops")
    perf = results.get("perf") or {}
    if perf.get("count"):
        bits.append(f"{perf['count']} ops")
    if perf.get("rate_hz"):
        bits.append(f"{perf['rate_hz']:.0f}/s")
    indep = results.get("indep") or {}
    for key, sub in (indep.get("results") or {}).items():
        lin = sub.get("linear", sub) if isinstance(sub, dict) else {}
        if isinstance(lin, dict) and lin.get("valid") is False:
            op = lin.get("failed_op")
            bits.append(f"key {key}: {op}" if op else f"key {key}: invalid")
    # Whole-history workloads (gset/mutex/multiregister) have no per-key
    # results — the failing op sits directly under indep.linear.
    whole_lin = indep.get("linear") or {}
    if whole_lin.get("valid") is False:
        op = whole_lin.get("failed_op")
        bits.append(str(op) if op else "invalid")
    elle = indep.get("elle") or {}
    if elle.get("anomaly_types"):
        bits.append("anomalies: " + ", ".join(elle["anomaly_types"]))
    if indep.get("lost_count"):   # untruncated ('lost' caps at 100)
        bits.append(f"lost adds: {indep['lost_count']}")
    return "; ".join(str(b) for b in bits[:4])


def _check_perf_columns(run) -> tuple[str, str, str, str]:
    """(throughput, padding-waste, sweep-mode, live-tile-ratio) columns
    for the run index, from the run's metrics.json (obs/): check
    throughput = encoded history events over the kernels'
    compile+execute wall, padding waste = the last launch's padded/real
    step ratio (wgl3._record_padding), sweep mode = which dense-lattice
    sweep the run's checks took (the wgl.sweep_* counters — sparse
    engine, ops/wgl3_sparse.py), live tiles = the wgl.live_tile_ratio
    occupancy gauge. Blank when the run has no telemetry or never
    launched a kernel."""
    try:
        metrics = read_metrics(run.path / METRICS_FILE)
    except Exception:
        return "", "", "", ""

    def counter(name: str) -> float:
        rec = metrics.get(name) or {}
        return float(rec.get("value", 0.0)) \
            if rec.get("type") == "counter" else 0.0

    events = counter("encode.event_bytes") / 24.0   # 6 int32 per event
    kernel_s = counter("wgl.compile_s") + counter("wgl.execute_s")
    eps = f"{events / kernel_s:,.0f}/s" if events and kernel_s else ""
    ratio = (metrics.get("wgl.step_padding_ratio") or {}).get("last")
    waste = f"{ratio:.2f}x" if isinstance(ratio, (int, float)) else ""
    sp = counter("wgl.sweep_steps_sparse")
    dn = counter("wgl.sweep_steps_dense")
    if sp and dn:
        sweep = f"mixed ({100 * sp / (sp + dn):.0f}% sp)"
    elif sp:
        sweep = "sparse"
    elif dn or counter("wgl.sweep_checks_dense") \
            or counter("wgl.sweep_checks_mixed"):
        sweep = "dense"
    else:
        sweep = ""
    lt = (metrics.get("wgl.live_tile_ratio") or {}).get("last")
    live = f"{lt:.1%}" if isinstance(lt, (int, float)) else ""
    return eps, waste, sweep, live


def _stream_columns(results: dict) -> tuple[str, str]:
    """(check mode, overlap ratio) columns for the run index, from the
    run's results.json (runner/core.py stamps check_mode + the stream
    session record; serve/daemon.py stamps "serve", ISSUE 13). Blank
    for runs recorded before streaming existed; overlap shows only for
    streamed runs (a post run has none by definition)."""
    mode = results.get("check_mode")
    if mode not in ("post", "stream", "serve"):
        return "", ""
    if mode == "serve":
        ov = ((results.get("serve") or {}).get("stream")
              or {}).get("overlap_ratio")
        return mode, (f"{ov:.0%}" if isinstance(ov, (int, float)) else "")
    if mode != "stream":
        return mode, ""
    ov = (results.get("stream") or {}).get("overlap_ratio")
    return mode, (f"{ov:.0%}" if isinstance(ov, (int, float)) else "")


def _corpus_banner_html(store: Store) -> str:
    """Regression-corpus summary strip for the index (campaign/bank.py,
    ISSUE 15): banked minimal witnesses per anomaly signature. Empty
    string when the store has no bank."""
    try:
        from ..campaign.bank import bank_summary

        summary = bank_summary(store.root)
    except Exception:
        return ""
    if not summary:
        return ""
    sigs = ", ".join(f"{html.escape(slug)} ({n})"
                     for slug, n in sorted(summary["signatures"].items()))
    return (f"<p style='background:#eef3fb;padding:8px'>regression "
            f"corpus: <b>{summary['total']}</b> banked witness(es) — "
            f"{sigs} — replay with <code>jepsen-tpu campaign "
            f"--replay-corpus</code></p>")


def _index_html(store: Store) -> str:
    rows = []
    for run in reversed(store.runs()):
        rel = run.path.relative_to(store.root)
        try:
            results = run.read_results()
            valid = results.get("valid")
        except Exception:
            results, valid = {}, "?"
        try:
            summary = _run_summary(results)
        except Exception:   # off-schema results must not hide the verdict
            summary = ""
        color = {True: "#2a9d43", False: "#d43a2a"}.get(valid, "#e9a820")
        href = urllib.parse.quote(f"/files/{rel}/")
        tele = ""
        if (run.path / TELEMETRY_FILE).exists():
            thref = urllib.parse.quote(f"/telemetry/{rel}")
            tele = f"<a href='{thref}'>telemetry</a>"
        eps, waste, sweep, live = _check_perf_columns(run)
        mode, overlap = _stream_columns(results)
        rows.append(
            f"<tr><td><a href='{href}'>"
            f"{html.escape(str(rel))}</a></td>"
            f"<td style='color:{color};font-weight:bold'>{valid}</td>"
            f"<td style='color:#666'>{html.escape(summary)}</td>"
            f"<td>{html.escape(eps)}</td>"
            f"<td>{html.escape(waste)}</td>"
            f"<td>{html.escape(sweep)}</td>"
            f"<td>{html.escape(live)}</td>"
            f"<td>{html.escape(mode)}</td>"
            f"<td>{html.escape(overlap)}</td>"
            f"<td><code>{html.escape(_profile_column(results))}</code></td>"
            f"<td>{tele}</td></tr>")
    return (
        "<!doctype html><html><head><meta charset='utf-8'>"
        "<title>jepsen-tpu store</title>"
        "<style>body{font-family:sans-serif}td{padding:4px 12px}</style>"
        "</head><body><h2>test runs</h2>"
        "<p><a href='/live'>live</a> · <a href='/metrics'>metrics</a> · "
        "<a href='/healthz'>healthz</a></p>"
        f"{_corpus_banner_html(store)}"
        f"<table><tr><th>run</th><th>valid</th><th>detail</th>"
        f"<th>check eps</th><th>pad waste</th>"
        f"<th>sweep</th><th>live tiles</th>"
        f"<th>check mode</th><th>overlap</th>"
        f"<th>profile</th>"
        f"<th>obs</th></tr>"
        f"{''.join(rows)}</table>"
        "</body></html>")


def _profile_column(results: dict) -> str:
    """Which tuning profile the run's check resolved (runner/core.py
    stamps results.json with tune/profile.run_record): the short hash,
    plus the tuned-field count when any applied. Blank for runs recorded
    before the autotuner existed."""
    prof = results.get("profile")
    if not isinstance(prof, dict) or not prof.get("hash"):
        return ""
    h = str(prof["hash"])
    n = prof.get("tuned_fields") or 0
    return f"{h} ({n} tuned)" if n else h


# -- telemetry page --------------------------------------------------------

def _perf_summary_html(run_dir) -> str:
    """Compact per-run strip on the telemetry page mirroring the index's
    perf columns (check eps / pad waste / sweep mode / live-tile ratio),
    plus the streaming check gauges (stream/engine.py) next to them —
    overlap ratio and the watermark's lag high-water mark; empty when
    the run recorded none of them."""
    class _Run:
        path = run_dir

    eps, waste, sweep, live = _check_perf_columns(_Run)
    bits = [("check eps", eps), ("pad waste", waste), ("sweep", sweep),
            ("live tiles", live)]
    bits += _dedup_bits(run_dir)
    bits += _stream_gauge_bits(run_dir)
    bits += _elle_bits(run_dir)
    bits += _spill_bits(run_dir)
    shown = [f"{name}: <b>{html.escape(val)}</b>"
             for name, val in bits if val]
    return f"<p class='a'>{' · '.join(shown)}</p>" if shown else ""


def _dedup_bits(run_dir) -> list[tuple[str, str]]:
    """Frontier-dedup telemetry (ISSUE 10, ops/canon.py) for the
    telemetry strip: configs pruned by canonicalization, the dedup
    ratio gauge, and the previously-silent sparse work-list overflow
    rounds — all blank for runs that recorded none."""
    try:
        metrics = read_metrics(run_dir / METRICS_FILE)
    except Exception:
        return []
    out: list[tuple[str, str]] = []
    c = metrics.get("wgl.configs_pruned") or {}
    if c.get("type") == "counter" and c.get("value"):
        out.append(("configs pruned", f"{c['value']:,.0f}"))
    g = metrics.get("wgl.frontier_dedup_ratio") or {}
    if g.get("type") == "gauge" and g.get("n") \
            and isinstance(g.get("last"), (int, float)):
        out.append(("dedup ratio", f"{g['last']:.1%}"))
    c = metrics.get("wgl.sparse_overflow_rounds") or {}
    if c.get("type") == "counter" and c.get("value"):
        out.append(("sparse overflow rounds", f"{c['value']:,.0f}"))
    return out


def _elle_bits(run_dir) -> list[tuple[str, str]]:
    """Elle closure-engine telemetry (ISSUE 11, ops/cycles.py) for the
    strip: graphs per route (dense / batched / tiled / oracle) and the
    streamed-session txn count — blank for runs without txn checks."""
    try:
        metrics = read_metrics(run_dir / METRICS_FILE)
    except Exception:
        return []

    def counter(name: str) -> int:
        c = metrics.get(name) or {}
        return int(c.get("value") or 0) if c.get("type") == "counter" \
            else 0

    routes = [(r, counter(f"elle.graphs_{r}"))
              for r in ("dense", "batched", "tiled", "oracle")]
    out: list[tuple[str, str]] = []
    if any(v for _, v in routes):
        out.append(("elle graphs",
                    " / ".join(f"{v} {r}" for r, v in routes if v)))
    txns = counter("elle.stream_txns")
    if txns:
        out.append(("elle streamed txns", f"{txns:,}"))
    return out


def _spill_bits(run_dir) -> list[tuple[str, str]]:
    """Out-of-core spill-tier telemetry (ISSUE 20, store/spill.py +
    store/encode_cache.py) for the strip: spill traffic (writes/reads
    with byte volumes), checkpoint compression ratio, eviction counts
    (window + encode-cache GC), and the long-haul lane's peak-RSS delta
    — all blank for runs that never spilled."""
    try:
        metrics = read_metrics(run_dir / METRICS_FILE)
    except Exception:
        return []

    def counter(name: str) -> int:
        c = metrics.get(name) or {}
        return int(c.get("value") or 0) if c.get("type") == "counter" \
            else 0

    out: list[tuple[str, str]] = []
    w, r = counter("spill.writes"), counter("spill.reads")
    if w or r:
        out.append(("spill",
                    f"{w} w / {r} r "
                    f"({counter('spill.bytes_written') / (1 << 20):.1f}"
                    f" / {counter('spill.bytes_read') / (1 << 20):.1f}"
                    " MB)"))
    g = metrics.get("spill.compress_ratio") or {}
    if g.get("type") == "gauge" and g.get("n") \
            and isinstance(g.get("last"), (int, float)):
        out.append(("spill compress", f"{g['last']:.2f}x"))
    ev = counter("spill.evictions") + counter("encode.cache_evictions")
    if ev:
        out.append(("spill evictions", f"{ev:,}"))
    g = metrics.get("spill.peak_rss_mb") or {}
    if g.get("type") == "gauge" and g.get("n") \
            and isinstance(g.get("last"), (int, float)):
        out.append(("long-haul peak rss", f"{g['last']:g} MB"))
    return out


def _stream_gauge_bits(run_dir) -> list[tuple[str, str]]:
    """The stream.overlap_ratio / stream.watermark_lag gauges from the
    run's metrics.json, formatted for the telemetry strip. A post-hoc
    run records both at zero-n (pre-registered, never set) — shown
    blank."""
    try:
        metrics = read_metrics(run_dir / METRICS_FILE)
    except Exception:
        return []
    out: list[tuple[str, str]] = []
    g = metrics.get("stream.overlap_ratio") or {}
    if g.get("type") == "gauge" and g.get("n") \
            and isinstance(g.get("last"), (int, float)):
        out.append(("stream overlap", f"{g['last']:.0%}"))
    g = metrics.get("stream.watermark_lag") or {}
    if g.get("type") == "gauge" and g.get("n") \
            and g.get("max") is not None:
        out.append(("watermark lag", f"{g.get('last'):g} "
                                     f"(max {g['max']:g})"))
    return out

def _fmt_ms(ns: int) -> str:
    return f"{ns / 1e6:,.1f}"


def _fmt_attrs(attrs: dict) -> str:
    if not attrs:
        return ""
    return html.escape(", ".join(f"{k}={v}" for k, v in attrs.items()))


def _span_tree_html(records: list[dict]) -> str:
    """Nested list of spans (parent links -> tree), each with duration
    and attrs; events render under their enclosing span. Spans keep
    completion order within one parent — close enough to timeline order
    for phase-level reading, and robust to concurrent workers."""
    spans = [r for r in records if r.get("kind") == "span"]
    events = [r for r in records if r.get("kind") == "event"]
    children: dict = {}
    for s in spans:
        children.setdefault(s.get("parent"), []).append(s)
    ev_by_span: dict = {}
    for e in events:
        ev_by_span.setdefault(e.get("span"), []).append(e)
    for group in (children, ev_by_span):
        for v in group.values():
            v.sort(key=lambda r: r.get("t0_ns", r.get("t_ns", 0)))

    def render(span_id) -> str:
        out = []
        for e in ev_by_span.get(span_id, []):
            out.append(
                f"<li class='ev'>⚡ {html.escape(str(e['name']))}"
                f" <span class='t'>@{_fmt_ms(e.get('t_ns', 0))} ms</span>"
                f" <span class='a'>{_fmt_attrs(e.get('attrs') or {})}"
                f"</span></li>")
        for s in children.get(span_id, []):
            dur = s.get("t1_ns", 0) - s.get("t0_ns", 0)
            err = " class='err'" if s.get("status") == "error" else ""
            out.append(
                f"<li><span{err}><b>{html.escape(str(s['name']))}</b></span>"
                f" <span class='t'>{_fmt_ms(dur)} ms</span>"
                f" <span class='a'>{_fmt_attrs(s.get('attrs') or {})}"
                f"</span><ul>{render(s['id'])}</ul></li>")
        return "".join(out)

    # Roots: spans with no recorded parent (parent None or missing — a
    # dropped/unclosed parent must not hide its finished children).
    known = {s["id"] for s in spans}
    roots = [s for s in spans
             if s.get("parent") is None or s.get("parent") not in known]
    children[None] = sorted(roots, key=lambda s: s.get("t0_ns", 0))
    return f"<ul class='tree'>{render(None)}</ul>"


def _kernel_attribution_html(metrics: dict) -> str:
    """Per-kernel deep-attribution table (ISSUE 8): every kernel
    geometry the run compiled, with its compile/execute wall (the
    wgl.compile_s.<k>/wgl.execute_s.<k> histograms) and the XLA
    cost_analysis estimates captured at lower time
    (wgl.kernel_flops/kernel_bytes gauges). Empty string when the run
    recorded no per-kernel series (pre-ISSUE-8 artifacts)."""
    kernels: dict[str, dict] = {}

    def fold(prefix: str, field: str, value_of):
        for name, rec in metrics.items():
            if name.startswith(prefix + "."):
                kernels.setdefault(name[len(prefix) + 1:], {})[field] = \
                    value_of(rec)

    fold("wgl.compile_s", "compiles", lambda r: r.get("count", 0))
    fold("wgl.compile_s", "compile_s", lambda r: r.get("sum", 0.0))
    fold("wgl.execute_s", "calls", lambda r: r.get("count", 0))
    fold("wgl.execute_s", "execute_s", lambda r: r.get("sum", 0.0))
    fold("wgl.execute_s", "p95_s", lambda r: r.get("p95"))
    fold("wgl.kernel_flops", "flops", lambda r: r.get("last"))
    fold("wgl.kernel_bytes", "bytes", lambda r: r.get("last"))
    if not kernels:
        return ""

    def num(v, unit="") -> str:
        if not isinstance(v, (int, float)):
            return ""
        if v >= 1e9:
            return f"{v / 1e9:,.2f}G{unit}"
        if v >= 1e6:
            return f"{v / 1e6:,.2f}M{unit}"
        if v >= 1e3:
            return f"{v / 1e3:,.2f}k{unit}"
        return f"{v:,.4g}{unit}"

    rows = []
    for k in sorted(kernels):
        r = kernels[k]
        rows.append(
            f"<tr><td><code>{html.escape(k)}</code></td>"
            f"<td>{r.get('compiles', 0)}</td>"
            f"<td>{r.get('compile_s', 0.0):.3f}</td>"
            f"<td>{r.get('calls', 0)}</td>"
            f"<td>{r.get('execute_s', 0.0):.3f}</td>"
            f"<td>{num(r.get('p95_s'), 's')}</td>"
            f"<td>{num(r.get('flops'))}</td>"
            f"<td>{num(r.get('bytes'), 'B')}</td></tr>")
    return ("<h3>kernel attribution</h3>"
            "<table><tr><th>kernel</th><th>compiles</th>"
            "<th>compile s</th><th>calls</th><th>execute s</th>"
            "<th>p95 call</th><th>flops/call</th><th>bytes/call</th></tr>"
            f"{''.join(rows)}</table>")


def _metrics_table_html(metrics: dict) -> str:
    rows = []
    for name, rec in sorted(metrics.items()):
        kind = rec.get("type", "?")
        if kind == "counter":
            val = f"{rec.get('value', 0):,.6g}"
        elif kind == "gauge":
            val = (f"last {rec.get('last')} / min {rec.get('min')} / "
                   f"max {rec.get('max')} (n={rec.get('n', 0)})")
        else:
            val = (f"n {rec.get('count', 0)}, sum {rec.get('sum', 0):.6g}, "
                   f"min {rec.get('min')}, max {rec.get('max')}, "
                   f"avg {round(rec['avg'], 6) if rec.get('avg') is not None else None}")
            if rec.get("p50") is not None:
                val += (f", p50 {rec['p50']:.4g} / p95 "
                        f"{rec.get('p95'):.4g} / p99 {rec.get('p99'):.4g}")
        rows.append(f"<tr><td><code>{html.escape(name)}</code></td>"
                    f"<td>{kind}</td><td>{html.escape(val)}</td></tr>")
    return (f"<table><tr><th>metric</th><th>type</th><th>value</th></tr>"
            f"{''.join(rows)}</table>")


def _ledger_waterfall_html(run_dir) -> str:
    """The scaling-ledger waterfall panel (ISSUE 16): merge the run's
    per-process ledger-<proc>.jsonl files into one pod timeline and
    render the loss-bucket decomposition — where the chip-seconds went.
    Empty string when the run carries no ledger files; merge warnings
    (truncated / meta-less files) surface in the panel, never a 500."""
    paths = obs_ledger.ledger_paths(run_dir)
    if not paths:
        return ""
    try:
        merged = obs_ledger.merge_ledgers(paths)
        att = obs_ledger.attribute(merged["records"])
    except Exception as e:   # a torn ledger must not 500 the page
        return (f"<h3>scaling ledger</h3><p class='err'>ledger "
                f"unreadable: {html.escape(str(e))}</p>")
    parts = [f"<h3>scaling ledger</h3>",
             f"<p class='a'>{len(paths)} file(s), processes "
             f"{merged['procs'] or [0]}; window "
             f"{att['window_s']:.3f}s, {att['launches']} launches, "
             f"coverage {100 * att['coverage']:.1f}%</p>"]
    for w in merged["warnings"]:
        parts.append(f"<p class='warn'>&#9888; {html.escape(w)}</p>")
    wall = max(att["wall_s"], 1e-9)
    rows = []
    for name, secs in sorted(att["buckets"].items(),
                             key=lambda kv: -kv[1]):
        pct = 100.0 * secs / wall
        bar = ("<div style='background:#2a6db0;height:10px;"
               f"width:{min(100.0, pct):.1f}%'></div>")
        rows.append(f"<tr><td><code>{html.escape(name)}</code></td>"
                    f"<td>{secs:.3f}s</td><td>{pct:.1f}%</td>"
                    f"<td style='width:220px'>{bar}</td></tr>")
    parts.append("<table><tr><th>bucket</th><th>seconds</th>"
                 "<th>share</th><th></th></tr>" + "".join(rows)
                 + "</table>")
    top = att.get("top_losses") or []
    if top:
        parts.append("<p class='a'>top losses: " + ", ".join(
            f"{html.escape(k)}={v:.3f}s" for k, v in top[:3]) + "</p>")
    return "".join(parts)


def _telemetry_html(store: Store, rel: str) -> str | None:
    """Render <store>/<rel>'s telemetry artifacts; None -> 404 (missing
    run, no artifacts, or a path escaping the store root)."""
    root = store.root.resolve()
    run_dir = (root / rel).resolve()
    if root not in run_dir.parents or not run_dir.is_dir():
        return None
    tele = run_dir / TELEMETRY_FILE
    metr = run_dir / METRICS_FILE
    if not tele.exists() and not metr.exists():
        return None
    parts = [
        "<!doctype html><html><head><meta charset='utf-8'>",
        f"<title>telemetry — {html.escape(rel)}</title>",
        "<style>body{font-family:sans-serif;margin:2em}"
        "td{padding:2px 10px;border-bottom:1px solid #eee}"
        "ul.tree,ul.tree ul{list-style:none;border-left:1px solid #ccc;"
        "padding-left:1.2em;margin:2px 0}"
        ".t{color:#2a6db0}.a{color:#888;font-size:90%}"
        ".err{color:#d43a2a}.ev{color:#555}"
        ".warn{color:#b05a00;background:#fff3e0;border:1px solid #e9a820;"
        "padding:8px;font-weight:bold}</style></head><body>",
        f"<h2>telemetry — {html.escape(rel)}</h2>",
        f"<p><a href='/'>index</a> · "
        f"<a href='{urllib.parse.quote(f'/files/{rel}/')}'>run files</a></p>",
        _perf_summary_html(run_dir),
        _ledger_waterfall_html(run_dir),
    ]
    if tele.exists():
        records = read_jsonl(tele)
        meta = next((r for r in records if r.get("kind") == "meta"), {})
        footer = next((r for r in records if r.get("kind") == "footer"), {})
        n_spans = sum(1 for r in records if r.get("kind") == "span")
        n_events = sum(1 for r in records if r.get("kind") == "event")
        dropped = int(meta.get("dropped") or footer.get("dropped") or 0)
        if dropped:
            # Truncation is a first-class warning, not a footnote: a
            # truncated span tree must never read as a complete one.
            parts.append(
                f"<p class='warn'>&#9888; telemetry TRUNCATED: {dropped} "
                f"record(s) dropped after the tracer's max_records cap "
                f"— the span tree below is incomplete</p>")
        parts.append(
            f"<h3>span tree</h3><p class='a'>{n_spans} spans, "
            f"{n_events} events; started {html.escape(str(meta.get('wall_start', '?')))}"
            f"</p>")
        parts.append(_span_tree_html(records))
    if metr.exists():
        try:
            metrics = read_metrics(metr)
            parts.append(_kernel_attribution_html(metrics))
            parts.append("<h3>metrics</h3>")
            parts.append(_metrics_table_html(metrics))
        except Exception as e:   # a torn metrics.json must not 500 the page
            parts.append(f"<p class='err'>metrics.json unreadable: "
                         f"{html.escape(str(e))}</p>")
    parts.append("</body></html>")
    return "".join(parts)


# -- live observability plane (ISSUE 8) ------------------------------------

def _metrics_text() -> str:
    """The /metrics payload: the active capture's registry as
    Prometheus text (empty registry outside a run), plus the process
    series — up and the backend supervisor's state (both as a level
    gauge and a labeled info series)."""
    reg = obs.get_metrics()
    snap = reg.snapshot() if getattr(reg, "enabled", False) else {}
    # The supervisor IS the authority on health: drop the capture's
    # pre-registered health.state gauge so the exposition carries
    # exactly one jepsen_tpu_health_state family (a duplicate TYPE line
    # would make the whole scrape invalid).
    snap.pop("health.state", None)
    hs = health.get_supervisor().snapshot()
    level = health.STATE_LEVEL.get(hs["state"], -1)
    extra = [
        "# TYPE jepsen_tpu_up gauge",
        "jepsen_tpu_up 1",
        "# TYPE jepsen_tpu_health_state gauge",
        f"jepsen_tpu_health_state {level}",
        "# TYPE jepsen_tpu_health_info gauge",
        f'jepsen_tpu_health_info{{state='
        f'"{export.sanitize_label_value(hs["state"])}"}} 1',
        "# TYPE jepsen_tpu_run_in_flight gauge",
        f"jepsen_tpu_run_in_flight {int(obs.capture_active())}",
    ]
    return export.render_prometheus(snap, extra_lines=extra)


def _healthz() -> tuple[int, dict]:
    """(status code, body) for /healthz: the supervisor snapshot with
    last-transition provenance. 503 when wedged — a load balancer (or
    the future daemon's failover watcher) can act on the code alone."""
    hs = health.get_supervisor().snapshot()
    body = {"status": hs["state"], **hs,
            "run_in_flight": obs.capture_active(),
            "telemetry_enabled": obs.telemetry_enabled()}
    return (503 if hs["state"] == health.WEDGED else 200), body


_LIVE_PAGE = """<!doctype html><html><head><meta charset='utf-8'>
<title>jepsen-tpu live</title>
<style>body{font-family:sans-serif;margin:2em}
#health{padding:8px;font-weight:bold;display:inline-block}
.healthy{background:#e2f5e5;color:#2a9d43}
.degraded{background:#fff3e0;color:#b05a00}
.wedged{background:#fde3e0;color:#d43a2a}
table{border-collapse:collapse}td,th{padding:2px 10px;
border-bottom:1px solid #eee;text-align:left}
ul.tree,ul.tree ul{list-style:none;border-left:1px solid #ccc;
padding-left:1.2em;margin:2px 0}
.t{color:#2a6db0}.a{color:#888;font-size:90%}.ev{color:#555}
#idle{color:#888}</style></head><body>
<h2>live run <span id='health'>connecting&hellip;</span></h2>
<p><a href='/'>index</a> &middot; <a href='/metrics'>metrics</a>
&middot; <a href='/healthz'>healthz</a></p>
<p id='idle' hidden>no run in flight in the serving process &mdash;
start one with <code>jepsen-tpu test &hellip; --live-port</code></p>
<table id='stats'><tr>
<th>ops ok</th><th>ops/s</th><th>ops fail</th><th>stream overlap</th>
<th>watermark lag</th><th>frontier peak</th><th>serve queue</th>
<th>batch fill</th><th>campaign specs</th><th>falsified</th>
<th>banked</th><th>chip util</th><th>SLO p99</th>
<th>SLO burn</th></tr><tr>
<td id='ok'>0</td><td id='rate'>&ndash;</td><td id='fail'>0</td>
<td id='overlap'>&ndash;</td><td id='lag'>&ndash;</td>
<td id='frontier'>&ndash;</td><td id='squeue'>&ndash;</td>
<td id='sfill'>&ndash;</td><td id='cspecs'>&ndash;</td>
<td id='cfals'>&ndash;</td><td id='cbank'>&ndash;</td>
<td id='lutil'>&ndash;</td><td id='slop99'>&ndash;</td>
<td id='sloburn'>&ndash;</td></tr></table>
<h3>nemesis / events</h3><ul id='events'></ul>
<h3>span tree</h3><ul class='tree' id='spans'></ul>
<script>
const spans = {}, waiting = {}, seenIds = new Set();
let okPrev = null, okPrevT = null;
function el(id){return document.getElementById(id);}
function met(name, m){
  if (name === 'runner.ops_ok'){
    const now = Date.now();
    if (okPrev !== null && now > okPrevT)
      el('rate').textContent = ((m.value - okPrev) * 1000 /
                                (now - okPrevT)).toFixed(1);
    okPrev = m.value; okPrevT = now;
    el('ok').textContent = m.value;
  } else if (name === 'runner.ops_fail') el('fail').textContent = m.value;
  else if (name === 'stream.overlap_ratio' && m.last !== null)
    el('overlap').textContent = (100 * m.last).toFixed(0) + '%';
  else if (name === 'stream.watermark_lag' && m.last !== null)
    el('lag').textContent = m.last;
  else if (name === 'wgl.frontier_peak' && m.max !== null)
    el('frontier').textContent = m.max;
  else if (name === 'serve.queue_depth' && m.last !== null)
    el('squeue').textContent = m.last;
  else if (name === 'serve.batch_fill' && m.last !== null)
    el('sfill').textContent = (100 * m.last).toFixed(0) + '%';
  else if (name === 'campaign.specs')
    el('cspecs').textContent = m.value;
  else if (name === 'campaign.runs_falsified')
    el('cfals').textContent = m.value;
  else if (name === 'campaign.banked')
    el('cbank').textContent = m.value;
  else if (name === 'ledger.execute_s'){
    ledgerExec = m.value; updUtil();
  } else if (name === 'ledger.dispatch_gap_s'){
    ledgerGap = m.value; updUtil();
  } else if (name === 'serve.slo_p99_s' && m.last !== null)
    el('slop99').textContent = (1000 * m.last).toFixed(0) + ' ms';
  else if (name === 'serve.slo_burn_rate' && m.last !== null)
    el('sloburn').textContent = m.last.toFixed(2) + 'x';
  else if (name === 'health.state') setHealth(m.last);
}
let ledgerExec = 0, ledgerGap = 0;
// Utilization derived from the scaling ledger's cumulative buckets:
// device-busy seconds over device-busy + host dispatch gap.
function updUtil(){
  const busy = ledgerExec + ledgerGap;
  if (busy > 0)
    el('lutil').textContent = (100 * ledgerExec / busy).toFixed(0) + '%';
}
function setHealth(v){
  const s = typeof v === 'string' ? v
          : ['healthy', 'degraded', 'wedged'][v] || '?';
  const h = el('health'); h.textContent = s; h.className = s;
}
function addSpan(r){
  const li = document.createElement('li');
  const ms = ((r.t1_ns - r.t0_ns) / 1e6).toFixed(1);
  li.innerHTML = '<b></b> <span class=t>' + ms + ' ms</span>';
  li.querySelector('b').textContent = r.name;
  const ul = document.createElement('ul'); li.appendChild(ul);
  spans[r.id] = ul;
  // Spans stream in COMPLETION order, so children precede their
  // parent: adopt any that already rendered at the root (appendChild
  // moves them), and if our own parent is still open, render at the
  // root now and wait to be adopted ourselves.
  for (const c of waiting[r.id] || []) ul.appendChild(c);
  delete waiting[r.id];
  if (r.parent === null || spans[r.parent]) {
    (spans[r.parent] || el('spans')).appendChild(li);
  } else {
    el('spans').appendChild(li);
    (waiting[r.parent] = waiting[r.parent] || []).push(li);
  }
}
function addRecord(r){
  if (r.id !== undefined){
    if (seenIds.has(r.id)) return;  // init tail / live queue overlap
    seenIds.add(r.id);
  }
  r.kind === 'span' ? addSpan(r) : addEvent(r);
}
function addEvent(r){
  const li = document.createElement('li');
  li.className = 'ev';
  li.textContent = '⚡ ' + r.name + ' ' + JSON.stringify(r.attrs);
  el('events').appendChild(li);
  if (el('events').children.length > 50)
    el('events').removeChild(el('events').firstChild);
}
const es = new EventSource('/live/events');
es.addEventListener('init', e => {
  const d = JSON.parse(e.data);
  setHealth(d.health.state);
  el('idle').hidden = d.run_in_flight;
  for (const [n, m] of Object.entries(d.metrics)) met(n, m);
  for (const r of d.records) addRecord(r);
});
es.addEventListener('span', e => addRecord(JSON.parse(e.data)));
es.addEventListener('event', e => addRecord(JSON.parse(e.data)));
es.addEventListener('metric', e => {
  const d = JSON.parse(e.data); met(d.name, d.metric);
});
</script></body></html>"""


class StoreHandler(SimpleHTTPRequestHandler):
    """/ -> run index; /telemetry/<run> -> span tree + metric table;
    /metrics, /healthz, /live, /live/events -> the serving process's
    live observability plane; /files/... -> static serving rooted at
    the store."""

    def __init__(self, *args, store_root: str = "store", **kw):
        self.store = Store(store_root)
        super().__init__(*args, directory=str(store_root), **kw)

    def _send_html(self, body: str, status: int = 200) -> None:
        self._send_payload(body.encode(), "text/html; charset=utf-8",
                           status)

    def _send_payload(self, payload: bytes, ctype: str,
                      status: int = 200) -> None:
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def _serve_sse(self) -> None:
        """/live/events: subscribe to the obs bus and stream records as
        Server-Sent Events until the client disconnects. Opens with an
        `init` event (current metrics snapshot, health, the tracer's
        buffered records so the span tree starts populated); then spans/
        events arrive in append order and coalesced `metric` records a
        few times per second (the bus's pump). A 1 s heartbeat detects
        dead clients promptly."""
        sub = obs.subscribe()
        try:
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            self.end_headers()
            reg = obs.get_metrics()
            tracer = obs.get_tracer()
            init = {
                "run_in_flight": obs.capture_active(),
                "health": health.get_supervisor().snapshot(),
                "metrics": reg.snapshot()
                if getattr(reg, "enabled", False) else {},
                # The most recent already-recorded trace tail — enough
                # to seed the page without replaying a whole long run.
                # Through the LOCKED tail() reader: handler threads
                # must not slice the live record list while the run's
                # threads append (jtsan's snapshot-under-lock
                # discipline), and copying the whole buffer per SSE
                # connect was O(max_records) anyway.
                "records": tracer.tail(500) if tracer.enabled else [],
            }
            self.wfile.write(export.sse_message(init, event="init"))
            self.wfile.flush()
            while True:
                rec = sub.get(timeout=1.0)
                if rec is None:
                    self.wfile.write(b": ping\n\n")
                else:
                    self.wfile.write(
                        export.sse_message(rec, event=rec.get("kind")))
                self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass   # client went away — the normal way an SSE stream ends
        finally:
            sub.close()

    def do_GET(self):
        if self.path in ("/", "/index.html"):
            self._send_html(_index_html(self.store))
            return
        if self.path.rstrip("/") == "/metrics":
            self._send_payload(_metrics_text().encode(),
                               export.PROM_CONTENT_TYPE)
            return
        if self.path.rstrip("/") == "/healthz":
            status, body = _healthz()
            self._send_payload(
                (json.dumps(body, indent=2) + "\n").encode(),
                "application/json; charset=utf-8", status)
            return
        if self.path.rstrip("/") == "/live":
            self._send_html(_LIVE_PAGE)
            return
        if self.path.rstrip("/") == "/live/events":
            self._serve_sse()
            return
        if self.path.startswith("/telemetry/"):
            rel = urllib.parse.unquote(
                self.path[len("/telemetry/"):]).strip("/")
            try:
                body = _telemetry_html(self.store, rel)
            except Exception as e:   # never 500 on a torn artifact
                body = (f"<!doctype html><p>telemetry unreadable: "
                        f"{html.escape(str(e))}</p>")
            if body is None:
                self._send_html("<!doctype html><p>no telemetry for "
                                f"{html.escape(rel)}</p>", status=404)
            else:
                self._send_html(body)
            return
        if self.path.startswith("/files/"):
            self.path = self.path[len("/files"):]
        return super().do_GET()

    def log_message(self, fmt, *args):  # quiet
        pass


def make_handler(store_root: str):
    return partial(StoreHandler, store_root=store_root)


def serve(store_root: str = "store", host: str = "127.0.0.1",
          port: int = 8080):
    httpd = ThreadingHTTPServer((host, port), make_handler(store_root))
    print(f"serving {store_root} on http://{host}:{port} "
          f"(/live, /metrics, /healthz)")
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        httpd.server_close()
