"""The Checker seam: one method, pure function of the recorded history.

Mirrors jepsen.checker/Checker — check(test, history, opts) -> map with
:valid? (reference call sites: src/jepsen/etcdemo.clj:115-119,165-167). The
TPU linearizable checker plugs in behind this exact seam so test composition
is untouched (BASELINE.json north star).

`valid` is tri-state like jepsen's: True, False, or "unknown" (e.g. frontier
overflow / nothing to check).
"""

from __future__ import annotations

import abc
from typing import Any, Sequence

from ..ops.op import Op


class CheckerError(Exception):
    pass


class Checker(abc.ABC):
    @abc.abstractmethod
    def check(self, test: dict, history: Sequence[Op],
              opts: dict | None = None) -> dict[str, Any]:
        """Return at least {"valid": True|False|"unknown"}."""


def merge_valid(vs: list) -> Any:
    """jepsen's validity merge: all true -> true; any false -> false;
    otherwise unknown."""
    if any(v is False for v in vs):
        return False
    if all(v is True for v in vs):
        return True
    return "unknown"
