"""checker/compose equivalent: run named sub-checkers, merge validity.

Reference call sites: the top-level {:perf, :indep} composition
(src/jepsen/etcdemo.clj:165-167) and the per-key {:linear, :timeline}
composition (src/jepsen/etcdemo.clj:115-119).
"""

from __future__ import annotations

from typing import Any, Sequence

from .base import Checker, merge_valid
from ..ops.op import Op


class Compose(Checker):
    def __init__(self, checkers: dict[str, Checker]):
        if "valid" in checkers:
            raise ValueError(
                "'valid' is reserved for the merged verdict; rename the "
                "sub-checker")
        self.checkers = dict(checkers)

    def check(self, test: dict, history: Sequence[Op],
              opts: dict | None = None) -> dict[str, Any]:
        results = {name: c.check(test, history, opts)
                   for name, c in self.checkers.items()}
        return {"valid": merge_valid([r.get("valid") for r in results.values()]),
                **results}
