"""Reference (oracle) linearizability checkers, pure Python/NumPy.

Two independent implementations used for differential testing of the JAX
kernel (SURVEY.md §4), both consuming the same event encoding as the kernel:

  * `check_events_oracle` — Wing–Gong/Lowe frontier search with set-based
    dedup. Same algorithmic idea as knossos's :linear algorithm
    (reference call site src/jepsen/etcdemo.clj:117): maintain the set of
    (model-state, linearized-bitmask) configurations; expand closure under
    firing pending ops; at each return, keep only configurations that have
    linearized the returning op.

  * `brute_force_check` — enumerate every linearization order consistent with
    the event stream (exponential; tiny histories only). Ground truth for the
    oracle itself.

Both treat `info` ops exactly like knossos: pending forever, may fire at any
later point, never required to fire (reference :info mapping at
src/jepsen/etcdemo.clj:100-102).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

from ..models.base import Model
from ..ops.encode import EncodedHistory, EV_INVOKE, EV_RETURN, EV_PAD


@dataclass
class OracleResult:
    valid: bool
    dead_event: int = -1       # first event index where the frontier emptied
    max_frontier: int = 0      # high-water mark of |frontier|
    configs_explored: int = 0

    def to_dict(self):
        return {
            "valid": self.valid,
            "dead_event": self.dead_event,
            "max_frontier": self.max_frontier,
            "configs_explored": self.configs_explored,
        }


class OracleBudgetExceeded(Exception):
    """Raised by check_events_oracle when `max_configs` transition
    attempts were spent without reaching a verdict. The caller (the
    product router at ops/wgl3_pallas.py) falls back to the capped
    device ladder — the oracle route must never become an unbounded
    exponential host search."""


def check_events_oracle(enc: EncodedHistory, model: Model,
                        max_configs: int | None = None) -> OracleResult:
    events = np.asarray(enc.events)
    slots: dict[int, tuple[int, int, int, int]] = {}
    frontier: set[tuple[int, int]] = {(int(model.init_state()), 0)}
    max_frontier = len(frontier)
    explored = 0

    def closure(configs: set[tuple[int, int]],
                target_slot: int) -> set[tuple[int, int]]:
        """Reachable configs, with just-in-time linearization: configs that
        have fired `target_slot` (the returning op) are banked, not expanded
        further — everything beyond that boundary is regenerable at the next
        return, so the stored frontier stays minimal (Lowe's JIT
        linearization, the knossos :linear algorithm's key optimization)."""
        nonlocal explored
        tbit = 1 << target_slot
        seen = set(configs)
        stack = [c for c in configs if not c[1] & tbit]
        while stack:
            state, mask = stack.pop()
            for slot, (f, a1, a2, rv) in slots.items():
                if mask >> slot & 1:
                    continue
                legal, nxt = model.step_py(state, f, a1, a2, rv)
                explored += 1
                if max_configs is not None and explored > max_configs:
                    raise OracleBudgetExceeded(
                        f"oracle spent {explored} transition attempts "
                        f"(budget {max_configs}) without a verdict")
                if legal:
                    cfg = (int(nxt), mask | (1 << slot))
                    if cfg not in seen:
                        seen.add(cfg)
                        if not cfg[1] & tbit:
                            stack.append(cfg)
        return seen

    for i in range(enc.n_events):
        kind, slot, f, a1, a2, rv = (int(x) for x in events[i])
        if kind == EV_PAD:
            continue
        if kind == EV_INVOKE:
            slots[slot] = (f, a1, a2, rv)
        elif kind == EV_RETURN:
            expanded = closure(frontier, slot)
            max_frontier = max(max_frontier, len(expanded))
            bit = 1 << slot
            frontier = {(s, m & ~bit) for (s, m) in expanded if m & bit}
            del slots[slot]
            if not frontier:
                return OracleResult(False, dead_event=i,
                                    max_frontier=max_frontier,
                                    configs_explored=explored)
        max_frontier = max(max_frontier, len(frontier))
    return OracleResult(True, max_frontier=max_frontier,
                        configs_explored=explored)


def brute_force_check(enc: EncodedHistory, model: Model,
                      max_ops: int = 12) -> Optional[bool]:
    """Exhaustive check by enumerating linearization orders.

    Returns None when the history is too large to enumerate. An op may fire at
    any point after its EV_INVOKE; ok ops must fire before their EV_RETURN;
    info ops may fire anytime after invoke or never.
    """
    events = np.asarray(enc.events)[: enc.n_events]
    if enc.n_ops > max_ops:
        return None

    # Assign each invocation a stable id and find its invoke/return event pos.
    ops = []           # id -> (f, a1, a2, rv, invoke_pos, return_pos or None)
    live: dict[int, int] = {}  # slot -> op id
    for pos, (kind, slot, f, a1, a2, rv) in enumerate(events):
        if kind == EV_INVOKE:
            live[int(slot)] = len(ops)
            ops.append([int(f), int(a1), int(a2), int(rv), pos, None])
        elif kind == EV_RETURN:
            ops[live.pop(int(slot))][5] = pos

    n = len(ops)
    seen: set[tuple[int, int, int]] = set()

    def search(pos: int, fired: int, state: int) -> bool:
        if (pos, fired, state) in seen:
            return False
        seen.add((pos, fired, state))
        return _search(pos, fired, state)

    def _search(pos: int, fired: int, state: int) -> bool:
        """Can we schedule linearization points for events[pos:]?"""
        if pos == len(events):
            return True
        # Option: fire any fireable op whose invoke is before `pos` boundary.
        # We process event boundaries one at a time; between boundaries any
        # set of pending ops may fire in any order.
        kind, slot, f, a1, a2, rv = (int(x) for x in events[pos])
        # Ops eligible to fire *now*: invoked (invoke_pos < pos boundary ...).
        for i in range(n):
            fop, fa1, fa2, frv, ipos, rpos = ops[i]
            if fired >> i & 1:
                continue
            if ipos >= pos:
                continue  # not yet invoked
            if rpos is not None and rpos < pos:
                continue  # unreachable: enforced at its return boundary
            legal, nxt = model.step_py(state, fop, fa1, fa2, frv)
            if legal and search(pos, fired | (1 << i), int(nxt)):
                return True
        if kind == EV_RETURN:
            i = next(j for j, o in enumerate(ops) if o[5] == pos)
            if not (fired >> i & 1):
                return False  # must have fired before returning
        return search(pos + 1, fired, state)

    return search(0, 0, int(model.init_state()))
