"""Grow-only-set durability checker — equivalent of `checker/set`.

Reference semantics (src/jepsen/etcdemo/set.clj:46): concurrent :add ops, one
final :read of the whole set. Every successfully-acknowledged add must appear
in the final read (lost = failures); elements that appear without ever being
invoked are corruption. Indeterminate (:info) adds that do appear are
"recovered"; absent ones are "unsure" (not failures) — matching jepsen's set
checker accounting.
"""

from __future__ import annotations

from typing import Any, Sequence

from .base import Checker
from ..ops.op import Op, INVOKE, OK, INFO


class SetChecker(Checker):
    def check(self, test: dict, history: Sequence[Op],
              opts: dict | None = None) -> dict[str, Any]:
        attempts: set = set()
        ok_adds: set = set()
        info_adds: set = set()
        final_read = None
        pending: dict[Any, Op] = {}
        for op in history:
            if op.type == INVOKE:
                pending[op.process] = op
                if op.f == "add":
                    attempts.add(op.value)
            else:
                inv = pending.pop(op.process, None)
                if inv is None:
                    continue
                if inv.f == "add":
                    if op.type == OK:
                        ok_adds.add(inv.value)
                    elif op.type == INFO:
                        info_adds.add(inv.value)
                elif inv.f == "read" and op.type == OK:
                    final_read = set(op.value) if op.value is not None else None
        # Adds whose completion never arrived are indeterminate too.
        for inv in pending.values():
            if inv.f == "add":
                info_adds.add(inv.value)

        if final_read is None:
            return {"valid": "unknown", "error": "no final read",
                    "attempt_count": len(attempts), "ok_count": len(ok_adds)}

        lost = ok_adds - final_read
        unexpected = final_read - attempts
        recovered = (final_read & info_adds) - ok_adds
        valid = not lost and not unexpected
        return {
            "valid": valid,
            "attempt_count": len(attempts),
            "ok_count": len(ok_adds),
            "lost_count": len(lost),
            "lost": sorted(lost)[:100],
            "unexpected_count": len(unexpected),
            "unexpected": sorted(unexpected)[:100],
            "recovered_count": len(recovered),
        }
