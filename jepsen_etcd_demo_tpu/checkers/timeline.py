"""HTML timeline checker — equivalent of jepsen.checker.timeline/html.

The reference renders a per-process swimlane of every op (invoke→complete
bars colored by outcome) as HTML via hiccup, per key under the independent
wrapper (reference call site src/jepsen/etcdemo.clj:16,119; SURVEY.md §5.1).
Same artifact here as a self-contained static HTML file (no JS deps).
"""

from __future__ import annotations

import html
from pathlib import Path
from typing import Any, Sequence

from ..ops.op import Op, INVOKE, OK, FAIL, INFO
from .base import Checker

SECOND = 1_000_000_000

COLORS = {OK: "#6fbf73", FAIL: "#e57373", INFO: "#ffd54f", "open": "#b0bec5"}

CSS = """
body { font-family: sans-serif; background: #fafafa; }
.lane { position: relative; height: 22px; border-bottom: 1px solid #eee; }
.lane .label { position: absolute; left: 0; width: 90px; font-size: 11px;
               line-height: 22px; color: #555; }
.ops { position: absolute; left: 100px; right: 0; top: 0; bottom: 0; }
.op { position: absolute; height: 16px; top: 3px; border-radius: 3px;
      font-size: 9px; overflow: hidden; white-space: nowrap;
      line-height: 16px; padding: 0 2px; box-sizing: border-box; }
.axis { margin-left: 100px; font-size: 10px; color: #888; }
"""


class TimelineChecker(Checker):
    def __init__(self, filename: str = "timeline.html"):
        self.filename = filename

    def check(self, test: dict, history: Sequence[Op],
              opts: dict | None = None) -> dict[str, Any]:
        store_dir = (opts or {}).get("store_dir")
        key = (opts or {}).get("key")
        if store_dir:
            name = (f"timeline-{key}.html" if key is not None
                    else self.filename)
            Path(store_dir, name).write_text(render_timeline(history))
            return {"valid": True, "file": name}
        return {"valid": True}


def render_timeline(history: Sequence[Op]) -> str:
    """Swimlane per process; one bar per invocation spanning invoke→complete."""
    pending: dict[Any, Op] = {}
    bars: dict[Any, list] = {}
    t_max = max((op.time for op in history), default=1)
    for op in history:
        if op.type == INVOKE:
            pending[op.process] = op
        elif op.type in (OK, FAIL, INFO):
            inv = pending.pop(op.process, None)
            if inv is not None:
                bars.setdefault(op.process, []).append(
                    (inv.time, op.time, op.type, inv.f, inv.value, op.value,
                     op.error))
    for proc, inv in pending.items():  # never-completed: open to the end
        bars.setdefault(proc, []).append(
            (inv.time, t_max, "open", inv.f, inv.value, None, None))

    t_max = max(t_max, 1)
    lanes = []
    for proc in sorted(bars, key=str):
        divs = []
        for t0, t1, typ, f, vin, vout, err in bars[proc]:
            left = 100.0 * t0 / t_max
            width = max(0.15, 100.0 * (t1 - t0) / t_max)
            title = html.escape(
                f"{f} {vin!r} -> {typ}"
                + (f" {vout!r}" if vout is not None else "")
                + (f" ({err})" if err else ""))
            divs.append(
                f'<div class="op" style="left:{left:.3f}%;'
                f'width:{width:.3f}%;background:{COLORS.get(typ, "#ccc")}"'
                f' title="{title}">{html.escape(str(f))}</div>')
        lanes.append(
            f'<div class="lane"><div class="label">proc {proc}</div>'
            f'<div class="ops">{"".join(divs)}</div></div>')
    axis = (f'<div class="axis">0s … {t_max / SECOND:.2f}s'
            f' — green ok / red fail / yellow info / gray never-returned</div>')
    return (f"<!doctype html><html><head><meta charset='utf-8'>"
            f"<style>{CSS}</style><title>timeline</title></head>"
            f"<body><h3>operation timeline</h3>{axis}{''.join(lanes)}"
            f"</body></html>")
