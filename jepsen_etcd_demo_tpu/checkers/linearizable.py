"""Linearizability checker behind the Checker seam.

Drop-in equivalent of `checker/linearizable {:model (model/cas-register)
:algorithm :linear}` (reference src/jepsen/etcdemo.clj:117), with the search
executed either by the JAX/TPU kernel (ops/wgl.py — the default and the point
of this framework) or by the pure-Python oracle (differential baseline).

On frontier/slot overflow the JAX backend escalates capacity once and, if the
verdict is still indeterminate, falls back to the oracle so the final answer
is exact.
"""

from __future__ import annotations

from typing import Any, Sequence

from .base import Checker
from .oracle import check_events_oracle
from ..ops.encode import EV_RETURN
from ..models import Model, get_model
from ..ops.op import Op
from ..ops.encode import (EncodedHistory, SlotOverflow,
                          encode_register_history)


def _event_to_step(enc: EncodedHistory, dead_event: int) -> int:
    """Translate an event index (oracle) into a return-step index (v2 kernel
    schema): the count of returns strictly before the fatal one."""
    if dead_event < 0:
        return -1
    ev = enc.events[:dead_event, 0]
    return int((ev == EV_RETURN).sum())


class Linearizable(Checker):
    def __init__(self, model: Model | str = "cas-register",
                 backend: str = "jax", k_slots: int = 24, f_cap: int = 256):
        self.model = get_model(model) if isinstance(model, str) else model
        if backend not in ("jax", "oracle"):
            raise ValueError(f"unknown backend {backend!r}")
        self.backend = backend
        self.k_slots = k_slots
        self.f_cap = f_cap

    # -- encoding ---------------------------------------------------------
    def encode(self, history: Sequence[Op]) -> EncodedHistory:
        k = self.k_slots
        while True:
            try:
                return encode_register_history(history, k_slots=k)
            except SlotOverflow:
                if k >= 4096:
                    raise
                k *= 2

    # -- checking ---------------------------------------------------------
    def check(self, test: dict, history: Sequence[Op],
              opts: dict | None = None) -> dict[str, Any]:
        enc = self.encode(history)
        if enc.n_events == 0:
            return {"valid": True, "op_count": 0, "backend": self.backend}
        if self.backend == "oracle":
            res = check_events_oracle(enc, self.model).to_dict()
            res["dead_step"] = _event_to_step(enc, res.pop("dead_event"))
            res["backend"] = "oracle"
            res["op_count"] = enc.n_ops
            return res
        return self._check_jax(enc)

    def _check_jax(self, enc: EncodedHistory) -> dict[str, Any]:
        from ..ops import wgl, wgl2, wgl3
        from ..ops.encode import encode_return_steps

        # Preferred path: the dense subset-lattice kernel (wgl3) — viable
        # whenever the whole (state × mask) config space fits a dense table,
        # i.e. for any realistic concurrency. Exact by construction: no
        # frontier capacity, no overflow, no escalation ladder.
        cfg3 = wgl3.dense_config(self.model, wgl3.tight_k_slots(enc),
                                 enc.max_value)
        if cfg3 is not None:
            out = wgl3.check_encoded3(enc, self.model, cfg3)
            return {"valid": out["valid"], "backend": "jax-dense",
                    "op_count": enc.n_ops,
                    "dead_step": int(out["dead_step"]),
                    "max_frontier": int(out["max_frontier"]),
                    "overflow": False,
                    "f_cap": cfg3.n_states * cfg3.n_masks}

        rs = encode_return_steps(enc)
        f_cap = self.f_cap
        for attempt in range(3):
            check = wgl2.cached_checker2(
                self.model, wgl2.config_for(rs, self.model, f_cap))
            out = {k: v.item() if hasattr(v, "item") else v
                   for k, v in check(*wgl2.steps_arrays(rs)).items()}
            valid = wgl.verdict(out)
            if valid != "unknown":
                break
            f_cap *= 4  # overflow killed the frontier; retry bigger
        if valid == "unknown":
            # Exact fallback: the oracle has no capacity limit. Result keys
            # are normalized to the jax schema (dead_step = return-step
            # index) so consumers see one shape whatever the path.
            res = check_events_oracle(enc, self.model).to_dict()
            res["dead_step"] = _event_to_step(enc, res.pop("dead_event"))
            res.update(backend="jax+oracle-fallback", op_count=enc.n_ops,
                       overflow=False, f_cap=None)
            return res
        return {"valid": valid, "backend": "jax", "op_count": enc.n_ops,
                "dead_step": out["dead_step"],
                "max_frontier": out["max_frontier"],
                "overflow": out["overflow"],
                "f_cap": f_cap}
