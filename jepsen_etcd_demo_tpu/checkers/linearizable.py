"""Linearizability checker behind the Checker seam.

Drop-in equivalent of `checker/linearizable {:model (model/cas-register)
:algorithm :linear}` (reference src/jepsen/etcdemo.clj:117), with the search
executed either by the JAX/TPU kernels (the dense subset-lattice kernel,
ops/wgl3.py / ops/wgl3_pallas.py, with the sort-ladder general path in
ops/wgl2.py — the default and the point of this framework) or by the
pure-Python oracle (differential baseline).

On frontier/slot overflow the JAX backend escalates through the exact
ladder (sort-kernel capacity escalation, then the chunked or
lattice-sharded dense sweep) — never a Python-oracle fallback; geometries
that defeat every rung yield the honest tri-state "unknown".
"""

from __future__ import annotations

from typing import Any, Sequence

from .base import Checker
from .oracle import check_events_oracle
from .. import obs
from ..ops.encode import EV_RETURN
from ..models import Model, get_model
from ..ops.op import Op
from ..ops.encode import EncodedHistory, SlotOverflow, encode_history


def _skipped_witness(dead_step: int, *errors: BaseException) -> dict:
    """The explicit never-silent marker (VERDICT r2 weak #3): every
    exhausted witness rung is named in the explanation."""
    chain = "; ".join(f"{type(e).__name__}: {e}" for e in errors)
    return {"valid": False, "witness": "skipped",
            "dead_step": dead_step,
            "explanation": f"witness reconstruction skipped: {chain}",
            "op": f"return step {dead_step}",
            "maximal_linearization": [], "final_configs": []}


def _event_to_step(enc: EncodedHistory, dead_event: int) -> int:
    """Translate an event index (oracle) into a return-step index (v2 kernel
    schema): the count of returns strictly before the fatal one."""
    if dead_event < 0:
        return -1
    ev = enc.events[:dead_event, 0]
    return int((ev == EV_RETURN).sum())


class Linearizable(Checker):
    def __init__(self, model: Model | str = "cas-register",
                 backend: str = "jax", k_slots: int = 24, f_cap: int = 256,
                 time_budget_s: float | None = None):
        self.model = get_model(model) if isinstance(model, str) else model
        if backend not in ("jax", "oracle"):
            raise ValueError(f"unknown backend {backend!r}")
        self.backend = backend
        self.k_slots = k_slots
        self.f_cap = f_cap
        # Wall-clock bound on the sort-ladder search; expiry yields the
        # honest tri-state "unknown" (combinatorial frontiers DNF every
        # WGL implementation, knossos included — ops/wgl2.py).
        self.time_budget_s = time_budget_s

    # -- encoding ---------------------------------------------------------
    def encode(self, history: Sequence[Op]) -> EncodedHistory:
        return self._encode_translated(self.model.prepare_history(history))

    def _encode_translated(self, history: Sequence[Op]) -> EncodedHistory:
        # Encoded-tensor cache (store/encode_cache.py): replays of an
        # unchanged history skip the pair/encode pass entirely. Inactive
        # unless the CLI (analyze/corpus) switched it on; the key covers
        # exactly the encoder's input, so a hit is bit-identical.
        from ..store import encode_cache

        cached = encode_cache.lookup(history, self.model.name, self.k_slots)
        if cached is not None:
            return cached
        k = self.k_slots
        while True:
            try:
                enc = encode_history(history, self.model, k_slots=k)
                break
            except SlotOverflow:
                if k >= 4096:
                    raise
                k *= 2
        encode_cache.store(history, self.model.name, self.k_slots, enc)
        return enc

    # -- checking ---------------------------------------------------------
    def check(self, test: dict, history: Sequence[Op],
              opts: dict | None = None) -> dict[str, Any]:
        with obs.get_tracer().span(
                "check.linearizable", model=self.model.name,
                backend=self.backend,
                key=str((opts or {}).get("key", ""))) as sp:
            res = self._check_traced(test, history, opts, sp)
        return res

    def _stream_result(self, opts: dict | None) -> dict | None:
        """A VALID streamed verdict for this (key, model) from the run's
        streaming check session (stream/engine.py, threaded through
        opts["stream_results"] by runner/core.py). Invalid/absent keys
        fall through to the full path — invalid ones must re-run it for
        counterexample witness reconstruction; the streamed and post-hoc
        verdicts are bit-identical, so re-running never flips one."""
        if self.backend != "jax":
            return None
        sr = (opts or {}).get("stream_results")
        if not sr:
            return None
        pre = sr.get((opts or {}).get("key"))
        if not isinstance(pre, dict) or pre.get("model") != self.model.name:
            return None
        return pre if pre.get("valid") is True else None

    def _check_traced(self, test: dict, history: Sequence[Op],
                      opts: dict | None, sp) -> dict[str, Any]:
        pre = self._stream_result(opts)
        if pre is not None:
            # The stream engine already encoded and swept this history;
            # persist the SAME tensor artifact the post-hoc path would
            # have (corpus replay's coverage contract), then settle.
            enc = pre.get("_enc")
            store_dir = (opts or {}).get("store_dir")
            if store_dir and enc is not None:
                from ..store.store import write_encoded_tensor

                write_encoded_tensor(store_dir, (opts or {}).get("key"),
                                     enc, self.model.name)
            res = {"valid": True, "backend": "jax-dense-streamed",
                   "op_count": int(pre.get("op_count", 0)),
                   "streamed": True}
            for f in ("dead_step", "max_frontier", "configs_explored"):
                if f in pre:
                    res[f] = int(pre[f])
            if "table_cells" in pre:
                res["overflow"] = False
                res["f_cap"] = int(pre["table_cells"])
                res["kernel"] = pre.get("kernel")
            sp.set(valid="True", backend="jax-dense-streamed",
                   op_count=res["op_count"])
            return res
        # Fault-plane ops (nemesis start/stop) are not client operations —
        # drop them like knossos does [dep]. Workloads under the
        # independent wrapper never see them (split_by_key filters), but a
        # bare whole-history checker (multiregister workload) does.
        history = [op for op in history if op.process != "nemesis"]
        # Translate ONCE (e.g. mutex acquire/release -> cas) so the
        # witness replay below sees the same op language the encoder did.
        history = self.model.prepare_history(history)
        enc = self._encode_translated(history)
        store_dir = (opts or {}).get("store_dir")
        if store_dir:
            # Empty encodings included: the artifact records the checker's
            # input for EVERY key, so corpus replay's tensor-coverage
            # check (len(tensors) == key_count) holds.
            from ..store.store import write_encoded_tensor

            write_encoded_tensor(store_dir, (opts or {}).get("key"), enc,
                                 self.model.name)
        if enc.n_events == 0:
            return {"valid": True, "op_count": 0, "backend": self.backend}
        if self.backend == "oracle":
            res = check_events_oracle(enc, self.model).to_dict()
            res["dead_step"] = _event_to_step(enc, res.pop("dead_event"))
            res["backend"] = "oracle"
            res["op_count"] = enc.n_ops
            # The jax branch's kernel paths record their own search
            # metrics at the launch sites (recording here too would
            # double-count wgl.configs_explored); the oracle path has no
            # kernel site, so it records here.
            obs.record_check_result(res)
        else:
            # f_cap_floor: a batched pre-pass (checkers/independent.py)
            # may have proven smaller frontier capacities dead — start the
            # escalation ladder past them.
            res = self._check_jax(
                enc, f_cap_floor=int((opts or {}).get("f_cap_floor", 0)))
        sp.set(valid=str(res.get("valid")),
               backend=res.get("backend", self.backend),
               op_count=res.get("op_count"))
        if res.get("valid") is False:
            with obs.get_tracer().span("check.witness"):
                self._explain(res, enc, history, opts)
        return res

    def _explain(self, res: dict, enc: EncodedHistory,
                 history: Sequence[Op], opts: dict | None) -> None:
        """Counterexample extraction (knossos linear.svg parity): write the
        witness artifacts into the store and name the unexplainable op in
        the result.

        Ladder (VERDICT r2 item 4 — never skip silently):
          1. full replay from the start (complete lineage);
          2. on effort-cap: recover the frontier near the known dead_step
             with the dense kernel and replay only a bounded window;
          3. if even that blows the cap (or the geometry defeats the
             dense kernel): record an explicit "skipped" witness with the
             dead_step context — in the result AND the store, so an
             artifact always exists (knossos always emits its failing-op
             analysis)."""
        from .witness import (WitnessEffortExceeded, reconstruct_witness,
                              reconstruct_witness_from_sort_checkpoint,
                              reconstruct_witness_windowed, write_witness)

        from .witness import WITNESS_WINDOW_STEPS

        dead_step = int(res.get("dead_step", -1))
        # Consume the sort search's death checkpoint (host arrays) so it
        # never reaches results.json, whichever rung produces the witness.
        ckpt = res.pop("death_checkpoint", None)
        try:
            w = reconstruct_witness(enc, self.model, history)
        except WitnessEffortExceeded as e:
            try:
                if dead_step <= WITNESS_WINDOW_STEPS:
                    # The window would start at step 0 — an exact re-run
                    # of the replay that just blew the cap. Go straight
                    # to the skipped marker.
                    raise ValueError(
                        "death inside the first window; windowed replay "
                        "would repeat the capped full replay")
                w = reconstruct_witness_windowed(
                    enc, self.model, dead_step, history)
            except ValueError as e2:
                # Dense recovery infeasible (or pointless): the sort
                # kernel's exact death checkpoint seeds the replay
                # instead (VERDICT r3 item 6 — K>23 invalid histories
                # used to stop at the skipped marker here).
                try:
                    w = reconstruct_witness_from_sort_checkpoint(
                        enc, self.model, history,
                        time_budget_s=self.time_budget_s,
                        checkpoint=ckpt, dead_step=dead_step)
                except (WitnessEffortExceeded, MemoryError) as e3:
                    w = _skipped_witness(dead_step, e, e2, e3)
            except WitnessEffortExceeded as e2:
                # A bigger window would blow the same cap: skip honestly.
                w = _skipped_witness(dead_step, e, e2)
        if w is None:
            return
        if w.get("witness") == "skipped":
            res["witness"] = "skipped"
            res["witness_detail"] = w["explanation"]
        else:
            res["failed_op"] = w["op"]
            res["witness"] = w["explanation"]
        store_dir = (opts or {}).get("store_dir")
        if store_dir:
            res["witness_file"] = write_witness(
                store_dir, (opts or {}).get("key"), w)

    def _check_jax(self, enc: EncodedHistory,
                   f_cap_floor: int = 0) -> dict[str, Any]:
        from ..ops import wgl2, wgl3
        from ..ops.encode import encode_return_steps

        # Preferred path: the dense subset-lattice kernel (wgl3) — viable
        # whenever the whole (state × mask) config space fits a dense table,
        # i.e. for any realistic concurrency. Exact by construction: no
        # frontier capacity, no overflow, no escalation ladder.
        cfg3 = wgl3.dense_config(self.model, wgl3.tight_k_slots(enc),
                                 enc.max_value)
        if cfg3 is not None:
            from ..ops import wgl3_pallas

            # Routed dispatch: fused pallas kernel on a live TPU (whole
            # scan on-chip, one launch, one fetch), XLA kernel elsewhere.
            results, kernel = wgl3_pallas.check_batch_encoded_auto(
                [enc], self.model)
            out = results[0]
            # "host-oracle-routed" = the latency router sent a tiny
            # single history to the exact host oracle (same algorithm;
            # device dispatch alone would cost more than the whole
            # check — ops/limits.py oracle_crossover_events).
            backend = ("jax-dense-pallas" if "pallas" in kernel
                       else "host-oracle-routed"
                       if kernel == "oracle-small-history"
                       else "jax-dense")
            return {"valid": out["valid"], "backend": backend,
                    "op_count": enc.n_ops,
                    "dead_step": int(out["dead_step"]),
                    "max_frontier": int(out["max_frontier"]),
                    "configs_explored": int(out["configs_explored"]),
                    "overflow": False,
                    "f_cap": cfg3.n_states * cfg3.n_masks}

        # General path (huge values / extreme pending counts): the sort
        # kernel run chunk-by-chunk with host-checkpointed frontier carry
        # and capacity escalation, falling back to the chunked dense
        # lattice for frontiers beyond any practical f_cap — exact native
        # verdicts all the way down, no Python-oracle fallback
        # (SURVEY.md §5.4/§5.7).
        from ..ops import wgl3_pallas

        out = wgl3_pallas.check_encoded_general(
            enc, self.model, f_cap=max(self.f_cap, f_cap_floor),
            time_budget_s=self.time_budget_s)
        res = {"valid": out["valid"], "backend": "jax",
               "op_count": out["op_count"],
               "dead_step": out["dead_step"],
               "max_frontier": out["max_frontier"],
               # exhaustion carries overflow=True + error context;
               # every exact rung reports False
               "overflow": out.get("overflow", False),
               "f_cap": out["f_cap"],
               "escalations": out["escalations"]}
        for extra in ("kernel", "error", "death_checkpoint"):
            if extra in out:
                res[extra] = out[extra]
        return res
