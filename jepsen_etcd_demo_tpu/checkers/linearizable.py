"""Linearizability checker behind the Checker seam.

Drop-in equivalent of `checker/linearizable {:model (model/cas-register)
:algorithm :linear}` (reference src/jepsen/etcdemo.clj:117), with the search
executed either by the JAX/TPU kernel (ops/wgl.py — the default and the point
of this framework) or by the pure-Python oracle (differential baseline).

On frontier/slot overflow the JAX backend escalates capacity once and, if the
verdict is still indeterminate, falls back to the oracle so the final answer
is exact.
"""

from __future__ import annotations

from typing import Any, Sequence

from .base import Checker
from .oracle import check_events_oracle
from ..models import Model, get_model
from ..ops.op import Op
from ..ops.encode import (EncodedHistory, SlotOverflow,
                          encode_register_history)


class Linearizable(Checker):
    def __init__(self, model: Model | str = "cas-register",
                 backend: str = "jax", k_slots: int = 32, f_cap: int = 256):
        self.model = get_model(model) if isinstance(model, str) else model
        if backend not in ("jax", "oracle"):
            raise ValueError(f"unknown backend {backend!r}")
        self.backend = backend
        self.k_slots = k_slots
        self.f_cap = f_cap

    # -- encoding ---------------------------------------------------------
    def encode(self, history: Sequence[Op]) -> EncodedHistory:
        k = self.k_slots
        while True:
            try:
                return encode_register_history(history, k_slots=k)
            except SlotOverflow:
                if k >= 4096:
                    raise
                k *= 2

    # -- checking ---------------------------------------------------------
    def check(self, test: dict, history: Sequence[Op],
              opts: dict | None = None) -> dict[str, Any]:
        enc = self.encode(history)
        if enc.n_events == 0:
            return {"valid": True, "op_count": 0, "backend": self.backend}
        if self.backend == "oracle":
            res = check_events_oracle(enc, self.model).to_dict()
            res["backend"] = "oracle"
            res["op_count"] = enc.n_ops
            return res
        return self._check_jax(enc)

    def _check_jax(self, enc: EncodedHistory) -> dict[str, Any]:
        from ..ops import wgl

        f_cap = self.f_cap
        for attempt in range(2):
            check = wgl.cached_checker(self.model,
                                       wgl.WGLConfig(enc.k_slots, f_cap))
            import jax.numpy as jnp
            out = {k: v.item() if hasattr(v, "item") else v
                   for k, v in check(jnp.asarray(enc.events)).items()}
            valid = wgl.verdict(out)
            if valid != "unknown":
                break
            f_cap *= 4  # overflow killed the frontier; retry bigger
        if valid == "unknown":
            # Exact fallback: the oracle has no capacity limit.
            res = check_events_oracle(enc, self.model).to_dict()
            res.update(backend="jax+oracle-fallback", op_count=enc.n_ops)
            return res
        return {"valid": valid, "backend": "jax", "op_count": enc.n_ops,
                "dead_event": out["dead_event"],
                "max_frontier": out["max_frontier"],
                "overflow": out["overflow"],
                "f_cap": f_cap}
