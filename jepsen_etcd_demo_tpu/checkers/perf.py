"""Performance checker — equivalent of checker/perf.

The reference turns the timestamped history into latency-over-time (raw and
quantile) and throughput charts via gnuplot, written into the run's store dir
(reference call site src/jepsen/etcdemo.clj:166; SURVEY.md §5.1). Same three
artifacts here via matplotlib: latency-raw.png, latency-quantiles.png,
rate.png — plus the summary stats in the result map (always "valid": perf is
observability, not an assertion).
"""

from __future__ import annotations

import logging
from collections import defaultdict
from pathlib import Path
from typing import Any, Optional, Sequence

import numpy as np

from ..ops.op import Op, INVOKE, OK, FAIL, INFO
from .base import Checker

log = logging.getLogger(__name__)

SECOND = 1_000_000_000
QUANTILES = [0.5, 0.95, 0.99, 1.0]


def nemesis_windows(history: Sequence[Op]):
    """[(start_s, stop_s)] intervals where the nemesis was active — jepsen
    shades these on its perf charts so latency spikes line up with faults.
    An un-stopped start extends to the history end."""
    out = []
    t_start = None
    t_max = 0.0
    for op in history:
        t = op.time / SECOND
        t_max = max(t_max, t)
        if op.process != "nemesis" or op.type == INVOKE:
            continue
        if op.f == "start" and t_start is None:
            t_start = t
        elif op.f == "stop" and t_start is not None:
            out.append((t_start, t))
            t_start = None
    if t_start is not None:
        out.append((t_start, t_max))
    return out


def latency_pairs(history: Sequence[Op]):
    """(f, completion-type, invoke-time-ns, latency-ns) per completed client
    op; nemesis excluded."""
    pending: dict[Any, Op] = {}
    out = []
    for op in history:
        if op.process == "nemesis":
            continue
        if op.type == INVOKE:
            pending[op.process] = op
        elif op.type in (OK, FAIL, INFO):
            inv = pending.pop(op.process, None)
            if inv is not None:
                out.append((op.f, op.type, inv.time, op.time - inv.time))
    return out


class PerfChecker(Checker):
    def __init__(self, dt_s: float = 1.0):
        self.dt_s = dt_s  # rate-chart bucket width

    def check(self, test: dict, history: Sequence[Op],
              opts: dict | None = None) -> dict[str, Any]:
        pairs = latency_pairs(history)
        result: dict[str, Any] = {"valid": True, "count": len(pairs)}
        if pairs:
            lat_s = np.array([p[3] for p in pairs]) / SECOND
            result["latency"] = {
                "mean": float(lat_s.mean()),
                **{f"p{int(q * 100)}": float(np.quantile(lat_s, q))
                   for q in QUANTILES},
            }
            span = max(p[2] for p in pairs) / SECOND
            result["rate_hz"] = len(pairs) / max(span, 1e-9)
        store_dir = (opts or {}).get("store_dir")
        if store_dir and pairs:
            try:
                self._render(Path(store_dir), pairs,
                             nemesis_windows(history))
                result["charts"] = ["latency-raw.png",
                                    "latency-quantiles.png", "rate.png"]
            except Exception as e:  # charts are best-effort observability
                log.warning("perf chart rendering failed: %s", e)
        return result

    def _render(self, store_dir: Path, pairs, windows=()) -> None:
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        colors = {OK: "#2a9d43", FAIL: "#d43a2a", INFO: "#e9a820"}
        markers = {"read": "o", "write": "s", "cas": "^", "add": "s"}

        def shade(ax):
            # Grey bands where the nemesis was active (jepsen chart parity).
            for lo, hi in windows:
                ax.axvspan(lo, hi, color="#cccccc", alpha=0.4, zorder=0)

        # latency-raw: scatter of every op, by type/outcome.
        fig, ax = plt.subplots(figsize=(10, 5))
        by = defaultdict(list)
        for f, typ, t_inv, lat in pairs:
            by[(f, typ)].append((t_inv / SECOND, lat / SECOND))
        for (f, typ), pts in sorted(by.items()):
            xs, ys = zip(*pts)
            ax.scatter(xs, ys, s=12, alpha=0.7, color=colors.get(typ, "gray"),
                       marker=markers.get(f, "x"), label=f"{f} {typ}")
        ax.set_yscale("log")
        ax.set_xlabel("time (s)")
        ax.set_ylabel("latency (s)")
        ax.legend(fontsize=7, ncol=3)
        shade(ax)
        ax.set_title("latency raw")
        fig.savefig(store_dir / "latency-raw.png", dpi=100,
                    bbox_inches="tight")
        plt.close(fig)

        # latency-quantiles over time windows.
        fig, ax = plt.subplots(figsize=(10, 5))
        t = np.array([p[2] for p in pairs]) / SECOND
        lat = np.array([p[3] for p in pairs]) / SECOND
        edges = np.arange(0, t.max() + self.dt_s, self.dt_s)
        for q in QUANTILES:
            xs, ys = [], []
            for lo, hi in zip(edges[:-1], edges[1:]):
                m = (t >= lo) & (t < hi)
                if m.any():
                    xs.append((lo + hi) / 2)
                    ys.append(np.quantile(lat[m], q))
            if xs:
                ax.plot(xs, ys, marker=".", label=f"p{int(q * 100)}")
        ax.set_yscale("log")
        ax.set_xlabel("time (s)")
        ax.set_ylabel("latency (s)")
        ax.legend(fontsize=8)
        shade(ax)
        ax.set_title("latency quantiles")
        fig.savefig(store_dir / "latency-quantiles.png", dpi=100,
                    bbox_inches="tight")
        plt.close(fig)

        # rate: ops/sec per outcome over time.
        fig, ax = plt.subplots(figsize=(10, 4))
        for typ in (OK, FAIL, INFO):
            ts = np.array([p[2] for p in pairs if p[1] == typ]) / SECOND
            if len(ts):
                hist, e = np.histogram(ts, bins=edges)
                ax.plot((e[:-1] + e[1:]) / 2, hist / self.dt_s,
                        color=colors[typ], label=typ)
        ax.set_xlabel("time (s)")
        ax.set_ylabel("ops/s")
        ax.legend(fontsize=8)
        shade(ax)
        ax.set_title("throughput")
        fig.savefig(store_dir / "rate.png", dpi=100, bbox_inches="tight")
        plt.close(fig)
