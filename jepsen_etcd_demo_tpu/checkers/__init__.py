"""Checker protocol + concrete checkers.

Equivalent of jepsen.checker as exercised by the reference: compose
(src/jepsen/etcdemo.clj:115-119,165-167), linearizable (:117), set
(src/jepsen/etcdemo/set.clj:46), perf (:166), timeline (:119), independent
(:115). A checker is a pure function of the recorded history.
"""

from .base import Checker, CheckerError  # noqa: F401
from .compose import Compose  # noqa: F401
from .linearizable import Linearizable  # noqa: F401
from .set_checker import SetChecker  # noqa: F401
from .independent import IndependentChecker  # noqa: F401
from .oracle import check_events_oracle, brute_force_check  # noqa: F401
from .elle import ElleChecker, ElleRwChecker  # noqa: F401
from .perf import PerfChecker  # noqa: F401
from .timeline import TimelineChecker  # noqa: F401
