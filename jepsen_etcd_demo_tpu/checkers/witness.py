"""Counterexample extraction: explain WHY a history is not linearizable.

Parity with knossos, which returns the unexplainable op and renders a
`linear.svg` into the store dir when the linearizable checker fails
(reference call site src/jepsen/etcdemo.clj:117 [dep]; SURVEY.md hard-part
#3). The TPU kernels report only the fatal return step (masked tensors keep
no lineage); this module reconstructs a human-readable witness HOST-SIDE by
replaying the oracle search WITH parent tracking up to the death point:

  * the failed operation (the return no reachable config had linearized),
  * one maximal linearization of the prefix (the firing order of a config
    that survived longest — concrete evidence the prefix IS linearizable),
  * the final reachable configurations (state + still-pending ops).

Artifacts: `linear.json` (machine-readable) and `linear.svg` (rendering),
`linear-<key>.{json,svg}` under the independent wrapper — matching the
timeline checker's per-key naming.
"""

from __future__ import annotations

import html
import json
from pathlib import Path
from typing import Any, Optional, Sequence

import numpy as np

from ..models.base import Model
from ..ops.encode import (EncodedHistory, EV_INVOKE, EV_RETURN, F_READ,
                          F_WRITE, F_CAS, NIL, Invocation, event_sources,
                          pair_history)
from ..ops.op import Op

# Reconstruction is exponential-ish like the search itself; witnesses are
# for humans, so cap the effort rather than DNF on adversarial histories.
MAX_WITNESS_EVENTS = 200_000


class WitnessEffortExceeded(Exception):
    """The host replay hit its effort cap before reaching the death point.

    Carries enough context for the caller to fall back to the WINDOWED
    reconstruction (dense-kernel frontier recovery + bounded replay,
    reconstruct_witness_windowed) or, failing that, to record an explicit
    "skipped" marker — a silent None here cost round 2 its witness
    artifacts on exactly the histories that most needed them (VERDICT r2
    weak #3)."""

    def __init__(self, event_index: int, effort: int):
        super().__init__(
            f"witness replay exceeded {effort} model steps at event "
            f"{event_index}")
        self.event_index = event_index
        self.effort = effort


def describe_op(f: int, a1: int, a2: int, rv: int) -> str:
    if f == F_READ:
        return f"read -> {'nil' if rv == NIL else rv}"
    if f == F_WRITE:
        return f"write({a1})"
    if f == F_CAS:
        return f"cas({a1} -> {a2})"
    return f"op({f}, {a1}, {a2}, {rv})"


def _inv_info(inv: Optional[Invocation]) -> dict[str, Any]:
    if inv is None:
        return {}
    return {"process": inv.process, "invoke_index": inv.invoke_index,
            "complete_index": inv.complete_index}


def _sources_fn(history: Sequence[Op] | None, model):
    sources: list[Optional[Invocation]] = []
    if history is not None:
        sources = list(event_sources(pair_history(history, model)))

    def src(i: int) -> Optional[Invocation]:
        return sources[i] if i < len(sources) else None

    return src


def slots_at_event(enc: EncodedHistory, e0: int):
    """Pending-slot state just before event e0: slot -> (f, a1, a2, rv)
    plus slot -> invoke event index. Linear walk — the cheap half of
    windowed reconstruction."""
    events = np.asarray(enc.events)
    slots: dict[int, tuple[int, int, int, int]] = {}
    slot_event: dict[int, int] = {}
    for i in range(min(e0, enc.n_events)):
        kind, slot, f, a1, a2, rv = (int(x) for x in events[i])
        if kind == EV_INVOKE:
            slots[slot] = (f, a1, a2, rv)
            slot_event[slot] = i
        elif kind == EV_RETURN:
            slots.pop(slot, None)
            slot_event.pop(slot, None)
    return slots, slot_event


def _replay(enc: EncodedHistory, model: Model, start_event: int,
            frontier: dict, slots: dict, slot_event: dict, src,
            effort_cap: int) -> Optional[dict[str, Any]]:
    """The lineage-tracking WGL replay from an arbitrary starting point.
    Returns the witness dict at the death point, None when the replayed
    range is linearizable; raises WitnessEffortExceeded past the cap."""
    events = np.asarray(enc.events)
    effort = 0

    for i in range(start_event, enc.n_events):
        kind, slot, f, a1, a2, rv = (int(x) for x in events[i])
        if kind == EV_INVOKE:
            slots[slot] = (f, a1, a2, rv)
            slot_event[slot] = i
        elif kind == EV_RETURN:
            tbit = 1 << slot
            seen = dict(frontier)
            stack = [c for c in frontier if not c[1] & tbit]
            while stack:
                state, mask = stack.pop()
                lin = seen[(state, mask)]
                for s, (sf, sa1, sa2, srv) in slots.items():
                    if mask >> s & 1:
                        continue
                    legal, nxt = model.step_py(state, sf, sa1, sa2, srv)
                    effort += 1
                    if legal:
                        cfg = (int(nxt), mask | (1 << s))
                        if cfg not in seen:
                            seen[cfg] = lin + ((slot_event.get(s, -1),
                                                int(nxt)),)
                            if not cfg[1] & tbit:
                                stack.append(cfg)
                if effort > effort_cap:
                    raise WitnessEffortExceeded(i, effort)
            survivors = {(s, m & ~tbit): lin
                         for (s, m), lin in seen.items() if m & tbit}
            if not survivors:
                return _build_witness(enc, model, i, slot, slots,
                                      slot_event, seen, src)
            frontier = survivors
            del slots[slot]
            del slot_event[slot]
    return None


def reconstruct_witness(enc: EncodedHistory, model: Model,
                        history: Sequence[Op] | None = None,
                        effort_cap: int | None = None
                        ) -> Optional[dict[str, Any]]:
    """Replay the WGL search with lineage from the start; returns the
    witness dict for an invalid history, None when the history is actually
    linearizable. Raises WitnessEffortExceeded past the effort cap —
    callers fall back to reconstruct_witness_windowed."""
    if effort_cap is None:
        effort_cap = MAX_WITNESS_EVENTS   # read at call time: tests and
        #                                   embedders may tune the module cap
    src = _sources_fn(history, model)
    frontier: dict[tuple[int, int], tuple] = {
        (int(model.init_state()), 0): ()}
    return _replay(enc, model, 0, frontier, {}, {}, src, effort_cap)


# Return steps replayed host-side after the dense-kernel frontier
# recovery. Enough to show the failing op in context; small enough that
# the replay is ~instant even on frontier-heavy histories.
WITNESS_WINDOW_STEPS = 64


def reconstruct_witness_windowed(enc: EncodedHistory, model: Model,
                                 dead_step: int,
                                 history: Sequence[Op] | None = None,
                                 window: int = WITNESS_WINDOW_STEPS,
                                 effort_cap: int | None = None
                                 ) -> Optional[dict[str, Any]]:
    """Big-history witness extraction (VERDICT r2 item 4): the dense
    kernel is exact and cheap, so recover the reachable-config frontier at
    `window` return steps before the known death point and replay ONLY
    that window host-side with lineage. The witness's maximal
    linearization then covers the window (the prefix before it is
    machine-verified linearizable by the kernel — recorded in the
    artifact as window_start_step).

    Requires a dense-sweepable geometry — under the RELAXED chunked cell
    budget, not the default routing budget, since recovery runs a single
    bounded sweep (wide histories are exactly the ones that need this
    path). Raises ValueError when even that is infeasible and
    WitnessEffortExceeded if the window replay blows the cap."""
    from ..ops import wgl3
    from ..ops.encode import encode_return_steps, reslot_events
    from ..ops.limits import limits

    if effort_cap is None:
        effort_cap = MAX_WITNESS_EVENTS
    k = wgl3.tight_k_slots(enc)
    cfg = wgl3.dense_config(model, k, enc.max_value,
                            budget=limits().dense_cell_budget_chunked)
    if cfg is None:
        raise ValueError(
            f"dense frontier recovery infeasible: max_pending="
            f"{enc.max_pending}, max_value={enc.max_value}")
    enc_r = reslot_events(enc, k) if enc.k_slots != k else enc
    rs = encode_return_steps(enc_r)
    s0 = max(0, min(dead_step, rs.n_steps - 1) - window)
    configs = wgl3.recover_table3(rs, model, cfg, s0)
    # Event index just after the s0-th return.
    events = np.asarray(enc_r.events[: enc_r.n_events])
    ret_pos = np.nonzero(events[:, 0] == EV_RETURN)[0]
    e0 = 0 if s0 == 0 else int(ret_pos[s0 - 1]) + 1
    slots, slot_event = slots_at_event(enc_r, e0)
    frontier = {(int(s), int(m)): () for s, m in configs}
    src = _sources_fn(history, model)
    w = _replay(enc_r, model, e0, frontier, slots, slot_event, src,
                effort_cap)
    if w is not None:
        w["window_start_step"] = s0
        w["window_start_event"] = e0
        w["note"] = (
            f"maximal_linearization covers the final window only "
            f"(from return step {s0}); the prefix before it is "
            f"machine-verified linearizable by the dense kernel")
    return w


def reconstruct_witness_from_sort_checkpoint(
        enc: EncodedHistory, model: Model,
        history: Sequence[Op] | None = None,
        effort_cap: int | None = None,
        time_budget_s: float | None = None,
        checkpoint: tuple | None = None,
        dead_step: int = -1) -> Optional[dict[str, Any]]:
    """Wide-geometry witness rung (VERDICT r3 item 6): when the dense
    frontier recovery is infeasible (pending sets past the chunked cell
    budget, ~K>23), seed the lineage replay from the resumable SORT
    search's exact frontier checkpoint at the boundary of the chunk the
    search died in — a bounded window of at most one chunk
    (wgl2.DEFAULT_CHUNK return steps) instead of the whole history.

    `checkpoint` is the (states, masks, valid, step) tuple the primary
    search already recorded (check_steps_resumable keep_death_checkpoint
    — the normal path: no second search). Without one, the search is
    RE-RUN here with the worker-profile capacity sizing of the routing
    ladder; `dead_step` lets the futile case (death inside the first
    chunk, checkpoint would be step 0) fail fast before that search.

    Returns None when a re-run finds the history linearizable (caller
    misdiagnosed); raises WitnessEffortExceeded / MemoryError when the
    window replay or the search is defeated — the caller's
    skipped-marker rung catches those."""
    from ..ops import wgl2
    from ..ops.encode import encode_return_steps, reslot_events
    from ..ops.limits import limits

    if effort_cap is None:
        effort_cap = MAX_WITNESS_EVENTS
    tight = wgl2.sort_k_slots(enc)
    enc_r = reslot_events(enc, tight) if enc.k_slots != tight else enc
    if checkpoint is None:
        if 0 <= dead_step < wgl2.DEFAULT_CHUNK:
            # The checkpoint would be the empty prefix: the seeded replay
            # would just repeat the full replay that already blew its cap.
            raise WitnessEffortExceeded(0, 0)
        # Same f_cap_max sizing as the routing ladder
        # (check_encoded_general): the axon worker faults allocating past
        # sort_row_budget rows, and a witness re-run must not crash where
        # the primary check survived.
        from ..ops.wgl3_pallas import pallas_available

        if pallas_available():
            f_cap_max = max(4096, min(1 << 20,
                                      limits().sort_row_budget
                                      // (tight + 1)))
        else:
            f_cap_max = 1 << 20
        out = wgl2.check_steps_resumable(
            encode_return_steps(enc_r), model, f_cap_max=f_cap_max,
            keep_death_checkpoint=True, time_budget_s=time_budget_s)
        if out["valid"]:
            return None
        checkpoint = out["death_checkpoint"]
    states, masks, valid, s0 = checkpoint
    if s0 == 0:
        # Checkpoint at the very start: the seeded replay would repeat
        # the full replay that already blew its cap.
        raise WitnessEffortExceeded(0, 0)
    configs = wgl2.checkpoint_configs(states, masks, valid)
    events = np.asarray(enc_r.events[: enc_r.n_events])
    ret_pos = np.nonzero(events[:, 0] == EV_RETURN)[0]
    e0 = int(ret_pos[s0 - 1]) + 1
    slots, slot_event = slots_at_event(enc_r, e0)
    frontier = {(int(s), int(m)): () for s, m in configs}
    src = _sources_fn(history, model)
    w = _replay(enc_r, model, e0, frontier, slots, slot_event, src,
                effort_cap)
    if w is not None:
        w["window_start_step"] = s0
        w["window_start_event"] = e0
        w["note"] = (
            f"maximal_linearization covers the final window only (from "
            f"return step {s0}, the sort kernel's exact checkpoint "
            f"nearest the death); the prefix before it is "
            f"machine-verified linearizable by the sort kernel")
    return w


def _build_witness(enc, model, event_index, slot, slots, slot_event,
                   seen, src):
    f, a1, a2, rv = slots[slot]
    desc = model.describe_op
    # The best explanation: a reachable config that linearized the MOST ops
    # (its lineage is a concrete maximal linearization of the prefix).
    best_cfg = max(seen, key=lambda c: bin(c[1]).count("1"))
    prefix = [{
        "event_index": ev_i,
        "op": desc(*_op_at(enc, ev_i)),
        "state_after": state,
        **_inv_info(src(ev_i)),
    } for ev_i, state in seen[best_cfg]]
    final_configs = sorted(
        {(s, _pending_desc(m, slots, model)) for s, m in seen},
        key=str)[:16]
    ret = int((np.asarray(enc.events[:event_index, 0]) == EV_RETURN).sum())
    return {
        "valid": False,
        "op": desc(f, a1, a2, rv),
        **_inv_info(src(slot_event[slot])),
        "event_index": event_index,
        "dead_step": ret,
        "maximal_linearization": prefix,
        "final_state": best_cfg[0],
        "final_configs": [
            {"state": s, "pending_unfired": list(p)}
            for s, p in final_configs],
        "explanation": (
            f"no reachable configuration could linearize "
            f"{desc(f, a1, a2, rv)} by the time it returned"),
    }


def _op_at(enc, event_index: int) -> tuple[int, int, int, int]:
    _, _, f, a1, a2, rv = (int(x) for x in enc.events[event_index])
    return f, a1, a2, rv


def _pending_desc(mask: int, slots, model) -> tuple:
    return tuple(model.describe_op(*op) for s, op in sorted(slots.items())
                 if not mask >> s & 1)


SVG_STYLE = ("font-family:sans-serif;font-size:12px")


def render_witness_svg(w: dict[str, Any]) -> str:
    """Minimal knossos-linear.svg-style rendering: the maximal linearization
    as a chain of state transitions, then the stuck op in red."""
    rows = []
    y = 28
    rows.append(f'<text x="10" y="{y}" font-weight="bold">'
                f'not linearizable: {html.escape(w["op"])}</text>')
    y += 22
    rows.append(f'<text x="10" y="{y}" fill="#555">'
                f'{html.escape(w["explanation"])}</text>')
    y += 28
    x = 10
    for stepd in w["maximal_linearization"]:
        label = f'{stepd["op"]} ⇒ {stepd["state_after"]}'
        wpx = 9 * len(label) + 16
        rows.append(
            f'<rect x="{x}" y="{y - 16}" width="{wpx}" height="22" rx="4" '
            f'fill="#e8f5e9" stroke="#66bb6a"/>'
            f'<text x="{x + 8}" y="{y}">{html.escape(label)}</text>')
        x += wpx + 10
        if x > 760:
            x = 10
            y += 30
    wpx = 9 * len(w["op"]) + 16
    rows.append(
        f'<rect x="{x}" y="{y - 16}" width="{wpx}" height="22" rx="4" '
        f'fill="#ffebee" stroke="#e53935"/>'
        f'<text x="{x + 8}" y="{y}" fill="#b71c1c">'
        f'{html.escape(w["op"])}</text>')
    height = y + 30
    return (f'<svg xmlns="http://www.w3.org/2000/svg" width="980" '
            f'height="{height}" style="{SVG_STYLE}">'
            f'<rect width="100%" height="100%" fill="white"/>'
            + "".join(rows) + "</svg>")


def write_witness(store_dir: str, key: Any, w: dict[str, Any]) -> str:
    """Persist a reconstructed witness as linear.json + linear.svg
    (per-key suffix under the independent wrapper); returns the json name."""
    suffix = f"-{key}" if key is not None else ""
    jname = f"linear{suffix}.json"
    Path(store_dir, jname).write_text(json.dumps(w, indent=2, default=str))
    Path(store_dir, f"linear{suffix}.svg").write_text(render_witness_svg(w))
    return jname
