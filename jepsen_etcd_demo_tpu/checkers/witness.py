"""Counterexample extraction: explain WHY a history is not linearizable.

Parity with knossos, which returns the unexplainable op and renders a
`linear.svg` into the store dir when the linearizable checker fails
(reference call site src/jepsen/etcdemo.clj:117 [dep]; SURVEY.md hard-part
#3). The TPU kernels report only the fatal return step (masked tensors keep
no lineage); this module reconstructs a human-readable witness HOST-SIDE by
replaying the oracle search WITH parent tracking up to the death point:

  * the failed operation (the return no reachable config had linearized),
  * one maximal linearization of the prefix (the firing order of a config
    that survived longest — concrete evidence the prefix IS linearizable),
  * the final reachable configurations (state + still-pending ops).

Artifacts: `linear.json` (machine-readable) and `linear.svg` (rendering),
`linear-<key>.{json,svg}` under the independent wrapper — matching the
timeline checker's per-key naming.
"""

from __future__ import annotations

import html
import json
from pathlib import Path
from typing import Any, Optional, Sequence

import numpy as np

from ..models.base import Model
from ..ops.encode import (EncodedHistory, EV_INVOKE, EV_RETURN, F_READ,
                          F_WRITE, F_CAS, NIL, Invocation, event_sources,
                          pair_history)
from ..ops.op import Op

# Reconstruction is exponential-ish like the search itself; witnesses are
# for humans, so cap the effort rather than DNF on adversarial histories.
MAX_WITNESS_EVENTS = 200_000


def describe_op(f: int, a1: int, a2: int, rv: int) -> str:
    if f == F_READ:
        return f"read -> {'nil' if rv == NIL else rv}"
    if f == F_WRITE:
        return f"write({a1})"
    if f == F_CAS:
        return f"cas({a1} -> {a2})"
    return f"op({f}, {a1}, {a2}, {rv})"


def _inv_info(inv: Optional[Invocation]) -> dict[str, Any]:
    if inv is None:
        return {}
    return {"process": inv.process, "invoke_index": inv.invoke_index,
            "complete_index": inv.complete_index}


def reconstruct_witness(enc: EncodedHistory, model: Model,
                        history: Sequence[Op] | None = None
                        ) -> Optional[dict[str, Any]]:
    """Replay the WGL search with lineage; returns the witness dict for an
    invalid history, None when the history is actually linearizable (or the
    effort cap was hit)."""
    events = np.asarray(enc.events)
    sources: list[Optional[Invocation]] = []
    if history is not None:
        sources = list(event_sources(pair_history(history, model)))

    def src(i: int) -> Optional[Invocation]:
        return sources[i] if i < len(sources) else None

    slots: dict[int, tuple[int, int, int, int]] = {}
    slot_event: dict[int, int] = {}           # slot -> invoke event index
    # lineage: config -> tuple of fired (event_index, state_after)
    frontier: dict[tuple[int, int], tuple] = {
        (int(model.init_state()), 0): ()}
    effort = 0

    for i in range(enc.n_events):
        kind, slot, f, a1, a2, rv = (int(x) for x in events[i])
        if kind == EV_INVOKE:
            slots[slot] = (f, a1, a2, rv)
            slot_event[slot] = i
        elif kind == EV_RETURN:
            tbit = 1 << slot
            seen = dict(frontier)
            stack = [c for c in frontier if not c[1] & tbit]
            while stack:
                state, mask = stack.pop()
                lin = seen[(state, mask)]
                for s, (sf, sa1, sa2, srv) in slots.items():
                    if mask >> s & 1:
                        continue
                    legal, nxt = model.step_py(state, sf, sa1, sa2, srv)
                    effort += 1
                    if legal:
                        cfg = (int(nxt), mask | (1 << s))
                        if cfg not in seen:
                            seen[cfg] = lin + ((slot_event[s], int(nxt)),)
                            if not cfg[1] & tbit:
                                stack.append(cfg)
                if effort > MAX_WITNESS_EVENTS:
                    return None
            survivors = {(s, m & ~tbit): lin
                         for (s, m), lin in seen.items() if m & tbit}
            if not survivors:
                return _build_witness(enc, model, i, slot, slots,
                                      slot_event, seen, src)
            frontier = survivors
            del slots[slot]
            del slot_event[slot]
    return None


def _build_witness(enc, model, event_index, slot, slots, slot_event,
                   seen, src):
    f, a1, a2, rv = slots[slot]
    desc = model.describe_op
    # The best explanation: a reachable config that linearized the MOST ops
    # (its lineage is a concrete maximal linearization of the prefix).
    best_cfg = max(seen, key=lambda c: bin(c[1]).count("1"))
    prefix = [{
        "event_index": ev_i,
        "op": desc(*_op_at(enc, ev_i)),
        "state_after": state,
        **_inv_info(src(ev_i)),
    } for ev_i, state in seen[best_cfg]]
    final_configs = sorted(
        {(s, _pending_desc(m, slots, model)) for s, m in seen},
        key=str)[:16]
    ret = int((np.asarray(enc.events[:event_index, 0]) == EV_RETURN).sum())
    return {
        "valid": False,
        "op": desc(f, a1, a2, rv),
        **_inv_info(src(slot_event[slot])),
        "event_index": event_index,
        "dead_step": ret,
        "maximal_linearization": prefix,
        "final_state": best_cfg[0],
        "final_configs": [
            {"state": s, "pending_unfired": list(p)}
            for s, p in final_configs],
        "explanation": (
            f"no reachable configuration could linearize "
            f"{desc(f, a1, a2, rv)} by the time it returned"),
    }


def _op_at(enc, event_index: int) -> tuple[int, int, int, int]:
    _, _, f, a1, a2, rv = (int(x) for x in enc.events[event_index])
    return f, a1, a2, rv


def _pending_desc(mask: int, slots, model) -> tuple:
    return tuple(model.describe_op(*op) for s, op in sorted(slots.items())
                 if not mask >> s & 1)


SVG_STYLE = ("font-family:sans-serif;font-size:12px")


def render_witness_svg(w: dict[str, Any]) -> str:
    """Minimal knossos-linear.svg-style rendering: the maximal linearization
    as a chain of state transitions, then the stuck op in red."""
    rows = []
    y = 28
    rows.append(f'<text x="10" y="{y}" font-weight="bold">'
                f'not linearizable: {html.escape(w["op"])}</text>')
    y += 22
    rows.append(f'<text x="10" y="{y}" fill="#555">'
                f'{html.escape(w["explanation"])}</text>')
    y += 28
    x = 10
    for stepd in w["maximal_linearization"]:
        label = f'{stepd["op"]} ⇒ {stepd["state_after"]}'
        wpx = 9 * len(label) + 16
        rows.append(
            f'<rect x="{x}" y="{y - 16}" width="{wpx}" height="22" rx="4" '
            f'fill="#e8f5e9" stroke="#66bb6a"/>'
            f'<text x="{x + 8}" y="{y}">{html.escape(label)}</text>')
        x += wpx + 10
        if x > 760:
            x = 10
            y += 30
    wpx = 9 * len(w["op"]) + 16
    rows.append(
        f'<rect x="{x}" y="{y - 16}" width="{wpx}" height="22" rx="4" '
        f'fill="#ffebee" stroke="#e53935"/>'
        f'<text x="{x + 8}" y="{y}" fill="#b71c1c">'
        f'{html.escape(w["op"])}</text>')
    height = y + 30
    return (f'<svg xmlns="http://www.w3.org/2000/svg" width="980" '
            f'height="{height}" style="{SVG_STYLE}">'
            f'<rect width="100%" height="100%" fill="white"/>'
            + "".join(rows) + "</svg>")


def write_witness(store_dir: str, key: Any, w: dict[str, Any]) -> str:
    """Persist a reconstructed witness as linear.json + linear.svg
    (per-key suffix under the independent wrapper); returns the json name."""
    suffix = f"-{key}" if key is not None else ""
    jname = f"linear{suffix}.json"
    Path(store_dir, jname).write_text(json.dumps(w, indent=2, default=str))
    Path(store_dir, f"linear{suffix}.svg").write_text(render_witness_svg(w))
    return jname
