"""Elle-equivalent transactional anomaly checker (BOTH inference
families: list-append here in ElleChecker, rw-register in ElleRwChecker).

The reference's dependency tree ships elle 0.1.2 (jepsen.etcdemo.iml:46,
reached transitively through jepsen.checker — SURVEY.md §2.2 lists it as a
dependency component; round-1 scope deferred it). This is the TPU-first
re-design of that capability for the canonical list-append workload:

  txn ops: Op(f="txn", value=[micro-op, ...]) with micro-ops
      ("append", k, v)  — append v to the list under key k
      ("r", k, vs)      — read the list under k (vs: None on invoke,
                           tuple/list of appended values on :ok)

Inference (elle's core trick): appends to a key are OBSERVABLE as list
prefixes, so any read totally orders every append it observed —
  * two reads of one key must be prefix-compatible   (else :incompatible-order)
  * consecutive observed values e_i, e_i+1 give a ww edge
    writer(e_i) -> writer(e_i+1)
  * a read ending at e gives a wr edge writer(e) -> reader
  * a read observing list L gives an rw (anti-dependency) edge
    reader -> writer(v) for EVERY committed append of a v absent from L
    (reads return the whole list, so an append serialized before the read
    must appear in it — this covers acked appends no read ever observed)

Anomalies (elle's taxonomy):
  * internal               — a txn's own read contradicts its own earlier
                             appends in the same txn (the observed list
                             must end with the txn's appends-so-far)
  * G1a aborted read       — read observes a value appended by a :fail txn
  * G1b intermediate read  — read observes a txn's non-final state of a key
  * incompatible-order     — reads of one key disagree beyond prefixing
  * duplicates             — a read observes the same value twice
  * lost-append            — a txn's appends to a key are atomic, so they
                             occupy a CONTIGUOUS run of the true list;
                             a read observing one of them with the txn's
                             neighbouring append absent from the adjacent
                             position proves an acked append went missing
                             (elle finds these through its internal/ww
                             machinery; here it is a direct check)
  * G0 write cycle         — cycle in ww
  * G1c circular info      — cycle in ww|wr (with >= 1 wr)
  * G-single               — cycle in ww|wr|rw with exactly one rw
  * G2-item                — cycle with >= 2 rw edges
  * …-realtime variants    — with ElleChecker(realtime=True), wall-clock
                             order joins the edge set (A completed before B
                             invoked => A precedes B): cycles that need a
                             realtime edge are the strict-serializability
                             anomalies elle reports as G0/G1c/G-single/
                             G2-item-realtime

Cycle search runs on the routed transitive-closure engine
(ops/cycles.py): cycle-presence probes fetch only the diagonal, the
classification ladder's same-size tier graphs close in ONE vmapped
batched launch, and big sparse graphs decompose into weak components
checked batched/tiled (ops/cycles_tiled.py). The found cycle is
reconstructed host-side as the witness. :info txns are treated soundly:
their appends may legitimately be observed (never G1a) but contribute no
graph edges (their order is unknowable), so no anomaly can be fabricated
from an indeterminate txn.

The inference itself lives in :class:`ElleGraph` — an INCREMENTAL state
machine fed one completed txn at a time. The post-hoc checker feeds it
the whole paired history; the streaming session (stream/elle.py) feeds
it as completions land and re-checks the grown graph periodically, and
both finalize through the same `_check_graph`, so streamed and post-hoc
verdicts are bit-identical by construction.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Any, Sequence

import numpy as np

from .base import Checker
from ..ops import cycles
from ..ops.cycles import bfs_path, extract_cycle, reach_and_cycles  # noqa: F401 (re-exported API)
from ..ops.op import Op


class TxnEncodeError(ValueError):
    pass


def _pair_txns(history: Sequence[Op]):
    """Invoke/completion pairing by process (the runner guarantees one
    outstanding op per process). Returns list of
    (invoke_value, completion_type, completion_value, invoke_pos,
    complete_pos) — positions are history indices (complete_pos = -1 when
    the txn never completed), the raw material for realtime edges."""
    pending: dict[Any, tuple[int, Op]] = {}
    txns = []
    for pos, op in enumerate(history):
        if op.process == "nemesis":   # fault-plane channel, not a txn
            continue
        if op.f != "txn":
            raise TxnEncodeError(f"non-txn op {op.f!r} in txn history")
        if op.type == "invoke":
            if op.process in pending:
                raise TxnEncodeError(f"process {op.process} double-invoke")
            pending[op.process] = (pos, op)
        elif op.type in ("ok", "fail", "info"):
            got = pending.pop(op.process, None)
            if got is None:
                raise TxnEncodeError(f"completion without invoke: {op}")
            inv_pos, inv = got
            txns.append((inv.value, op.type,
                         op.value if op.type == "ok" else inv.value,
                         inv_pos, pos))
    for inv_pos, inv in pending.values():  # still-open at history end = info
        txns.append((inv.value, "info", inv.value, inv_pos, -1))
    return txns


class ElleGraph:
    """Incremental list-append dependency-graph inference — ONE state
    machine behind both the post-hoc checker and the streaming session
    (stream/elle.py), so the two can never drift.

    Feed completed txns in history order with :meth:`add_txn`; per-key
    derived state (direct anomalies + ww/wr/rw edge contributions) is
    recomputed lazily for DIRTY keys only on :meth:`refresh` — a key is
    dirty when a new read, a new committed append, or a newly-known
    failed append touches it, which is exactly when its derived record
    can change. Every edge and every direct anomaly is derivable from
    per-key state alone, so the incremental recompute is equal by
    construction to the one-shot pass over the full history."""

    def __init__(self):
        self.oks: list[tuple] = []           # the _pair_txns 5-tuples
        self.append_of: dict[tuple, int] = {}
        self.failed_vals: set[tuple] = set()
        self.multi_appends: dict[tuple, list] = defaultdict(list)
        self.appends_by_key: dict[Any, list] = {}
        self.reads: dict[Any, list] = {}     # k -> [(reader, vs tuple)]
        self.internal: list[dict] = []       # txn-ordered
        self._dirty: set = set()
        self._per_key: dict[Any, dict] = {}

    # -- feeding ----------------------------------------------------------
    def add_txn(self, value, typ, comp_value, inv_pos: int = -1,
                comp_pos: int = -1) -> None:
        """One completed txn, in history order (the _pair_txns tuple
        shape). :ok txns join the graph; :fail txns contribute their
        append values to the aborted-read set; :info txns contribute
        nothing (their order is unknowable)."""
        if typ == "fail":
            for mop in value:
                if mop[0] == "append":
                    self.failed_vals.add((mop[1], mop[2]))
                    self._dirty.add(mop[1])
            return
        if typ != "ok":
            return
        i = len(self.oks)
        self.oks.append((value, typ, comp_value, inv_pos, comp_pos))
        own: dict[Any, list] = defaultdict(list)
        for mop in comp_value:
            if mop[0] == "append":
                k, v = mop[1], mop[2]
                if (k, v) in self.append_of:
                    raise TxnEncodeError(
                        f"append value {v!r} reused for key {k!r}")
                self.append_of[(k, v)] = i
                self.multi_appends[(i, k)].append(v)
                self.appends_by_key.setdefault(k, []).append((v, i))
                self._dirty.add(k)
                own[k].append(v)
            elif mop[0] == "r" and mop[2] is not None:
                k = mop[1]
                # Internal consistency: a read of k must observe the
                # txn's own earlier appends to k as the list's suffix
                # (elle's :internal — the txn's own completed micro-op
                # order, before any cross-txn inference).
                o = own[k]
                vs = list(mop[2])
                if o and vs[len(vs) - len(o):] != o:
                    self.internal.append(
                        {"key": k, "expected_suffix": list(o),
                         "read": vs, "txn": i})
                self.reads.setdefault(k, []).append((i, tuple(mop[2])))
                self._dirty.add(k)

    # -- per-key derivation ----------------------------------------------
    def refresh(self) -> None:
        for k in self._dirty:
            if k in self.reads:
                self._per_key[k] = self._derive_key(k)
        self._dirty.clear()

    def _derive_key(self, k) -> dict:
        """The full per-key derived record: direct anomaly lists (reader
        order), the observed version order, and this key's ww/wr/rw edge
        contributions — the one copy of the inference both the post-hoc
        and the streamed paths run."""
        append_of, multi_appends = self.append_of, self.multi_appends
        rec: dict[str, Any] = {"duplicates": [], "G1a": [],
                               "lost-append": [], "G1b": [],
                               "incompatible-order": []}
        obs = self.reads[k]
        for reader, vs in obs:
            if len(set(vs)) != len(vs):
                rec["duplicates"].append(
                    {"key": k, "read": list(vs), "reader": reader})
            for v in vs:
                if (k, v) in self.failed_vals \
                        and (k, v) not in append_of:
                    rec["G1a"].append(
                        {"key": k, "value": v, "reader": reader})
            # A committed txn's appends to k are atomic: they occupy a
            # contiguous run of the true list, and any read is a prefix
            # of that list. So an observed value must have the writer's
            # previous append IMMEDIATELY before it, and — unless the
            # read ends there — the writer's next append immediately
            # after it. A violation proves an acked append vanished
            # (lost-append), regardless of which txn wrote the value
            # that sits there instead.
            for p, v in enumerate(vs):
                owner = append_of.get((k, v))
                if owner is None or owner == reader:
                    continue
                own = multi_appends[(owner, k)]
                i = own.index(v)
                if i > 0 and (p == 0 or vs[p - 1] != own[i - 1]):
                    rec["lost-append"].append(
                        {"key": k, "missing": own[i - 1],
                         "observed": v, "read": list(vs),
                         "writer": owner, "reader": reader})
                if (i + 1 < len(own) and p + 1 < len(vs)
                        and vs[p + 1] != own[i + 1]):
                    rec["lost-append"].append(
                        {"key": k, "missing": own[i + 1],
                         "observed": v, "read": list(vs),
                         "writer": owner, "reader": reader})
            if vs:
                owner = append_of.get((k, vs[-1]))
                if owner is not None:
                    own = multi_appends[(owner, k)]
                    if own and vs[-1] != own[-1] and owner != reader:
                        rec["G1b"].append(
                            {"key": k, "value": vs[-1],
                             "reader": reader, "writer": owner})
        # Prefix-compatibility: ascending by length, every read must
        # extend the previous longest (two equal-length reads that
        # differ fail the prefix test directly).
        longest: tuple = ()
        for _, vs in sorted(obs, key=lambda rv: len(rv[1])):
            if vs[:len(longest)] != longest:
                rec["incompatible-order"].append(
                    {"key": k, "read_a": list(longest),
                     "read_b": list(vs)})
                break
            longest = vs
        rec["order"] = longest

        # Edge contributions. ww: consecutive observed versions order
        # their writers. wr: the read's last value orders its writer
        # before the reader. rw (anti-dependency): a read returns the
        # WHOLE list, so a committed append serialized before it must
        # appear in it — contrapositive: every committed append of a
        # value ABSENT from the observed list is serialized after the
        # read, including acked appends no read ever observed (ADVICE
        # r2). The absent-writer set depends only on (key, observed
        # tuple): memoized so many readers of one prefix share a scan;
        # self-edges dropped (a txn is not its own anti-dependency).
        ww_pairs: set = set()
        for a, b in zip(longest, longest[1:]):
            wa, wb = append_of.get((k, a)), append_of.get((k, b))
            if wa is not None and wb is not None and wa != wb:
                ww_pairs.add((wa, wb))
        wr_pairs: set = set()
        rw_pairs: set = set()
        absent: dict[tuple, list] = {}
        appends = self.appends_by_key.get(k, ())
        for reader, vs in obs:
            if vs:
                wa = append_of.get((k, vs[-1]))
                if wa is not None and wa != reader:
                    wr_pairs.add((wa, reader))
            tgt = absent.get(vs)
            if tgt is None:
                seen = set(vs)
                tgt = [wb for v, wb in appends if v not in seen]
                absent[vs] = tgt
            for wb in tgt:
                if wb != reader:
                    rw_pairs.add((reader, wb))
        rec["ww"], rec["wr"], rec["rw"] = ww_pairs, wr_pairs, rw_pairs
        return rec

    # -- assembled views --------------------------------------------------
    def direct_anomalies(self) -> dict[str, list]:
        """Fresh anomaly dict of every non-cycle anomaly found so far —
        internal in txn order, then per-key lists in key-first-read
        order (the exact order the one-shot pass produced)."""
        self.refresh()
        anomalies: dict[str, list] = defaultdict(list)
        anomalies["internal"].extend(self.internal)
        if not self.internal:
            del anomalies["internal"]
        for k in self.reads:
            rec = self._per_key[k]
            for t in ("duplicates", "G1a", "lost-append", "G1b",
                      "incompatible-order"):
                if rec[t]:
                    anomalies[t].extend(rec[t])
        return anomalies

    def edge_matrices(self) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(ww, wr, rw) boolean matrices over the ok txns so far."""
        self.refresh()
        n = len(self.oks)
        ww = np.zeros((n, n), bool)
        wr = np.zeros((n, n), bool)
        rw = np.zeros((n, n), bool)
        for k in self.reads:
            rec = self._per_key[k]
            for m, pairs in ((ww, rec["ww"]), (wr, rec["wr"]),
                             (rw, rec["rw"])):
                if pairs:
                    idx = np.fromiter((x for p in pairs for x in p),
                                      dtype=np.intp,
                                      count=2 * len(pairs)).reshape(-1, 2)
                    m[idx[:, 0], idx[:, 1]] = True
        return ww, wr, rw

    def rt_matrix(self) -> np.ndarray | None:
        """Wall-clock order over the ok txns (A completed before B
        invoked => A precedes B) — the strict-serializability edges."""
        n = len(self.oks)
        if not n:
            return None
        inv_pos = np.array([t[3] for t in self.oks])
        comp_pos = np.array([t[4] for t in self.oks])
        return comp_pos[:, None] < inv_pos[None, :]


class ElleChecker(Checker):
    """checker/elle equivalent over list-append txn histories.

    realtime=True additionally asserts STRICT serializability: wall-clock
    completion-before-invocation order joins the dependency graph, so a
    serialization that reorders non-overlapping txns becomes a cycle
    (reported under the elle "-realtime" anomaly names)."""

    name = "elle"

    def __init__(self, realtime: bool = False):
        self.realtime = realtime

    def check(self, test: dict, history: Sequence[Op],
              opts: dict | None = None) -> dict[str, Any]:
        # A valid verdict the run's streaming elle session already
        # settled (stream/elle.py — the same ElleGraph fed live) skips
        # the post-hoc pass entirely; invalid/absent re-runs post-hoc,
        # exactly the Linearizable stream-settling discipline.
        pre = ((opts or {}).get("stream_results") or {}).get("elle")
        if (isinstance(pre, dict) and pre.get("streamed")
                and pre.get("valid") is True
                and pre.get("realtime") == self.realtime):
            return pre
        graph = ElleGraph()
        for txn in _pair_txns(history):
            graph.add_txn(*txn)
        return self._check_graph(graph)

    def _check_graph(self, graph: ElleGraph) -> dict[str, Any]:
        """Verdict assembly from an (incrementally or batch) fed graph —
        the one finalization path post-hoc and streamed checks share."""
        n = len(graph.oks)
        anomalies = graph.direct_anomalies()
        ww, wr, rw = graph.edge_matrices()
        rt = graph.rt_matrix() if self.realtime else None
        self._find_cycles(ww, wr, rw, graph.oks, anomalies, rt)

        types = sorted(anomalies)
        edge_counts = {"ww": int(ww.sum()), "wr": int(wr.sum()),
                       "rw": int(rw.sum())}
        if rt is not None:
            edge_counts["rt"] = int(rt.sum())
        return {
            "valid": not types,
            "anomaly_types": types,
            "anomalies": {t: anomalies[t] for t in types},
            "txn_count": n,
            "realtime": self.realtime,
            "edge_counts": edge_counts,
            "backend": "jax-mxu-closure",
        }

    # -- cycle classification --------------------------------------------
    def _find_cycles(self, ww, wr, rw, oks, anomalies, rt=None):
        def witness(cyc):
            return {"cycle": cyc,
                    "txns": [list(oks[i][2]) for i in cyc[:-1]]}

        if rt is None:
            self._classify(ww, wr, rw, None, "", witness, anomalies)
            return
        # Realtime mode, still ONE closure launch on the (common) valid
        # path: full|rt is a superset of every tier of both ladders, so
        # acyclic(full|rt) clears them all at once. On a cycle, run the
        # serializable ladder first (its anomaly names are stronger); only
        # when the cycle NEEDS a realtime edge does the "-realtime" ladder
        # name it.
        if not cycles.cycle_mask(ww | wr | rw | rt).any():
            return
        if not self._classify(ww, wr, rw, None, "", witness, anomalies):
            self._classify(ww, wr, rw, rt, "-realtime", witness, anomalies)

    @staticmethod
    def _classify(ww, wr, rw, rt, suffix, witness, anomalies) -> bool:
        """One G0/G1c/G-single/G2-item classification ladder over
        ww|wr|rw (plus rt when given, with `suffix` on the anomaly
        names). Returns True iff a cycle was found."""
        def with_rt(adj):
            return adj if rt is None else adj | rt

        # Full graph first: acyclic full graph implies every subset is
        # acyclic — ONE cycle-presence probe (diagonal-only fetch,
        # component-decomposed for big graphs) on the common valid path.
        full = with_rt(ww | wr | rw)
        cyc_f = cycles.cycle_mask(full)
        if not cyc_f.any():
            return False
        # The two sub-ladder tiers share the full graph's size: ONE
        # vmapped batched launch closes both — except past the dense
        # crossover / cell budget, where each tier routes individually
        # (decomposition / tiled / host oracle) instead of stacking two
        # full-size copies.
        g0 = with_rt(ww)
        g1 = with_rt(ww | wr)
        if cycles.batchable(full.shape[0]):
            cyc_g0, cyc_g1 = cycles.cycle_masks_batch([g0, g1])
        else:
            cyc_g0 = cycles.cycle_mask(g0)
            cyc_g1 = cycles.cycle_mask(g1)
        if cyc_g0.any():
            anomalies["G0" + suffix].append(witness(
                cycles.extract_cycle_any(g0, cyc_g0)))
        if cyc_g1.any() and not cyc_g0.any():
            anomalies["G1c" + suffix].append(witness(
                cycles.extract_cycle_any(g1, cyc_g1)))
        if not cyc_g1.any():
            # Cycles need rw edges. G-single holds iff SOME rw edge is
            # closed by a (ww|wr|rt)-only path (exactly one
            # anti-dependency) — exact, unlike counting rw edges on one
            # arbitrary extracted cycle, which can mis-classify when 1-rw
            # and 2-rw cycles coexist. Reachability answers come from
            # reach_pairs (per-component closures), never a full [N, N]
            # slab fetch.
            edges = list(zip(*np.nonzero(rw & ~g1)))
            hits = cycles.reach_pairs(
                g1, [(int(b), int(a)) for a, b in edges])
            for (a, b), hit in zip(edges, hits):
                if hit:
                    back = bfs_path(g1, int(b), int(a))  # [b, ..., a]
                    anomalies["G-single" + suffix].append(
                        witness([int(a)] + back))
                    break
            else:
                anomalies["G2-item" + suffix].append(witness(
                    cycles.extract_cycle_any(full, cyc_f)))
        return True


class ElleRwChecker(ElleChecker):
    """elle.rw-register equivalent: transactional anomaly inference over
    REGISTER txns — elle 0.1.2's other workload family (VERDICT r3 item
    8; the reference ships it at jepsen.etcdemo.iml:46).

      txn ops: Op(f="txn", value=[micro-op, ...]) with micro-ops
          ("w", k, v)  — write v to register k (values unique per key)
          ("r", k, v)  — read register k (v: None on invoke; the observed
                          value, or None for the initial nil, on :ok)

    Unlike list-append, a register read observes only the LAST write, so
    the per-key version order must be INFERRED rather than read off a
    list prefix. Sources (each sound for a register with unique writes
    and no deletes):
      * own-txn write order — successive writes to k inside one :ok txn;
      * writes-follow-reads — an :ok txn that reads k=v1 before its own
        first write v2 to k places v1 before v2 (the read saw the state
        its write replaced or succeeded);
      * the initial nil precedes every written version.
    The per-key version DAG is closed transitively (tiny host matrices);
    a CYCLIC version graph is itself reported (:cyclic-versions, elle's
    name) and that key contributes no ww/rw edges — deriving order from
    a contradiction would fabricate anomalies.

    Dependency edges over :ok txns, fed to the SAME G0/G1c/G-single/
    G2-item (+ -realtime) classification ladder as list-append:
      * wr  writer(v) -> reader that observed v;
      * ww  writer(v1) -> writer(v2) for v1 < v2 in the version order;
      * rw  reader of v -> writer(v2) for every v2 > v (a register holds
        the last write, so a later version's writer must serialize after
        any read that still saw v); a read of nil anti-depends on EVERY
        writer of the key.

    Direct anomalies: internal (own-txn read contradicts the state the
    txn's earlier writes OR reads established), G1a (observed a :fail
    txn's value), G1b (observed a txn's
    non-final write), garbage-read (observed a value nobody wrote),
    cyclic-versions. :info txns: their writes may legitimately be
    observed (never G1a) but contribute no edges."""

    name = "elle-rw"

    def check(self, test: dict, history: Sequence[Op],
              opts: dict | None = None) -> dict[str, Any]:
        txns = _pair_txns(history)
        oks = [t for t in txns if t[1] == "ok"]
        n = len(oks)
        anomalies: dict[str, list] = defaultdict(list)

        # Ownership: (k, v) -> ok writer idx; final write per (txn, k);
        # failed and indeterminate writes.
        writer_of: dict[tuple, int] = {}
        final_write: dict[tuple, Any] = {}
        info_vals: set[tuple] = set()
        failed_vals: set[tuple] = set()
        for i, (_, _, value, *_pos) in enumerate(oks):
            for mop in value:
                if mop[0] == "w":
                    k, v = mop[1], mop[2]
                    if (k, v) in writer_of:
                        raise TxnEncodeError(
                            f"write value {v!r} reused for key {k!r}")
                    writer_of[(k, v)] = i
                    final_write[(i, k)] = v
        for value, typ, *_rest in txns:
            if typ in ("fail", "info"):
                for mop in value:
                    if mop[0] == "w":
                        (failed_vals if typ == "fail"
                         else info_vals).add((mop[1], mop[2]))

        # Internal: each read must match the txn's own intermediate state
        # for that key — established by a prior own WRITE or a prior own
        # READ (elle's rw-register :internal covers both; ADVICE r4: a
        # read-read contradiction was only caught indirectly via wr/rw
        # cycles before, which needs the versions to be orderable). A
        # read also PINS the observed state: later reads must agree
        # until an own write changes it.
        for i, (_, _, value, *_pos) in enumerate(oks):
            own_last: dict[Any, Any] = {}
            for mop in value:
                if mop[0] == "w":
                    own_last[mop[1]] = mop[2]
                elif mop[0] == "r":
                    if (mop[1] in own_last
                            and mop[2] != own_last[mop[1]]):
                        anomalies["internal"].append(
                            {"key": mop[1], "expected": own_last[mop[1]],
                             "read": mop[2], "txn": i})
                    own_last[mop[1]] = mop[2]

        # External reads: (reader, key, observed) with own-value reads
        # excluded (covered by internal above; no self-edges).
        ext_reads: list[tuple[int, Any, Any]] = []
        for i, (_, _, value, *_pos) in enumerate(oks):
            own_written: set = set()
            for mop in value:
                if mop[0] == "w":
                    own_written.add((mop[1], mop[2]))
                elif mop[0] == "r":
                    k, v = mop[1], mop[2]
                    if (k, v) in own_written:
                        continue
                    ext_reads.append((i, k, v))
                    if v is None:
                        continue
                    # Same guard as the append family: a value a :fail
                    # txn shares with a committed write was legitimately
                    # observable.
                    if (k, v) in failed_vals and (k, v) not in writer_of:
                        anomalies["G1a"].append(
                            {"key": k, "value": v, "reader": i})
                    elif ((k, v) not in writer_of
                            and (k, v) not in info_vals):
                        anomalies["garbage-read"].append(
                            {"key": k, "value": v, "reader": i})
                    owner = writer_of.get((k, v))
                    if owner is not None and final_write[(owner, k)] != v:
                        anomalies["G1b"].append(
                            {"key": k, "value": v, "reader": i,
                             "writer": owner})

        # Per-key version DAG -> transitive closure -> ww/rw edges.
        versions: dict[Any, list] = defaultdict(lambda: [None])
        for (k, v) in writer_of:
            versions[k].append(v)
        prec: dict[Any, np.ndarray] = {}
        for k, vs in versions.items():
            idx = {v: j for j, v in enumerate(vs)}
            m = np.zeros((len(vs), len(vs)), bool)
            m[0, 1:] = True                      # nil precedes everything
            for i, (_, _, value, *_pos) in enumerate(oks):
                last_own = None
                first_read: Any = "__none__"
                for mop in value:
                    if mop[0] == "w" and mop[1] == k:
                        if last_own is not None:
                            m[idx[last_own], idx[mop[2]]] = True
                        elif (first_read != "__none__"
                                and first_read in idx):
                            # writes-follow-reads: the pre-write read
                            m[idx[first_read], idx[mop[2]]] = True
                        last_own = mop[2]
                    elif (mop[0] == "r" and mop[1] == k
                            and last_own is None
                            and first_read == "__none__"):
                        first_read = mop[2]   # may be None = nil (idx 0)
            closure = _bool_closure(m)
            if closure.diagonal().any():
                cyc_vals = [vs[j] for j in
                            np.nonzero(closure.diagonal())[0]]
                anomalies["cyclic-versions"].append(
                    {"key": k, "values": cyc_vals})
                continue   # contradictory order: derive no edges from k
            prec[k] = closure

        ww = np.zeros((n, n), bool)
        wr = np.zeros((n, n), bool)
        rw = np.zeros((n, n), bool)
        vidx = {k: {x: j for j, x in enumerate(vs)}
                for k, vs in versions.items()}
        for k, closure in prec.items():
            vs = versions[k]
            owners = np.full(len(vs), -1, dtype=np.intp)
            for j, v in enumerate(vs[1:], start=1):
                owners[j] = writer_of[(k, v)]
            for a, b in zip(*np.nonzero(closure)):
                wa, wb = owners[a], owners[b]
                if wa >= 0 and wb >= 0 and wa != wb:
                    ww[wa, wb] = True
        for reader, k, v in ext_reads:
            # wr needs no version order — sound even when the key's
            # inferred order is contradictory (cyclic-versions only
            # withholds the order-DERIVED ww/rw edges).
            if v is not None and (k, v) in writer_of:
                wa = writer_of[(k, v)]
                if wa != reader:
                    wr[wa, reader] = True
            if k not in prec:
                continue
            vs = versions[k]
            j = vidx[k].get(v)
            if j is None:
                continue   # garbage / info value: no inferable position
            for succ in np.nonzero(prec[k][j])[0]:
                wb = writer_of[(k, vs[succ])]
                if wb != reader:
                    rw[reader, wb] = True

        rt = None
        if self.realtime and n:
            inv_pos = np.array([t[3] for t in oks])
            comp_pos = np.array([t[4] for t in oks])
            rt = comp_pos[:, None] < inv_pos[None, :]
        self._find_cycles(ww, wr, rw, oks, anomalies, rt)

        types = sorted(anomalies)
        edge_counts = {"ww": int(ww.sum()), "wr": int(wr.sum()),
                       "rw": int(rw.sum())}
        if rt is not None:
            edge_counts["rt"] = int(rt.sum())
        return {
            "valid": not types,
            "anomaly_types": types,
            "anomalies": {t: anomalies[t] for t in types},
            "txn_count": n,
            "realtime": self.realtime,
            "edge_counts": edge_counts,
            "backend": "jax-mxu-closure",
        }


def _bool_closure(m: np.ndarray) -> np.ndarray:
    """Transitive closure by boolean matrix squaring (host: per-key
    version matrices are tiny; the TXN graph uses the MXU closure in
    ops/cycles.py)."""
    out = m.copy()
    while True:
        nxt = out | (out @ out)
        if (nxt == out).all():
            return out
        out = nxt


# -- pure-Python oracle (differential tests) -----------------------------

def tarjan_has_cycle(adj: np.ndarray) -> bool:
    """Iterative DFS cycle detection — the CPU oracle the MXU closure is
    differentially tested against."""
    n = adj.shape[0]
    color = [0] * n   # 0 white, 1 grey, 2 black
    for root in range(n):
        if color[root]:
            continue
        stack = [(root, iter(np.flatnonzero(adj[root])))]
        color[root] = 1
        while stack:
            node, it = stack[-1]
            adv = False
            for s in it:
                s = int(s)
                if color[s] == 1:
                    return True
                if color[s] == 0:
                    color[s] = 1
                    stack.append((s, iter(np.flatnonzero(adj[s]))))
                    adv = True
                    break
            if not adv:
                color[node] = 2
                stack.pop()
    return False
