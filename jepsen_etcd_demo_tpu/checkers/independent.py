"""Independent-keys checker — equivalent of `independent/checker`.

The reference lifts a single-register workload onto many independent keys:
values become (key, value) tuples (src/jepsen/etcdemo.clj:90), and
`independent/checker` splits the history per key and runs the sub-checker on
each (src/jepsen/etcdemo.clj:115).

TPU twist: when the sub-checker is a `Linearizable` with the JAX backend — or
a `Compose` whose direct entries include one — all per-key histories are
encoded, padded to a common event length, stacked, and checked in ONE vmapped
kernel launch; per-key histories are embarrassingly parallel, so the key axis
is the batch axis (BASELINE.json configs[2]). Each distinct Linearizable
entry gets its own batched launch under its own result name; every other
composed checker still runs per key, unbatched.
"""

from __future__ import annotations

from typing import Any, Sequence

import numpy as np

from .base import Checker, merge_valid
from .compose import Compose
from .linearizable import Linearizable
from ..ops.op import Op, INVOKE


def split_by_key(history: Sequence[Op]) -> dict[Any, list[Op]]:
    """Split a tuple-valued history into per-key sub-histories.

    Invocations carry (key, v) tuples; completions may or may not (e.g. a
    :write completion keeps the tuple, a timeout :info has whatever the invoke
    had). Like jepsen.independent, the key is taken from the op's tuple value;
    completions are routed to the key of their pending invocation.
    """
    keyed: dict[Any, list[Op]] = {}
    key_of_process: dict[Any, Any] = {}
    for op in history:
        if op.process == "nemesis":
            continue
        if op.type == INVOKE:
            if not (isinstance(op.value, tuple) and len(op.value) == 2):
                raise ValueError(
                    f"independent history op without (key, value) tuple: {op}")
            k, v = op.value
            key_of_process[op.process] = k
        else:
            k = key_of_process.pop(op.process, None)
            if k is None:
                continue
            v = op.value[1] if (isinstance(op.value, tuple)
                                and len(op.value) == 2) else op.value
        sub = Op(type=op.type, f=op.f, value=v, process=op.process,
                 time=op.time, index=op.index, error=op.error)
        keyed.setdefault(k, []).append(sub)
    return keyed


class IndependentChecker(Checker):
    def __init__(self, sub_checker: Checker, batch_jax: bool = True):
        self.sub_checker = sub_checker
        self.batch_jax = batch_jax

    def check(self, test: dict, history: Sequence[Op],
              opts: dict | None = None) -> dict[str, Any]:
        keyed = split_by_key(history)
        if not keyed:
            return {"valid": True, "key_count": 0}
        keys = sorted(keyed, key=str)

        # Which checkers can ride the batched kernel? Only direct entries:
        # either the sub-checker itself, or first-level values of a Compose.
        batchable: dict[str | None, Linearizable] = {}
        if self.batch_jax and len(keyed) > 1:
            if (isinstance(self.sub_checker, Linearizable)
                    and self.sub_checker.backend == "jax"):
                batchable[None] = self.sub_checker
            elif isinstance(self.sub_checker, Compose):
                for name, sub in self.sub_checker.checkers.items():
                    if isinstance(sub, Linearizable) and sub.backend == "jax":
                        batchable[name] = sub

        batched: dict[str | None, dict[Any, dict]] = {
            name: _batched_linearizable(lin, keyed)
            for name, lin in batchable.items()
        }

        results: dict[Any, dict] = {}
        for k in keys:
            results[k] = self._check_key(test, keyed[k], opts, batched, k)
        valid = merge_valid([r.get("valid") for r in results.values()])
        return {"valid": valid, "key_count": len(keyed),
                "results": {str(k): v for k, v in results.items()}}

    def _check_key(self, test, sub_history, opts, batched, key):
        def pick(name, checker):
            pre = batched.get(name, {}).get(key)
            if pre is not None and pre["valid"] != "unknown":
                return pre
            return checker.check(test, sub_history, opts)

        if not isinstance(self.sub_checker, Compose):
            return pick(None, self.sub_checker)
        sub_results = {name: pick(name, sub)
                       for name, sub in self.sub_checker.checkers.items()}
        return {"valid": merge_valid([r.get("valid")
                                      for r in sub_results.values()]),
                **sub_results}


def _batched_linearizable(lin: Linearizable, keyed: dict[Any, list[Op]]
                          ) -> dict[Any, dict]:
    """Encode every key's history, pad to one event length, run one vmapped
    kernel launch over the key batch."""
    from ..ops import wgl
    import jax.numpy as jnp

    encs = {k: lin.encode(h) for k, h in keyed.items()}
    k_slots = max(e.k_slots for e in encs.values())
    e_cap = max(1, max(e.events.shape[0] for e in encs.values()))
    keys = list(encs)
    stack = np.stack([encs[k].padded_to(e_cap).events for k in keys])
    check = wgl.cached_batch_checker(lin.model,
                                     wgl.WGLConfig(k_slots, lin.f_cap))
    out = {name: np.asarray(v) for name, v in
           check(jnp.asarray(stack)).items()}
    results = {}
    for i, k in enumerate(keys):
        one = {name: out[name][i].item() for name in out}
        results[k] = {
            "valid": wgl.verdict(one),
            "backend": "jax-batched",
            "op_count": encs[k].n_ops,
            "dead_event": one["dead_event"],
            "max_frontier": one["max_frontier"],
        }
    return results
