"""Independent-keys checker — equivalent of `independent/checker`.

The reference lifts a single-register workload onto many independent keys:
values become (key, value) tuples (src/jepsen/etcdemo.clj:90), and
`independent/checker` splits the history per key and runs the sub-checker on
each (src/jepsen/etcdemo.clj:115).

TPU twist: when the sub-checker is a `Linearizable` with the JAX backend — or
a `Compose` whose direct entries include one — all per-key histories are
encoded, padded to a common event length, stacked, and checked in ONE vmapped
kernel launch; per-key histories are embarrassingly parallel, so the key axis
is the batch axis (BASELINE.json configs[2]). Each distinct Linearizable
entry gets its own batched launch under its own result name; every other
composed checker still runs per key, unbatched.
"""

from __future__ import annotations

from typing import Any, Sequence

import numpy as np

from .base import Checker, merge_valid
from .compose import Compose
from .linearizable import Linearizable
from .. import obs
from ..ops.op import Op, INVOKE


def split_by_key(history: Sequence[Op]) -> dict[Any, list[Op]]:
    """Split a tuple-valued history into per-key sub-histories.

    Invocations carry (key, v) tuples; completions may or may not (e.g. a
    :write completion keeps the tuple, a timeout :info has whatever the invoke
    had). Like jepsen.independent, the key is taken from the op's tuple value;
    completions are routed to the key of their pending invocation.
    """
    keyed: dict[Any, list[Op]] = {}
    key_of_process: dict[Any, Any] = {}
    for op in history:
        if op.process == "nemesis":
            continue
        if op.type == INVOKE:
            if not (isinstance(op.value, tuple) and len(op.value) == 2):
                raise ValueError(
                    f"independent history op without (key, value) tuple: {op}")
            k, v = op.value
            key_of_process[op.process] = k
        else:
            k = key_of_process.pop(op.process, None)
            if k is None:
                continue
            v = op.value[1] if (isinstance(op.value, tuple)
                                and len(op.value) == 2) else op.value
        sub = Op(type=op.type, f=op.f, value=v, process=op.process,
                 time=op.time, index=op.index, error=op.error, seq=op.seq)
        keyed.setdefault(k, []).append(sub)
    return keyed


class IndependentChecker(Checker):
    def __init__(self, sub_checker: Checker, batch_jax: bool = True):
        self.sub_checker = sub_checker
        self.batch_jax = batch_jax

    def check(self, test: dict, history: Sequence[Op],
              opts: dict | None = None) -> dict[str, Any]:
        keyed = split_by_key(history)
        if not keyed:
            return {"valid": True, "key_count": 0}
        keys = sorted(keyed, key=str)

        # Which checkers can ride the batched kernel? Only direct entries:
        # either the sub-checker itself, or first-level values of a Compose.
        batchable: dict[str | None, Linearizable] = {}
        if self.batch_jax and len(keyed) > 1:
            if (isinstance(self.sub_checker, Linearizable)
                    and self.sub_checker.backend == "jax"):
                batchable[None] = self.sub_checker
            elif isinstance(self.sub_checker, Compose):
                for name, sub in self.sub_checker.checkers.items():
                    if isinstance(sub, Linearizable) and sub.backend == "jax":
                        batchable[name] = sub

        # Keys the run's streaming check session (stream/engine.py) has
        # already settled valid for a given model skip the batched
        # launch entirely — _check_key's per-key path picks the streamed
        # verdict up via Linearizable._stream_result. Invalid/unsettled
        # keys keep the full batched + ladder treatment (witnesses).
        stream_results = (opts or {}).get("stream_results") or {}

        def settled_for(lin: Linearizable) -> set:
            return {k for k, r in stream_results.items()
                    if isinstance(r, dict) and r.get("valid") is True
                    and r.get("model") == lin.model.name}

        def batch_keys(lin: Linearizable) -> dict[Any, list[Op]]:
            settled = settled_for(lin)
            return {k: h for k, h in keyed.items() if k not in settled}

        batched: dict[str | None, dict[Any, dict]] = {
            name: (_batched_linearizable(lin, sub_keyed,
                                         (opts or {}).get("store_dir"))
                   if (sub_keyed := batch_keys(lin)) else {})
            for name, lin in batchable.items()
        }

        results: dict[Any, dict] = {}
        for k in keys:
            results[k] = self._check_key(test, keyed[k], opts, batched, k)
        valid = merge_valid([r.get("valid") for r in results.values()])
        return {"valid": valid, "key_count": len(keyed),
                "results": {str(k): v for k, v in results.items()}}

    def _check_key(self, test, sub_history, opts, batched, key):
        opts = dict(opts or {})
        opts["key"] = key  # sub-checkers emit per-key artifacts (timeline)

        def pick(name, checker):
            # A batched result settles the key only when valid: invalid keys
            # re-run the single-history path, which reconstructs and stores
            # the counterexample witness (linear-<key>.json/svg); "unknown"
            # re-runs for the escalation ladder, seeded past the capacities
            # the batched tiers already proved dead (f_cap_floor).
            pre = batched.get(name, {}).get(key)
            if pre is not None and pre["valid"] is True:
                return pre
            sub_opts = opts
            if pre and pre.get("f_cap_floor"):
                sub_opts = dict(opts)
                sub_opts["f_cap_floor"] = pre["f_cap_floor"]
            return checker.check(test, sub_history, sub_opts)

        if not isinstance(self.sub_checker, Compose):
            return pick(None, self.sub_checker)
        sub_results = {name: pick(name, sub)
                       for name, sub in self.sub_checker.checkers.items()}
        return {"valid": merge_valid([r.get("valid")
                                      for r in sub_results.values()]),
                **sub_results}


def _batched_linearizable(lin: Linearizable, keyed: dict[Any, list[Op]],
                          store_dir=None) -> dict[Any, dict]:
    """Encode every key's history into the return-major form, pad to one
    step count, run one vmapped kernel launch over the key batch.

    Prefers the dense lattice kernel (wgl3) — exact, no overflow — whenever
    the shared config table is feasible; falls back to the sort kernel."""
    with obs.get_tracer().span("check.linearizable.batched",
                               model=lin.model.name,
                               keys=len(keyed)) as sp:
        out = _batched_linearizable_traced(lin, keyed, store_dir)
        sp.set(settled=sum(1 for r in out.values()
                           if r.get("valid") is True))
        return out


def _batched_linearizable_traced(lin: Linearizable,
                                 keyed: dict[Any, list[Op]],
                                 store_dir=None) -> dict[Any, dict]:
    from ..ops import wgl3

    event_encs = {k: lin.encode(h) for k, h in keyed.items()}
    if store_dir:
        from ..store.store import write_encoded_tensor

        for k, e in event_encs.items():
            # Empty encodings included (corpus tensor-coverage contract).
            write_encoded_tensor(store_dir, k, e, lin.model.name)
    max_value = max(e.max_value for e in event_encs.values())

    # Dense path: one table geometry serves the whole batch — mask width =
    # the largest key's real concurrency. Launches go through the corpus
    # scheduler (sched/engine.py): per-key histories land in padded-length
    # buckets instead of all padding to the longest key, so a run with one
    # long-lived key no longer taxes every other key's launch.
    tight = max(wgl3.tight_k_slots(e) for e in event_encs.values())
    cfg3 = wgl3.dense_config(lin.model, tight, max_value)
    if cfg3 is not None:
        from .. import sched

        keys = list(event_encs)
        batch, _kernel, _stats = sched.check_corpus(
            [event_encs[k] for k in keys], lin.model)
        return {
            k: {
                "valid": one["valid"],
                "backend": "jax-dense-batched",
                "op_count": one["op_count"],
                "dead_step": one["dead_step"],
                "max_frontier": one["max_frontier"],
                "configs_explored": one["configs_explored"],
                "overflow": False,
                "f_cap": one["table_cells"],
            }
            for k, one in zip(keys, batch)
        }

    # Sort-kernel path: the shared batched general pass (one copy of the
    # pad/stack/launch/verdict logic, with its row-budget chunking and
    # LONG_SCAN_MAX guard — wgl3_pallas._batch_general). Keys the tiers
    # could not settle get an "unknown" marker carrying an f_cap_floor:
    # _check_key's pick() threads it into the single-path re-run, so the
    # ladder there starts past the capacities the tiers proved dead (one
    # ladder run per unsettled key, witnesses included).
    from ..ops.wgl3_pallas import LADDER_SEED_FACTOR, _batch_general

    keys = list(event_encs)
    slots: list = [None] * len(keys)
    overflowed, too_long, top = _batch_general(
        [event_encs[k] for k in keys], list(range(len(keys))),
        lin.model, slots, set(), f_cap=lin.f_cap)
    results = {}
    for i in overflowed:
        results[keys[i]] = {"valid": "unknown",
                            "f_cap_floor": LADDER_SEED_FACTOR * top}
    for k, one in zip(keys, slots):
        if one is None:
            continue
        # Keys mirror the single-history jax path's normalized schema
        # (linearizable.py) so consumers see one shape whatever path ran.
        results[k] = {
            "valid": one["valid"],
            "backend": "jax-batched",
            "op_count": one["op_count"],
            "dead_step": one["dead_step"],
            "max_frontier": one["max_frontier"],
            "overflow": one["overflow"],
            "f_cap": one["f_cap"],
        }
    return results
