"""Cross-tenant continuous-batching check scheduler — the serve core.

Every prior layer amortizes compiles and padding *within* one run: the
sched bucket engine within one corpus call, the warm-kernel LRU within
one process, the stream dispatcher within one live run. A CLI
invocation per client therefore re-pays the whole cold path per client.
This module is the same fix modern inference serving applies to LLM
decode: ONE persistent scheduler that coalesces concurrent requests
from *different* tenants into shared bucketed launches, so tenant N's
compile and bucket fill benefit tenant N+1 by construction —
``plan.cache_key()`` (PR 12) makes the sharing safe (a kernel resolved
for one tenant's bucket shape is exactly the kernel any tenant's
same-shape launch needs).

Mechanics:

  * **Coalescing queue** — requests land in per-tenant FIFO queues; the
    dispatch thread wakes on the first arrival, lingers up to
    ``limits().serve_coalesce_ms`` for more requests to coalesce
    (latency <-> batch-fill, the capacity-planning knob), then drains a
    batch of up to ``serve_max_batch`` requests **weighted-fair** across
    tenants (round-robin, ``weights[tenant]`` requests per turn) so a
    flooding tenant cannot starve a light one.
  * **Shared bucketed launches** — the coalesced batch goes through
    ``sched.submit_corpus`` (the async face of the PR 2 bucket engine):
    different tenants' same-bucket histories stack into ONE kernel
    launch, resolved via the KernelPlan dispatch spine against the
    process-wide warm-kernel LRU. Aggregate events/s under K concurrent
    clients approaches the single-client corpus-batch record because
    the daemon *is* the corpus batcher, fed by the network.
  * **Admission control** — at most ``serve_max_inflight`` admitted-but-
    unfinished requests per tenant; past the bound a submission is
    rejected (HTTP 429 upstream) instead of queueing unboundedly.
  * **Supervisor-driven backpressure** (obs/health.py): ``wedged``
    rejects new work outright (HTTP 503 + Retry-After) and parks the
    dispatcher — already-admitted requests drain when the backend
    recovers; ``degraded`` sheds work to the exact CPU oracle path
    (same algorithm, same verdicts, no device dispatch) instead of
    risking the sick backend; any dispatch failure on a healthy backend
    falls back to the oracle for that batch and notes the failure.

Verdicts are bit-identical to ``jepsen-tpu analyze`` on the same
histories: the batched path IS the post-hoc corpus path (test_sched.py
equivalence), and the oracle shed runs the same WGL algorithm on host
(tests/test_serve.py pins both).
"""

from __future__ import annotations

import itertools
import threading
import time
import uuid
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from .. import obs
from ..obs import health
from ..obs.sync import maybe_wrap
from ..ops.limits import limits

# Retry-After seconds a wedged rejection advertises: long enough that a
# well-behaved client backs off past a probe cycle's worth of recovery
# chances, short enough to re-attach promptly after one.
RETRY_AFTER_S = 5

# Retry-After seconds a 429 in-flight-bound rejection advertises: the
# bound clears as soon as one batch drains (tens of ms on a warm
# kernel), so 1s is the floor a well-behaved client — and the fleet
# router's backoff (serve/router.py) — can act on.
RETRY_AFTER_INFLIGHT_S = 1

# Kernel label of the degraded-shed route (results / bench / web).
ORACLE_KERNEL = "cpu-oracle-shed"

# Most tenants whose recent-latency windows are retained (each window
# itself caps at 1024 samples) — like the queue/rotation eviction,
# client-supplied tenant ids must not grow process state unboundedly.
TENANT_LATENCY_TENANTS = 256

# The scenario factory's tenant id (campaign/engine.py submits its
# check waves here under route="serve"): campaign traffic rides the
# same WFQ rotation as everyone else — one turn per rotation like any
# tenant, so a million-scenario campaign cannot starve an interactive
# tenant, which is the whole point of submitting it AS a tenant.
CAMPAIGN_TENANT = "campaign"


class Rejected(Exception):
    """A submission the scheduler refused to admit. ``status`` is the
    HTTP code the daemon maps it to (429 admission bound / 503 wedged);
    ``retry_after_s`` is set for wedged rejections."""

    def __init__(self, reason: str, status: int,
                 retry_after_s: Optional[int] = None):
        super().__init__(reason)
        self.reason = reason
        self.status = status
        self.retry_after_s = retry_after_s


@dataclass
class ServeRequest:
    """One admitted check request riding the coalescing queue."""

    tenant: str
    model_name: str
    enc: Any                                   # EncodedHistory
    ops: Optional[list] = None                 # raw Op history (artifacts)
    webhook: Optional[str] = None
    id: str = field(default_factory=lambda: uuid.uuid4().hex)
    submitted_mono: float = field(default_factory=time.monotonic)
    done: threading.Event = field(default_factory=threading.Event)
    result: Optional[dict] = None
    error: Optional[str] = None

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self.done.wait(timeout)


class CoalescingScheduler:
    """The continuous-batching dispatcher (module docstring). One
    instance per daemon process; ``submit`` is called from any number of
    HTTP handler threads, everything else happens on the dispatch
    thread. Shared state is guarded by ONE condition (``_lock``)."""

    def __init__(self, coalesce_ms: Optional[int] = None,
                 max_batch: Optional[int] = None,
                 max_inflight: Optional[int] = None,
                 weights: Optional[dict[str, int]] = None,
                 artifact_sink: Optional[Callable] = None,
                 webhook_sink: Optional[Callable] = None,
                 batch_telemetry: bool = False):
        self._coalesce_ms = coalesce_ms
        self._max_batch = max_batch
        self._max_inflight = max_inflight
        self._weights = dict(weights or {})
        # Both sinks run on the dispatch thread, after verdicts settle:
        # artifact_sink(requests, batch_tracer) persists store
        # artifacts, webhook_sink(request) delivers the verdict
        # callback.
        self._artifact_sink = artifact_sink
        self._webhook_sink = webhook_sink
        # batch_telemetry: record each batch under a PRIVATE tracer so
        # the per-request store artifacts carry the batch's span
        # record. Deliberately not a nested obs capture: the capture
        # stack is process-global, so nesting would shadow the
        # daemon's registry for every handler thread mid-batch (serve
        # counters and /metrics scrapes landing in a throwaway
        # registry) — kernel attribution and the serve.* series belong
        # on the daemon's own capture.
        self._batch_telemetry = batch_telemetry
        self._lock = maybe_wrap(
            threading.Condition(),
            "serve.scheduler.CoalescingScheduler._lock")
        # jtsan: guarded-by=self._lock
        self._queues: dict[str, deque[ServeRequest]] = {}
        self._rotation: deque[str] = deque()    # WFQ tenant turn order
        # jtsan: guarded-by=self._lock
        self._inflight: dict[str, int] = {}
        self._pending = 0
        self._models: dict[str, Any] = {}       # model name -> Model
        self._batch_ids = itertools.count(1)
        self._stop = threading.Event()
        # Dispatch-thread-only accounting (handler threads read it
        # through stats(), which copies under the lock).
        self._batches = 0
        self._requests_done = 0
        self._coalesced_requests = 0
        self._shed_cpu = 0
        self._fill_sum = 0.0
        self._tenant_latency: dict[str, deque] = {}
        # Rolling-window SLO gauges (obs/ledger.py RollingWindow,
        # ISSUE 16): p50/p99 over the last minute + burn rate, updated
        # by the dispatch thread only; stats() reads the copied dict.
        self._slo_window = obs.ledger.RollingWindow()
        self._slo = {"slo_p50_s": 0.0, "slo_p99_s": 0.0,
                     "slo_burn_rate": 0.0,
                     "slo_target_s": obs.ledger.slo_target_s()}
        self._thread = threading.Thread(target=self._run,
                                        name="serve-dispatch", daemon=True)
        self._thread.start()

    # -- knobs (resolved late so env/tuned-profile overrides apply) ------
    def coalesce_s(self) -> float:
        ms = self._coalesce_ms if self._coalesce_ms is not None \
            else limits().serve_coalesce_ms
        return max(0.0, ms / 1000.0)

    def max_batch(self) -> int:
        return self._max_batch if self._max_batch is not None \
            else limits().serve_max_batch

    def max_inflight(self) -> int:
        return self._max_inflight if self._max_inflight is not None \
            else limits().serve_max_inflight

    # -- submit side (HTTP handler threads) ------------------------------
    def submit(self, tenant: str, enc, model_name: str = "cas-register",
               ops: Optional[list] = None,
               webhook: Optional[str] = None) -> ServeRequest:
        """Admit one request (or raise :class:`Rejected`). Returns the
        request handle; await the verdict with ``req.wait()`` /
        ``req.result``."""
        m = obs.get_metrics()
        sup = health.get_supervisor()
        if sup.snapshot()["state"] == health.WEDGED:
            m.counter("serve.rejected_wedged").add(1)
            raise Rejected(
                "backend wedged; shedding new work "
                f"(retry after {RETRY_AFTER_S}s)", 503,
                retry_after_s=RETRY_AFTER_S)
        req = ServeRequest(tenant=str(tenant), model_name=model_name,
                           enc=enc, ops=ops, webhook=webhook)
        with self._lock:
            if self._inflight.get(req.tenant, 0) >= self.max_inflight():
                m.counter("serve.rejected_inflight").add(1)
                raise Rejected(
                    f"tenant {req.tenant!r} at the in-flight bound "
                    f"({self.max_inflight()}); drain verdicts first", 429,
                    retry_after_s=RETRY_AFTER_INFLIGHT_S)
            q = self._queues.get(req.tenant)
            if q is None:
                q = self._queues[req.tenant] = deque()
                self._rotation.append(req.tenant)
            q.append(req)
            self._inflight[req.tenant] = \
                self._inflight.get(req.tenant, 0) + 1
            self._pending += 1
            depth = self._pending
            self._lock.notify_all()
        m.counter("serve.requests").add(1)
        m.gauge("serve.queue_depth").set(depth)
        return req

    def submit_many(self, tenant: str, encs, model_name: str = "cas-register"
                    ) -> list[ServeRequest]:
        """Admit a WAVE of same-tenant requests under one lock
        acquisition (the campaign's check batches: thousands of tiny
        histories, where per-submit lock churn and wakeups would
        dominate). All-or-nothing against the admission bound — a wave
        that would overrun ``serve_max_inflight`` is Rejected whole, so
        the caller chunks by ``max_inflight()`` and drains between
        waves exactly like any well-behaved tenant."""
        m = obs.get_metrics()
        sup = health.get_supervisor()
        if sup.snapshot()["state"] == health.WEDGED:
            m.counter("serve.rejected_wedged").add(len(encs))
            raise Rejected(
                "backend wedged; shedding new work "
                f"(retry after {RETRY_AFTER_S}s)", 503,
                retry_after_s=RETRY_AFTER_S)
        tenant = str(tenant)
        reqs = [ServeRequest(tenant=tenant, model_name=model_name, enc=e)
                for e in encs]
        with self._lock:
            if self._inflight.get(tenant, 0) + len(reqs) \
                    > self.max_inflight():
                m.counter("serve.rejected_inflight").add(len(reqs))
                raise Rejected(
                    f"tenant {tenant!r} wave of {len(reqs)} would "
                    f"overrun the in-flight bound "
                    f"({self.max_inflight()}); chunk and drain", 429,
                    retry_after_s=RETRY_AFTER_INFLIGHT_S)
            q = self._queues.get(tenant)
            if q is None:
                q = self._queues[tenant] = deque()
                self._rotation.append(tenant)
            q.extend(reqs)
            self._inflight[tenant] = \
                self._inflight.get(tenant, 0) + len(reqs)
            self._pending += len(reqs)
            depth = self._pending
            self._lock.notify_all()
        m.counter("serve.requests").add(len(reqs))
        m.gauge("serve.queue_depth").set(depth)
        return reqs

    def model_for(self, name: str):
        """Resolved (and cached) Model instance per model name. The
        dispatch thread and session-opening handler threads race here;
        binding setdefault's RETURN re-validates under the second
        acquisition, so both racers end up using the ONE instance the
        registry actually holds (jtsan JTL503 pinned the unbound form:
        each racer kept its own instance)."""
        with self._lock:
            mdl = self._models.get(name)
        if mdl is None:
            from ..models import get_model

            mdl = get_model(name)
            with self._lock:
                mdl = self._models.setdefault(name, mdl)
        return mdl

    # -- dispatch thread --------------------------------------------------
    def _run(self) -> None:
        while not self._stop.is_set():
            with self._lock:
                while self._pending == 0 and not self._stop.is_set():
                    self._lock.wait(0.5)
            if self._stop.is_set():
                return
            # Wedged park: admitted work is NOT shed — it re-attaches
            # and drains the moment the supervisor sees a success
            # (recovery is immediate in the state machine).
            sup = health.get_supervisor()
            while sup.snapshot()["state"] == health.WEDGED \
                    and not self._stop.is_set():
                self._stop.wait(0.05)
            if self._stop.is_set():
                return
            # Max-linger: wait for more tenants' requests to coalesce
            # into this batch, bounded by serve_coalesce_ms.
            deadline = time.monotonic() + self.coalesce_s()
            with self._lock:
                while self._pending < self.max_batch() \
                        and not self._stop.is_set():
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._lock.wait(remaining)
            # Re-check the park AFTER the linger too: a backend that
            # wedged while we coalesced must not receive the batch —
            # admitted work waits out the park and drains on recovery.
            while sup.snapshot()["state"] == health.WEDGED \
                    and not self._stop.is_set():
                self._stop.wait(0.05)
            if self._stop.is_set():
                return
            # Drain under its own acquisition (a submission racing in
            # between simply rides this batch or the next one).
            batch = self._drain_batch()
            if batch:
                self._dispatch(batch)

    def _drain_batch(self) -> list[ServeRequest]:
        """Weighted-fair drain: round-robin the tenant rotation, each
        turn taking up to ``weights[tenant]`` (default 1) queued
        requests, until the batch cap or the queues run dry. Tenants
        keep their rotation slot across batches, so a backlogged
        tenant's turn comes around exactly as often as an interactive
        one's."""
        cap = self.max_batch()
        batch: list[ServeRequest] = []
        with self._lock:
            turns_without_progress = 0
            while len(batch) < cap and self._pending > 0 \
                    and turns_without_progress < len(self._rotation):
                tenant = self._rotation[0]
                self._rotation.rotate(-1)
                q = self._queues.get(tenant)
                take = max(1, int(self._weights.get(tenant, 1)))
                took = 0
                while q and took < take and len(batch) < cap:
                    batch.append(q.popleft())
                    self._pending -= 1
                    took += 1
                turns_without_progress = 0 if took else \
                    turns_without_progress + 1
        return batch

    def _dispatch(self, batch: list[ServeRequest]) -> None:
        m = obs.get_metrics()
        batch_id = next(self._batch_ids)
        sup = health.get_supervisor()
        state = sup.snapshot()["state"]
        shed = state == health.DEGRADED
        route = "cpu-oracle" if shed else "jax"
        t0 = time.monotonic()
        # Per-batch artifact tracer: a PRIVATE tracer for the store
        # artifact's span record — deliberately NOT a nested capture on
        # the global stack, which would shadow the daemon's registry
        # for every handler thread mid-batch (submit()-side serve.*
        # counters and concurrent /metrics scrapes would land in — or
        # read — the ephemeral batch registry). Kernel attribution and
        # the serve.* series stay on the daemon's own capture.
        batch_tracer = obs.Tracer(enabled=True) \
            if self._batch_telemetry else None
        error: Optional[str] = None
        with obs.get_tracer().span("serve.batch", id=batch_id,
                                   size=len(batch), route=route):
            import contextlib

            span_cm = batch_tracer.span(
                "serve.batch", id=batch_id, size=len(batch),
                route=route) if batch_tracer is not None \
                else contextlib.nullcontext()
            with span_cm:
                try:
                    if shed:
                        results, kernel = self._check_oracle(batch)
                    else:
                        try:
                            results, kernel = self._check_jax(batch)
                        except Exception as e:
                            # A dispatch failure on a not-yet-degraded
                            # backend: tell the supervisor (sched's
                            # drain already did for fetch failures) and
                            # shed THIS batch to the oracle so admitted
                            # work still gets verdicts.
                            sup.note_failure(f"{type(e).__name__}: {e}",
                                             source="serve.dispatch")
                            results, kernel = self._check_oracle(batch)
                            shed = True
                            route = "cpu-oracle"
                except Exception as e:
                    # Even the oracle failed (or the shed path itself
                    # crashed): the dispatch thread must SURVIVE — mark
                    # every request errored, release its admission
                    # slot, and wake the waiter. A dead dispatch
                    # thread would leave the daemon accepting work
                    # that never gets verdicts.
                    import logging

                    error = f"{type(e).__name__}: {e}"
                    logging.getLogger(__name__).exception(
                        "serve batch %s failed on every route", batch_id)
                    route = "error"
                    kernel = "none"
                    results = [{"valid": None, "op_count":
                                int(req.enc.n_ops), "dead_step": -1,
                                "kernel": "none", "error": error}
                               for req in batch]
        wall = time.monotonic() - t0
        fill = len(batch) / self.max_batch()
        now = time.monotonic()
        for req, res in zip(batch, results):
            latency = now - req.submitted_mono
            if error is not None:
                req.error = error
            req.result = {
                **res,
                "request_id": req.id,
                "tenant": req.tenant,
                "model": req.model_name,
                "route": route,
                "kernel": res.get("kernel", kernel),
                "batch": {"id": batch_id, "size": len(batch),
                          "fill": round(fill, 4),
                          "coalesced": len(batch) > 1,
                          "wall_s": round(wall, 4)},
                "latency_s": round(latency, 4),
            }
            m.histogram("serve.request_latency_s").observe(latency)
            self._slo_window.observe(latency, now=now)
            # Under the lock: tenant_latencies()/stats() iterate this
            # dict from handler threads — an unlocked setdefault here
            # could resize it mid-iteration (jtsan JTL501 finding).
            with self._lock:
                lat = self._tenant_latency.setdefault(
                    req.tenant, deque(maxlen=1024))
                lat.append(latency)
        m.counter("serve.batches").add(1)
        if len(batch) > 1:
            m.counter("serve.coalesced_requests").add(len(batch))
        if shed:
            m.counter("serve.shed_cpu").add(len(batch))
        m.gauge("serve.batch_fill").set(fill)
        # The live SLO cells (/live, ledger_stats): rolling-window
        # quantiles, not the cumulative histogram — a recovered daemon
        # must not wear its worst minute forever.
        p50, p99 = self._slo_window.quantiles(now=now)
        burn = self._slo_window.burn_rate(now=now)
        m.gauge("serve.slo_p50_s").set(round(p50, 6))
        m.gauge("serve.slo_p99_s").set(round(p99, 6))
        m.gauge("serve.slo_burn_rate").set(burn)
        with self._lock:
            self._batches += 1
            self._slo.update(slo_p50_s=round(p50, 6),
                             slo_p99_s=round(p99, 6),
                             slo_burn_rate=burn)
            self._requests_done += len(batch)
            self._fill_sum += fill
            if len(batch) > 1:
                self._coalesced_requests += len(batch)
            if shed:
                self._shed_cpu += len(batch)
            for req in batch:
                self._inflight[req.tenant] = \
                    max(0, self._inflight.get(req.tenant, 1) - 1)
                # Tenant-state eviction: client-supplied tenant ids
                # must not grow process state without bound — a tenant
                # with nothing queued and nothing in flight gives its
                # queue/rotation slot back (re-created on its next
                # submit; the latency window below is capped too).
                if not self._inflight.get(req.tenant) \
                        and not self._queues.get(req.tenant):
                    self._queues.pop(req.tenant, None)
                    self._inflight.pop(req.tenant, None)
                    try:
                        self._rotation.remove(req.tenant)
                    except ValueError:
                        pass
            while len(self._tenant_latency) > TENANT_LATENCY_TENANTS:
                self._tenant_latency.pop(
                    next(iter(self._tenant_latency)))
            m.gauge("serve.queue_depth").set(self._pending)
        # Waiters wake (and webhooks fire) BEFORE the store writes:
        # artifact I/O is batch-wide and must not ride every request's
        # latency — it only delays the dispatch thread's next coalesce
        # cycle, which the linger window absorbs.
        for req in batch:
            req.done.set()
            if req.webhook and self._webhook_sink is not None:
                self._webhook_sink(req)
                m.counter("serve.webhooks").add(1)
        if self._artifact_sink is not None:
            try:
                self._artifact_sink(batch, batch_tracer)
            except Exception:
                import logging

                logging.getLogger(__name__).exception(
                    "serve artifact sink failed (verdicts unaffected)")

    def _check_jax(self, batch: list[ServeRequest]
                   ) -> tuple[list[dict], str]:
        """The shared-launch path: one sched corpus submission per model
        group (different tenants' histories stack into the same bucket
        launches), awaited through the async submit face."""
        from .. import sched

        results: list[Optional[dict]] = [None] * len(batch)
        kernels: set[str] = set()
        by_model: dict[str, list[int]] = {}
        for i, req in enumerate(batch):
            by_model.setdefault(req.model_name, []).append(i)
        for name in sorted(by_model):
            idxs = by_model[name]
            model = self.model_for(name)
            outs, kernel, _stats = sched.submit_corpus(
                [batch[i].enc for i in idxs], model).result()
            kernels.add(kernel)
            for i, one in zip(idxs, outs):
                results[i] = {
                    "valid": one.get("valid"),
                    "op_count": int(batch[i].enc.n_ops),
                    "dead_step": int(one.get("dead_step", -1)),
                    "kernel": one.get("kernel", kernel),
                }
        kernel = kernels.pop() if len(kernels) == 1 else "mixed"
        # check_corpus's alignment contract: one result per input, in
        # order. A dropped slot here would zip tenant A's verdict onto
        # tenant B's request — fail loudly instead (the caller's
        # dispatch-failure handler sheds the batch to the oracle).
        missing = [i for i, r in enumerate(results) if r is None]
        if missing:
            raise RuntimeError(
                f"corpus check returned no result for batch slots "
                f"{missing} — misaligned results would cross tenants")
        return results, kernel

    def _check_oracle(self, batch: list[ServeRequest]
                      ) -> tuple[list[dict], str]:
        """The degraded shed: the exact pure-Python WGL oracle — same
        algorithm, same verdicts, zero device dispatch (a sick backend
        is never touched by admitted work)."""
        from ..checkers.linearizable import _event_to_step
        from ..checkers.oracle import check_events_oracle

        results = []
        for req in batch:
            model = self.model_for(req.model_name)
            if req.enc.n_events == 0:
                results.append({"valid": True, "op_count": 0,
                                "dead_step": -1, "kernel": ORACLE_KERNEL})
                continue
            out = check_events_oracle(req.enc, model).to_dict()
            results.append({
                "valid": out["valid"],
                "op_count": int(req.enc.n_ops),
                "dead_step": _event_to_step(req.enc,
                                            out.pop("dead_event")),
                "kernel": ORACLE_KERNEL,
            })
        return results, ORACLE_KERNEL

    # -- introspection / lifecycle ----------------------------------------
    def stats(self) -> dict:
        """The /serve/stats + bench view (copied under the lock)."""
        from .. import sched

        with self._lock:
            per_tenant = {
                t: {"inflight": self._inflight.get(t, 0),
                    "queued": len(self._queues.get(t) or ()),
                    "served": len(self._tenant_latency.get(t) or ()),
                    "latency_p50_s": quantile(
                        self._tenant_latency.get(t), 0.50),
                    "latency_p99_s": quantile(
                        self._tenant_latency.get(t), 0.99)}
                for t in sorted(self._queues)}
            out = {
                "pending": self._pending,
                "batches": self._batches,
                "requests_done": self._requests_done,
                "coalesced_requests": self._coalesced_requests,
                "shed_cpu": self._shed_cpu,
                "batch_fill_avg": round(
                    self._fill_sum / self._batches, 4)
                if self._batches else 0.0,
                "coalesce_ms": int(self.coalesce_s() * 1000),
                "max_batch": self.max_batch(),
                "max_inflight": self.max_inflight(),
                "slo": dict(self._slo),
                "tenants": per_tenant,
            }
        out["kernel_cache"] = sched.kernel_cache().stats()
        out["health"] = health.get_supervisor().snapshot()["state"]
        return out

    def tenant_latencies(self) -> dict[str, list[float]]:
        """Per-tenant recent request latencies (bounded), for the
        /metrics tenant-labeled exposition lines."""
        with self._lock:
            return {t: list(d) for t, d in self._tenant_latency.items()}

    def drain(self, timeout: float = 30.0) -> bool:
        """Block until every admitted request has a verdict (bench's
        between-arm barrier). True when drained inside the timeout."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if self._pending == 0 \
                        and not any(self._inflight.values()):
                    return True
            time.sleep(0.005)
        return False

    def close(self) -> None:
        """Stop the dispatch thread (pending requests keep their queue
        state; a daemon shutdown follows with the process)."""
        self._stop.set()
        with self._lock:
            self._lock.notify_all()
        self._thread.join(timeout=5.0)


def quantile(values, q: float) -> float:
    """Empirical quantile over a bounded latency window — the ONE copy
    /serve/stats, the /metrics tenant summaries, and the bench lane
    share (drifting duplicates would make the same window report
    different quantiles per surface)."""
    if not values:
        return 0.0
    xs = sorted(values)
    i = min(len(xs) - 1, int(q * len(xs)))
    return round(xs[i], 6)
