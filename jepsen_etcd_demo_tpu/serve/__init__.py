"""serve — checking-as-a-service (ISSUE 13 tentpole; ROADMAP item 2).

The harness as infrastructure instead of a CLI: a persistent daemon
whose HTTP API ingests histories from many concurrent tenants and whose
core is a cross-tenant continuous-batching scheduler over the
process-wide warm-kernel pool.

  * scheduler.py — the coalescing queue: per-tenant weighted-fair
    queuing, bounded in-flight admission, `serve_coalesce_ms`
    max-linger, shared sched bucket launches via the KernelPlan spine,
    supervisor-driven backpressure (degraded -> CPU oracle shed,
    wedged -> reject + park, drain on recovery)
  * sessions.py  — streaming ingestion: per-tenant stream sessions over
    the incremental encoder, sharing the compiled chunk kernels
  * daemon.py    — the HTTP surface (`jepsen-tpu serve --check`): the
    ingestion endpoints on top of web/server.py's observability plane,
    store artifacts for every verdict, webhooks
  * router.py    — the fleet router (ISSUE 18): rendezvous-hashes
    (model, sched bucket shape) to a replica so each shard's kernel
    LRU/XLA cache stays hot for its slice, with health-aware spillover
  * fleet.py     — the fleet supervisor (`jepsen-tpu serve --check
    --fleet`): spawn/adopt N replicas over one shared store root,
    zero-downtime warm restarts, the /fleet/stats surface

See doc/serve.md for the API schema and capacity-planning notes.
"""

from .scheduler import (CAMPAIGN_TENANT, CoalescingScheduler, Rejected,
                        ServeRequest)
from .sessions import ServeSession, SessionManager, op_from_dict
from .daemon import ServeDaemon, make_serve_handler, serve_check
from .router import FleetRouter, rendezvous_order, routing_key
from .fleet import FleetSupervisor, make_fleet_handler, serve_fleet

__all__ = [
    "CAMPAIGN_TENANT",
    "CoalescingScheduler",
    "FleetRouter",
    "FleetSupervisor",
    "Rejected",
    "ServeDaemon",
    "ServeRequest",
    "ServeSession",
    "SessionManager",
    "make_fleet_handler",
    "make_serve_handler",
    "op_from_dict",
    "rendezvous_order",
    "routing_key",
    "serve_check",
    "serve_fleet",
]
