"""`jepsen-tpu serve --check` — the checking-as-a-service HTTP daemon.

The traffic half of ROADMAP item 2 on top of the PR 7 operational half:
one long-running process whose HTTP surface ingests histories from many
concurrent clients and whose core is the continuous-batching scheduler
(scheduler.py) over the process-wide warm-kernel pool. The handler
extends web/server.py's StoreHandler, so the daemon serves the full
observability plane (/metrics with serve.* families + per-tenant
latency summaries, /healthz, /live, the run index) next to the
ingestion API:

  POST /check                     submit one history single-shot
      {"tenant": "t1", "model": "cas-register",
       "history": [ {op}, ... ],        # history.jsonl entry objects
       "wait": true,                    # default: block for the verdict
       "timeout_s": 60,                 # wait bound -> 202 + poll URL
       "webhook": "http://..."}         # optional verdict callback
  GET  /check/<request-id>        poll an async submission
  POST /serve/session             open a streaming session
      {"tenant": "t1", "model": "cas-register", "keyed": false}
  POST /serve/session/<id>/ops    feed ops ({"ops": [ {op}, ... ]})
  POST /serve/session/<id>/close  drain + finalize -> verdict
  GET  /serve/stats               scheduler + session stats JSON

Backpressure surfaces as HTTP codes (scheduler.Rejected): 429 when a
tenant hits the in-flight bound, 503 + Retry-After while the backend
supervisor says wedged; degraded sheds checks to the CPU oracle path
(the verdict JSON's `route` says which path served it). Every verdict
lands in the store as a browsable run (store/serve/<ts>-<id>/ with
test.json / history.jsonl / results.json + the batch's telemetry), so
served checks are history on the web index, not ghosts."""

from __future__ import annotations

import json
import sys
import threading
import time
import urllib.request
from collections import OrderedDict
from http.server import ThreadingHTTPServer
from pathlib import Path
from typing import Any, Optional

from .. import obs
from ..obs import TELEMETRY_FILE, export
from ..store.store import RunDir, Store
from ..web import server as web_server
from .scheduler import (CoalescingScheduler, Rejected, ServeRequest,
                        quantile)
from .sessions import SessionManager, op_from_dict

# Completed-request registry bound: polled verdicts of finished
# requests stay addressable this long after completion, oldest
# COMPLETED entry evicted (pending requests stay pollable — their
# count is already bounded by the per-tenant admission control).
REQUEST_REGISTRY_CAP = 4096
# Tenants rendered on the per-tenant /metrics latency summaries —
# bounded so client-supplied tenant ids cannot explode the exposition.
METRICS_TENANT_CAP = 32
DEFAULT_WAIT_TIMEOUT_S = 120.0
# Largest request body accepted (client-supplied Content-Length must
# not size an unbounded read — every other client-supplied dimension
# is capped too). 64 MiB fits ~100k-op histories with headroom.
MAX_BODY_BYTES = 64 << 20


class ServeDaemon:
    """Process state shared by every handler thread: the scheduler, the
    streaming sessions, the request registry, and the store sink."""

    def __init__(self, store_root: str = "store",
                 default_model: str = "cas-register",
                 coalesce_ms: Optional[int] = None,
                 max_batch: Optional[int] = None,
                 max_inflight: Optional[int] = None,
                 write_artifacts: bool = True,
                 warmup: Optional[dict] = None):
        from ..obs.sync import maybe_wrap

        self.store = Store(store_root)
        self.default_model = default_model
        # The startup warmup record (sched/warmup.startup_warmup), or
        # None when skipped — /healthz surfaces it so the fleet router
        # never routes to a cold replica (ISSUE 18 satellite).
        self.warmup_record = warmup
        self.ready = threading.Event()
        self.ready.set()
        self._write_artifacts = write_artifacts
        self._lock = maybe_wrap(threading.Lock(),
                                "serve.daemon.ServeDaemon._lock")
        self._requests: "OrderedDict[str, ServeRequest]" = OrderedDict()
        self._lins: dict[str, Any] = {}     # model name -> Linearizable
        self.scheduler = CoalescingScheduler(
            coalesce_ms=coalesce_ms, max_batch=max_batch,
            max_inflight=max_inflight,
            artifact_sink=self._artifact_sink if write_artifacts else None,
            webhook_sink=self._webhook_sink,
            batch_telemetry=write_artifacts)
        self.sessions = SessionManager(max_per_tenant=max_inflight)

    # -- request plumbing -------------------------------------------------
    def encode(self, model_name: str, ops: list) -> Any:
        """History -> EncodedHistory through the same checker-side
        encoder `analyze` uses (model translation + slot escalation), so
        served verdicts are bit-identical to the post-hoc path's."""
        from ..checkers.linearizable import Linearizable

        with self._lock:
            lin = self._lins.get(model_name)
        if lin is None:
            lin = Linearizable(model=model_name)
            with self._lock:
                lin = self._lins.setdefault(model_name, lin)
        history = [op for op in ops if op.process != "nemesis"]
        return lin.encode(history)

    def submit(self, tenant: str, model_name: str, ops: list,
               webhook: Optional[str] = None) -> ServeRequest:
        enc = self.encode(model_name, ops)
        req = self.scheduler.submit(tenant, enc, model_name=model_name,
                                    ops=ops, webhook=webhook)
        with self._lock:
            self._requests[req.id] = req
            if len(self._requests) > REQUEST_REGISTRY_CAP:
                # Evict oldest COMPLETED entries only: a pending
                # request's poll URL must keep answering until its
                # verdict lands (202 + poll is the async contract).
                done_ids = [rid for rid, r in self._requests.items()
                            if r.done.is_set()]
                for rid in done_ids[:len(self._requests)
                                    - REQUEST_REGISTRY_CAP]:
                    self._requests.pop(rid, None)
        return req

    def request(self, request_id: str) -> Optional[ServeRequest]:
        with self._lock:
            return self._requests.get(request_id)

    # -- sinks (scheduler dispatch thread) --------------------------------
    def _artifact_sink(self, batch: list[ServeRequest],
                       batch_tracer) -> None:
        """Persist each verdict as a browsable store run (the web
        index's per-run layout): test.json + history.jsonl +
        results.json, plus the batch's span record. A shared batch
        legitimately writes the SAME telemetry into every member — the
        launch was shared; that is the point."""
        for req in batch:
            if req.result is None:
                continue
            serve_meta = {"tenant": req.tenant, "model": req.model_name,
                          "request_id": req.id}
            run = self._write_serve_run(
                req.id, serve_meta, req.ops,
                valid=req.result.get("valid"),
                serve_record={k: v for k, v in req.result.items()
                              if k != "_enc"})
            if run is not None and batch_tracer is not None:
                try:
                    batch_tracer.write(run.path / TELEMETRY_FILE)
                except OSError:
                    pass   # telemetry is an aid, never a failure mode

    def _write_serve_run(self, ident: str, serve_meta: dict,
                         ops, valid, serve_record: dict
                         ) -> Optional[RunDir]:
        """The ONE serve run-dir layout (single-shot requests and
        streamed sessions share it, so the web index renders both
        identically): store/serve/<ts>Z-<id12>/ with test.json /
        history.jsonl / results.json[check_mode=serve]."""
        try:
            ts = time.strftime("%Y%m%dT%H%M%S", time.gmtime())
            path = self.store.root / "serve" / f"{ts}Z-{ident[:12]}"
            path.mkdir(parents=True, exist_ok=True)
            run = RunDir(path)
            run.write_test({"name": "serve", "workload": "serve",
                            "serve": serve_meta})
            if ops:
                run.write_history(ops)
            run.write_results({"valid": valid, "check_mode": "serve",
                               "serve": serve_record})
            return run
        except OSError:
            return None

    def _webhook_sink(self, req: ServeRequest) -> None:
        """Fire-and-forget verdict callback: POST the result JSON to the
        request's webhook URL from a short-lived thread (delivery
        failures are logged, never block the dispatch loop)."""
        def deliver():
            try:
                body = json.dumps(req.result).encode()
                r = urllib.request.Request(
                    req.webhook, data=body,
                    headers={"Content-Type": "application/json"})
                urllib.request.urlopen(r, timeout=10).read()
            except Exception:
                import logging

                logging.getLogger(__name__).warning(
                    "webhook delivery to %s failed for request %s",
                    req.webhook, req.id)

        threading.Thread(target=deliver, name="serve-webhook",
                         daemon=True).start()

    # -- /metrics extras --------------------------------------------------
    def tenant_metric_lines(self) -> list[str]:
        """Bounded per-tenant latency summaries + request counts for the
        /metrics exposition (client-supplied tenant ids are capped at
        METRICS_TENANT_CAP so they cannot explode label cardinality)."""
        lats = self.scheduler.tenant_latencies()
        if not lats:
            return []
        lines = ["# TYPE jepsen_tpu_serve_tenant_latency_seconds summary",
                 "# TYPE jepsen_tpu_serve_tenant_requests_total counter"]
        for tenant in sorted(lats)[:METRICS_TENANT_CAP]:
            xs = lats[tenant]
            if not xs:
                continue
            lv = export.sanitize_label_value(tenant)
            for q in (0.5, 0.95, 0.99):
                lines.append(
                    f'jepsen_tpu_serve_tenant_latency_seconds'
                    f'{{tenant="{lv}",quantile="{q:g}"}} '
                    f'{quantile(xs, q):.6g}')
            lines.append(f'jepsen_tpu_serve_tenant_requests_total'
                         f'{{tenant="{lv}"}} {len(xs)}')
        return lines

    def stats(self) -> dict:
        return {"scheduler": self.scheduler.stats(),
                "sessions": self.sessions.stats()}

    def close(self) -> None:
        """Shut down BOTH thread sources: the dispatch thread and every
        open streaming session's consumer (the latter was the jtsan
        JTL505 shutdown gap — sessions kept their encoder state and
        threads past close)."""
        self.sessions.close_all()
        self.scheduler.close()


class ServeHandler(web_server.StoreHandler):
    """StoreHandler (run index, /metrics, /healthz, /live, telemetry
    pages) + the checking-as-a-service ingestion endpoints."""

    daemon_obj: ServeDaemon = None   # bound by make_serve_handler

    # -- helpers ----------------------------------------------------------
    def _send_json(self, body: dict, status: int = 200,
                   headers: Optional[dict] = None) -> None:
        payload = (json.dumps(body, indent=2, default=str) + "\n").encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json; charset=utf-8")
        self.send_header("Content-Length", str(len(payload)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(payload)

    def _read_body(self) -> dict:
        n = int(self.headers.get("Content-Length") or 0)
        if n <= 0:
            return {}
        if n > MAX_BODY_BYTES:
            raise ValueError(
                f"request body of {n} bytes exceeds the "
                f"{MAX_BODY_BYTES}-byte bound")
        raw = self.rfile.read(n)
        body = json.loads(raw.decode("utf-8"))
        if not isinstance(body, dict):
            raise ValueError("request body must be a JSON object")
        return body

    def _rejected(self, e: Rejected) -> None:
        headers = {}
        if e.retry_after_s is not None:
            headers["Retry-After"] = str(int(e.retry_after_s))
        self._send_json({"error": e.reason, "rejected": True},
                        status=e.status, headers=headers)

    def _result_view(self, req: ServeRequest) -> dict:
        return {k: v for k, v in (req.result or {}).items()
                if k != "_enc"}

    # -- POST -------------------------------------------------------------
    def do_POST(self):
        d = self.daemon_obj
        path = self.path.rstrip("/")
        try:
            if path == "/check":
                return self._post_check(d)
            if path == "/serve/session":
                return self._post_session_open(d)
            if path.startswith("/serve/session/"):
                rest = path[len("/serve/session/"):]
                if rest.endswith("/ops"):
                    return self._post_session_ops(d, rest[:-len("/ops")])
                if rest.endswith("/close"):
                    return self._post_session_close(
                        d, rest[:-len("/close")])
            self._send_json({"error": f"unknown endpoint {self.path}"},
                            status=404)
        except Rejected as e:
            self._rejected(e)
        except (ValueError, KeyError, json.JSONDecodeError) as e:
            self._send_json({"error": f"bad request: {e}"}, status=400)
        except (BrokenPipeError, ConnectionResetError):
            pass
        except Exception as e:   # a handler bug must not kill the thread
            self._send_json({"error": f"{type(e).__name__}: {e}"},
                            status=500)

    def _post_check(self, d: ServeDaemon) -> None:
        body = self._read_body()
        tenant = str(body.get("tenant") or "default")
        model_name = str(body.get("model") or d.default_model)
        raw_ops = body.get("history")
        if not isinstance(raw_ops, list) or not raw_ops:
            raise ValueError("history must be a non-empty list of ops")
        ops = [op_from_dict(o) for o in raw_ops]
        req = d.submit(tenant, model_name, ops,
                       webhook=body.get("webhook"))
        if body.get("wait", True):
            timeout = float(body.get("timeout_s",
                                     DEFAULT_WAIT_TIMEOUT_S))
            if req.wait(timeout):
                return self._send_json(self._result_view(req))
        self._send_json({"request_id": req.id, "pending": True,
                         "poll": f"/check/{req.id}"}, status=202)

    def _post_session_open(self, d: ServeDaemon) -> None:
        body = self._read_body()
        tenant = str(body.get("tenant") or "default")
        model_name = str(body.get("model") or d.default_model)
        model = d.scheduler.model_for(model_name)
        sess = d.sessions.open(tenant, model, model_name,
                               keyed=bool(body.get("keyed", False)))
        self._send_json({"session_id": sess.id, "tenant": tenant,
                         "model": model_name,
                         "ops": f"/serve/session/{sess.id}/ops",
                         "close": f"/serve/session/{sess.id}/close"},
                        status=201)

    def _post_session_ops(self, d: ServeDaemon, session_id: str) -> None:
        sess = d.sessions.get(session_id)
        if sess is None:
            return self._send_json(
                {"error": f"no session {session_id}"}, status=404)
        body = self._read_body()
        raw_ops = body.get("ops")
        if not isinstance(raw_ops, list):
            raise ValueError("ops must be a list")
        self._send_json(sess.feed([op_from_dict(o) for o in raw_ops]))

    def _post_session_close(self, d: ServeDaemon,
                            session_id: str) -> None:
        sess = d.sessions.get(session_id)
        if sess is None:
            return self._send_json(
                {"error": f"no session {session_id}"}, status=404)
        ops = sess.ops
        verdict = d.sessions.close(session_id)
        if verdict is None:   # closed concurrently
            return self._send_json(
                {"error": f"no session {session_id}"}, status=404)
        if d._write_artifacts:
            d._write_serve_run(
                verdict["session_id"],
                {"tenant": verdict.get("tenant"),
                 "model": verdict.get("model"),
                 "session_id": verdict["session_id"],
                 "streamed": True},
                ops, verdict.get("valid"), verdict)
        self._send_json(verdict)

    # -- GET --------------------------------------------------------------
    def do_GET(self):
        d = self.daemon_obj
        path = self.path.rstrip("/")
        try:
            if path.startswith("/check/"):
                rid = path[len("/check/"):]
                req = d.request(rid)
                if req is None:
                    return self._send_json(
                        {"error": f"no request {rid}"}, status=404)
                if req.done.is_set():
                    return self._send_json(self._result_view(req))
                return self._send_json(
                    {"request_id": rid, "pending": True}, status=202)
            if path == "/serve/stats":
                return self._send_json(d.stats())
            if path == "/healthz":
                # The StoreHandler healthz (supervisor snapshot, 503
                # when wedged) + the replica's serving readiness and
                # warmup provenance, so a fleet router can distinguish
                # a cold replica from a merely healthy one.
                status, body = web_server._healthz()
                wrec = d.warmup_record
                body["serve"] = {
                    "ready": d.ready.is_set(),
                    "warmed": wrec is not None,
                    "warmup_launches": (wrec or {}).get("launches", 0),
                    "warmup_families": (wrec or {}).get("families", []),
                }
                return self._send_json(body, status=status)
            if path == "/metrics":
                text = web_server._metrics_text()
                extra = d.tenant_metric_lines()
                if extra:
                    text = text.rstrip("\n") + "\n" \
                        + "\n".join(extra) + "\n"
                return self._send_payload(text.encode(),
                                          export.PROM_CONTENT_TYPE)
        except (BrokenPipeError, ConnectionResetError):
            return
        return super().do_GET()


def make_serve_handler(store_root: str, daemon: ServeDaemon):
    class _Bound(ServeHandler):
        daemon_obj = daemon

        def __init__(self, *args, **kw):
            super().__init__(*args, store_root=store_root, **kw)

    return _Bound


def serve_check(store_root: str = "store", host: str = "127.0.0.1",
                port: int = 8080, default_model: str = "cas-register",
                coalesce_ms: Optional[int] = None,
                max_batch: Optional[int] = None,
                max_inflight: Optional[int] = None,
                ready_file: Optional[str] = None,
                warmup: Optional[dict] = None) -> int:
    """Run the checking daemon until interrupted. Binds first and
    prints one JSON line naming the actual port (port 0 = ephemeral —
    the subprocess-integration contract), optionally also written to
    ``ready_file`` for parentless discovery. The whole daemon lifetime
    runs under one obs capture so /metrics and /live are live."""
    daemon = ServeDaemon(store_root=store_root,
                         default_model=default_model,
                         coalesce_ms=coalesce_ms, max_batch=max_batch,
                         max_inflight=max_inflight, warmup=warmup)
    httpd = ThreadingHTTPServer((host, port),
                                make_serve_handler(store_root, daemon))
    actual_port = httpd.server_address[1]
    # `warmed` rides the ready line/file: cmd_serve runs the startup
    # warmup BEFORE serve_check, so ready implies warm (unless the
    # JEPSEN_TPU_NO_WARMUP kill switch skipped it) — the fleet
    # supervisor's zero-downtime restart gates on exactly this record.
    ready = {"serving": f"http://{host}:{actual_port}",
             "port": actual_port, "store": str(store_root),
             "check": True, "warmed": warmup is not None}
    print(json.dumps(ready), flush=True)
    if ready_file:
        Path(ready_file).write_text(json.dumps(ready))
    with obs.capture():
        try:
            httpd.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            daemon.close()
            httpd.server_close()
            # Fold the jtsan runtime sanitizer's witness table (empty
            # unless JEPSEN_TPU_SYNC_TRACE=1) into the daemon's final
            # metrics snapshot — doc/telemetry.md "Sync trace".
            from ..obs import sync as obs_sync

            obs_sync.publish_metrics()
    return 0
