"""Streaming serve sessions: many tenants' live op feeds, one warm pool.

The single-shot ``POST /check`` path coalesces whole histories; this is
the other ingestion mode the tentpole names — a tenant opens a session,
POSTs ops as they happen, and the daemon checks the stable prefix WHILE
the tenant's run is still going (exactly ``--check-mode stream``, with
the network replacing the in-process recorder listener).

Each session wraps a :class:`stream.engine.StreamSession` (incremental
encoder -> watermark -> resumable dense chunk dispatch). Multiplexing
across sessions happens one layer down, by construction: every
session's chunk launches resolve through ``plan_stream_chunk`` against
the ONE process-wide kernel LRU keyed by ``plan.cache_key()``, so
session N+1's (cfg, chunk) shapes reuse session N's compiled kernels —
cross-tenant warm-pool sharing on the streaming path, same as the
coalesced batches on the single-shot path. Sessions are admitted under
the same per-tenant in-flight bound and the same supervisor gate as
single-shot work (wedged -> 503 + Retry-After at open)."""

from __future__ import annotations

import threading
import time
import uuid
from typing import Any, Optional

from ..obs import health
from ..obs.sync import maybe_wrap
from ..ops.op import Op
from .scheduler import RETRY_AFTER_INFLIGHT_S, RETRY_AFTER_S, Rejected

# Bounds on client-driven session state (the same no-unbounded-growth
# discipline the scheduler applies to tenant queues): most sessions
# open at once across ALL tenants, and the idle age past which an
# abandoned session is finalized and dropped at the next open() (each
# open session holds an encoder + a consumer thread).
MAX_OPEN_SESSIONS = 512
SESSION_IDLE_TTL_S = 900.0


class ServeSession:
    """One tenant's streaming check session. Ops are re-stamped with a
    session-local monotonic ``seq`` (the recorder's contract the
    incremental encoder's watermark rests on), so clients submit plain
    op JSON without sequencing obligations beyond in-order delivery."""

    def __init__(self, tenant: str, model, model_name: str,
                 keyed: bool = False):
        from ..stream.engine import StreamSession

        self.id = uuid.uuid4().hex
        self.tenant = tenant
        self.model_name = model_name
        self.created_mono = time.monotonic()
        self.last_fed_mono = time.monotonic()
        self.ops_fed = 0
        self._seq = 0
        self._closed = False
        # Guards the seq stamp + feed order: each POST /ops runs on its
        # own HTTP handler thread, and the incremental encoder's
        # watermark rests on strictly-increasing seq in arrival order —
        # interleaved stamping would corrupt the stable prefix.
        self._lock = maybe_wrap(threading.Lock(),
                                "serve.sessions.ServeSession._lock")
        self._session = StreamSession(model, keyed=keyed)
        self._ops: list[Op] = []    # the full feed, for store artifacts

    def feed(self, ops: list[Op]) -> dict:
        # Same supervisor gate as single-shot admission: a wedged
        # backend takes no new streaming work either (the session
        # itself survives — the client retries the chunk).
        sup = health.get_supervisor()
        if sup.snapshot()["state"] == health.WEDGED:
            raise Rejected("backend wedged; not accepting stream ops "
                           f"(retry after {RETRY_AFTER_S}s)", 503,
                           retry_after_s=RETRY_AFTER_S)
        with self._lock:
            if self._closed:
                # A feed racing a concurrent close must not answer
                # "accepted" for ops that were silently dropped.
                raise Rejected(f"session {self.id} already closed", 409)
            for op in ops:
                op.seq = self._seq
                self._seq += 1
                self._ops.append(op)
                self._session.feed(op)
            self.ops_fed += len(ops)
            self.last_fed_mono = time.monotonic()
            return {"accepted": len(ops), "ops_fed": self.ops_fed,
                    "falsified": self._session.falsified()}

    def close(self) -> dict:
        """Drain + finalize: the session verdict. Keys the stream
        abandoned (infeasible geometry, malformed shapes) re-run through
        the post-hoc oracle of record — the daemon reports them
        ``streamed: false`` rather than guessing.

        The lock only latches ``_closed`` (so a racing feed gets its
        409 and no op lands after the latch); ``finalize()`` — which
        JOINS the stream consumer thread — runs OUTSIDE it. Joining
        under the lock stalled every other session call behind the
        drain (jtsan JTL504), and once ``_closed`` is set no feed can
        touch ``_session`` again, so the unlock is safe."""
        with self._lock:
            self._closed = True
            fed = self.ops_fed
        results = self._session.finalize()
        stats = self._session.stats()
        if results is None:
            return {"valid": None, "streamed": False,
                    "error": stats.get("fallback",
                                       "no streamable verdicts"),
                    "stream": stats, "ops_fed": fed}
        keys = {}
        valid = True
        for key, res in sorted(results.items(), key=lambda kv: str(kv[0])):
            keys[str(key) if key is not None else "_"] = {
                "valid": res.get("valid"),
                "dead_step": int(res.get("dead_step", -1)),
                "op_count": int(res.get("op_count", 0)),
                "kernel": res.get("kernel"),
            }
            if res.get("valid") is not True:
                valid = False
        return {"valid": valid, "streamed": True, "keys": keys,
                "stream": stats, "ops_fed": fed}

    def idle_at(self) -> float:
        """Last-fed monotonic stamp, read under the session lock — the
        reaper's view (feed() writes it under the same lock; an
        unlocked read from the manager thread was a jtsan JTL501
        divergent-lockset shape)."""
        with self._lock:
            return self.last_fed_mono

    @property
    def ops(self) -> list[Op]:
        with self._lock:
            return list(self._ops)


class SessionManager:
    """Admission + registry for the daemon's streaming sessions."""

    def __init__(self, max_per_tenant: Optional[int] = None):
        self._max_per_tenant = max_per_tenant
        self._lock = maybe_wrap(threading.Lock(),
                                "serve.sessions.SessionManager._lock")
        # jtsan: guarded-by=self._lock
        self._sessions: dict[str, ServeSession] = {}
        # jtsan: guarded-by=self._lock
        self._per_tenant: dict[str, int] = {}

    def _cap(self) -> int:
        if self._max_per_tenant is not None:
            return self._max_per_tenant
        from ..ops.limits import limits

        return limits().serve_max_inflight

    def open(self, tenant: str, model, model_name: str,
             keyed: bool = False) -> ServeSession:
        sup = health.get_supervisor()
        if sup.snapshot()["state"] == health.WEDGED:
            raise Rejected("backend wedged; not opening new stream "
                           f"sessions (retry after {RETRY_AFTER_S}s)",
                           503, retry_after_s=RETRY_AFTER_S)
        tenant = str(tenant)
        self._reap_idle()
        with self._lock:
            if len(self._sessions) >= MAX_OPEN_SESSIONS:
                raise Rejected(
                    f"daemon at the global session bound "
                    f"({MAX_OPEN_SESSIONS}); close sessions first", 429,
                    retry_after_s=RETRY_AFTER_INFLIGHT_S)
            if self._per_tenant.get(tenant, 0) >= self._cap():
                raise Rejected(
                    f"tenant {tenant!r} at the session bound "
                    f"({self._cap()}); close sessions first", 429,
                    retry_after_s=RETRY_AFTER_INFLIGHT_S)
            sess = ServeSession(tenant, model, model_name, keyed=keyed)
            self._sessions[sess.id] = sess
            self._per_tenant[tenant] = self._per_tenant.get(tenant, 0) + 1
        return sess

    def _reap_idle(self) -> None:
        """Finalize + drop sessions idle past SESSION_IDLE_TTL_S —
        abandoned sessions must not hold their encoder state and
        consumer thread forever (run lazily on open(), so an idle
        daemon spends nothing)."""
        cutoff = time.monotonic() - SESSION_IDLE_TTL_S
        # Snapshot the registry under the manager lock, probe each
        # session's locked idle_at() AFTER releasing it: taking every
        # session lock while holding the manager lock would convoy all
        # tenants' opens behind one tenant's bulk feed (the JTL504
        # shape, held one level up).
        with self._lock:
            sessions = list(self._sessions.items())
        stale = [sid for sid, s in sessions if s.idle_at() < cutoff]
        for sid in stale:
            self.close(sid)

    def get(self, session_id: str) -> Optional[ServeSession]:
        with self._lock:
            return self._sessions.get(session_id)

    def close(self, session_id: str) -> Optional[dict]:
        with self._lock:
            sess = self._sessions.pop(session_id, None)
            if sess is not None:
                n = self._per_tenant.get(sess.tenant, 1) - 1
                if n > 0:
                    self._per_tenant[sess.tenant] = n
                else:
                    self._per_tenant.pop(sess.tenant, None)
        if sess is None:
            return None
        verdict = sess.close()
        verdict["session_id"] = session_id
        verdict["tenant"] = sess.tenant
        verdict["model"] = sess.model_name
        return verdict

    def close_all(self) -> int:
        """Finalize every open session — the daemon's shutdown path.
        Each open session holds an incremental encoder and a live
        consumer thread; a daemon close() that only stopped the
        scheduler leaked them past shutdown (jtsan JTL505's unjoined-
        thread gap). Returns how many sessions were closed."""
        with self._lock:
            open_ids = list(self._sessions)
        n = 0
        for sid in open_ids:
            if self.close(sid) is not None:
                n += 1
        return n

    def stats(self) -> dict:
        with self._lock:
            return {"open_sessions": len(self._sessions),
                    "per_tenant": dict(self._per_tenant)}


def op_from_dict(d: dict[str, Any]) -> Op:
    """One history entry from the HTTP JSON shape — the same fields as a
    history.jsonl line (ops/op.py). 2-lists normalize to tuples so
    independent (key, value) ops survive the JSON trip."""
    if not isinstance(d, dict) or "type" not in d or "f" not in d:
        raise ValueError(f"op entry must be an object with type/f: {d!r}")
    v = d.get("value")
    if isinstance(v, list) and len(v) == 2:
        v = tuple(v)
    return Op(type=str(d["type"]), f=str(d["f"]), value=v,
              process=d.get("process", 0), time=int(d.get("time", 0)),
              index=int(d.get("index", -1)), error=d.get("error"),
              seq=int(d.get("seq", -1)))
