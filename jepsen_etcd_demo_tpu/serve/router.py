"""Shape-affine fleet router (ISSUE 18 tentpole).

One serve replica's kernel LRU and XLA cache are the only warm state in
the world; a fleet of N replicas behind a shape-BLIND balancer compiles
every (model, bucket) geometry N times and keeps N copies resident.
This router closes that gap: it hashes the request's *routing key* —
``(model, sched step bucket)``, i.e. the plan cache key
(plan/core.py `KernelPlan.cache_key`) minus the mesh, which a replica
derives locally — to a replica via rendezvous (HRW) hashing, so each
shard's kernel LRU and persistent XLA cache stay hot for *its* slice of
shape space and a replica joining/leaving only re-deals 1/N of keys.

Health-aware spillover: the router polls every replica's ``/healthz``
(plus passive connect-failure signals) and walks the rendezvous
preference order, skipping replicas per the ``fleet_spillover_mode``
knob (ops/limits.py):

* 0 — affine with spillover: prefer the key's owner, spill down the
  HRW order past ``degraded``/``wedged``/``down`` replicas (degraded
  still serves as last resort — shedding load elsewhere is exactly
  what a degraded replica wants).
* 1 — strict affinity: owner or 503 (capacity experiments).
* 2 — random: ignore the key (the bench's control arm).

Wedged/down replicas are *drained*: no new work, re-admitted the first
time a ``/healthz`` poll comes back clean. Per-replica state is
surfaced on ``/fleet/stats`` and the fleet.* counters/gauges
(obs/__init__.py, pre-registered on every capture) on ``/metrics``.

The router is deliberately thin: stdlib HTTP client, no jax import —
the step-bucket ladder is 6 lines of integer math mirrored from
ops/wgl3.step_bucket (drift-pinned by tests/test_fleet.py).
"""

from __future__ import annotations

import hashlib
import json
import threading
import urllib.error
import urllib.request
from collections import OrderedDict
from typing import Any, Optional

from ..obs.sync import maybe_wrap

#: Spillover modes (fleet_spillover_mode knob).
AFFINE, STRICT, RANDOM = 0, 1, 2

#: Replica routing states. READY accepts traffic; COLD is spawned but
#: not yet past its --ready-file contract; DEGRADED serves only as
#: spillover of last resort; WEDGED/DOWN are drained until a clean
#: /healthz poll re-admits them.
READY, COLD, DEGRADED, WEDGED, DOWN = (
    "ready", "cold", "degraded", "wedged", "down")

#: States the router will hand new work to, in preference tiers.
_ROUTABLE = (READY, DEGRADED)

#: Stickiness maps are bounded: verdict ids older than this many
#: entries fall out (matches the daemon's own results ring order of
#: magnitude — a poller that lost the race re-submits, checks are pure).
STICKY_CAP = 4096


def step_bucket(n_steps: int, floor: int) -> int:
    """The {2^k, 1.5*2^k} step-bucket ladder — the same boundary set
    the corpus scheduler groups launches by. Mirrors ops/wgl3
    .step_bucket (pure int math; re-stated here so the router never
    imports jax). Parity is pinned by tests/test_fleet.py."""
    r = max(1, floor)
    while r < n_steps:
        if r + r // 2 >= n_steps:
            return r + r // 2
        r *= 2
    return r


def routing_key(model: str, history: list[dict], floor: int) -> str:
    """``(model, sched bucket shape)`` as a string — the plan cache key
    minus the mesh. The shape a replica compiles for is set by the step
    bucket of the history's *completion* count (ops/encode.py builds
    one return step per ok/fail/info, nemesis ops excluded), so one
    cheap pass over the raw op dicts lands the request on the replica
    whose kernel LRU already holds that geometry."""
    steps = 0
    for op in history:
        if not isinstance(op, dict):
            continue
        if op.get("process") == "nemesis":
            continue
        if op.get("type") in ("ok", "fail", "info"):
            steps += 1
    return f"{model}|r{step_bucket(max(1, steps), floor)}"


def rendezvous_order(key: str, replica_ids: list[str],
                     salt: int = 0) -> list[str]:
    """Replica ids in highest-random-weight order for `key`: each
    replica scores sha1(salt|key|id); the max owns the key and the
    descending order IS the spillover preference. Removing a replica
    re-deals only its own keys; adding one steals 1/N from everyone."""
    prefix = f"{salt}|{key}|".encode()
    return sorted(
        replica_ids,
        key=lambda rid: hashlib.sha1(prefix + rid.encode()).digest(),
        reverse=True)


class Replica:
    """One serve --check replica as the router sees it: base URL,
    routing state, passive/active health evidence, and per-replica
    traffic counters (surfaced on /fleet/stats)."""

    def __init__(self, rid: str, url: str):
        self.id = rid
        self.url = url.rstrip("/")
        self.state = COLD
        self.last_error: Optional[str] = None
        self.last_healthz: dict[str, Any] = {}
        self.routed = 0          # requests this replica owned
        self.spilled_in = 0      # requests it served for another owner
        self.consecutive_failures = 0


class FleetRouter:
    """Rendezvous-hash router over N serve replicas with health-aware
    spillover and warm hand-off (serve/fleet.py swaps a warmed
    replacement in atomically before the old replica drains)."""

    def __init__(self, *, salt: Optional[int] = None,
                 spillover_mode: Optional[int] = None,
                 bucket_floor: Optional[int] = None,
                 poll_interval_s: float = 1.0,
                 request_timeout_s: float = 300.0,
                 health_timeout_s: float = 5.0):
        from ..ops.limits import limits
        lim = limits()
        self.salt = lim.fleet_hash_salt if salt is None else int(salt)
        self.mode = (lim.fleet_spillover_mode if spillover_mode is None
                     else int(spillover_mode))
        self.bucket_floor = (lim.step_bucket_floor if bucket_floor is None
                             else int(bucket_floor))
        self.poll_interval_s = poll_interval_s
        self.request_timeout_s = request_timeout_s
        self.health_timeout_s = health_timeout_s
        self._lock = maybe_wrap(threading.Lock(),
                                "serve.router.FleetRouter._lock")
        # jtsan: guarded-by=self._lock
        self._replicas: dict[str, Replica] = {}
        # jtsan: guarded-by=self._lock
        self._verdict_origin: OrderedDict[str, str] = OrderedDict()
        # jtsan: guarded-by=self._lock
        self._session_origin: OrderedDict[str, str] = OrderedDict()
        self._rr = 0             # jtsan: guarded-by=self._lock
        self._closed = threading.Event()
        self._poller: Optional[threading.Thread] = None

    # ------------------------------------------------------------------
    # membership

    def add_replica(self, url: str, rid: Optional[str] = None,
                    state: str = COLD) -> Replica:
        rep = Replica(rid or url.rsplit(":", 1)[-1], url)
        with self._lock:
            rep.state = state
            self._replicas[rep.id] = rep
        return rep

    def remove_replica(self, rid: str) -> Optional[Replica]:
        """Drop a replica from the hash ring (its keys re-deal to the
        survivors). The caller owns draining/terminating the process."""
        with self._lock:
            rep = self._replicas.pop(rid, None)
            for sticky in (self._verdict_origin, self._session_origin):
                stale = [k for k, v in sticky.items() if v == rid]
                for k in stale:
                    del sticky[k]
        return rep

    def swap_replica(self, old_rid: str, url: str,
                     rid: Optional[str] = None) -> Replica:
        """Zero-downtime hand-off: admit the (already warm) replacement
        READY and drop the old replica in one lock hold, so no routing
        decision ever sees neither."""
        rep = Replica(rid or url.rsplit(":", 1)[-1], url)
        with self._lock:
            rep.state = READY
            self._replicas[rep.id] = rep
            self._replicas.pop(old_rid, None)
            for sticky in (self._verdict_origin, self._session_origin):
                stale = [k for k, v in sticky.items() if v == old_rid]
                for k in stale:
                    del sticky[k]
        from .. import obs
        obs.get_metrics().counter("fleet.restarts").add(1)
        return rep

    def replica_ids(self) -> list[str]:
        with self._lock:
            return list(self._replicas)

    # ------------------------------------------------------------------
    # health

    def poll_health_once(self) -> None:
        """One active /healthz sweep: state transitions READY/DEGRADED/
        WEDGED from the body, DOWN on connect failure; a clean poll
        re-admits a drained replica (the recovery path)."""
        with self._lock:
            targets = [(r.id, r.url) for r in self._replicas.values()]
        for rid, url in targets:
            state, body, err = self._probe(url)
            with self._lock:
                rep = self._replicas.get(rid)
                if rep is None:
                    continue
                rep.last_healthz = body
                rep.last_error = err
                if state is not None:
                    rep.state = state
                    rep.consecutive_failures = 0
                else:
                    rep.consecutive_failures += 1
                    rep.state = DOWN

    def _probe(self, url: str):
        """(state, healthz body, error) for one replica; state None on
        connect failure."""
        try:
            req = urllib.request.Request(url + "/healthz")
            try:
                with urllib.request.urlopen(
                        req, timeout=self.health_timeout_s) as resp:
                    body = json.loads(resp.read().decode())
            except urllib.error.HTTPError as e:
                # 503 wedged still has a JSON body — that's a live,
                # drained replica, not a dead one.
                body = json.loads(e.read().decode())
        except Exception as e:
            return None, {}, f"{type(e).__name__}: {e}"
        serve = body.get("serve") or {}
        if serve and not serve.get("ready", True):
            return COLD, body, None
        st = body.get("status", "healthy")
        if st == "wedged":
            return WEDGED, body, None
        if st == "degraded":
            return DEGRADED, body, None
        return READY, body, None

    def start(self) -> None:
        """Start the background health poller (joined by close —
        JTL505)."""
        if self._poller is not None:
            return
        self._closed.clear()
        self._poller = threading.Thread(
            target=self._poll_loop, name="fleet-health-poller",
            daemon=True)
        self._poller.start()

    def _poll_loop(self) -> None:
        while not self._closed.is_set():
            try:
                self.poll_health_once()
            except Exception:
                pass   # the poller must outlive any one bad replica
            self._closed.wait(self.poll_interval_s)

    def close(self) -> None:
        self._closed.set()
        if self._poller is not None:
            self._poller.join(timeout=10)
            self._poller = None

    # ------------------------------------------------------------------
    # routing

    def candidates(self, key: str) -> list[Replica]:
        """Replicas to try for `key`, in order. Affine modes walk the
        rendezvous order with READY tiers before DEGRADED; random mode
        round-robins over routable replicas (the bench control arm)."""
        with self._lock:
            reps = dict(self._replicas)
            self._rr += 1
            rr = self._rr
        if not reps:
            return []
        if self.mode == RANDOM:
            routable = [reps[i] for i in sorted(reps)
                        if reps[i].state in _ROUTABLE]
            if not routable:
                return []
            k = rr % len(routable)
            return routable[k:] + routable[:k]
        order = [reps[i] for i in rendezvous_order(
            key, list(reps), self.salt)]
        ready = [r for r in order if r.state == READY]
        degraded = [r for r in order if r.state == DEGRADED]
        if self.mode == STRICT:
            owner = order[0]
            return [owner] if owner.state in _ROUTABLE else []
        return ready + degraded

    def forward(self, method: str, path: str, body: Optional[bytes],
                key: str) -> tuple[int, bytes, Optional[str]]:
        """Send one request to the key's owner, spilling down the
        preference order on connect failure or 5xx/429 (checks are
        pure — a replica that died mid-request is safe to retry
        elsewhere, which is what makes kill-mid-load lossless).
        Returns (status, body bytes, answering replica id or None)."""
        from .. import obs
        met = obs.get_metrics()
        met.counter("fleet.requests").add(1)
        cands = self.candidates(key)
        if not cands:
            met.counter("fleet.rejected").add(1)
            return 503, json.dumps(
                {"error": "no routable replica for key",
                 "key": key, "retry_after_s": 5}).encode(), None
        last: tuple[int, bytes] = (502, b'{"error": "unreachable"}')
        for i, rep in enumerate(cands):
            status, out = self._send(rep, method, path, body)
            if status is None:                      # connect failure
                met.counter("fleet.replica_errors").add(1)
                with self._lock:
                    rep.consecutive_failures += 1
                    rep.state = DOWN
                    rep.last_error = out.decode(errors="replace")
                continue
            if status in (429, 503) or status >= 500:
                # Per-replica admission bound or wedge: another replica
                # has its own inflight budget — spill before bouncing
                # the client.
                met.counter("fleet.replica_errors").add(1)
                last = (status, out)
                continue
            with self._lock:
                if i == 0 and self.mode != RANDOM:
                    rep.routed += 1
                else:
                    rep.spilled_in += 1
            if i > 0:
                met.counter("fleet.spillover").add(1)
            return status, out, rep.id
        met.counter("fleet.rejected").add(1)
        return last[0], last[1], None

    def record_sticky(self, kind: str, sticky_id: str,
                      rep_id: str) -> None:
        """Bind a verdict/session id to the replica that answered, so
        follow-ups (polls, session ops) land on the same process."""
        with self._lock:
            smap = (self._verdict_origin if kind == "verdict"
                    else self._session_origin)
            smap[sticky_id] = rep_id
            while len(smap) > STICKY_CAP:
                smap.popitem(last=False)

    def send_to(self, rid: str, method: str, path: str,
                body: Optional[bytes] = None):
        """One request to one named replica (fan-out stats, drains).
        (status, body); status None on connect failure/unknown id."""
        with self._lock:
            rep = self._replicas.get(rid)
        if rep is None:
            return None, b'{"error": "unknown replica"}'
        return self._send(rep, method, path, body)

    def forward_sticky(self, method: str, path: str,
                       body: Optional[bytes], sticky_map: str,
                       sticky_id: str) -> tuple[int, bytes]:
        """Route a follow-up (verdict poll, session op) to the replica
        that owns the id; 404 when the origin is unknown or gone."""
        with self._lock:
            smap = (self._verdict_origin if sticky_map == "verdict"
                    else self._session_origin)
            rid = smap.get(sticky_id)
            rep = self._replicas.get(rid) if rid else None
        if rep is None:
            return 404, json.dumps(
                {"error": f"unknown id {sticky_id!r} "
                          "(origin replica gone — re-submit)"}).encode()
        status, out = self._send(rep, method, path, body)
        if status is None:
            from .. import obs
            obs.get_metrics().counter("fleet.replica_errors").add(1)
            return 502, out
        return status, out

    def _send(self, rep: Replica, method: str, path: str,
              body: Optional[bytes]):
        """(status, body) from one replica; (None, error bytes) on
        connect failure."""
        req = urllib.request.Request(
            rep.url + path, data=body, method=method,
            headers={"Content-Type": "application/json"} if body else {})
        try:
            try:
                with urllib.request.urlopen(
                        req, timeout=self.request_timeout_s) as resp:
                    return resp.status, resp.read()
            except urllib.error.HTTPError as e:
                return e.code, e.read()
        except Exception as e:
            return None, f"{type(e).__name__}: {e}".encode()

    # ------------------------------------------------------------------
    # observability

    def refresh_gauges(self) -> None:
        from .. import obs
        met = obs.get_metrics()
        with self._lock:
            n = len(self._replicas)
            ready = sum(1 for r in self._replicas.values()
                        if r.state == READY)
        met.gauge("fleet.replicas").set(n)
        met.gauge("fleet.replicas_ready").set(ready)

    def stats(self) -> dict[str, Any]:
        self.refresh_gauges()
        from .. import obs
        with self._lock:
            # Snapshot inline under the membership lock (JTL501: the
            # per-replica health fields are poller-written).
            reps = [{"id": r.id, "url": r.url, "state": r.state,
                     "routed": r.routed, "spilled_in": r.spilled_in,
                     "last_error": r.last_error,
                     "health": r.last_healthz}
                    for r in self._replicas.values()]
            sticky = {"verdicts": len(self._verdict_origin),
                      "sessions": len(self._session_origin)}
        return {
            "mode": {AFFINE: "affine", STRICT: "strict",
                     RANDOM: "random"}.get(self.mode, str(self.mode)),
            "salt": self.salt,
            "bucket_floor": self.bucket_floor,
            "replicas": reps,
            "sticky": sticky,
            "fleet": obs.fleet_stats(obs.get_metrics()),
        }
