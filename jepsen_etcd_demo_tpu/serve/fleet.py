"""Fleet supervisor: N `serve --check` replicas behind the shape-affine
router (ISSUE 18 tentpole; serve/router.py is the routing half).

The supervisor owns replica *processes*; the router owns replica
*membership*. Keeping them separate is what makes zero-downtime restart
a three-line protocol:

1. spawn a replacement (`serve --check --port 0 --ready-file ...`) —
   the replica runs `sched/warmup.warmup_plans` before binding, so the
   ready-file contract means "warm", not just "listening";
2. optionally replay a warmup corpus through the replacement's own
   POST /check (tenant ``_warmup``) so its kernel LRU holds the fleet's
   live shapes, then `router.swap_replica` — one lock hold admits the
   replacement and evicts the old replica, so no routing decision ever
   sees neither;
3. drain the old replica (poll /serve/stats until pending+inflight hit
   zero, bounded) and only then terminate it — in-flight verdicts land.

Every replica shares one store root, which is the fleet-wide warm
state: one persistent XLA compile cache (<store>/.xla-cache — passed
explicitly via JEPSEN_TPU_COMPILE_CACHE so sharing never depends on a
warmup's side effects) and one O_EXCL-locked tuned-profile file next to
it (tune/profile.py), so one replica's tune benefits all.

`serve_fleet` is the CLI entry (`jepsen-tpu serve --check --fleet`):
supervisor + router + the fleet HTTP surface (web/server.py StoreHandler
+ /check forwarding + /fleet/stats) under one obs capture, so the
fleet.* counters land on the router's own /metrics.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time
import urllib.request
from http.server import ThreadingHTTPServer
from pathlib import Path
from typing import Any, Optional

from .. import obs
from ..obs.sync import maybe_wrap
from ..web import server as web_server
from .daemon import MAX_BODY_BYTES
from .router import READY, FleetRouter, routing_key

#: How long a replica may take from spawn to ready-file (imports +
#: startup warmup + bind). Generous: a cold XLA cache pays real
#: compiles here so traffic never does.
READY_TIMEOUT_S = 180.0

#: Drain bound for a replaced replica: in-flight verdicts get this long
#: to land before the old process is terminated anyway.
DRAIN_TIMEOUT_S = 60.0


class ReplicaProc:
    """One spawned replica: process handle + the ready record."""

    def __init__(self, rid: str, proc: subprocess.Popen,
                 ready_file: str, log_path: str):
        self.id = rid
        self.proc = proc
        self.ready_file = ready_file
        self.log_path = log_path
        self.ready: dict[str, Any] = {}
        self.url: Optional[str] = None

    def wait_ready(self, timeout: float = READY_TIMEOUT_S) -> dict:
        """Block on the --ready-file contract and return the ready
        record. Raises RuntimeError when the process dies or the
        deadline passes first. Does NOT publish ``self.url`` — that
        write belongs to the supervisor, under its membership lock
        (handler threads read it through replica_urls())."""
        deadline = time.monotonic() + timeout
        path = Path(self.ready_file)
        while time.monotonic() < deadline:
            if self.proc.poll() is not None:
                tail = ""
                try:
                    tail = Path(self.log_path).read_text()[-2000:]
                except OSError:
                    pass
                raise RuntimeError(
                    f"replica {self.id} exited rc={self.proc.returncode} "
                    f"before ready; log tail:\n{tail}")
            if path.exists():
                try:
                    text = path.read_text()
                    if text.strip():
                        rec = json.loads(text)
                        if "serving" not in rec:
                            raise KeyError("serving")
                        self.ready = rec
                        return rec
                except (json.JSONDecodeError, KeyError):
                    pass   # partial write — poll again
            time.sleep(0.05)
        raise RuntimeError(
            f"replica {self.id} not ready within {timeout}s")

    def terminate(self, grace_s: float = 10.0) -> None:
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=grace_s)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=grace_s)

    def kill(self) -> None:
        """Hard kill — the failure-injection path (tests): no drain, no
        grace, exactly what a crashed replica looks like."""
        if self.proc.poll() is None:
            self.proc.kill()
            self.proc.wait(timeout=10)


class FleetSupervisor:
    """Spawn/adopt N replicas, keep the router's membership in sync,
    and run the zero-downtime restart protocol."""

    def __init__(self, store_root: str = "store", *,
                 n: Optional[int] = None, host: str = "127.0.0.1",
                 default_model: str = "cas-register",
                 coalesce_ms: Optional[int] = None,
                 max_batch: Optional[int] = None,
                 max_inflight: Optional[int] = None,
                 router: Optional[FleetRouter] = None,
                 env: Optional[dict] = None,
                 warm_corpus: Optional[list[dict]] = None,
                 ready_timeout_s: float = READY_TIMEOUT_S):
        import threading

        from ..ops.limits import limits

        self.store_root = str(store_root)
        self.n = limits().fleet_replicas if n is None else int(n)
        self.host = host
        self.default_model = default_model
        self.coalesce_ms = coalesce_ms
        self.max_batch = max_batch
        self.max_inflight = max_inflight
        self.router = router if router is not None else FleetRouter()
        self.env_overrides = dict(env or {})
        #: Histories replayed through a replacement replica before it
        #: takes traffic (each a {"model": ..., "history": [...]}).
        self.warm_corpus = list(warm_corpus or [])
        self.ready_timeout_s = ready_timeout_s
        self._lock = maybe_wrap(threading.Lock(),
                                "serve.fleet.FleetSupervisor._lock")
        # jtsan: guarded-by=self._lock
        self._procs: dict[str, ReplicaProc] = {}
        self._seq = 0            # jtsan: guarded-by=self._lock
        self._tmpdir = tempfile.mkdtemp(prefix="jepsen-fleet-")

    # ------------------------------------------------------------------
    # spawning

    def _child_env(self) -> dict:
        env = dict(os.environ)
        # The package must be importable in the child no matter where
        # the fleet was launched from.
        pkg_root = str(Path(__file__).resolve().parents[2])
        env["PYTHONPATH"] = pkg_root + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH")
            else "")
        # Fleet-wide warm state: pin every replica's persistent XLA
        # cache (and therefore the tuned-profile file next to it) to
        # the shared store root, unless the operator pinned it already.
        env.setdefault("JEPSEN_TPU_COMPILE_CACHE",
                       str(Path(self.store_root) / ".xla-cache"))
        env.update(self.env_overrides)
        return env

    def spawn_replica(self) -> ReplicaProc:
        """Start one `serve --check` subprocess (not yet routed)."""
        with self._lock:
            rid = f"r{self._seq}"
            self._seq += 1
        ready_file = os.path.join(self._tmpdir, f"{rid}.ready.json")
        log_path = os.path.join(self._tmpdir, f"{rid}.log")
        cmd = [sys.executable, "-m", "jepsen_etcd_demo_tpu.cli.main",
               "serve", "--check", "--host", self.host, "--port", "0",
               "--store", self.store_root, "--model", self.default_model,
               "--ready-file", ready_file]
        if self.coalesce_ms is not None:
            cmd += ["--coalesce-ms", str(self.coalesce_ms)]
        if self.max_batch is not None:
            cmd += ["--max-batch", str(self.max_batch)]
        if self.max_inflight is not None:
            cmd += ["--max-inflight", str(self.max_inflight)]
        logf = open(log_path, "wb")
        try:
            proc = subprocess.Popen(cmd, stdout=logf, stderr=logf,
                                    env=self._child_env())
        finally:
            logf.close()
        rp = ReplicaProc(rid, proc, ready_file, log_path)
        with self._lock:
            self._procs[rid] = rp
        return rp

    def start(self) -> None:
        """Spawn the fleet, wait for every ready-file, admit everyone
        READY, start the router's health poller."""
        procs = [self.spawn_replica() for _ in range(self.n)]
        for rp in procs:
            rec = rp.wait_ready(self.ready_timeout_s)
            url = rec["serving"]
            with self._lock:
                rp.url = url
            if self.warm_corpus:
                self.warm_replica(url)
            self.router.add_replica(url, rid=rp.id, state=READY)
        self.router.refresh_gauges()
        self.router.start()

    def adopt(self, url: str, rid: Optional[str] = None):
        """Route to a replica this supervisor did not spawn (it owns
        its own lifecycle; health polling still applies)."""
        return self.router.add_replica(url, rid=rid, state=READY)

    # ------------------------------------------------------------------
    # warm restart

    def warm_replica(self, url: str,
                     timeout_s: float = READY_TIMEOUT_S) -> int:
        """Replay the warmup corpus through the replica's own /check
        (tenant ``_warmup``, wait=true) so its kernel LRU holds the
        fleet's live shapes before it takes traffic. Best-effort: a
        failed warmup request leaves the replica cold for that shape,
        never broken."""
        warmed = 0
        for item in self.warm_corpus:
            body = json.dumps({
                "tenant": "_warmup",
                "model": item.get("model", self.default_model),
                "history": item["history"], "wait": True,
            }).encode()
            req = urllib.request.Request(
                url + "/check", data=body,
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=timeout_s):
                    warmed += 1
            except Exception:
                pass
        return warmed

    def restart_replica(self, rid: str) -> str:
        """Zero-downtime restart: replacement up + warm first, swap the
        hash slice, drain the old replica, then terminate it. Returns
        the replacement's id."""
        with self._lock:
            if rid not in self._procs:
                raise KeyError(f"no replica {rid!r}")
        new = self.spawn_replica()
        rec = new.wait_ready(self.ready_timeout_s)
        new_url = rec["serving"]
        with self._lock:
            new.url = new_url
        if self.warm_corpus:
            self.warm_replica(new_url)
        self.router.swap_replica(rid, new_url, rid=new.id)
        self.router.refresh_gauges()
        # Re-validate under this acquisition and bind what the dict
        # actually holds (JTL503): the drained/terminated process is
        # exactly the one popped, not the earlier peek.
        with self._lock:
            old = self._procs.pop(rid, None)
            old_url = old.url if old is not None else None
        if old is not None:
            if old_url:
                self._drain(old_url)
            old.terminate()
        return new.id

    def rolling_restart(self) -> list[str]:
        """Restart every replica one at a time (config/code rollout):
        the fleet never drops below n-0 routable replicas because each
        replacement is admitted before its predecessor drains."""
        with self._lock:
            rids = list(self._procs)
        return [self.restart_replica(rid) for rid in rids]

    def _drain(self, url: str,
               timeout_s: float = DRAIN_TIMEOUT_S) -> bool:
        """Poll the evicted replica's /serve/stats until every admitted
        request has a verdict (pending 0, inflight 0)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            try:
                with urllib.request.urlopen(
                        url + "/serve/stats", timeout=10) as resp:
                    st = json.loads(resp.read().decode())
                sch = st.get("scheduler", {})
                inflight = sum(t.get("inflight", 0)
                               for t in sch.get("tenants", {}).values())
                if sch.get("pending", 0) == 0 and inflight == 0:
                    return True
            except Exception:
                return False   # already gone — nothing left to drain
            time.sleep(0.1)
        return False

    # ------------------------------------------------------------------
    # failure injection / teardown

    def kill_replica(self, rid: str) -> None:
        """Crash one replica (tests): no drain, no router courtesy —
        the router finds out via connect failures and health polls."""
        with self._lock:
            rp = self._procs.get(rid)
        if rp is not None:
            rp.kill()

    def replica_urls(self) -> dict[str, str]:
        with self._lock:
            return {rid: rp.url for rid, rp in self._procs.items()
                    if rp.url}

    def close(self) -> None:
        self.router.close()
        with self._lock:
            procs = list(self._procs.values())
            self._procs.clear()
        for rp in procs:
            rp.terminate()


# ----------------------------------------------------------------------
# the fleet's HTTP surface


class FleetHandler(web_server.StoreHandler):
    """StoreHandler (run index, /metrics with fleet.* families,
    /healthz for the ROUTER process) + request forwarding:

    * POST /check               -> routing_key(model, history) -> owner
    * GET  /check/<id>          -> sticky to the verdict's origin
    * POST /serve/session*      -> sticky session routing
    * GET  /fleet/stats         -> router + per-replica view
    * GET  /serve/stats         -> fan-out to every replica
    """

    router_obj: FleetRouter = None        # bound by make_fleet_handler
    supervisor_obj: FleetSupervisor = None

    def _send_json(self, body: dict, status: int = 200) -> None:
        payload = (json.dumps(body, indent=2, default=str) + "\n").encode()
        self.send_response(status)
        self.send_header("Content-Type",
                         "application/json; charset=utf-8")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def _relay(self, status: int, body: bytes) -> None:
        """Pass an upstream response through byte-identical (verdict
        parity is a contract — the router must not re-encode JSON)."""
        self.send_response(status)
        self.send_header("Content-Type",
                         "application/json; charset=utf-8")
        self.send_header("Content-Length", str(len(body)))
        if status in (429, 503):
            # Upstream Retry-After is in the JSON body; re-surface the
            # header for clients that only look there.
            try:
                ra = json.loads(body.decode()).get("retry_after_s")
            except (json.JSONDecodeError, UnicodeDecodeError, AttributeError):
                ra = None
            self.send_header("Retry-After", str(int(ra)) if ra else "1")
        self.end_headers()
        self.wfile.write(body)

    def _read_raw(self) -> bytes:
        n = int(self.headers.get("Content-Length") or 0)
        if n <= 0:
            return b"{}"
        if n > MAX_BODY_BYTES:
            raise ValueError(
                f"request body of {n} bytes exceeds the "
                f"{MAX_BODY_BYTES}-byte bound")
        return self.rfile.read(n)

    def do_POST(self):
        r = self.router_obj
        path = self.path.rstrip("/")
        try:
            raw = self._read_raw()
            if path == "/check":
                body = json.loads(raw.decode("utf-8"))
                key = routing_key(
                    str(body.get("model")
                        or (self.supervisor_obj.default_model
                            if self.supervisor_obj else "cas-register")),
                    body.get("history") or [], r.bucket_floor)
                status, out, rep = r.forward("POST", "/check", raw, key)
                if rep and status in (200, 202):
                    try:
                        rid = json.loads(out.decode()).get("request_id")
                    except json.JSONDecodeError:
                        rid = None
                    if rid:
                        r.record_sticky("verdict", rid, rep)
                return self._relay(status, out)
            if path == "/serve/session":
                body = json.loads(raw.decode("utf-8"))
                model = str(body.get("model")
                            or (self.supervisor_obj.default_model
                                if self.supervisor_obj
                                else "cas-register"))
                status, out, rep = r.forward(
                    "POST", "/serve/session", raw, f"{model}|session")
                if rep and status in (200, 201):
                    try:
                        sid = json.loads(out.decode()).get("session_id")
                    except json.JSONDecodeError:
                        sid = None
                    if sid:
                        r.record_sticky("session", sid, rep)
                return self._relay(status, out)
            if path.startswith("/serve/session/"):
                rest = path[len("/serve/session/"):]
                for suffix in ("/ops", "/close"):
                    if rest.endswith(suffix):
                        sid = rest[:-len(suffix)]
                        status, out = r.forward_sticky(
                            "POST", path, raw, "session", sid)
                        return self._relay(status, out)
            self._send_json({"error": f"unknown endpoint {self.path}"},
                            status=404)
        except (ValueError, KeyError, json.JSONDecodeError) as e:
            self._send_json({"error": f"{type(e).__name__}: {e}"},
                            status=400)
        except (BrokenPipeError, ConnectionResetError):
            return

    def do_GET(self):
        r = self.router_obj
        path = self.path.rstrip("/")
        try:
            if path.startswith("/check/"):
                rid = path[len("/check/"):]
                status, out = r.forward_sticky(
                    "GET", path, None, "verdict", rid)
                return self._relay(status, out)
            if path == "/fleet/stats":
                view = r.stats()
                if self.supervisor_obj is not None:
                    view["processes"] = self.supervisor_obj.replica_urls()
                return self._send_json(view)
            if path == "/serve/stats":
                out = {}
                for rep in list(r.replica_ids()):
                    status, body = r.send_to(rep, "GET", "/serve/stats")
                    if status == 200:
                        try:
                            out[rep] = json.loads(body.decode())
                        except json.JSONDecodeError:
                            pass
                return self._send_json({"replicas": out})
        except (BrokenPipeError, ConnectionResetError):
            return
        return super().do_GET()


def make_fleet_handler(store_root: str, router: FleetRouter,
                       supervisor: Optional[FleetSupervisor] = None):
    class _Bound(FleetHandler):
        router_obj = router
        supervisor_obj = supervisor

        def __init__(self, *args, **kw):
            super().__init__(*args, store_root=store_root, **kw)

    return _Bound


def serve_fleet(store_root: str = "store", host: str = "127.0.0.1",
                port: int = 8080, replicas: Optional[int] = None,
                default_model: str = "cas-register",
                coalesce_ms: Optional[int] = None,
                max_batch: Optional[int] = None,
                max_inflight: Optional[int] = None,
                ready_file: Optional[str] = None) -> int:
    """`jepsen-tpu serve --check --fleet`: spawn the replica fleet,
    bind the router surface, serve until interrupted."""
    sup = FleetSupervisor(store_root, n=replicas, host=host,
                          default_model=default_model,
                          coalesce_ms=coalesce_ms, max_batch=max_batch,
                          max_inflight=max_inflight)
    with obs.capture():
        try:
            sup.start()
            httpd = ThreadingHTTPServer(
                (host, port),
                make_fleet_handler(store_root, sup.router, sup))
            actual_port = httpd.server_address[1]
            ready = {"serving": f"http://{host}:{actual_port}",
                     "port": actual_port, "store": str(store_root),
                     "check": True, "fleet": sup.n,
                     "replicas": sup.replica_urls()}
            print(json.dumps(ready), flush=True)
            if ready_file:
                Path(ready_file).write_text(json.dumps(ready))
            try:
                httpd.serve_forever()
            except KeyboardInterrupt:
                pass
            finally:
                httpd.server_close()
        finally:
            sup.close()
    return 0
