"""Device-mesh execution of the checker kernels.

The reference's parallelism axes map onto the TPU mesh like this
(SURVEY.md §2.4):
  * independent-key / corpus axis (embarrassingly parallel histories) →
    data-parallel sharding of the [B, E, 6] event batch over mesh axis
    "batch" (`batch.py`) — configs[2]/[4] of BASELINE.json;
  * checker search axis (knossos's JVM search threads) → the WGL frontier
    sharded over mesh axis "frontier" with shard_map + all_gather compaction
    (`frontier.py`) — configs[3], the 10k-op north star.

Collectives ride ICI inside a slice; the corpus axis is the DCN axis across
slices (§2.5).
"""

from .mesh import make_mesh, device_count  # noqa: F401
from .batch import sharded_corpus_checker, check_corpus  # noqa: F401
from .frontier import (  # noqa: F401
    make_frontier_sharded_checker, make_grid_sharded_checker,
)
