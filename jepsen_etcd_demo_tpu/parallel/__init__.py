"""Device-mesh execution of the PRODUCTION checker kernels.

The reference's parallelism axes map onto the TPU mesh like this
(SURVEY.md §2.4):
  * independent-key / corpus axis (embarrassingly parallel histories) →
    batch-axis sharding of the dense wgl3/pallas kernels (`dense.py`) —
    configs[2]/[4] of BASELINE.json; engaged automatically by
    check_batch_encoded_auto whenever more than one device is present;
  * checker search axis (knossos's JVM search threads; this domain's
    sequence parallelism, §5.7) → the dense subset-lattice table's word
    axis sharded with shard_map + ppermute exchange (`lattice.py`) —
    configs[3], wide geometries past one chip's cell budget;
  * across hosts, the corpus axis rides DCN (`multislice.py`) — §2.5.

Collectives ride ICI inside a slice; the corpus axis is the DCN axis across
slices. The round-2 frontier/batch shardings of the retired v1 sort kernel
were deleted with it (ops/wgl.py docstring has the history).
"""

from .mesh import make_mesh, device_count  # noqa: F401
from .dense import (  # noqa: F401
    batch_mesh, check_batch_sharded, check_steps_sharded,
    sharded_packed_batch_checker,
)
from .lattice import (  # noqa: F401
    check_steps_lattice_long, lattice_dense_config, lattice_mesh,
)
