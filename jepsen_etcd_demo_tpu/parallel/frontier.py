"""Frontier-sharded WGL search: one huge history, many devices.

This is the TPU analogue of knossos's multithreaded search (reference hot
loop #2, SURVEY.md §3.4) and the build's answer to the "sequence
parallelism" requirement (§5.7): history length is the sequence axis, and the
search frontier — the per-step state — is sharded across mesh axis
"frontier" the way ring attention shards KV state.

Per EV_RETURN expansion round (inside a lax.while_loop inside lax.scan):
  1. LOCAL expand: each device steps its F/D configs against all K pending
     slots (vmapped model step) and sort-dedups its F/D·(K+1) candidates down
     to F/D survivors. This is the compute-heavy part and scales 1/D.
  2. GLOBAL merge: all_gather the survivors (F rows total) over ICI, dedup
     the gathered frontier (replicated computation), and have each device
     keep its F/D slice of the compacted result. This both deduplicates
     globally and REBALANCES, so no shard starves while another overflows.

Soundness: the local stage can drop configs when one shard locally exceeds
F/D uniques even though global room exists; that is recorded as overflow, and
overflow only ever converts a would-be "invalid" verdict into "unknown"
(dropping configs can only lose linearization witnesses — same argument as
ops/wgl.py). A surviving run is a genuine proof.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map

from ..models.base import Model
from ..ops.encode import EV_INVOKE, EV_RETURN
from ..ops.wgl import WGLConfig, _dedup, _slot_constants, _Carry


def _build_local_check(model: Model, cfg: WGLConfig, axis: str, d: int):
    """The per-device search body (runs inside shard_map): local expansion +
    all_gather global compaction over mesh axis `axis` (size d)."""
    f_loc = cfg.f_cap // d
    k = cfg.k_slots
    word_of, bit_of, slot_bitmask = _slot_constants(cfg)

    def bits_set(masks):
        return (masks[:, word_of] >> bit_of) & jnp.uint32(1)

    def expand_once(states, masks, valid, slot_tab, slot_active, t_word,
                    t_bit):
        f = slot_tab[:, 0]
        a1 = slot_tab[:, 1]
        a2 = slot_tab[:, 2]
        rv = slot_tab[:, 3]
        legal, nxt = jax.vmap(lambda s: model.step(s, f, a1, a2, rv))(states)
        # Just-in-time linearization: see ops/wgl.py expand_once.
        not_done = ((masks[:, t_word] >> t_bit) & jnp.uint32(1)) == 0
        cand_valid = (valid[:, None] & not_done[:, None]
                      & slot_active[None, :]
                      & (bits_set(masks) == 0) & legal)
        cand_masks = masks[:, None, :] | slot_bitmask[None, :, :]
        all_states = jnp.concatenate([states, nxt.reshape(-1)])
        all_masks = jnp.concatenate([masks, cand_masks.reshape(-1, cfg.words)])
        all_valid = jnp.concatenate([valid, cand_valid.reshape(-1)])
        # 1. local compaction (scales 1/D)
        s2, m2, v2, n_loc = _dedup(all_states, all_masks, all_valid, f_loc)
        local_overflow = n_loc > f_loc
        # 2. global merge + rebalance over ICI
        gs = jax.lax.all_gather(s2, axis, tiled=True)       # [F]
        gm = jax.lax.all_gather(m2, axis, tiled=True)       # [F, W]
        gv = jax.lax.all_gather(v2, axis, tiled=True)       # [F]
        cs, cm, cv, n_glob = _dedup(gs, gm, gv, cfg.f_cap)
        # Deal compacted configs ROUND-ROBIN across shards. Dedup packs the
        # survivors to the front, so a contiguous slice would concentrate
        # every config on device 0 whenever the frontier is smaller than
        # f_loc — collapsing effective capacity to f_cap/D and wasting the
        # other devices. Strided dealing keeps shards balanced.
        dev = jax.lax.axis_index(axis)
        mine = jnp.arange(f_loc) * d + dev
        return (cs[mine], cm[mine], cv[mine], n_glob, local_overflow)

    def closure(states, masks, valid, slot_tab, slot_active, overflow,
                t_word, t_bit):
        n0 = jax.lax.psum(jnp.sum(valid.astype(jnp.int32)), axis)

        def cond(st):
            _s, _m, _v, _n, changed, _o, it = st
            return changed & (it < cfg.rounds)

        def body(st):
            s, m, v, n_prev, _c, o, it = st
            s2, m2, v2, n_glob, loc_of = expand_once(
                s, m, v, slot_tab, slot_active, t_word, t_bit)
            o = o | (jax.lax.psum(loc_of.astype(jnp.int32), axis) > 0)
            return (s2, m2, v2, n_glob, n_glob > n_prev, o, it + 1)

        init = (states, masks, valid, n0, jnp.bool_(True), overflow,
                jnp.int32(0))
        s, m, v, n, _c, o, _it = jax.lax.while_loop(cond, body, init)
        return s, m, v, n, o

    def step(carry: _Carry, ev_and_idx):
        ev, idx = ev_and_idx
        kind, slot = ev[0], ev[1]

        def on_invoke(c: _Carry) -> _Carry:
            slot_tab = c.slot_tab.at[slot].set(ev[2:6])
            slot_active = c.slot_active.at[slot].set(True)
            return c._replace(slot_tab=slot_tab, slot_active=slot_active)

        def on_return(c: _Carry) -> _Carry:
            s, m, v, n, overflow = closure(
                c.states, c.masks, c.valid, c.slot_tab, c.slot_active,
                c.overflow, word_of[slot], bit_of[slot])
            bit_word = jnp.take(m, word_of[slot], axis=-1)
            has_bit = ((bit_word >> bit_of[slot]) & jnp.uint32(1)) == 1
            keep = v & has_bit
            cleared = m & ~slot_bitmask[slot][None, :]
            slot_active = c.slot_active.at[slot].set(False)
            alive = jax.lax.psum(jnp.any(keep).astype(jnp.int32), axis) > 0
            died = ~alive
            return c._replace(
                states=s, masks=cleared, valid=keep,
                slot_active=slot_active,
                dead=died, overflow=overflow,
                dead_event=jnp.where(died & (c.dead_event < 0), idx,
                                     c.dead_event),
                max_frontier=jnp.maximum(c.max_frontier, n))

        def active_step(c: _Carry) -> _Carry:
            return jax.lax.cond(kind == EV_INVOKE, on_invoke, on_return, c)

        skip = carry.dead | (kind != EV_INVOKE) & (kind != EV_RETURN)
        carry = jax.lax.cond(skip, lambda c: c, active_step, carry)
        return carry, None

    def init_carry() -> _Carry:
        dev = jax.lax.axis_index(axis)
        seed = (jnp.arange(f_loc) == 0) & (dev == 0)
        return _Carry(
            states=jnp.where(seed, model.init_state(), 0).astype(jnp.int32),
            masks=jnp.zeros((f_loc, cfg.words), jnp.uint32),
            valid=seed,
            slot_tab=jnp.zeros((k, 4), jnp.int32),
            slot_active=jnp.zeros((k,), bool),
            dead=jnp.bool_(False),
            overflow=jnp.bool_(False),
            dead_event=jnp.int32(-1),
            max_frontier=jnp.int32(1),
        )

    def check_local(events):
        carry = init_carry()
        idxs = jnp.arange(events.shape[0], dtype=jnp.int32)
        final, _ = jax.lax.scan(step, carry, (events, idxs))
        overflow = jax.lax.psum(final.overflow.astype(jnp.int32), axis) > 0
        return {
            "survived": ~final.dead,
            "overflow": overflow,
            "dead_event": final.dead_event,
            "max_frontier": final.max_frontier,
        }

    return check_local


def _shard_map(fn, **specs):
    try:  # jax>=0.8 names the replication check check_vma; older check_rep
        return shard_map(fn, check_vma=False, **specs)
    except TypeError:
        return shard_map(fn, check_rep=False, **specs)


def make_frontier_sharded_checker(model: Model, cfg: WGLConfig, mesh: Mesh,
                                  axis: str = "frontier"):
    """Returns jitted check(events[E, 6]) -> dict of replicated scalars.

    cfg.f_cap is the GLOBAL frontier capacity; each device holds
    f_cap / axis_size configs. Requires f_cap % axis_size == 0."""
    d = mesh.shape[axis]
    if cfg.f_cap % d != 0:
        raise ValueError(f"f_cap {cfg.f_cap} not divisible by axis size {d}")
    check_local = _build_local_check(model, cfg, axis, d)
    sharded = _shard_map(
        check_local, mesh=mesh,
        in_specs=(P(*(None,) * 2),),
        out_specs={"survived": P(), "overflow": P(), "dead_event": P(),
                   "max_frontier": P()})
    return jax.jit(sharded)


def make_grid_sharded_checker(model: Model, cfg: WGLConfig, mesh: Mesh,
                              batch_axis: str = "batch",
                              frontier_axis: str = "frontier"):
    """2D-sharded corpus check: histories data-parallel over `batch_axis`,
    each history's frontier sharded over `frontier_axis`.

    check(events[B, E, 6]) -> dict of [B] vectors. B must be a multiple of
    the batch axis size. This is the full production sharding — the corpus
    axis rides DCN across slices, the frontier axis rides ICI within one
    (SURVEY.md §2.5)."""
    d = mesh.shape[frontier_axis]
    if cfg.f_cap % d != 0:
        raise ValueError(f"f_cap {cfg.f_cap} not divisible by axis size {d}")
    check_local = _build_local_check(model, cfg, frontier_axis, d)
    body = jax.vmap(check_local)  # over the local batch shard
    sharded = _shard_map(
        body, mesh=mesh,
        in_specs=(P(batch_axis, None, None),),
        out_specs={"survived": P(batch_axis), "overflow": P(batch_axis),
                   "dead_event": P(batch_axis),
                   "max_frontier": P(batch_axis)})
    return jax.jit(sharded)


_CACHE: dict[tuple, Any] = {}


def cached_frontier_checker(model: Model, cfg: WGLConfig, mesh: Mesh):
    key = (model.cache_key(), cfg, id(mesh))
    if key not in _CACHE:
        _CACHE[key] = make_frontier_sharded_checker(model, cfg, mesh)
    return _CACHE[key]
