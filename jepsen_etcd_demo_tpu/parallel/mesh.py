"""Mesh construction helpers."""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


def device_count() -> int:
    return len(jax.devices())


def make_mesh(n_devices: Optional[int] = None,
              axes: Sequence[str] = ("batch",),
              shape: Optional[Sequence[int]] = None) -> Mesh:
    """Mesh over the first n devices. 1-axis by default ("batch"); pass
    axes=("batch", "frontier") with a shape to split ICI between the corpus
    axis and the frontier axis."""
    all_devs = jax.devices()
    want = n_devices or len(all_devs)
    if want > len(all_devs):
        raise ValueError(
            f"make_mesh: need {want} devices, have {len(all_devs)} "
            f"({all_devs[0].platform}). Hint: force a virtual CPU mesh "
            f"before any backend init — JAX_PLATFORMS=cpu plus "
            f"jax.config.update('jax_num_cpu_devices', {want}) (see "
            f"tests/conftest.py / __graft_entry__.dryrun_multichip).")
    devs = all_devs[:want]
    if shape is None:
        shape = [len(devs)] + [1] * (len(axes) - 1)
    if int(np.prod(shape)) != len(devs):
        raise ValueError(
            f"make_mesh: shape {tuple(shape)} needs {int(np.prod(shape))} "
            f"devices but {len(devs)} were selected")
    arr = np.array(devs).reshape(tuple(shape))
    return Mesh(arr, tuple(axes))
