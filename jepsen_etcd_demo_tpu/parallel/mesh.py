"""Mesh construction — N-D, multi-host-aware, and ELASTIC.

Every sharded lane (parallel/dense.py batch axis, parallel/lattice.py
word axis, parallel/multislice.py DCN corpus axis) builds its mesh
here. Three properties this module owns (ROADMAP item 3, SNIPPETS.md
[2]/[3] — ``shard_map`` + ``NamedSharding`` over an N-D
``(hosts, chips)`` mesh):

  * **N-D**: ``make_mesh`` accepts any axis tuple and shape —
    ``make_mesh(axes=("host", "lattice"), shape=(hosts, chips))`` is
    the pod form; the single-host 1-axis meshes the existing kernels
    compile are the degenerate case, so their compiled shapes are
    byte-identical to the pre-pod build.
  * **Multi-host**: ``pod_mesh`` lays ALL global devices out
    process-major, so the outer axis is exactly the one that crosses
    DCN (the multislice_mesh convention generalized); collectives may
    name a TUPLE of axes (``("host", "lattice")``) and reduce across
    both — jax flattens the product row-major, matching the layout.
  * **Elastic**: a request for more devices than the platform has is
    NOT an error by default — the shape is re-derived to the largest
    valid mesh that fits (and the downgrade logged), so a plan
    written for 16 chips re-buckets on an 8-chip host instead of
    crashing. Compiled-shape safety is the caller's key discipline:
    every kernel-LRU / tuned-profile key carries ``mesh_key(mesh)``
    (axes + shape + device ids), so a re-shard can only MISS a cache,
    never serve a stale compiled launch (plan/dispatch.py,
    tests/test_plan_elastic.py). ``strict=True`` restores the old
    raise for callers that pinned a count deliberately
    (tests / certification dryruns).
"""

from __future__ import annotations

import logging
import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

log = logging.getLogger(__name__)

# Env override for the default N-D mesh shape ("HxC", e.g. "2x4") — the
# CLI's --mesh-shape flag rides this so subprocesses inherit it.
MESH_SHAPE_ENV = "JEPSEN_TPU_MESH_SHAPE"


def device_count() -> int:
    return len(jax.devices())


def host_count() -> int:
    """JAX processes in the distributed system (1 = single host)."""
    return jax.process_count()


def largest_pow2(n: int) -> int:
    """Largest power of two <= n (>=1)."""
    return 1 << (max(1, n).bit_length() - 1)


def parse_mesh_shape(spec: str) -> tuple[int, ...]:
    """"2x4" / "8" -> (2, 4) / (8,). The CLI flag grammar."""
    try:
        shape = tuple(int(p) for p in spec.lower().split("x"))
    except ValueError:
        raise ValueError(
            f"mesh shape {spec!r} is not NxM integers (e.g. 2x4)") from None
    if not shape or any(s < 1 for s in shape):
        raise ValueError(f"mesh shape {spec!r} must be positive integers")
    return shape


def requested_shape() -> Optional[tuple[int, ...]]:
    """The operator-requested default mesh shape (CLI --mesh-shape via
    the env override), or None. Parsed on every call — the flag applies
    per invocation, never cached across them."""
    spec = os.environ.get(MESH_SHAPE_ENV)
    return parse_mesh_shape(spec) if spec else None


def elastic_shape(shape: Sequence[int], have: int) -> tuple[int, ...]:
    """The largest valid mesh shape <= `shape` that fits on `have`
    devices, shrinking OUTER axes first (the host/corpus axes — inner
    axes are the collective-heavy ICI ones whose width the kernels
    keyed their geometry on). Every axis stays >= 1; the result's
    product always fits within `have`."""
    shape = [int(s) for s in shape]
    for i in range(len(shape)):
        rest = int(np.prod(shape[i + 1:])) if i + 1 < len(shape) else 1
        if rest > have:
            shape[i] = 1
            continue
        shape[i] = max(1, min(shape[i], have // rest))
    return tuple(shape)


def make_mesh(n_devices: Optional[int] = None,
              axes: Sequence[str] = ("batch",),
              shape: Optional[Sequence[int]] = None,
              strict: bool = False) -> Mesh:
    """Mesh over the visible devices — N-D when `axes`/`shape` say so,
    ELASTIC by default: a request exceeding the platform re-derives the
    largest valid shape and logs the downgrade instead of raising.
    ``strict=True`` restores the historical hard failure (callers that
    pinned a device count deliberately — certification dryruns, tests).

    With neither `n_devices` nor `shape`, the mesh is 1-D over every
    device on the first axis (trailing axes size 1) — exactly the
    pre-pod behavior every existing compiled shape keys on."""
    all_devs = jax.devices()
    if jax.process_count() > 1:
        # Multi-host: process-major order, like pod_mesh — the outer
        # axis of an explicit N-D shape must be the one that crosses
        # DCN, or the tuple-axis collective flattening argument (and
        # the ICI-only premise of the inner axes) breaks.
        all_devs = sorted(all_devs, key=lambda d: (d.process_index, d.id))
    have = len(all_devs)
    want = n_devices if n_devices is not None else (
        int(np.prod(shape)) if shape is not None else have)
    if want > have:
        if strict:
            raise ValueError(
                f"make_mesh: need {want} devices, have {have} "
                f"({all_devs[0].platform}). Hint: force a virtual CPU mesh "
                f"before any backend init — JAX_PLATFORMS=cpu plus "
                f"jax.config.update('jax_num_cpu_devices', {want}) (see "
                f"tests/conftest.py / __graft_entry__.dryrun_multichip).")
        if shape is not None:
            shape = elastic_shape(shape, have)
        log.warning(
            "make_mesh: %d device(s) requested but only %d visible — "
            "re-deriving the largest valid mesh (%s over %s); pass "
            "strict=True to fail instead", want, have, tuple(axes),
            tuple(shape) if shape is not None else (have,))
        want = min(want, have)
        if shape is not None:
            want = int(np.prod(shape))
    devs = all_devs[:want]
    if shape is None:
        shape = [len(devs)] + [1] * (len(axes) - 1)
    if int(np.prod(shape)) != len(devs):
        raise ValueError(
            f"make_mesh: shape {tuple(shape)} needs {int(np.prod(shape))} "
            f"devices but {len(devs)} were selected")
    arr = np.array(devs).reshape(tuple(shape))
    return Mesh(arr, tuple(axes))


# jtflow: mesh-axes host
def pod_mesh(axes: Sequence[str] = ("host", "batch"),
             local_shape: Optional[Sequence[int]] = None) -> Mesh:
    """N-D multi-host mesh: ALL global devices laid out process-major,
    outer axis = the hosts (the DCN axis), inner axes = each host's
    chips over ICI. On a single process this is a (1, chips) mesh —
    callers that key compiled shapes on the 1-D single-host form should
    route through their existing 1-D helper when host_count() == 1.

    `local_shape` splits the per-host chips over the trailing axes
    (len(axes) - 1 of them); default = all chips on the first inner
    axis."""
    devs = jax.devices()
    n_proc = jax.process_count()
    per = len(devs) // n_proc
    order = sorted(devs, key=lambda d: (d.process_index, d.id))
    if local_shape is None:
        local_shape = [per] + [1] * (len(axes) - 2)
    arr = np.array(order).reshape((n_proc, *local_shape))
    return Mesh(arr, tuple(axes))


def mesh_key(mesh: Mesh) -> tuple:
    """The cache-key identity of a mesh: axis names + shape + device
    ids. EVERY kernel-LRU / tuned-profile key that resolves a compiled
    launch for a sharded kernel must include this — it is what makes a
    re-shard (device count changed between runs) a cache MISS instead
    of a stale compiled launch (doc/perf.md "KernelPlan & pod-scale")."""
    return (tuple(mesh.axis_names), tuple(mesh.shape.values()),
            tuple(d.id for d in mesh.devices.flat))


def mesh_total(mesh: Mesh) -> int:
    """Total device count of a mesh (the product over every axis)."""
    return int(np.prod(list(mesh.shape.values())))


def resolve_axis(mesh: Mesh, axis):
    """Auto-upgrade a 1-D string axis default to the full axis tuple on
    an N-D pod mesh: a bare "batch"/"lattice" on a ("host", ...) mesh
    would shard over one axis and silently replicate the other. ONE
    copy, shared by parallel/dense.py and parallel/lattice.py (their
    sharding specs and collectives name whatever this returns)."""
    if isinstance(axis, str) and len(mesh.axis_names) > 1:
        return tuple(mesh.axis_names)
    return axis
