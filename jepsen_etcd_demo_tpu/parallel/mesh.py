"""Mesh construction helpers."""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


def device_count() -> int:
    return len(jax.devices())


def make_mesh(n_devices: Optional[int] = None,
              axes: Sequence[str] = ("batch",),
              shape: Optional[Sequence[int]] = None) -> Mesh:
    """Mesh over the first n devices. 1-axis by default ("batch"); pass
    axes=("batch", "frontier") with a shape to split ICI between the corpus
    axis and the frontier axis."""
    devs = jax.devices()[: (n_devices or len(jax.devices()))]
    if shape is None:
        shape = [len(devs)] + [1] * (len(axes) - 1)
    arr = np.array(devs).reshape(tuple(shape))
    return Mesh(arr, tuple(axes))
