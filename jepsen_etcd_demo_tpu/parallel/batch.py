"""Data-parallel corpus checking: shard the history batch over the mesh.

The per-key histories of jepsen.independent (reference
src/jepsen/etcdemo.clj:115,120-125) and stored-corpus replays
(BASELINE.json configs[2]/[4]) are embarrassingly parallel: one vmapped
kernel launch, batch axis sharded over mesh axis "batch" with NamedSharding.
XLA needs no collectives here — each device checks its shard of histories;
results come back replicated scalars per history.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.base import Model
from ..ops.wgl import WGLConfig, make_batch_checker
from .mesh import make_mesh

_SHARDED_CACHE: dict[tuple, Any] = {}


def sharded_corpus_checker(model: Model, cfg: WGLConfig, mesh: Mesh,
                           batch_axis: str = "batch"):
    """jitted check(events[B, E, 6]) with B sharded over `batch_axis`.

    B must be a multiple of the axis size (pad with all-PAD histories via
    `check_corpus`, which handles ragged corpora)."""
    key = (model.cache_key(), cfg, id(mesh), batch_axis)
    if key in _SHARDED_CACHE:
        return _SHARDED_CACHE[key]
    base = make_batch_checker(model, cfg)
    in_sharding = NamedSharding(mesh, P(batch_axis, None, None))
    out_sharding = NamedSharding(mesh, P(batch_axis))
    fn = jax.jit(base, in_shardings=(in_sharding,),
                 out_shardings={"survived": out_sharding,
                                "overflow": out_sharding,
                                "dead_event": out_sharding,
                                "max_frontier": out_sharding})
    _SHARDED_CACHE[key] = fn
    return fn


def check_corpus(events: np.ndarray, model: Model,
                 cfg: Optional[WGLConfig] = None,
                 mesh: Optional[Mesh] = None) -> dict[str, np.ndarray]:
    """Check a ragged corpus of encoded histories on the mesh.

    events: [B, E, 6] int32 (pre-padded per history). B is padded up to a
    multiple of the mesh's batch axis; padding histories are all-PAD events
    (trivially valid) and stripped from the result.
    """
    if mesh is None:
        mesh = make_mesh()
    if cfg is None:
        cfg = WGLConfig()
    b = events.shape[0]
    d = mesh.shape["batch"]
    b_pad = ((b + d - 1) // d) * d
    if b_pad != b:
        from ..ops.encode import EV_PAD
        pad = np.zeros((b_pad - b,) + events.shape[1:], dtype=events.dtype)
        pad[:, :, 0] = EV_PAD
        events = np.concatenate([events, pad], axis=0)
    check = sharded_corpus_checker(model, cfg, mesh)
    with mesh:
        out = check(jnp.asarray(events))
    return {k: np.asarray(v)[:b] for k, v in out.items()}
