"""Lattice-sharded dense WGL search: ONE wide history, many devices.

The dense subset-lattice kernel (ops/wgl3.py) holds the search frontier as
the characteristic table u32[S, W] over (state, pending-mask) configs,
W = 2^(K-5) packed words. Past K ~ 17 the table outgrows one device's cell
budget and the single-device ladder falls back to the sort kernel or the
host-chunked sweep (ops/wgl3_pallas.check_encoded_general). This module
shards the table's WORD axis over a mesh axis instead — the build's
sequence-parallelism analogue (SURVEY.md §5.7): history length is the
sequence, the lattice is the per-step state, and each device owns the
2^(K-5)/D words whose global index falls in its contiguous shard.

What each table operation becomes under the shard (device count D = 2^dbits,
local words W_loc = W/D, lbits = log2(W_loc); global word index = low lbits
local | high dbits device):

  * expanding slot j < 5            in-word shift — LOCAL
  * expanding 5 <= j < 5+lbits      local word-axis reshape — LOCAL
  * expanding j >= 5+lbits          the mask bit lives in the DEVICE index:
                                    devices with bit b = j-5-lbits clear OR
                                    their fired configs into partner
                                    d | 1<<b — ONE lax.ppermute over ICI
  * pruning at return t             same split; the remote case is the
                                    reverse ppermute (bit-set partner sends
                                    its half down), selected by lax.switch
                                    over the dbits static permutations
  * frontier size / death           psum of local popcount / any

Exactness is unchanged — the sharded table is the same whole config space,
just partitioned; no capacity, no overflow, no dropped configs. Verdicts
are bit-identical to the single-device dense kernel (differentially
tested), and the chunked host loop (`check_steps_lattice_long`) mirrors
check_steps3_long with the carry staying sharded on-device between chunks.

Production routing: check_encoded_general's dense-chunked rung upgrades to
this path automatically when jax.device_count() > 1 and the geometry
shards (W >= D) — with the cell budget scaled by D, geometries the
single-device rung must refuse become checkable at all.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map

from ..models.base import Model
from ..obs import instrument_kernel, record_check_result
from ..ops import wgl3
from ..ops.encode import ReturnSteps
from ..ops.limits import limits
from ..ops.wgl3 import DenseConfig, _LO_MASK
from .mesh import (host_count, make_mesh, mesh_key as _mesh_key,
                   mesh_total, pod_mesh, requested_shape,
                   resolve_axis as _resolve_axis)

_CACHE: dict[tuple, Any] = {}


def lattice_mesh(n_devices: int | None = None) -> Mesh:
    """The table-word-axis mesh. Single host: the 1-axis ("lattice",)
    mesh (or an explicit N-D shape via --mesh-shape, axes
    ("host", "lattice")). Multi-host: the process-major
    ("host", "lattice") pod mesh — the sweep's collectives name the
    axis TUPLE, so the word axis shards (and psum/pmax/ppermute
    all-reduce) across DCN and ICI jointly."""
    if n_devices is None:
        shape = requested_shape()
        if shape is not None:
            if len(shape) > 2:
                raise ValueError(
                    f"--mesh-shape {'x'.join(map(str, shape))}: the "
                    f"lattice lane builds at most 2-D "
                    f"('host', 'lattice') meshes")
            if len(shape) > 1:
                return make_mesh(axes=("host", "lattice"), shape=shape)
            return make_mesh(shape[0], axes=("lattice",))
        if host_count() > 1:
            return pod_mesh(axes=("host", "lattice"))
    return make_mesh(n_devices, axes=("lattice",))


def lattice_dense_config(model: Model, k_slots: int, max_value: int,
                         n_devices: int,
                         budget: int | None = None) -> DenseConfig | None:
    """DenseConfig for the SHARDED lattice: the cell budget scales with the
    device count (each device holds cells/D), and the word axis must split
    evenly — D a power of two with W >= D (the ppermute pairing addresses
    devices by mask bits). Infeasible platforms get None so routing falls
    back to the single-device rung instead of crashing mid-check."""
    if n_devices < 2 or n_devices & (n_devices - 1):
        return None
    if budget is None:
        budget = limits().dense_cell_budget_chunked * n_devices
    cfg = wgl3.dense_config(model, k_slots, max_value, budget=budget)
    if cfg is None or (1 << (cfg.k_slots - 5)) < n_devices:
        return None
    return cfg


def _build_local_step(model: Model, cfg: DenseConfig, axis: str, d: int,
                      plan=None, canon: bool = False,
                      min_frontier: int = 0, memo_slots: int = 0):
    """The per-device scan body over one shard of the table. Mirrors
    wgl3.make_step_fn3 exactly (same banking/closure/prune semantics, same
    metrics) with the word axis split over `axis`.

    ``canon`` enables the per-step frontier canonicalization pass
    (ops/canon.py) SHARD-LOCALLY: the caller filters the exchange
    network to pairs whose slot bits stay inside the shard
    (max_bit = 5 + log2(w_loc)), which is sound because every
    compare-exchange is individually sound — device-bit pairs are
    simply not reduced. The engage decision keys on the psum'd global
    frontier size, so every device takes the same branch. ``memo_slots``
    enables the sparse engine's per-tile seen memo per shard (consumed
    popcounts, ops/wgl3_sparse.make_step_fn3_sparse rationale); the
    nothing-eligible skip keys on the psum'd eligible count so the
    branch — and the ppermutes inside the sweep — stay collective-
    consistent.

    With a `plan` (ops/wgl3_sparse.SparsePlan built on the SHARD width),
    each closure round runs the sparse active-tile sweep over the shard's
    LIVE tiles: occupancy is shard-local, but the dense/sparse decision
    comes from the ALL-REDUCED density signal (psum of live tiles + pmax
    of the per-shard work-list pressure), so every device takes the same
    branch and the branch-internal ppermutes stay collective-consistent.
    A shard whose live tiles overflow the work list forces a dense round
    EVERYWHERE — configs are never dropped. Verdicts stay bit-identical
    to the single-device kernel (same monotone fixpoint argument as
    ops/wgl3_sparse.py, with the device-bit fires crossing the mesh in
    both formulations)."""
    K, S = cfg.k_slots, cfg.n_states
    assert K >= 5 and S <= 32
    W = 1 << (K - 5)
    assert W % d == 0 and (d & (d - 1)) == 0
    w_loc = W // d
    lbits = w_loc.bit_length() - 1
    dbits = d.bit_length() - 1
    lo_masks = jnp.asarray(np.array(_LO_MASK, dtype=np.uint32))
    full = jnp.uint32(0xFFFFFFFF)
    w_idx_loc = jnp.arange(w_loc, dtype=jnp.int32)

    def dev():
        return jax.lax.axis_index(axis)

    def allowed_mask(t):
        """u32[w_loc]: this shard's positions whose mask has bit t CLEAR
        (global word index = dev * w_loc + local)."""
        in_word = lo_masks[jnp.minimum(t, 4)]
        w_glob = dev() * w_loc + w_idx_loc
        word_level = jnp.where(
            ((w_glob >> jnp.maximum(t - 5, 0)) & 1) == 0, full,
            jnp.uint32(0))
        return jnp.where(t < 5, jnp.broadcast_to(in_word, (w_loc,)),
                         word_level)

    def or_reduce(tj, src):
        acc = jnp.zeros_like(src)
        for s in range(S):
            sel = tj[s].reshape((S,) + (1,) * (src.ndim - 1))
            acc = acc | jnp.where(sel, src[s][None], jnp.uint32(0))
        return acc

    def expand(T, trans, allowed):
        """One Gauss-Seidel sweep over all K slots; high slots cross the
        mesh with one ppermute each."""
        for j in range(K):
            src = T & allowed[None, :]
            if j < 5:
                fired = or_reduce(trans[j], src & _LO_MASK[j])
                T = T | (fired << np.uint32(1 << j))
            elif j - 5 < lbits:
                lo_w, hi = 1 << (j - 5), w_loc >> (j - 4)
                Tr = T.reshape(S, hi, 2, lo_w)
                srcj = src.reshape(S, hi, 2, lo_w)[:, :, 0, :]
                fired = or_reduce(trans[j], srcj)
                T = jnp.stack([Tr[:, :, 0, :], Tr[:, :, 1, :] | fired],
                              axis=2).reshape(S, w_loc)
            else:
                b = j - 5 - lbits
                src_dev = ((dev() >> b) & 1) == 0
                fired = or_reduce(trans[j], src)
                fired = jnp.where(src_dev, fired, jnp.uint32(0))
                recv = jax.lax.ppermute(
                    fired, axis,
                    perm=[(p, p | (1 << b)) for p in range(d)
                          if not (p >> b) & 1])
                T = T | recv
        return T

    def prune_local(T, t, allowed):
        """t's mask bit is in-word or in the LOCAL word bits: the
        single-device addressing verbatim (w_loc in place of W)."""
        shift = jnp.where(t < 5, jnp.uint32(1) << jnp.minimum(
            t.astype(jnp.uint32), jnp.uint32(4)), jnp.uint32(0))
        wsel = jnp.where(t < 5, w_idx_loc,
                         w_idx_loc | (jnp.int32(1)
                                      << jnp.maximum(t - 5, 0)))
        # Clamp: when t's bit is beyond the local bits this branch is not
        # taken (lax.switch routes to a remote branch); clamp keeps the
        # gather in bounds for the untaken trace.
        wsel = jnp.minimum(wsel, w_loc - 1)
        return (T[:, wsel] >> shift) & allowed[None, :]

    def prune_remote(b):
        def f(T, t, allowed):
            recv = jax.lax.ppermute(
                T, axis,
                perm=[(p, p ^ (1 << b)) for p in range(d) if (p >> b) & 1])
            return recv & allowed[None, :]
        return f

    def prune(T, t, allowed):
        # switch index: 0 = local bit, 1+b = device bit b.
        idx = jnp.clip(t - 5 - lbits + 1, 0, dbits)
        return jax.lax.switch(
            idx,
            [lambda T, t, a: prune_local(T, t, a)]
            + [prune_remote(b) for b in range(dbits)],
            T, t, allowed)

    # -- shard-local occupancy tiling (telemetry always; sparse when a
    #    plan is given) — the one shared tiling policy, clamped to the
    #    SHARD width (wgl3.live_tile_geometry).
    lim = limits()
    if plan is not None:
        tile, nt_loc = plan.tile_words, plan.n_tiles
    else:
        tile, nt_loc = wgl3.live_tile_geometry(cfg, words=w_loc)
    nt_glob = nt_loc * d
    tbits = tile.bit_length() - 1
    tile_off = jnp.arange(tile, dtype=jnp.int32)
    memo = memo_slots > 0
    assert not memo or (plan is not None and memo_slots == nt_loc), \
        (memo_slots, nt_loc)
    if plan is not None:
        CAP = plan.cap
        cap_ids = jnp.arange(CAP, dtype=jnp.int32)
        thresh_glob = (nt_glob if lim.sparse_mode == 2 else
                       max(1, nt_glob * lim.sparse_density_threshold_pct
                           // 100))
    if canon:
        from ..ops.canon import apply_step_canon, make_table_canon

        canon_fn = make_table_canon(w_loc)

    def occupancy(T):
        any_w = jnp.any(T != jnp.uint32(0), axis=0)
        occ_t = jnp.any(any_w.reshape(nt_loc, tile), axis=1)
        return occ_t, jnp.sum(occ_t, dtype=jnp.int32)

    def tile_popcounts(T):
        """Shard-local per-tile config counts; the memo loop carries
        the vector between rounds so eligibility and the psum'd
        convergence check share one reduce (the wgl3_sparse twin's
        rationale)."""
        pc = jax.lax.population_count(T).astype(jnp.int32)
        return jnp.sum(pc.reshape(S, nt_loc, tile), axis=(0, 2))

    def sweep_sparse(T, trans, allowed, idx, count):
        """Gather->expand->scatter over this SHARD's listed tiles (the
        caller builds the list from shard-local occupancy — or, with
        the seen memo, from the tiles that grew since last swept). Local
        slot bits mirror ops/wgl3_sparse.make_sparse_sweep on the shard;
        device-bit fires scatter to full shard width first, then cross
        the mesh with the same ppermute the dense expand uses.

        LOCKSTEP NOTE: keep the in-word/in-tile/tile-bit branches and
        the valid/src_ok masking identical to make_sparse_sweep (see its
        docstring) — fixes must land in both copies."""
        valid = cap_ids < count
        cols = idx[:, None] * tile + tile_off[None, :]
        flat = cols.reshape(-1)
        G = jnp.where(valid[None, :, None], T[:, cols], jnp.uint32(0))
        aG = allowed[cols][None]
        crossT = T
        for j in range(K):
            src = G & aG
            if j < 5:
                fired = or_reduce(trans[j], src & _LO_MASK[j])
                G = G | (fired << np.uint32(1 << j))
            elif j - 5 < tbits:
                lo_w = 1 << (j - 5)
                hi = tile >> (j - 4)
                Gr = G.reshape(S, CAP, hi, 2, lo_w)
                srcj = src.reshape(S, CAP, hi, 2, lo_w)[:, :, :, 0, :]
                fired = or_reduce(trans[j], srcj)
                G = jnp.stack(
                    [Gr[:, :, :, 0, :], Gr[:, :, :, 1, :] | fired],
                    axis=3).reshape(S, CAP, tile)
            elif j - 5 < lbits:
                # Local tile-index bit: scatter-OR into this shard.
                b = j - 5 - tbits
                src_ok = ((idx >> b) & 1) == 0
                fired = or_reduce(trans[j], src)
                fired = jnp.where((valid & src_ok)[None, :, None], fired,
                                  jnp.uint32(0))
                dcols = ((idx | (1 << b))[:, None] * tile
                         + tile_off[None, :]).reshape(-1)
                crossT = crossT | jnp.zeros_like(T).at[:, dcols].add(
                    fired.reshape(S, CAP * tile))
            else:
                # Device bit: fired configs scatter to full shard width,
                # then cross the mesh exactly like the dense expand.
                b = j - 5 - lbits
                src_dev = ((dev() >> b) & 1) == 0
                fired = or_reduce(trans[j], src)
                fired = jnp.where(valid[None, :, None] & src_dev, fired,
                                  jnp.uint32(0))
                fired_full = jnp.zeros_like(T).at[:, flat].add(
                    fired.reshape(S, CAP * tile))
                recv = jax.lax.ppermute(
                    fired_full, axis,
                    perm=[(p, p | (1 << b)) for p in range(d)
                          if not (p >> b) & 1])
                crossT = crossT | recv
        Gv = jnp.where(valid[None, :, None], G, jnp.uint32(0))
        return crossT | jnp.zeros_like(T).at[:, flat].add(
            Gv.reshape(S, CAP * tile))

    def step(carry, xs):
        T, dead, dead_step, maxf = carry
        if canon:
            trans, target, idx, pairs = xs
        else:
            trans, target, idx = xs
        is_pad = target < 0
        t = jnp.maximum(target, 0)
        allowed = allowed_mask(t)

        def body(st):
            if memo:
                (Tw, pc, n_prev, _c, rounds, sp_rounds, ovf_rounds,
                 swept) = st
            else:
                Tw, n_prev, _c, rounds, sp_rounds, ovf_rounds = st
            if plan is None:
                Tw = expand(Tw, trans, allowed)
                use_sparse = jnp.int32(0)
                ovf = jnp.int32(0)
            else:
                if memo:
                    occ_t = pc > 0
                    live_loc = jnp.sum(occ_t, dtype=jnp.int32)
                    elig_t = occ_t & (pc != swept)
                    elig_loc = jnp.sum(elig_t, dtype=jnp.int32)
                    elig_g = jax.lax.psum(elig_loc, axis)
                else:
                    occ_t, live_loc = occupancy(Tw)
                    elig_t, elig_loc = occ_t, live_loc
                    elig_g = None
                # All-reduced density signal: every device sees the same
                # global live count AND the worst shard's work-list
                # pressure, so the branch — and the ppermutes inside it —
                # is uniform across the mesh.
                live_g = jax.lax.psum(live_loc, axis)
                live_max = jax.lax.pmax(live_loc, axis)
                take_density = live_g <= thresh_glob
                take = take_density & (live_max <= CAP)
                # The previously-silent fallback, surfaced: a round the
                # density signal WANTED sparse but a shard's work-list
                # pressure forced dense (wgl.sparse_overflow_rounds).
                ovf = (take_density & ~take).astype(jnp.int32)
                wl = jnp.nonzero(elig_t, size=CAP, fill_value=0)[0]
                count = jnp.minimum(elig_loc, jnp.int32(CAP))
                if memo:
                    # Nothing grew anywhere on the mesh: the sweep is a
                    # no-op — skip it UNIFORMLY (the predicate is the
                    # psum'd count, so the collectives stay consistent).
                    take_sweep = take & (elig_g > 0)
                else:
                    take_sweep = take
                Tw = jax.lax.cond(
                    take_sweep,
                    lambda T: sweep_sparse(T, trans, allowed, wl, count),
                    lambda T: jax.lax.cond(
                        take, lambda T: T,
                        lambda T: expand(T, trans, allowed), T),
                    Tw)
                use_sparse = take.astype(jnp.int32)
                if memo:
                    swept2 = swept.at[
                        jnp.where(cap_ids < count, wl,
                                  jnp.int32(nt_loc))].set(
                            pc[wl], mode="drop")
                    swept = jnp.where(take, swept2,
                                      jnp.full((nt_loc,), -1, jnp.int32))
            if memo:
                # One shard-local reduce serves next round's eligibility
                # AND this round's psum'd convergence check.
                pc2 = tile_popcounts(Tw)
                n_now = jax.lax.psum(jnp.sum(pc2, dtype=jnp.int32), axis)
                return (Tw, pc2, n_now, n_now > n_prev, rounds + 1,
                        sp_rounds + use_sparse, ovf_rounds + ovf, swept)
            n_now = jax.lax.psum(
                jnp.sum(jax.lax.population_count(Tw), dtype=jnp.int32),
                axis)
            return (Tw, n_now, n_now > n_prev, rounds + 1,
                    sp_rounds + use_sparse, ovf_rounds + ovf)

        ci = 3 if memo else 2   # index of `changed` in the loop state

        def cond(st):
            return st[ci] & (st[ci + 1] < cfg.rounds)

        if memo:
            pc0 = tile_popcounts(T)
            init = (T, pc0,
                    jax.lax.psum(jnp.sum(pc0, dtype=jnp.int32), axis),
                    ~is_pad, jnp.int32(0), jnp.int32(0), jnp.int32(0),
                    jnp.full((nt_loc,), -1, jnp.int32))
            fin = jax.lax.while_loop(cond, body, init)
            T, _pc, n, _c, rounds, sp_rounds, ovf_rounds = fin[:7]
        else:
            n0 = jax.lax.psum(
                jnp.sum(jax.lax.population_count(T), dtype=jnp.int32),
                axis)
            init = (T, n0, ~is_pad, jnp.int32(0), jnp.int32(0),
                    jnp.int32(0))
            fin = jax.lax.while_loop(cond, body, init)
            T, n, _c, rounds, sp_rounds, ovf_rounds = fin[:6]
        if canon:
            # Shard-local canonicalization of the converged frontier
            # (pairs pre-filtered to shard-local bits by the caller);
            # the gate keys on the GLOBAL frontier size and the count
            # reduce is psum'd, so the branch — and the collective
            # inside it — is uniform across the mesh.
            T, n, canon_pruned, canon_base = apply_step_canon(
                canon_fn, T, pairs, n, is_pad, min_frontier,
                count_fn=lambda Tc: jax.lax.psum(
                    jnp.sum(jax.lax.population_count(Tc),
                            dtype=jnp.int32), axis))
        _occ, live_fin = occupancy(T)
        live_g_fin = jax.lax.psum(live_fin, axis)

        pruned = prune(T, t, allowed)
        T_new = jnp.where(is_pad, T, pruned)
        alive = jax.lax.psum(
            jnp.any(T_new != 0).astype(jnp.int32), axis) > 0
        died = ~is_pad & ~dead & ~alive
        dead = dead | died
        T_new = jnp.where(dead, jnp.zeros_like(T_new), T_new)
        sparse_all = ((~is_pad) & (rounds > 0)
                      & (sp_rounds == rounds)).astype(jnp.int32)
        outs = (jnp.where(is_pad, 0, n),
                jnp.where(is_pad, 0, live_g_fin),
                jnp.where(is_pad, 0, sparse_all),
                jnp.where(is_pad, 0, ovf_rounds))
        if canon:
            outs = outs + (canon_pruned, canon_base)
        return (T_new, dead,
                jnp.where(died & (dead_step < 0), idx, dead_step),
                jnp.maximum(maxf, n)), outs

    return step, w_loc, (tile, nt_glob)


def lattice_sparse_plan(cfg: DenseConfig, d: int):
    """The sparse plan for one SHARD of the lattice (None = dense): the
    work list and tile geometry are sized on the per-device word count,
    so each shard gathers its own live tiles."""
    from ..ops.wgl3_sparse import sparse_plan

    return sparse_plan(cfg, words=(1 << (cfg.k_slots - 5)) // d)


def make_lattice_chunk_fn(model: Model, cfg: DenseConfig, mesh: Mesh,
                          axis: str = "lattice", plan=None,
                          canon: bool = False, min_frontier: int = 0,
                          memo_slots: int = 0):
    """(jitted chunk fn, (tile_words, global n_tiles)): the chunk fn is
    (table[S, W] sharded, dead, dead_step, maxf, trans[C,K,S,S'],
    tgts[C], [pairs[C,P,2] when canon,] idx0) -> (table', dead',
    dead_step', maxf', f32[7] partials [configs, live-tile sum, real
    steps, sparse steps, overflow rounds, canon pruned, canon base —
    the canon columns are zeros in a canon-off build])
    — the sharded twin of wgl3._chunk_fn. The table stays a
    mesh-sharded jax.Array between host-loop chunks; the tiling rides
    along so the caller's sweep_summary denominator is EXACTLY the
    tiling the kernel swept. `axis` may be a tuple of mesh axis names
    (the N-D pod mesh: the word axis shards over the product, and
    every collective in the step reduces across both axes); default =
    every axis of `mesh`."""
    axis = _resolve_axis(mesh, axis)
    d = mesh_total(mesh)
    step, w_loc, tiling = _build_local_step(
        model, cfg, axis, d, plan=plan, canon=canon,
        min_frontier=min_frontier, memo_slots=memo_slots)

    def run(table, dead, dead_step, maxf, trans, tgts, *rest):
        if canon:
            pairs, idx0 = rest
        else:
            (idx0,) = rest
        idxs = idx0 + jnp.arange(tgts.shape[0], dtype=jnp.int32)
        xs = (trans, tgts, idxs) + ((pairs,) if canon else ())
        (table, dead, dead_step, maxf), outs = jax.lax.scan(
            step, (table, dead, dead_step, maxf), xs)
        # FIXED seven-column row in both builds (canon-off emits zero
        # canon columns): one partial layout, one consumer indexing.
        # jtflow: partials configs_explored,live_tile_sum,real_steps,sparse_steps,overflow_rounds,canon_pruned,canon_base
        parts = jnp.stack([
            jnp.sum(outs[0].astype(jnp.float32)),
            jnp.sum(outs[1].astype(jnp.float32)),
            jnp.sum((tgts >= 0).astype(jnp.float32)),
            jnp.sum(outs[2].astype(jnp.float32)),
            jnp.sum(outs[3].astype(jnp.float32)),
            jnp.sum(outs[4].astype(jnp.float32)) if canon
            else jnp.float32(0),
            jnp.sum(outs[5].astype(jnp.float32)) if canon
            else jnp.float32(0)])
        return table, dead, dead_step, maxf, parts

    in_specs = [P(None, axis), P(), P(), P(), P(None, None, None, None),
                P(None)]
    if canon:
        in_specs.append(P(None, None))   # pairs: replicated
    in_specs.append(P())
    specs = dict(
        mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=(P(None, axis), P(), P(), P(), P()))
    try:
        sharded = shard_map(run, check_vma=False, **specs)
    except TypeError:
        sharded = shard_map(run, check_rep=False, **specs)
    # obs/ compile/execute attribution (the PR 1 invariant, enforced by
    # jtlint JTL105): this lane shipped uninstrumented in PR 3 — under
    # virtual-device CI it IS the production wide-geometry path.
    return instrument_kernel("wgl3-lattice-chunk", jax.jit(sharded)), \
        tiling


def cached_lattice_chunk(model: Model, cfg: DenseConfig, mesh: Mesh,
                         axis: str = "lattice", plan=None,
                         canon: bool = False, min_frontier: int = 0,
                         memo_slots: int = 0):
    axis = _resolve_axis(mesh, axis)
    key = ("lattice-chunk", model.cache_key(), cfg, _mesh_key(mesh), axis,
           plan, canon, min_frontier, memo_slots)
    if key not in _CACHE:
        _CACHE[key] = make_lattice_chunk_fn(
            model, cfg, mesh, axis, plan=plan, canon=canon,
            min_frontier=min_frontier, memo_slots=memo_slots)
    return _CACHE[key]


def _transitions_fn(model: Model, cfg: DenseConfig):
    key = ("lattice-trans", model.cache_key(), cfg)
    if key not in _CACHE:
        _, transitions = wgl3.make_step_fn3(model, cfg)
        _CACHE[key] = instrument_kernel("lattice-transitions",
                                        jax.jit(jax.vmap(transitions)))
    return _CACHE[key]


def check_steps_lattice_long(rs: ReturnSteps, model: Model,
                             cfg: DenseConfig, mesh: Mesh | None = None,
                             chunk: int | None = None,
                             time_budget_s: float | None = None) -> dict:
    """Sharded host-chunked dense sweep: the wide-geometry twin of
    wgl3.check_steps3_long. Same result schema, same honest "unknown" on
    budget expiry; exact otherwise. Eligible geometries run the sparse
    active-tile engine per shard (lattice_sparse_plan; limits().
    sparse_mode gates it) with the all-reduced density switch — this is
    the K ~ 20 lane the sparse engine exists for, so the win compounds
    with the device count."""
    import time as _time

    from ..ops.wgl import verdict
    from ..ops.wgl3 import sweep_summary

    t0 = _time.monotonic()
    if mesh is None:
        mesh = lattice_mesh()
    d = int(np.prod(list(mesh.shape.values())))
    plan = lattice_sparse_plan(cfg, d)
    if chunk is None:
        cells = cfg.n_states * cfg.n_masks // d   # per-device sweep cost
        base = limits().long_scan_chunk
        chunk = min(base, max(128, base * (1 << 15) // max(cells, 1)))
    n = rs.n_steps
    n_pad = (n + chunk - 1) // chunk * chunk
    rs = rs.padded_to(n_pad)
    # Frontier canonicalization (ops/canon.py): dedup SHARD-LOCALLY —
    # pairs touching device-index bits are filtered out host-side
    # (every compare-exchange is individually sound, so the partial
    # network is exact too), then the occupancy/density signals are
    # all-reduced exactly like the PR 3 sparse branch.
    from ..ops.canon import dedup_min_frontier_active, history_canon_pairs
    from ..ops.wgl3_sparse import memo_slots_for

    w_loc = (1 << (cfg.k_slots - 5)) // d
    pairs = history_canon_pairs(rs, table=True,
                                max_bit=5 + w_loc.bit_length() - 1)
    memo = memo_slots_for(plan) if plan is not None else 0
    run, tiling = cached_lattice_chunk(
        model, cfg, mesh, plan=plan, canon=pairs is not None,
        min_frontier=(dedup_min_frontier_active()
                      if pairs is not None else 0),
        memo_slots=memo)
    trans_of = _transitions_fn(model, cfg)
    # Carry starts as host values; jit output keeps the table sharded
    # across chunks.
    w = 1 << (cfg.k_slots - 5)
    table = jnp.zeros((cfg.n_states, w), jnp.uint32)
    row = int(model.init_state()) + cfg.state_offset
    table = table.at[row, 0].set(jnp.uint32(1))
    dead = jnp.bool_(False)
    dead_step = jnp.int32(-1)
    maxf = jnp.int32(1)
    cfgs_dev = None
    for c in range(n_pad // chunk):
        if (time_budget_s is not None
                and _time.monotonic() - t0 > time_budget_s):
            return {"valid": "unknown", "survived": False, "overflow": True,
                    "dead_step": -1, "max_frontier": -1,
                    "configs_explored": -1, "kernel": "exhausted",
                    "error": f"sharded dense sweep exceeded its "
                             f"{time_budget_s:.0f}s time budget at return "
                             f"step {c * chunk}"}
        sl = slice(c * chunk, (c + 1) * chunk)
        trans = trans_of(jnp.asarray(rs.slot_tabs[sl]),
                         jnp.asarray(rs.slot_active[sl]))
        args = (jnp.asarray(rs.targets[sl]),)
        if pairs is not None:
            args = args + (jnp.asarray(pairs[sl]),)
        table, dead, dead_step, maxf, part = run(
            table, dead, dead_step, maxf, trans, *args,
            jnp.int32(c * chunk))
        cfgs_dev = part if cfgs_dev is None else cfgs_dev + part
        # jtlint: disable=JTL103 -- per-chunk death fetch: chunk sizes here
        # are large (>=128 scanned steps each), so the fetch amortizes; it
        # is what bounds a falsified history's sweep to one extra chunk.
        if bool(np.asarray(dead)):
            break
    if cfgs_dev is None:
        cfgs_dev = jnp.zeros((7,), jnp.float32)
    # jtflow: partials-from lattice.make_lattice_chunk_fn
    parts = np.asarray(jnp.clip(cfgs_dev, 0, 2**31 - 1).astype(jnp.int32))
    out = {
        "survived": not bool(np.asarray(dead)),
        "overflow": False,
        "dead_step": int(np.asarray(dead_step)),
        "max_frontier": int(np.asarray(maxf)),
        "configs_explored": int(parts[0]),
        "kernel": ("wgl3-dense-lattice-sparse" if plan is not None
                   else "wgl3-dense-lattice-sharded"),
    }
    # Global sweep telemetry: the live counts were psum'd device-side
    # and `tiling` is exactly (tile_words, global tile count) the
    # compiled step swept — no recomputation to drift.
    out["sweep"] = sweep_summary(cfg, live_sum=float(parts[1]),
                                 real_steps=int(parts[2]),
                                 sparse_steps=int(parts[3]),
                                 tiling=tiling,
                                 overflow_rounds=int(parts[4]))
    out["live_tile_ratio"] = out["sweep"]["live_tile_ratio"]
    if pairs is not None:
        # Columns 5/6 are zeros in a canon-off build — only attach the
        # record when the canonicalizing kernel actually ran.
        wgl3.attach_dedup_record(out, pruned=float(parts[5]),
                                 base=float(parts[6]))
    out["valid"] = verdict(out)
    record_check_result(out)
    return out
