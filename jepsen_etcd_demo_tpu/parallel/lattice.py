"""Lattice-sharded dense WGL search: ONE wide history, many devices.

The dense subset-lattice kernel (ops/wgl3.py) holds the search frontier as
the characteristic table u32[S, W] over (state, pending-mask) configs,
W = 2^(K-5) packed words. Past K ~ 17 the table outgrows one device's cell
budget and the single-device ladder falls back to the sort kernel or the
host-chunked sweep (ops/wgl3_pallas.check_encoded_general). This module
shards the table's WORD axis over a mesh axis instead — the build's
sequence-parallelism analogue (SURVEY.md §5.7): history length is the
sequence, the lattice is the per-step state, and each device owns the
2^(K-5)/D words whose global index falls in its contiguous shard.

What each table operation becomes under the shard (device count D = 2^dbits,
local words W_loc = W/D, lbits = log2(W_loc); global word index = low lbits
local | high dbits device):

  * expanding slot j < 5            in-word shift — LOCAL
  * expanding 5 <= j < 5+lbits      local word-axis reshape — LOCAL
  * expanding j >= 5+lbits          the mask bit lives in the DEVICE index:
                                    devices with bit b = j-5-lbits clear OR
                                    their fired configs into partner
                                    d | 1<<b — ONE lax.ppermute over ICI
  * pruning at return t             same split; the remote case is the
                                    reverse ppermute (bit-set partner sends
                                    its half down), selected by lax.switch
                                    over the dbits static permutations
  * frontier size / death           psum of local popcount / any

Exactness is unchanged — the sharded table is the same whole config space,
just partitioned; no capacity, no overflow, no dropped configs. Verdicts
are bit-identical to the single-device dense kernel (differentially
tested), and the chunked host loop (`check_steps_lattice_long`) mirrors
check_steps3_long with the carry staying sharded on-device between chunks.

Production routing: check_encoded_general's dense-chunked rung upgrades to
this path automatically when jax.device_count() > 1 and the geometry
shards (W >= D) — with the cell budget scaled by D, geometries the
single-device rung must refuse become checkable at all.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map

from ..models.base import Model
from ..ops import wgl3
from ..ops.encode import ReturnSteps
from ..ops.limits import limits
from ..ops.wgl3 import DenseConfig, _LO_MASK
from .mesh import make_mesh

_CACHE: dict[tuple, Any] = {}


def lattice_mesh(n_devices: int | None = None) -> Mesh:
    return make_mesh(n_devices, axes=("lattice",))


def _mesh_key(mesh: Mesh) -> tuple:
    return (tuple(mesh.axis_names), tuple(mesh.shape.values()),
            tuple(d.id for d in mesh.devices.flat))


def lattice_dense_config(model: Model, k_slots: int, max_value: int,
                         n_devices: int,
                         budget: int | None = None) -> DenseConfig | None:
    """DenseConfig for the SHARDED lattice: the cell budget scales with the
    device count (each device holds cells/D), and the word axis must split
    evenly — D a power of two with W >= D (the ppermute pairing addresses
    devices by mask bits). Infeasible platforms get None so routing falls
    back to the single-device rung instead of crashing mid-check."""
    if n_devices < 2 or n_devices & (n_devices - 1):
        return None
    if budget is None:
        budget = limits().dense_cell_budget_chunked * n_devices
    cfg = wgl3.dense_config(model, k_slots, max_value, budget=budget)
    if cfg is None or (1 << (cfg.k_slots - 5)) < n_devices:
        return None
    return cfg


def _build_local_step(model: Model, cfg: DenseConfig, axis: str, d: int):
    """The per-device scan body over one shard of the table. Mirrors
    wgl3.make_step_fn3 exactly (same banking/closure/prune semantics, same
    metrics) with the word axis split over `axis`."""
    K, S = cfg.k_slots, cfg.n_states
    assert K >= 5 and S <= 32
    W = 1 << (K - 5)
    assert W % d == 0 and (d & (d - 1)) == 0
    w_loc = W // d
    lbits = w_loc.bit_length() - 1
    dbits = d.bit_length() - 1
    lo_masks = jnp.asarray(np.array(_LO_MASK, dtype=np.uint32))
    full = jnp.uint32(0xFFFFFFFF)
    w_idx_loc = jnp.arange(w_loc, dtype=jnp.int32)

    def dev():
        return jax.lax.axis_index(axis)

    def allowed_mask(t):
        """u32[w_loc]: this shard's positions whose mask has bit t CLEAR
        (global word index = dev * w_loc + local)."""
        in_word = lo_masks[jnp.minimum(t, 4)]
        w_glob = dev() * w_loc + w_idx_loc
        word_level = jnp.where(
            ((w_glob >> jnp.maximum(t - 5, 0)) & 1) == 0, full,
            jnp.uint32(0))
        return jnp.where(t < 5, jnp.broadcast_to(in_word, (w_loc,)),
                         word_level)

    def or_reduce(tj, src):
        acc = jnp.zeros_like(src)
        for s in range(S):
            sel = tj[s].reshape((S,) + (1,) * (src.ndim - 1))
            acc = acc | jnp.where(sel, src[s][None], jnp.uint32(0))
        return acc

    def expand(T, trans, allowed):
        """One Gauss-Seidel sweep over all K slots; high slots cross the
        mesh with one ppermute each."""
        for j in range(K):
            src = T & allowed[None, :]
            if j < 5:
                fired = or_reduce(trans[j], src & _LO_MASK[j])
                T = T | (fired << np.uint32(1 << j))
            elif j - 5 < lbits:
                lo_w, hi = 1 << (j - 5), w_loc >> (j - 4)
                Tr = T.reshape(S, hi, 2, lo_w)
                srcj = src.reshape(S, hi, 2, lo_w)[:, :, 0, :]
                fired = or_reduce(trans[j], srcj)
                T = jnp.stack([Tr[:, :, 0, :], Tr[:, :, 1, :] | fired],
                              axis=2).reshape(S, w_loc)
            else:
                b = j - 5 - lbits
                src_dev = ((dev() >> b) & 1) == 0
                fired = or_reduce(trans[j], src)
                fired = jnp.where(src_dev, fired, jnp.uint32(0))
                recv = jax.lax.ppermute(
                    fired, axis,
                    perm=[(p, p | (1 << b)) for p in range(d)
                          if not (p >> b) & 1])
                T = T | recv
        return T

    def prune_local(T, t, allowed):
        """t's mask bit is in-word or in the LOCAL word bits: the
        single-device addressing verbatim (w_loc in place of W)."""
        shift = jnp.where(t < 5, jnp.uint32(1) << jnp.minimum(
            t.astype(jnp.uint32), jnp.uint32(4)), jnp.uint32(0))
        wsel = jnp.where(t < 5, w_idx_loc,
                         w_idx_loc | (jnp.int32(1)
                                      << jnp.maximum(t - 5, 0)))
        # Clamp: when t's bit is beyond the local bits this branch is not
        # taken (lax.switch routes to a remote branch); clamp keeps the
        # gather in bounds for the untaken trace.
        wsel = jnp.minimum(wsel, w_loc - 1)
        return (T[:, wsel] >> shift) & allowed[None, :]

    def prune_remote(b):
        def f(T, t, allowed):
            recv = jax.lax.ppermute(
                T, axis,
                perm=[(p, p ^ (1 << b)) for p in range(d) if (p >> b) & 1])
            return recv & allowed[None, :]
        return f

    def prune(T, t, allowed):
        # switch index: 0 = local bit, 1+b = device bit b.
        idx = jnp.clip(t - 5 - lbits + 1, 0, dbits)
        return jax.lax.switch(
            idx,
            [lambda T, t, a: prune_local(T, t, a)]
            + [prune_remote(b) for b in range(dbits)],
            T, t, allowed)

    def step(carry, xs):
        T, dead, dead_step, maxf = carry
        trans, target, idx = xs
        is_pad = target < 0
        t = jnp.maximum(target, 0)
        allowed = allowed_mask(t)

        def body(st):
            Tw, n_prev, _c, rounds = st
            Tw = expand(Tw, trans, allowed)
            n_now = jax.lax.psum(
                jnp.sum(jax.lax.population_count(Tw), dtype=jnp.int32),
                axis)
            return Tw, n_now, n_now > n_prev, rounds + 1

        def cond(st):
            return st[2] & (st[3] < cfg.rounds)

        n0 = jax.lax.psum(
            jnp.sum(jax.lax.population_count(T), dtype=jnp.int32), axis)
        T, n, _c, _r = jax.lax.while_loop(
            cond, body, (T, n0, ~is_pad, jnp.int32(0)))

        pruned = prune(T, t, allowed)
        T_new = jnp.where(is_pad, T, pruned)
        alive = jax.lax.psum(
            jnp.any(T_new != 0).astype(jnp.int32), axis) > 0
        died = ~is_pad & ~dead & ~alive
        dead = dead | died
        T_new = jnp.where(dead, jnp.zeros_like(T_new), T_new)
        return (T_new, dead,
                jnp.where(died & (dead_step < 0), idx, dead_step),
                jnp.maximum(maxf, n)), jnp.where(is_pad, 0, n)

    return step, w_loc


def make_lattice_chunk_fn(model: Model, cfg: DenseConfig, mesh: Mesh,
                          axis: str = "lattice"):
    """jitted (table[S, W] sharded, dead, dead_step, maxf,
    trans[C,K,S,S'], tgts[C], idx0) -> (table', dead', dead_step', maxf',
    configs-partial) — the sharded twin of wgl3._chunk_fn. The table stays
    a mesh-sharded jax.Array between host-loop chunks."""
    d = mesh.shape[axis]
    step, w_loc = _build_local_step(model, cfg, axis, d)

    def run(table, dead, dead_step, maxf, trans, tgts, idx0):
        idxs = idx0 + jnp.arange(tgts.shape[0], dtype=jnp.int32)
        (table, dead, dead_step, maxf), ns = jax.lax.scan(
            step, (table, dead, dead_step, maxf), (trans, tgts, idxs))
        return table, dead, dead_step, maxf, jnp.sum(
            ns.astype(jnp.float32))

    specs = dict(
        mesh=mesh,
        in_specs=(P(None, axis), P(), P(), P(), P(None, None, None, None),
                  P(None), P()),
        out_specs=(P(None, axis), P(), P(), P(), P()))
    try:
        sharded = shard_map(run, check_vma=False, **specs)
    except TypeError:
        sharded = shard_map(run, check_rep=False, **specs)
    return jax.jit(sharded)


def cached_lattice_chunk(model: Model, cfg: DenseConfig, mesh: Mesh,
                         axis: str = "lattice"):
    key = ("lattice-chunk", model.cache_key(), cfg, _mesh_key(mesh), axis)
    if key not in _CACHE:
        _CACHE[key] = make_lattice_chunk_fn(model, cfg, mesh, axis)
    return _CACHE[key]


def _transitions_fn(model: Model, cfg: DenseConfig):
    key = ("lattice-trans", model.cache_key(), cfg)
    if key not in _CACHE:
        _, transitions = wgl3.make_step_fn3(model, cfg)
        _CACHE[key] = jax.jit(jax.vmap(transitions))
    return _CACHE[key]


def check_steps_lattice_long(rs: ReturnSteps, model: Model,
                             cfg: DenseConfig, mesh: Mesh | None = None,
                             chunk: int | None = None,
                             time_budget_s: float | None = None) -> dict:
    """Sharded host-chunked dense sweep: the wide-geometry twin of
    wgl3.check_steps3_long. Same result schema, same honest "unknown" on
    budget expiry; exact otherwise."""
    import time as _time

    from ..ops.wgl import verdict

    t0 = _time.monotonic()
    if mesh is None:
        mesh = lattice_mesh()
    d = int(np.prod(list(mesh.shape.values())))
    if chunk is None:
        cells = cfg.n_states * cfg.n_masks // d   # per-device sweep cost
        base = limits().long_scan_chunk
        chunk = min(base, max(128, base * (1 << 15) // max(cells, 1)))
    run = cached_lattice_chunk(model, cfg, mesh)
    trans_of = _transitions_fn(model, cfg)
    n = rs.n_steps
    n_pad = (n + chunk - 1) // chunk * chunk
    rs = rs.padded_to(n_pad)
    # Carry starts as host values; jit output keeps the table sharded
    # across chunks.
    w = 1 << (cfg.k_slots - 5)
    table = jnp.zeros((cfg.n_states, w), jnp.uint32)
    row = int(model.init_state()) + cfg.state_offset
    table = table.at[row, 0].set(jnp.uint32(1))
    dead = jnp.bool_(False)
    dead_step = jnp.int32(-1)
    maxf = jnp.int32(1)
    cfgs_dev = None
    for c in range(n_pad // chunk):
        if (time_budget_s is not None
                and _time.monotonic() - t0 > time_budget_s):
            return {"valid": "unknown", "survived": False, "overflow": True,
                    "dead_step": -1, "max_frontier": -1,
                    "configs_explored": -1, "kernel": "exhausted",
                    "error": f"sharded dense sweep exceeded its "
                             f"{time_budget_s:.0f}s time budget at return "
                             f"step {c * chunk}"}
        sl = slice(c * chunk, (c + 1) * chunk)
        trans = trans_of(jnp.asarray(rs.slot_tabs[sl]),
                         jnp.asarray(rs.slot_active[sl]))
        table, dead, dead_step, maxf, part = run(
            table, dead, dead_step, maxf, trans,
            jnp.asarray(rs.targets[sl]), jnp.int32(c * chunk))
        cfgs_dev = part if cfgs_dev is None else cfgs_dev + part
        if bool(np.asarray(dead)):
            break
    out = {
        "survived": not bool(np.asarray(dead)),
        "overflow": False,
        "dead_step": int(np.asarray(dead_step)),
        "max_frontier": int(np.asarray(maxf)),
        "configs_explored": int(np.asarray(
            jnp.clip(cfgs_dev, 0, 2**31 - 1))),
    }
    out["valid"] = verdict(out)
    return out
