"""Batch-axis mesh sharding for the PRODUCTION dense kernels (wgl3/pallas).

Round-2 verdict, missing #1/#2: the mesh-sharded paths wrapped only the
superseded v1 sort kernel, and nothing a user could invoke ever engaged a
mesh. This module shards the kernels that actually win the bench — the
dense subset-lattice XLA kernel and its fused pallas form — over the
corpus/independent-key batch axis (the reference's data parallelism:
independent per-key histories, src/jepsen/etcdemo.clj:115,120-125 [dep];
BASELINE.json configs[2]/[4]), and `check_batch_encoded_auto`
(ops/wgl3_pallas.py) routes through it AUTOMATICALLY whenever
`jax.device_count() > 1` — `corpus`, `analyze`, and the independent
checker inherit multi-device execution with no caller changes.

Per-history checks are embarrassingly parallel, so the sharding needs no
collectives: a NamedSharding over the [B] axis partitions the vmapped XLA
kernel directly, and the pallas kernel runs under shard_map with each
device launching its own (B/D, NC) grid over its shard. Ragged corpora are
padded to a multiple of the axis size with all-pad histories (targets=-1,
trivially valid — same convention as parallel/multislice.py) and results
are stripped back.
"""

from __future__ import annotations

import functools
import time
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map

from ..models.base import Model
from .. import obs
from ..obs import instrument_kernel
from ..ops import wgl3
from ..ops.limits import limits
from ..ops.wgl3 import DenseConfig
from .mesh import (host_count, make_mesh, mesh_key as _mesh_key,
                   pod_mesh, resolve_axis as _resolve_axis)

_CACHE: dict[tuple, Any] = {}


def batch_mesh(n_devices: int | None = None) -> Mesh:
    """The corpus batch-axis mesh. Single host: the 1-axis ("batch",)
    mesh every existing compiled shape keys on (or an explicit N-D
    shape via --mesh-shape / JEPSEN_TPU_MESH_SHAPE, axes
    ("host", "batch")). Multi-host (a pod, jax.process_count() > 1):
    the process-major ("host", "batch") pod mesh — NamedShardings over
    BOTH axes partition the corpus across DCN and ICI together
    (sharding specs name the axis tuple, so the 1-D and 2-D forms
    share every kernel)."""
    from .mesh import requested_shape

    if n_devices is None:
        shape = requested_shape()
        if shape is not None:
            if len(shape) > 2:
                raise ValueError(
                    f"--mesh-shape {'x'.join(map(str, shape))}: the "
                    f"batch lane builds at most 2-D ('host', 'batch') "
                    f"meshes")
            if len(shape) > 1:
                return make_mesh(axes=("host", "batch"), shape=shape)
            return make_mesh(shape[0], axes=("batch",))
        if host_count() > 1:
            return pod_mesh(axes=("host", "batch"))
    return make_mesh(n_devices, axes=("batch",))


def sharded_batch_checker3_packed(model: Model, cfg: DenseConfig,
                                  mesh: Mesh, axis: str = "batch"):
    """The XLA dense kernel, batch-sharded: jitted
    check(slot_tabs[B,R,K,4], slot_active[B,R,K], targets[B,R]) ->
    DEVICE i32[B, 6] (wgl3.PACKED_FIELDS_XLA — the verdict fields plus
    the live-tile occupancy telemetry column), with B partitioned over
    `axis` — a name, or a TUPLE of names on an N-D pod mesh
    (("host", "batch") partitions jointly; default = every mesh axis).
    B must be a multiple of the total device count."""
    axis = _resolve_axis(mesh, axis)
    key = ("dense-sharded", model.cache_key(), cfg, _mesh_key(mesh), axis)
    if key not in _CACHE:
        fn = jax.vmap(wgl3._check_one_fn(model, cfg))
        in_sh = (NamedSharding(mesh, P(axis, None, None, None)),
                 NamedSharding(mesh, P(axis, None, None)),
                 NamedSharding(mesh, P(axis, None)))
        out_sh = NamedSharding(mesh, P(axis, None))
        # instrument_kernel (obs/): compile/execute attribution for the
        # sharded lane too — under virtual-device CI this IS the
        # production dense path, and it must not be a telemetry blind
        # spot.
        # jtflow: packed wgl3.PACKED_FIELDS_XLA
        _CACHE[key] = instrument_kernel(
            "wgl3-dense-sharded",
            jax.jit(lambda *a: wgl3._pack_result(fn(*a)),
                    in_shardings=in_sh, out_shardings=out_sh))
    return _CACHE[key]


def sharded_device_encoder(k_slots: int, e_cap: int, r_cap: int,
                           mesh: Mesh, axis: str = "batch"):
    """The device-side history encoder (ops/encode_device.py),
    batch-sharded: jitted encode(events i32[B, e_cap, 6]) ->
    (slot_tabs i32[B, r_cap, K, 4], slot_active bool[B, r_cap, K],
    targets i32[B, r_cap]) with B partitioned over `axis`. Output
    shardings match the sharded checker's input shardings exactly, so
    the encoded tables NEVER visit the host: the compact event tensor
    crosses once and each device expands its own shard in place —
    killing the packed-table H2D that dominated the r06 pod waterfall.
    B must be a multiple of the total device count."""
    from ..ops import encode_device

    axis = _resolve_axis(mesh, axis)
    key = ("encode-sharded", k_slots, e_cap, r_cap, _mesh_key(mesh), axis)
    if key not in _CACHE:
        fn = jax.vmap(encode_device._encode_fn(k_slots, e_cap, r_cap))
        in_sh = NamedSharding(mesh, P(axis, None, None))
        out_sh = (NamedSharding(mesh, P(axis, None, None, None)),
                  NamedSharding(mesh, P(axis, None, None)),
                  NamedSharding(mesh, P(axis, None)))
        _CACHE[key] = instrument_kernel(
            "wgl3-encode-sharded",
            jax.jit(fn, in_shardings=(in_sh,), out_shardings=out_sh))
    return _CACHE[key]


def sharded_batch_checker2(model: Model, cfg2, mesh: Mesh,
                           axis: str = "batch"):
    """The SORT kernel (ops/wgl2.py — the non-dense production path:
    queue/multi-register geometries), batch-sharded like the dense
    kernel: jitted check(slot_tabs[B,R,K,4], slot_active[B,R,K],
    targets[B,R]) -> dict of [B] arrays partitioned over `axis` (name
    or tuple; default = every mesh axis). B must be a multiple of the
    total device count."""
    from ..ops import wgl2

    axis = _resolve_axis(mesh, axis)
    key = ("sort-sharded", model.cache_key(), cfg2, _mesh_key(mesh), axis)
    if key not in _CACHE:
        fn = jax.vmap(wgl2._check_one_fn(model, cfg2))
        in_sh = (NamedSharding(mesh, P(axis, None, None, None)),
                 NamedSharding(mesh, P(axis, None, None)),
                 NamedSharding(mesh, P(axis, None)))
        out_sh = NamedSharding(mesh, P(axis))
        _CACHE[key] = instrument_kernel(
            "wgl2-sort-sharded",
            jax.jit(fn, in_shardings=in_sh,
                    out_shardings={"survived": out_sh, "overflow": out_sh,
                                   "dead_step": out_sh,
                                   "max_frontier": out_sh}))
    return _CACHE[key]


def sharded_batch_checker_pallas(model: Model, cfg: DenseConfig, mesh: Mesh,
                                 axis: str = "batch",
                                 interpret: bool = False,
                                 group: int = 1):
    """The fused pallas kernel under shard_map: each device launches its
    own (B/D, NC) grid over its batch shard — the GROUPED grid when
    `group` > 1 (local shard batch must divide into groups; the router
    guarantees it via the batch multiple). Same signature and packed
    i32[B, 5] result as the sharded XLA checker. The prep half stays a
    plain sharded XLA jit (separate dispatch — the two pipeline, see
    make_batch_checker_pallas)."""
    from ..ops import wgl3_pallas

    axis = _resolve_axis(mesh, axis)
    key = ("pallas-sharded", model.cache_key(), cfg, _mesh_key(mesh), axis,
           interpret, group)
    if key in _CACHE:
        return _CACHE[key]

    # Both halves wear instrument_kernel (PR 1 invariant, jtlint
    # JTL105): prep is a real XLA program and the launcher wrapper is
    # cached per (b_loc, r) by the lru_cache below — uninstrumented,
    # the sharded pallas lane would be a telemetry blind spot.
    prep = instrument_kernel("wgl3-pallas-sharded-prep", jax.jit(
        functools.partial(wgl3_pallas.prepare_pallas_batch, model, cfg),
        in_shardings=(NamedSharding(mesh, P(axis, None, None, None)),
                      NamedSharding(mesh, P(axis, None, None)),
                      NamedSharding(mesh, P(axis, None))),
        out_shardings=(NamedSharding(mesh, P(axis, None, None, None)),
                       NamedSharding(mesh, P(axis, None)),
                       NamedSharding(mesh, P(axis)))))
    if group > 1:
        launcher = wgl3_pallas.local_pallas_launcher_grouped(
            model, cfg, group, interpret=interpret)
    else:
        launcher = wgl3_pallas.cached_pallas_launcher(model, cfg,
                                                      interpret=interpret)
    d = _axis_size(mesh, axis)

    @functools.lru_cache(maxsize=None)
    def sharded_launch(b_loc: int, r: int):
        def local(ln, tg, cm):  # i32[B/D], i32[B/D, R], u32[B/D, R, Sp, 128]
            return launcher(b_loc, r)(ln, tg, cm)

        specs = dict(mesh=mesh,
                     in_specs=(P(axis), P(axis, None),
                               P(axis, None, None, None)),
                     out_specs=P(axis, None))
        try:   # pallas_call out_shapes carry no vma: disable the check
            sharded = shard_map(local, check_vma=False, **specs)
        except TypeError:  # older jax names it check_rep
            sharded = shard_map(local, check_rep=False, **specs)
        return instrument_kernel("wgl3-pallas-sharded", jax.jit(sharded))

    def check(slot_tabs, slot_active, targets):
        b, r = targets.shape
        if b % d:
            raise ValueError(f"batch {b} not a multiple of axis size {d}")
        cm, tg, ln = prep(slot_tabs, slot_active, targets)
        return sharded_launch(b // d, r)(ln, tg, cm)

    _CACHE[key] = check
    return check


def _axis_size(mesh: Mesh, axis) -> int:
    """Device count along `axis` — a name, or a tuple of names (the
    N-D pod form: the product across every named axis)."""
    if isinstance(axis, tuple):
        d = 1
        for a in axis:
            d *= mesh.shape[a]
        return d
    return mesh.shape[axis]


def batch_multiple(model: Model, cfg: DenseConfig, mesh: Mesh,
                   n_steps: int | None = None,
                   batch: int | None = None,
                   axis: str = "batch") -> int:
    """The [B]-axis padding multiple the routed sharded checker needs:
    D devices, times the pallas group when the grouped kernel will run
    (each device's shard must split into whole groups)."""
    from ..ops import wgl3_pallas

    axis = _resolve_axis(mesh, axis)
    d = _axis_size(mesh, axis)
    sp = max(8, (cfg.n_states + 7) // 8 * 8)
    G = limits().pallas_group
    local_batch = None if batch is None else (batch + d - 1) // d
    if (sp == 8 and G > 1 and local_batch is not None and local_batch >= G
            and wgl3_pallas.use_pallas(
                cfg, n_steps, (local_batch + G - 1) // G * G)):
        return d * G
    return d


def sharded_packed_batch_checker(model: Model, cfg: DenseConfig, mesh: Mesh,
                                 n_steps: int | None = None,
                                 batch: int | None = None):
    """Mesh-sharded dense routing, now a shim over the KernelPlan layer
    (plan/dispatch.py plan_dense_batch — the one copy of the
    per-device-envelope pallas-vs-XLA/grouped policy): returns
    (packed_check_fn, kernel_name). `batch` must already be padded to
    batch_multiple()."""
    from ..plan import plan_dense_batch, resolve

    p = plan_dense_batch(model, cfg, n_steps=n_steps, batch=batch,
                         mesh=mesh)
    return resolve(p), p.label


def pad_batch_arrays(arrays, multiple: int):
    """Pad the [B] axis of (tabs, act, tgt) up to a multiple with all-pad
    histories (targets=-1 — every step a pad step, trivially valid).
    Returns (padded_arrays, original_b)."""
    tabs, act, tgt = (np.asarray(a) for a in arrays)
    b = tgt.shape[0]
    b_pad = ((b + multiple - 1) // multiple) * multiple
    if b_pad != b:
        extra = b_pad - b
        tabs = np.concatenate(
            [tabs, np.zeros((extra,) + tabs.shape[1:], tabs.dtype)])
        act = np.concatenate(
            [act, np.zeros((extra,) + act.shape[1:], act.dtype)])
        tgt = np.concatenate(
            [tgt, np.full((extra,) + tgt.shape[1:], -1, tgt.dtype)])
    return (tabs, act, tgt), b


def check_steps_sharded(model: Model, cfg: DenseConfig, steps,
                        r_cap: int, mesh: Mesh | None = None, *,
                        encs: Sequence | None = None
                        ) -> tuple[list[dict], str]:
    """Device-side half of the sharded batch check, for callers that
    already ran wgl3.batch_steps3. Returns (per-history results,
    kernel_name of the last launch).

    Two bucketing disciplines, switched by limits().shard_bucket_mode:

      0  legacy: ONE launch at the corpus-wide r_cap — every history
         pays the longest history's step count in padding, and shard
         load is whatever corpus order dealt (the r06 straggler table's
         [3913, .., 2305, 0, 0] smoking gun).
      1  shard-aware (default): histories split into {2^k, 1.5*2^k}
         step-length buckets, each bucket's batch is LPT-packed
         (sched/engine.py lpt_shard_order) so contiguous per-shard
         blocks carry balanced REAL steps, and successive bucket
         launches overlap through the LaunchPipeline window.

    When `encs` (the EncodedHistory per entry, aligned with `steps`) is
    given and limits().encode_mode allows it, the packed tables are
    built ON DEVICE from the compact event tensors
    (sharded_device_encoder) and never visit the host. Verdicts are
    bit-identical across all four mode combinations — padding steps are
    no-ops and the device encoder mirrors the host one exactly."""
    if mesh is None:
        mesh = batch_mesh()
    if not limits().shard_bucket_mode:
        return _check_steps_one_launch(model, cfg, steps, r_cap, mesh)
    return _check_steps_bucketed(model, cfg, steps, r_cap, mesh, encs)


def _check_steps_one_launch(model: Model, cfg: DenseConfig, steps,
                            r_cap: int, mesh: Mesh
                            ) -> tuple[list[dict], str]:
    """The legacy shard_bucket_mode=0 body: pad the [B] axis to a
    {2^k, 1.5*2^k} bucket (then the sharding multiple), launch ONCE at
    the corpus-wide r_cap, strip pads. Pad histories are all-pad scans
    (targets=-1, zero work)."""
    from ..obs import ledger as obs_ledger
    from ..plan import plan_dense_batch, resolve

    mult = batch_multiple(model, cfg, mesh, n_steps=r_cap,
                          batch=len(steps))
    b_bucket = wgl3.step_bucket(len(steps),
                                floor=limits().batch_bucket_floor)
    target = (b_bucket + mult - 1) // mult * mult
    arrays, b = pad_batch_arrays(wgl3.stack_steps3(steps, r_cap), target)
    b_pad = arrays[2].shape[0]
    p = plan_dense_batch(model, cfg, n_steps=r_cap, batch=b_pad,
                         mesh=mesh)
    check = resolve(p)
    # Scaling ledger launch context: the bucket economics of this one
    # sharded launch — per-shard real steps make straggler wait (the
    # mesh idling behind its slowest shard on a ragged corpus)
    # attributable, not folklore.
    step_counts = [s.n_steps for s in steps] + [0] * (b_pad - b)
    lctx = obs_ledger.plan_context(p)
    lctx.update(batch_real=b, batch_padded=b_pad,
                steps_real=sum(step_counts),
                steps_padded=b_pad * r_cap)
    if lctx.get("n_shards", 1) > 1:
        lctx["shard_real"] = obs_ledger.shard_real_steps(
            step_counts, lctx["n_shards"])
    with obs_ledger.launch_context(**lctx):
        dev = check(*(jnp.asarray(a) for a in arrays))
        t0f = time.monotonic_ns()
        fetched = np.asarray(dev)
        obs.get_ledger().record_fetch(t0f, time.monotonic_ns(),
                                      ctx=lctx)
    out = wgl3.unpack_np(fetched[:b])
    return wgl3.assemble_batch_results(out, steps, cfg), p.label


def _pad_steps(k_slots: int):
    """An all-pad zero-step ReturnSteps (batch filler — padded_to emits
    only targets=-1 pad rows, trivially valid)."""
    from ..ops.encode import ReturnSteps

    return ReturnSteps(
        slot_tabs=np.zeros((0, k_slots, 4), np.int32),
        slot_active=np.zeros((0, k_slots), bool),
        targets=np.zeros((0,), np.int32),
        n_steps=0, n_ops=0, k_slots=k_slots, max_pending=0, max_value=0)


def _pad_enc(k_slots: int):
    """The event-stream twin of _pad_steps: an empty EncodedHistory the
    device encoder expands to all-pad rows."""
    from ..ops.encode import EVENT_WIDTH, EncodedHistory

    return EncodedHistory(
        events=np.zeros((0, EVENT_WIDTH), np.int32), n_events=0,
        n_ops=0, k_slots=k_slots, max_pending=0, max_value=0)


def _batch_slabs(n: int, floor: int, mult: int) -> list[int]:
    """Slab decomposition of a launch's batch axis: ladder-shaped slab
    sizes (multiples of the mesh multiple `mult`) covering `n` rows
    with bounded tail padding. Rounding one giant launch up the
    {2^k, 1.5*2^k} ladder costs up to 33% pure batch padding (517 rows
    -> 768); peeling full rungs first ([512, 8]) keeps every slab but
    the tail 100% full, on ladder shapes the compile cache already
    holds — and hands the launch pipeline more launches to overlap."""
    mult = max(1, mult)
    slabs: list[int] = []
    rem = max(0, n)
    while True:
        b = wgl3.step_bucket(max(rem, 1), floor=floor)
        b = (b + mult - 1) // mult * mult
        # Terminal slab once its padding is small: at most one mesh
        # row-block or 1/8 of the remaining real rows.
        if b - rem <= max(mult, rem // 8):
            slabs.append(b)
            return slabs
        # Otherwise peel the largest ladder rung that fits FULL.
        full = floor
        nxt = wgl3.step_bucket(full + 1, floor=floor)
        while nxt <= rem and nxt > full:
            full = nxt
            nxt = wgl3.step_bucket(full + 1, floor=floor)
        full = full // mult * mult
        if full < mult or full > rem:
            # No full rung fits below the remainder: pad the tail up.
            slabs.append(b)
            return slabs
        slabs.append(full)
        rem -= full
        if rem == 0:
            return slabs


def _check_steps_bucketed(model: Model, cfg: DenseConfig, steps,
                          r_cap: int, mesh: Mesh, encs
                          ) -> tuple[list[dict], str]:
    """The shard-aware discipline: per-length step buckets, LPT shard
    packing inside each launch, pipelined launches, optional device-side
    encoding. See check_steps_sharded."""
    from ..obs import ledger as obs_ledger
    from ..ops import encode_device
    from ..ops.encode import reslot_events
    from ..plan import LaunchPipeline, plan_dense_batch, resolve
    from ..sched.engine import lpt_shard_order

    lim = limits()
    # Device-encode engages on this lane for encode_mode 0 (auto) and 2;
    # 1 pins the host encoder. Per-bucket geometry can still veto it.
    want_dev = encs is not None and lim.encode_mode != 1
    if want_dev:
        encs = [reslot_events(e, cfg.k_slots)
                if e.k_slots != cfg.k_slots else e for e in encs]

    buckets: dict[int, list[int]] = {}
    for i, s in enumerate(steps):
        r = min(wgl3.step_bucket(s.n_steps), r_cap)
        buckets.setdefault(r, []).append(i)

    results: list = [None] * len(steps)

    def _fetch_launch(entry):
        part, part_steps, dev, lctx, perm = entry
        t0f = time.monotonic_ns()
        fetched = np.asarray(dev)
        obs.get_ledger().record_fetch(t0f, time.monotonic_ns(),
                                      ctx=lctx)
        if perm is None:
            rows = fetched[:len(part)]
        else:
            inv = [0] * len(perm)
            for j, p in enumerate(perm):
                inv[p] = j
            rows = fetched[[inv[p] for p in range(len(part))]]
        out = wgl3.unpack_np(rows)
        for i, one in zip(part, wgl3.assemble_batch_results(
                out, part_steps, cfg)):
            results[i] = one

    pipe = LaunchPipeline(resolve=_fetch_launch)
    label = ""
    slabbed: list[tuple[int, list[int], int]] = []
    tail_pool: list[tuple[int, list[int]]] = []
    for r in sorted(buckets):
        idx = buckets[r]
        mult = batch_multiple(model, cfg, mesh, n_steps=r,
                              batch=len(idx))
        slabs = _batch_slabs(len(idx), lim.batch_bucket_floor, mult)
        off = 0
        for k, slab in enumerate(slabs):
            part = idx[off:off + slab]
            off += slab
            if (k == len(slabs) - 1 and len(part) < slab
                    and len(buckets) > 1):
                tail_pool.append((r, part))
            else:
                slabbed.append((r, part, slab))
    if tail_pool:
        # Every bucket's partial tail slab pooled into ONE launch at
        # the pooled maximum rung: N per-bucket tails of 1-2 real rows
        # each leave most shards idle (the straggler table's
        # [52, 50, 0, 0, 0, 0, 0, 0] shape); one pooled launch
        # LPT-balances the same rows across all shards. Padding the
        # shorter buckets' histories up to r_t is inert pad rows —
        # verdicts are unchanged.
        r_t = max(r for r, _ in tail_pool)
        pool = [i for _, p in tail_pool for i in p]
        mult = batch_multiple(model, cfg, mesh, n_steps=r_t,
                              batch=len(pool))
        off = 0
        for slab in _batch_slabs(len(pool), lim.batch_bucket_floor,
                                 mult):
            slabbed.append((r_t, pool[off:off + slab], slab))
            off += slab
    for r, part, b_pad in slabbed:
        part_steps = [steps[i] for i in part]
        padded = part_steps + [_pad_steps(cfg.k_slots)] * (
            b_pad - len(part))
        p = plan_dense_batch(model, cfg, n_steps=r, batch=b_pad,
                             mesh=mesh)
        check = resolve(p)
        lctx = obs_ledger.plan_context(p)
        lctx.update(batch_real=len(part), batch_padded=b_pad,
                    steps_real=sum(s.n_steps for s in part_steps),
                    steps_padded=b_pad * r)
        perm = None
        n_shards = lctx.get("n_shards", 1)
        if n_shards > 1:
            perm = lpt_shard_order([s.n_steps for s in padded],
                                   n_shards)
            if perm == list(range(len(padded))):
                perm = None
            else:
                padded = [padded[j] for j in perm]
                lctx["shard_packed"] = True
            lctx["shard_real"] = obs_ledger.shard_real_steps(
                [s.n_steps for s in padded], n_shards)
        # Device-encode geometry check is per bucket: the one-hot
        # expansion must fit the launch element budget at this bucket's
        # event capacity.
        e_cap = 0
        if want_dev:
            e_cap = encode_device.event_bucket(
                max((encs[i].n_events for i in part), default=1))
            if e_cap * max(1, cfg.k_slots) > lim.stack_element_budget:
                e_cap = 0
        with obs_ledger.launch_context(**lctx):
            if e_cap:
                bucket_encs = ([encs[i] for i in part]
                               + [_pad_enc(cfg.k_slots)]
                               * (b_pad - len(part)))
                if perm is not None:
                    bucket_encs = [bucket_encs[j] for j in perm]
                ev = encode_device.stack_events(bucket_encs, e_cap)
                enc_fn = sharded_device_encoder(cfg.k_slots, e_cap, r,
                                                mesh)
                dev = check(*enc_fn(ev))
            else:
                arrays = wgl3.stack_steps3(padded, r)
                dev = check(*arrays)
        pipe.submit((part, part_steps, dev, lctx, perm))
        label = p.label
    pipe.drain()
    return results, label


def check_batch_sharded(encs: Sequence, model: Model,
                        mesh: Mesh | None = None) -> tuple[list[dict], str]:
    """Batch-sharded dense check over encoded histories: [B] partitioned
    over the mesh, shard-aware bucketing and device-side encoding when
    the knobs allow. Mirrors wgl3.check_batch_encoded3's result schema;
    returns (per-history results, kernel_name). Caller guarantees dense
    feasibility under one shared DenseConfig; ragged B is padded
    internally."""
    cfg, steps, r_cap = wgl3.batch_steps3(encs, model)
    return check_steps_sharded(model, cfg, steps, r_cap, mesh, encs=encs)
