"""Batch-axis mesh sharding for the PRODUCTION dense kernels (wgl3/pallas).

Round-2 verdict, missing #1/#2: the mesh-sharded paths wrapped only the
superseded v1 sort kernel, and nothing a user could invoke ever engaged a
mesh. This module shards the kernels that actually win the bench — the
dense subset-lattice XLA kernel and its fused pallas form — over the
corpus/independent-key batch axis (the reference's data parallelism:
independent per-key histories, src/jepsen/etcdemo.clj:115,120-125 [dep];
BASELINE.json configs[2]/[4]), and `check_batch_encoded_auto`
(ops/wgl3_pallas.py) routes through it AUTOMATICALLY whenever
`jax.device_count() > 1` — `corpus`, `analyze`, and the independent
checker inherit multi-device execution with no caller changes.

Per-history checks are embarrassingly parallel, so the sharding needs no
collectives: a NamedSharding over the [B] axis partitions the vmapped XLA
kernel directly, and the pallas kernel runs under shard_map with each
device launching its own (B/D, NC) grid over its shard. Ragged corpora are
padded to a multiple of the axis size with all-pad histories (targets=-1,
trivially valid — same convention as parallel/multislice.py) and results
are stripped back.
"""

from __future__ import annotations

import functools
import time
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map

from ..models.base import Model
from .. import obs
from ..obs import instrument_kernel
from ..ops import wgl3
from ..ops.limits import limits
from ..ops.wgl3 import DenseConfig
from .mesh import (host_count, make_mesh, mesh_key as _mesh_key,
                   pod_mesh, resolve_axis as _resolve_axis)

_CACHE: dict[tuple, Any] = {}


def batch_mesh(n_devices: int | None = None) -> Mesh:
    """The corpus batch-axis mesh. Single host: the 1-axis ("batch",)
    mesh every existing compiled shape keys on (or an explicit N-D
    shape via --mesh-shape / JEPSEN_TPU_MESH_SHAPE, axes
    ("host", "batch")). Multi-host (a pod, jax.process_count() > 1):
    the process-major ("host", "batch") pod mesh — NamedShardings over
    BOTH axes partition the corpus across DCN and ICI together
    (sharding specs name the axis tuple, so the 1-D and 2-D forms
    share every kernel)."""
    from .mesh import requested_shape

    if n_devices is None:
        shape = requested_shape()
        if shape is not None:
            if len(shape) > 2:
                raise ValueError(
                    f"--mesh-shape {'x'.join(map(str, shape))}: the "
                    f"batch lane builds at most 2-D ('host', 'batch') "
                    f"meshes")
            if len(shape) > 1:
                return make_mesh(axes=("host", "batch"), shape=shape)
            return make_mesh(shape[0], axes=("batch",))
        if host_count() > 1:
            return pod_mesh(axes=("host", "batch"))
    return make_mesh(n_devices, axes=("batch",))


def sharded_batch_checker3_packed(model: Model, cfg: DenseConfig,
                                  mesh: Mesh, axis: str = "batch"):
    """The XLA dense kernel, batch-sharded: jitted
    check(slot_tabs[B,R,K,4], slot_active[B,R,K], targets[B,R]) ->
    DEVICE i32[B, 6] (wgl3.PACKED_FIELDS_XLA — the verdict fields plus
    the live-tile occupancy telemetry column), with B partitioned over
    `axis` — a name, or a TUPLE of names on an N-D pod mesh
    (("host", "batch") partitions jointly; default = every mesh axis).
    B must be a multiple of the total device count."""
    axis = _resolve_axis(mesh, axis)
    key = ("dense-sharded", model.cache_key(), cfg, _mesh_key(mesh), axis)
    if key not in _CACHE:
        fn = jax.vmap(wgl3._check_one_fn(model, cfg))
        in_sh = (NamedSharding(mesh, P(axis, None, None, None)),
                 NamedSharding(mesh, P(axis, None, None)),
                 NamedSharding(mesh, P(axis, None)))
        out_sh = NamedSharding(mesh, P(axis, None))
        # instrument_kernel (obs/): compile/execute attribution for the
        # sharded lane too — under virtual-device CI this IS the
        # production dense path, and it must not be a telemetry blind
        # spot.
        # jtflow: packed wgl3.PACKED_FIELDS_XLA
        _CACHE[key] = instrument_kernel(
            "wgl3-dense-sharded",
            jax.jit(lambda *a: wgl3._pack_result(fn(*a)),
                    in_shardings=in_sh, out_shardings=out_sh))
    return _CACHE[key]


def sharded_batch_checker2(model: Model, cfg2, mesh: Mesh,
                           axis: str = "batch"):
    """The SORT kernel (ops/wgl2.py — the non-dense production path:
    queue/multi-register geometries), batch-sharded like the dense
    kernel: jitted check(slot_tabs[B,R,K,4], slot_active[B,R,K],
    targets[B,R]) -> dict of [B] arrays partitioned over `axis` (name
    or tuple; default = every mesh axis). B must be a multiple of the
    total device count."""
    from ..ops import wgl2

    axis = _resolve_axis(mesh, axis)
    key = ("sort-sharded", model.cache_key(), cfg2, _mesh_key(mesh), axis)
    if key not in _CACHE:
        fn = jax.vmap(wgl2._check_one_fn(model, cfg2))
        in_sh = (NamedSharding(mesh, P(axis, None, None, None)),
                 NamedSharding(mesh, P(axis, None, None)),
                 NamedSharding(mesh, P(axis, None)))
        out_sh = NamedSharding(mesh, P(axis))
        _CACHE[key] = instrument_kernel(
            "wgl2-sort-sharded",
            jax.jit(fn, in_shardings=in_sh,
                    out_shardings={"survived": out_sh, "overflow": out_sh,
                                   "dead_step": out_sh,
                                   "max_frontier": out_sh}))
    return _CACHE[key]


def sharded_batch_checker_pallas(model: Model, cfg: DenseConfig, mesh: Mesh,
                                 axis: str = "batch",
                                 interpret: bool = False,
                                 group: int = 1):
    """The fused pallas kernel under shard_map: each device launches its
    own (B/D, NC) grid over its batch shard — the GROUPED grid when
    `group` > 1 (local shard batch must divide into groups; the router
    guarantees it via the batch multiple). Same signature and packed
    i32[B, 5] result as the sharded XLA checker. The prep half stays a
    plain sharded XLA jit (separate dispatch — the two pipeline, see
    make_batch_checker_pallas)."""
    from ..ops import wgl3_pallas

    axis = _resolve_axis(mesh, axis)
    key = ("pallas-sharded", model.cache_key(), cfg, _mesh_key(mesh), axis,
           interpret, group)
    if key in _CACHE:
        return _CACHE[key]

    # Both halves wear instrument_kernel (PR 1 invariant, jtlint
    # JTL105): prep is a real XLA program and the launcher wrapper is
    # cached per (b_loc, r) by the lru_cache below — uninstrumented,
    # the sharded pallas lane would be a telemetry blind spot.
    prep = instrument_kernel("wgl3-pallas-sharded-prep", jax.jit(
        functools.partial(wgl3_pallas.prepare_pallas_batch, model, cfg),
        in_shardings=(NamedSharding(mesh, P(axis, None, None, None)),
                      NamedSharding(mesh, P(axis, None, None)),
                      NamedSharding(mesh, P(axis, None))),
        out_shardings=(NamedSharding(mesh, P(axis, None, None, None)),
                       NamedSharding(mesh, P(axis, None)),
                       NamedSharding(mesh, P(axis)))))
    if group > 1:
        launcher = wgl3_pallas.local_pallas_launcher_grouped(
            model, cfg, group, interpret=interpret)
    else:
        launcher = wgl3_pallas.cached_pallas_launcher(model, cfg,
                                                      interpret=interpret)
    d = _axis_size(mesh, axis)

    @functools.lru_cache(maxsize=None)
    def sharded_launch(b_loc: int, r: int):
        def local(ln, tg, cm):  # i32[B/D], i32[B/D, R], u32[B/D, R, Sp, 128]
            return launcher(b_loc, r)(ln, tg, cm)

        specs = dict(mesh=mesh,
                     in_specs=(P(axis), P(axis, None),
                               P(axis, None, None, None)),
                     out_specs=P(axis, None))
        try:   # pallas_call out_shapes carry no vma: disable the check
            sharded = shard_map(local, check_vma=False, **specs)
        except TypeError:  # older jax names it check_rep
            sharded = shard_map(local, check_rep=False, **specs)
        return instrument_kernel("wgl3-pallas-sharded", jax.jit(sharded))

    def check(slot_tabs, slot_active, targets):
        b, r = targets.shape
        if b % d:
            raise ValueError(f"batch {b} not a multiple of axis size {d}")
        cm, tg, ln = prep(slot_tabs, slot_active, targets)
        return sharded_launch(b // d, r)(ln, tg, cm)

    _CACHE[key] = check
    return check


def _axis_size(mesh: Mesh, axis) -> int:
    """Device count along `axis` — a name, or a tuple of names (the
    N-D pod form: the product across every named axis)."""
    if isinstance(axis, tuple):
        d = 1
        for a in axis:
            d *= mesh.shape[a]
        return d
    return mesh.shape[axis]


def batch_multiple(model: Model, cfg: DenseConfig, mesh: Mesh,
                   n_steps: int | None = None,
                   batch: int | None = None,
                   axis: str = "batch") -> int:
    """The [B]-axis padding multiple the routed sharded checker needs:
    D devices, times the pallas group when the grouped kernel will run
    (each device's shard must split into whole groups)."""
    from ..ops import wgl3_pallas

    axis = _resolve_axis(mesh, axis)
    d = _axis_size(mesh, axis)
    sp = max(8, (cfg.n_states + 7) // 8 * 8)
    G = limits().pallas_group
    local_batch = None if batch is None else (batch + d - 1) // d
    if (sp == 8 and G > 1 and local_batch is not None and local_batch >= G
            and wgl3_pallas.use_pallas(
                cfg, n_steps, (local_batch + G - 1) // G * G)):
        return d * G
    return d


def sharded_packed_batch_checker(model: Model, cfg: DenseConfig, mesh: Mesh,
                                 n_steps: int | None = None,
                                 batch: int | None = None):
    """Mesh-sharded dense routing, now a shim over the KernelPlan layer
    (plan/dispatch.py plan_dense_batch — the one copy of the
    per-device-envelope pallas-vs-XLA/grouped policy): returns
    (packed_check_fn, kernel_name). `batch` must already be padded to
    batch_multiple()."""
    from ..plan import plan_dense_batch, resolve

    p = plan_dense_batch(model, cfg, n_steps=n_steps, batch=batch,
                         mesh=mesh)
    return resolve(p), p.label


def pad_batch_arrays(arrays, multiple: int):
    """Pad the [B] axis of (tabs, act, tgt) up to a multiple with all-pad
    histories (targets=-1 — every step a pad step, trivially valid).
    Returns (padded_arrays, original_b)."""
    tabs, act, tgt = (np.asarray(a) for a in arrays)
    b = tgt.shape[0]
    b_pad = ((b + multiple - 1) // multiple) * multiple
    if b_pad != b:
        extra = b_pad - b
        tabs = np.concatenate(
            [tabs, np.zeros((extra,) + tabs.shape[1:], tabs.dtype)])
        act = np.concatenate(
            [act, np.zeros((extra,) + act.shape[1:], act.dtype)])
        tgt = np.concatenate(
            [tgt, np.full((extra,) + tgt.shape[1:], -1, tgt.dtype)])
    return (tabs, act, tgt), b


def check_steps_sharded(model: Model, cfg: DenseConfig, steps,
                        r_cap: int, mesh: Mesh | None = None
                        ) -> tuple[list[dict], str]:
    """Device-side half of the sharded batch check, for callers that
    already ran wgl3.batch_steps3: pad the [B] axis to the mesh, launch
    once, strip pads. Returns (per-history results, kernel_name).

    The [B] axis pads to a {2^k, 1.5*2^k} BUCKET (then the sharding
    multiple), not just the multiple: ragged corpora of nearby sizes
    share one compiled shape instead of recompiling per batch size —
    the batch-axis twin of the scheduler's step-length buckets
    (sched/engine.py). Pad histories are all-pad scans (targets=-1,
    zero work) and are stripped before assembly."""
    from ..obs import ledger as obs_ledger
    from ..plan import plan_dense_batch, resolve

    if mesh is None:
        mesh = batch_mesh()
    mult = batch_multiple(model, cfg, mesh, n_steps=r_cap,
                          batch=len(steps))
    b_bucket = wgl3.step_bucket(len(steps),
                                floor=limits().batch_bucket_floor)
    target = (b_bucket + mult - 1) // mult * mult
    arrays, b = pad_batch_arrays(wgl3.stack_steps3(steps, r_cap), target)
    b_pad = arrays[2].shape[0]
    p = plan_dense_batch(model, cfg, n_steps=r_cap, batch=b_pad,
                         mesh=mesh)
    check = resolve(p)
    # Scaling ledger launch context: the bucket economics of this one
    # sharded launch — per-shard real steps make straggler wait (the
    # mesh idling behind its slowest shard on a ragged corpus)
    # attributable, not folklore.
    step_counts = [s.n_steps for s in steps] + [0] * (b_pad - b)
    lctx = obs_ledger.plan_context(p)
    lctx.update(batch_real=b, batch_padded=b_pad,
                steps_real=sum(step_counts),
                steps_padded=b_pad * r_cap)
    if lctx.get("n_shards", 1) > 1:
        lctx["shard_real"] = obs_ledger.shard_real_steps(
            step_counts, lctx["n_shards"])
    with obs_ledger.launch_context(**lctx):
        dev = check(*(jnp.asarray(a) for a in arrays))
        t0f = time.monotonic_ns()
        fetched = np.asarray(dev)
        obs.get_ledger().record_fetch(t0f, time.monotonic_ns(),
                                      ctx=lctx)
    out = wgl3.unpack_np(fetched[:b])
    return wgl3.assemble_batch_results(out, steps, cfg), p.label


def check_batch_sharded(encs: Sequence, model: Model,
                        mesh: Mesh | None = None) -> tuple[list[dict], str]:
    """Batch-sharded dense check over encoded histories: one launch,
    [B] partitioned over the mesh. Mirrors wgl3.check_batch_encoded3's
    result schema; returns (per-history results, kernel_name). Caller
    guarantees dense feasibility under one shared DenseConfig; ragged B
    is padded internally."""
    cfg, steps, r_cap = wgl3.batch_steps3(encs, model)
    return check_steps_sharded(model, cfg, steps, r_cap, mesh)
