"""DCN multi-slice corpus sharding (BASELINE configs[4]; SURVEY.md §2.5).

The reference's only inter-machine planes are SSH + HTTP; the TPU build adds
a device-collective plane. Within a slice, the batch/lattice axes ride ICI
(parallel/dense.py, parallel/lattice.py). ACROSS slices — separate hosts,
each running one JAX process — the corpus axis rides DCN:

  * every process calls `init_multislice` (jax.distributed.initialize) so
    all slices form one global device set;
  * `multislice_mesh` builds a ("slice", "batch") mesh whose OUTER axis is
    process-major — exactly the axis that crosses DCN;
  * `check_corpus_multislice` shards the history batch over both axes with
    a NamedSharding: each slice checks its shard of the stored corpus, and
    the per-history verdict scalars are gathered back to every host.

The whole path is simulatable on one machine: N local processes, each with
M virtual CPU devices (`dryrun_multislice`), which is how the tests and the
driver exercise it without a pod.
"""

from __future__ import annotations

import os
import subprocess
import sys
from typing import Any, Optional, Sequence

import numpy as np


def init_multislice(coordinator: str, num_processes: int, process_id: int,
                    local_devices: Optional[int] = None) -> None:
    """Join the global JAX distributed system. Must run before any backend
    initialization. `local_devices` forces a virtual CPU platform with that
    many devices (simulation on one machine / CI)."""
    if local_devices is not None:
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count="
                f"{local_devices}").strip()
    import jax

    if local_devices is not None:
        jax.config.update("jax_platforms", "cpu")
        try:
            jax.config.update("jax_num_cpu_devices", local_devices)
        except Exception:
            pass
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)


# jtflow: mesh-axes slice,batch
def multislice_mesh(slice_axis: str = "slice", batch_axis: str = "batch"):
    """2D mesh over ALL global devices: [processes, devices-per-process].
    The outer (process-major) axis is the DCN axis."""
    import jax
    from jax.sharding import Mesh

    devs = jax.devices()
    n_proc = jax.process_count()
    per = len(devs) // n_proc
    order = sorted(devs, key=lambda d: (d.process_index, d.id))
    arr = np.array(order).reshape(n_proc, per)
    return Mesh(arr, (slice_axis, batch_axis))


def check_corpus_multislice(encs: Sequence, model, mesh=None
                            ) -> tuple[list[dict[str, Any]], str]:
    """Check a corpus of EncodedHistory across every slice in ONE launch.

    Every process passes the SAME corpus (each host reads the same store);
    the mesh sharding assigns each device its shard. Returns (full
    per-history result list — identical on every process, gathered over
    DCN — , kernel name). The name reports what ACTUALLY ran (ADVICE r4:
    the dense-infeasible minority falls back to the per-process local
    ladder, and a whole corpus can): "wgl3-dense-multislice", a local
    ladder kernel, or "mixed"."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import multihost_utils
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..ops import wgl3, wgl3_pallas
    from ..ops.limits import limits
    from ..ops.wgl import verdict

    if mesh is None:
        mesh = multislice_mesh()

    # Partition like check_batch_encoded_auto: one dense-infeasible or
    # over-long history must not crash the whole multislice pass. The
    # non-dense minority runs the exact general ladder LOCALLY on every
    # process (deterministic — identical results on all hosts); only the
    # dense majority shards over the mesh.
    dense_idx, general_idx = [], []
    for i, e in enumerate(encs):
        ok = wgl3.dense_config(model, wgl3.tight_k_slots(e), e.max_value)
        (dense_idx if ok is not None else general_idx).append(i)
    if dense_idx:
        sub = [encs[i] for i in dense_idx]
        try:
            cfg, arrays, steps = wgl3.batch_arrays3(sub, model)
        except ValueError:
            general_idx = sorted(general_idx + dense_idx)
            dense_idx = []
        else:
            if arrays[2].shape[1] > limits().long_scan_max:
                general_idx = sorted(general_idx + dense_idx)
                dense_idx = []
    kernels: set[str] = set()
    if not dense_idx:
        results = [wgl3_pallas.check_encoded_general(e, model)
                   for e in encs]
        kernels.update(r["kernel"] for r in results)
        return results, (kernels.pop() if len(kernels) == 1 else "mixed")
    full_results: list = [None] * len(encs)
    for i in general_idx:
        full_results[i] = wgl3_pallas.check_encoded_general(encs[i], model)
        kernels.add(full_results[i]["kernel"])
    encs = sub
    axes = tuple(mesh.axis_names)
    total = int(np.prod([mesh.shape[a] for a in axes]))
    b = arrays[0].shape[0]
    b_pad = ((b + total - 1) // total) * total
    tabs, act, tgt = (np.asarray(a) for a in arrays)
    if b_pad != b:
        # Pad with empty histories: target -1 = pad step, trivially valid.
        extra = b_pad - b
        tabs = np.concatenate(
            [tabs, np.zeros((extra,) + tabs.shape[1:], tabs.dtype)])
        act = np.concatenate(
            [act, np.zeros((extra,) + act.shape[1:], act.dtype)])
        tgt = np.concatenate(
            [tgt, np.full((extra,) + tgt.shape[1:], -1, tgt.dtype)])
    global_arrays = tuple(
        jax.make_array_from_callback(
            a.shape,
            NamedSharding(mesh, P(axes, *(None,) * (a.ndim - 1))),
            lambda idx, a=a: a[idx])
        for a in (tabs, act, tgt))
    fn = _sharded_batch_checker(model, cfg, mesh)
    out = fn(*global_arrays)
    gathered = {k: np.asarray(multihost_utils.process_allgather(
        v, tiled=True)) for k, v in out.items()}
    for i, s in enumerate(steps):
        one = {k: gathered[k][i].item() for k in gathered}
        one["valid"] = verdict(one)
        one["op_count"] = s.n_ops
        # int like every other backend (the dict path carries f32).
        one["configs_explored"] = int(one["configs_explored"])
        one["kernel"] = "wgl3-dense-multislice"
        wgl3.attach_live_ratio(one)
        full_results[dense_idx[i]] = one
    kernels.add("wgl3-dense-multislice")
    return full_results, (kernels.pop() if len(kernels) == 1 else "mixed")


_SHARDED_CACHE: dict = {}


def _sharded_batch_checker(model, cfg, mesh):
    """The multislice-sharded dense batch checker, cached per
    (model, cfg, mesh) and wearing obs.instrument_kernel. Re-jitting
    inside check_corpus_multislice per call both discarded jax's C++
    fast path every corpus pass (a fresh jit wrapper re-traces) and
    escaped compile/execute attribution (jtlint JTL101/JTL105)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..obs import instrument_kernel
    from ..ops import wgl3
    from .dense import _mesh_key

    axes = tuple(mesh.axis_names)
    key = (model.cache_key(), cfg, _mesh_key(mesh))
    if key not in _SHARDED_CACHE:
        check = wgl3.cached_batch_checker3(model, cfg)
        out_spec = NamedSharding(mesh, P(axes))
        _SHARDED_CACHE[key] = instrument_kernel(
            "wgl3-dense-multislice",
            jax.jit(check, out_shardings={
                "survived": out_spec, "overflow": out_spec,
                "dead_step": out_spec, "max_frontier": out_spec,
                "configs_explored": out_spec, "live_tile_pm": out_spec}))
    return _SHARDED_CACHE[key]


# --- one-machine simulation / dryrun ---------------------------------------

def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class MultisliceWorkerFailed(RuntimeError):
    """One worker of a multi-process run exited (or died) while its peers
    were still running. The supervisor kills the survivors IMMEDIATELY and
    raises this — a dead peer means every pending collective would block
    until the distributed-runtime timeout, so waiting is never useful
    (VERDICT r4 weak #5: failure must be fast and named, not a hang)."""

    def __init__(self, pid: int, returncode: int, tail: str):
        self.pid = pid
        self.returncode = returncode
        super().__init__(
            f"multislice worker {pid} exited {returncode} while peers "
            f"were still running; survivors killed. Tail:\n{tail[-2000:]}")


def supervise_workers(procs: Sequence[subprocess.Popen],
                      timeout_s: float = 600.0,
                      poll_s: float = 0.2) -> list[str]:
    """Await a fleet of worker Popens (stdout=PIPE), CONCURRENTLY: poll
    rather than serially communicate(), so one dead worker is detected
    while the rest still run. Returns each worker's decoded stdout.

    Failure modes: a worker exiting non-zero (or killed by a signal)
    before its peers -> survivors killed, MultisliceWorkerFailed;
    timeout -> everything killed, subprocess.TimeoutExpired. Stdout is
    drained only at the end — these workers print a few lines, far under
    any pipe buffer."""
    import time

    deadline = time.monotonic() + timeout_s
    procs = list(procs)
    while True:
        codes = [p.poll() for p in procs]
        bad = next((i for i, c in enumerate(codes)
                    if c is not None and c != 0), None)
        # The non-zero check runs BEFORE the all-exited break: a worker
        # crashing in the same poll window its peers finish in must
        # still surface as the named error, not as survivors' garbage
        # stdout handed to the caller.
        if bad is None and all(c is not None for c in codes):
            break
        if bad is not None:
            for q in procs:
                if q.poll() is None:
                    q.kill()
            out, _ = procs[bad].communicate()
            for i, q in enumerate(procs):
                if i != bad:
                    q.communicate()          # reap, drop survivor output
            raise MultisliceWorkerFailed(bad, codes[bad], out or "")
        if time.monotonic() > deadline:
            for q in procs:
                if q.poll() is None:
                    q.kill()
            for q in procs:
                q.communicate()
            raise subprocess.TimeoutExpired(procs[0].args, timeout_s)
        time.sleep(poll_s)
    return [p.communicate()[0] or "" for p in procs]


def dryrun_multislice(n_procs: int = 2, devices_per_proc: int = 2,
                      timeout_s: float = 600.0) -> None:
    """Spawn n_procs local JAX processes (virtual CPU devices), form the
    distributed system, and run one multi-slice corpus check. Raises on any
    disagreement or failure."""
    coord = f"127.0.0.1:{_free_port()}"
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "jepsen_etcd_demo_tpu.parallel.multislice",
             coord, str(n_procs), str(pid), str(devices_per_proc)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env)
        for pid in range(n_procs)
    ]
    outs = supervise_workers(procs, timeout_s)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        if p.returncode != 0 or "MULTISLICE_OK" not in out:
            raise RuntimeError(
                f"multislice worker {pid} failed (rc={p.returncode}):\n"
                f"{out[-2000:]}")
    lines = [next(ln for ln in out.splitlines()
                  if ln.startswith("MULTISLICE_OK")) for out in outs]
    if len(set(lines)) != 1:
        raise RuntimeError(f"workers disagree: {lines}")
    print(f"dryrun_multislice({n_procs}x{devices_per_proc}): ok — {lines[0]}")


def _worker(coord: str, n: int, pid: int, local_devices: int) -> None:
    """Subprocess entry: join the cluster, check a deterministic corpus,
    print the verdict summary (identical across processes)."""
    init_multislice(coord, n, pid, local_devices=local_devices)
    if os.environ.get("JEPSEN_TPU_MULTISLICE_CRASH_PID") == str(pid):
        # Failure-injection hook for the supervisor test: die AFTER
        # joining the distributed system (peers are now committed to
        # collectives with this process) but before contributing.
        # os._exit, not sys.exit: a crash must not run atexit hooks —
        # jax.distributed's shutdown handler would block on the very
        # peers this test wants to see orphaned.
        print("CRASH_HOOK: worker exiting mid-run", flush=True)
        sys.stdout.flush()
        os._exit(3)
    import random

    from ..models import CASRegister
    from ..ops.encode import encode_register_history
    from ..utils.fuzz import gen_register_history, mutate_history

    rng = random.Random(0xDC4)
    encs = []
    expect = []
    for i in range(2 * n * local_devices + 1):   # ragged on purpose
        h = gen_register_history(rng, n_ops=30, n_procs=4)
        if i % 3 == 0:
            h = mutate_history(rng, h)
        encs.append(encode_register_history(h, k_slots=16))
    model = CASRegister()
    results, _kernel = check_corpus_multislice(encs, model)
    # Cross-check against the oracle locally (small corpus).
    from ..checkers.oracle import check_events_oracle

    for enc, res in zip(encs, results):
        want = check_events_oracle(enc, model).valid
        assert res["valid"] is want, (res, want)
    summary = "".join("T" if r["valid"] else "F" for r in results)
    print(f"MULTISLICE_OK {summary}")


if __name__ == "__main__":
    _worker(sys.argv[1], int(sys.argv[2]), int(sys.argv[3]),
            int(sys.argv[4]))
