"""stream — the streaming check engine (ISSUE 5 tentpole).

Overlaps linearizability checking with the live run: history entries
feed a stable-prefix incremental encoder (ops/encode.py
IncrementalEncoder) as the recorder appends them, and stable chunks of
return steps dispatch into the resumable dense WGL3 frontier carry
while workers are still executing — converting the harness's largest
remaining serial section (run_time + check_time) into overlap, and
enabling ``--fail-fast`` teardown the moment a history is falsified.

See engine.py for the architecture; doc/perf.md "Streaming checks" for
the watermark rule and knobs.
"""

from .elle import ElleStreamSession
from .engine import KeyStream, StreamSession, session_for_test

__all__ = ["ElleStreamSession", "KeyStream", "StreamSession",
           "session_for_test"]
