"""stream/longhaul.py — billion-event out-of-core checking (ISSUE 20).

The quiescent-boundary insight: at any history point where every invoked
op has RETURNED, each surviving config's pending mask is zero — the
whole search frontier collapses to a plain set of model states. A long
history cut at quiescent points therefore checks EXACTLY, segment by
segment, with an O(frontier) carry between segments: wgl2's
``init_frontier`` seeds segment k+1 from segment k's final state set,
and the concatenated verdict (survived / global dead step) is
bit-identical to checking the whole history in one piece — which is the
point: the whole history NEVER EXISTS. Each segment is generated on
demand from a seed (deterministic, resumable), encoded through the
content-addressed encode-cache tier, checked through the chunked sort
kernel (which spills its own intra-segment chunk checkpoints through
store/spill.py), and dropped.

Determinism under resume: every segment ends with an ANCHOR WRITE whose
value is derived from (seed, segment) alone, so segment k+1's
ground-truth initial register value is computable WITHOUT generating
segment k — a crash-resumed lane regenerates only the segment it died
in. The segment-chain checkpoint (``<tag>.seg`` in the active SpillDir)
carries the checker's own frontier state set; a torn checkpoint decodes
as absent and the lane recomputes from the start — slower, never wrong.

RSS accounting: the lane reports ``peak_rss_mb`` as the DELTA of
``ru_maxrss`` over the lane (store/spill.py rss_mb), checked against the
``host_rss_budget_mb`` knob — the long-haul bench gate
(tools/bench_compare.py ``longhaul_peak_rss_mb``, inverted: lower is
better) holds the whole out-of-core claim to a pinned ceiling.
"""

from __future__ import annotations

import random
import time
from typing import Any, Optional

import numpy as np

from .. import obs
from ..ops.limits import limits
from ..ops.op import INVOKE, OK, Op
from ..store import encode_cache
from ..store import spill as _spill
from ..utils.fuzz import gen_register_history

DEFAULT_SEG_EVENTS = 8192


def anchor_value(seed: int, k: int, value_range: int) -> int:
    """The deterministic register value segment k ends on — a pure
    function of (seed, k), so a resume at segment k+1 knows its initial
    state without generating segment k."""
    return random.Random(f"{seed}|anchor|{k}").randrange(value_range)


def segment_history(seed: int, k: int, n_ops: int, n_procs: int = 4,
                    value_range: int = 5) -> list[Op]:
    """Segment k of the synthetic long-haul history: a valid concurrent
    register history (utils/fuzz.py ground-truth simulation) starting
    from segment k-1's anchor value, QUIESCENT at both ends (p_info=0:
    every invoked op returns), closed by the anchor write for segment
    k. Deterministic per (seed, k) — resumable generation."""
    rng = random.Random(f"{seed}|seg|{k}")
    init = anchor_value(seed, k - 1, value_range) if k > 0 else None
    hist = gen_register_history(
        rng, n_ops=max(1, n_ops - 1), n_procs=n_procs,
        value_range=value_range, p_info=0.0, p_fail_read=0.05,
        initial_value=init)
    w = anchor_value(seed, k, value_range)
    proc = n_procs + 1000   # a process id no concurrent op ever holds
    hist.append(Op(type=INVOKE, f="write", value=w, process=proc))
    hist.append(Op(type=OK, f="write", value=w, process=proc))
    for i, op in enumerate(hist):
        op.index = i
        op.time = i * 1000
    return hist


def _seg_checkpoint_name(tag: str) -> str:
    return f"{tag}.seg"


def run_longhaul(model=None, *, events: int = 1_000_000,
                 seg_events: int = DEFAULT_SEG_EVENTS, seed: int = 0,
                 n_procs: int = 4, value_range: int = 5,
                 k_slots: int = 32, f_cap: int = 256,
                 tag: str = "longhaul", resume: bool = True,
                 mutate_segment: Optional[int] = None,
                 time_budget_s: Optional[float] = None
                 ) -> dict[str, Any]:
    """Check a synthetic ``events``-long history end to end without ever
    materializing it: generate → encode (through the encode-cache tier)
    → check → carry, one segment at a time. Returns the lane record —
    verdict fields (``survived``, global ``dead_step`` in cumulative
    return-step units) are bit-identical to a single whole-history
    check_encoded_resumable run (the parity tests hold this at every
    cross-checkable scale), plus throughput and RSS accounting.

    `mutate_segment` corrupts that segment's history
    (utils/fuzz.mutate_history) — the test hook for dead-verdict parity.
    With an active spill tier (store/spill.py) and the
    ``host_spill_mode`` policy engaged, the lane checkpoints its
    segment chain (and wgl2 its intra-segment chunks) to disk and
    `resume=True` continues a crashed lane from the last durable
    boundary; a torn checkpoint degrades to recompute, never a wrong
    verdict."""
    from ..ops import wgl2

    if model is None:
        from ..models import CASRegister
        model = CASRegister()
    t0 = time.monotonic()
    rss0 = _spill.rss_mb()
    n_ops_per_seg = max(2, seg_events // 2)
    n_segments = max(1, (events + seg_events - 1) // seg_events)
    sdir = _spill.active_spill()
    # The working-set estimate is the footprint the OLD route would pay:
    # the whole materialized history (~32 B/event host-side) — exactly
    # what the out-of-core route exists to avoid.
    est_mb = events * 32 / (1 << 20)
    do_spill = sdir is not None and _spill.spill_active(est_mb)
    ck_name = _seg_checkpoint_name(tag)

    start_k = 0
    carry: Optional[np.ndarray] = None
    returns_done = 0
    events_done = 0
    esc_total = 0
    mf_max = 0
    resumed_from = -1
    if do_spill and resume:
        d = _spill.load_frontier(sdir, ck_name)
        mt = (d or {}).get("meta") or {}
        if d is not None and mt.get("seed") == seed \
                and mt.get("seg_events") == seg_events \
                and mt.get("n_segments") == n_segments \
                and 0 < int(mt.get("seg", 0)) <= n_segments:
            start_k = int(mt["seg"])
            carry = np.asarray(d["states"])[
                np.asarray(d["valid"])].astype(np.int32)
            returns_done = int(mt.get("returns_done", 0))
            events_done = int(mt.get("events_done", 0))
            esc_total = int(mt.get("escalations", 0))
            mf_max = int(mt.get("max_frontier", 0))
            resumed_from = start_k

    survived = True
    dead_step = -1
    segments_run = 0
    for k in range(start_k, n_segments):
        hist = segment_history(seed, k, n_ops_per_seg,
                               n_procs=n_procs, value_range=value_range)
        if mutate_segment is not None and k == mutate_segment:
            from ..utils.fuzz import mutate_history
            hist = mutate_history(
                random.Random(f"{seed}|mut|{k}"), hist,
                value_range=value_range)
        enc = encode_cache.lookup(hist, model.name, k_slots)
        if enc is None:
            from ..ops.encode import encode_register_history
            enc = encode_register_history(hist, k_slots=k_slots)
            encode_cache.store(hist, model.name, k_slots, enc)
        res = wgl2.check_encoded_resumable(
            enc, model, f_cap=f_cap, time_budget_s=time_budget_s,
            init_frontier=carry, return_frontier=True,
            spill_tag=f"{tag}.s{k}" if do_spill else None)
        segments_run += 1
        events_done += len(hist)
        esc_total += int(res.get("escalations", 0))
        mf_max = max(mf_max, int(res.get("max_frontier", 0)))
        if do_spill:
            sdir.delete(f"{tag}.s{k}.ck")   # intra-segment ck consumed
        if not res["survived"]:
            survived = False
            dead_step = returns_done + int(res["dead_step"])
            break
        returns_done += int(res["n_steps"])
        states, masks, valid = res["frontier"]
        rows = np.flatnonzero(valid)
        # Quiescent boundary by construction (p_info=0): every pending
        # mask is zero, so the carry IS a plain state set.
        assert not masks[rows].any(), "non-quiescent segment boundary"
        carry = np.unique(states[rows]).astype(np.int32)
        if do_spill:
            _spill.spill_frontier(
                sdir, ck_name, carry,
                np.zeros((carry.size, 1), np.uint32),
                np.ones((carry.size,), bool),
                meta={"seg": k + 1, "seed": seed,
                      "seg_events": seg_events,
                      "n_segments": n_segments,
                      "returns_done": returns_done,
                      "events_done": events_done,
                      "escalations": esc_total,
                      "max_frontier": mf_max})
    if do_spill:
        sdir.delete(ck_name)    # lane complete: the chain checkpoint
        for k in range(start_k, n_segments):
            sdir.delete(f"{tag}.s{k}.ck")
    wall_s = time.monotonic() - t0
    peak_rss_mb = max(0.0, _spill.rss_mb() - rss0)
    rss_budget_mb = limits().host_rss_budget_mb
    m = obs.get_metrics()
    m.gauge("spill.peak_rss_mb").set(round(peak_rss_mb, 2))
    return {
        "events": events_done,
        "segments": n_segments,
        "segments_run": segments_run,
        "resumed_from": resumed_from,
        "survived": survived,
        "dead_step": dead_step,
        "max_frontier": mf_max,
        "escalations": esc_total,
        "spilled": do_spill,
        "wall_s": round(wall_s, 4),
        "events_per_sec": round(events_done / wall_s, 2)
        if wall_s > 0 else 0.0,
        "peak_rss_mb": round(peak_rss_mb, 2),
        "rss_budget_mb": rss_budget_mb,
        "rss_ok": peak_rss_mb <= rss_budget_mb,
    }
