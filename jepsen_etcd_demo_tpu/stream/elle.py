"""Streaming elle: incremental dependency graphs against the live run.

The transactional checker used to be strictly post-hoc: the whole txn
history was recorded, then checkers/elle.py paired it and built the
ww/wr/rw dependency graph from scratch — so a G1c or G-single anomaly
produced in the first seconds of a run was not reported until the run's
time budget expired. This module is the elle face of the ISSUE 5
streaming engine (ISSUE 11 tentpole layer 3):

  * **Watermark = completion.** Elle inference consumes COMPLETED txns
    (an open invoke contributes nothing — its eventual edges are
    unknowable), so a txn becomes stable the moment its completion is
    recorded, in the recorder's order. History positions are assigned
    at feed time exactly as the post-hoc pairer assigns them
    (enumerate over the full record, nemesis rows included), so the
    realtime edge set is bit-identical.
  * **Incremental graph.** Completed txns feed the SAME
    :class:`checkers.elle.ElleGraph` the post-hoc checker uses — per-key
    derived state (direct anomalies + edge contributions) recomputed
    for dirty keys only, never the whole history.
  * **Periodic re-check.** Every ``limits().elle_stream_flush``
    completed txns (or after an idle interval under ``--fail-fast``
    eager flush) the grown graph re-checks: direct anomalies are read
    off the refreshed per-key records, and cycle presence runs through
    the routed closure engine (ops/cycles.py — diagonal-only fetch,
    fixpoint early exit, which warm re-checks convert into one or two
    squaring rounds). Dependency edges only ACCUMULATE as txns
    complete, so an anomaly found on a prefix is an anomaly of the
    full history — the fail-fast trigger is sound.
  * **Finalize = the post-hoc path.** The check phase drains the queue,
    resolves still-open invokes as :info (exactly `_pair_txns`), and
    runs ``ElleChecker._check_graph`` on the accumulated graph — the
    same code over the same state, so streamed and post-hoc results
    are bit-identical by construction (tests/test_elle_kernels.py pins
    golden + fuzz histories, valid and anomalous).

Valid streamed verdicts settle in ElleChecker.check via
``opts["stream_results"]["elle"]``; invalid runs re-check post-hoc so
witness artifacts are unchanged — the Linearizable settling discipline.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Any, Optional

import numpy as np

from .. import obs
from ..ops import cycles
from ..ops.limits import limits
from ..ops.op import Op

log = logging.getLogger(__name__)

_DONE = object()


class ElleStreamSession:
    """Run-facing streaming session for the elle txn checkers: a queue +
    consumer thread feeding completed txns into an incremental
    ElleGraph, with periodic closure re-checks driving ``--fail-fast``.
    API-compatible with stream.engine.StreamSession (the runner treats
    sessions uniformly)."""

    def __init__(self, checker):
        from ..checkers.elle import ElleGraph

        self.checker = checker
        self.aborted = False        # set by the runner's fail-fast watcher
        self._q: "queue.SimpleQueue" = queue.SimpleQueue()
        self._graph = ElleGraph()
        self._pending: dict[Any, tuple[int, Op]] = {}
        self._pos = 0               # history position (the pairer's index)
        self._since_flush = 0
        self._txns = 0
        self._txns_live = 0
        self._rechecks = 0
        self._recheck_s = 0.0
        self._falsified = False
        self._broken: Optional[str] = None
        self._results: Optional[dict] = None
        self._run_live = threading.Event()
        self._run_live.set()
        self._done_sent = False
        self._eager_flush_s: Optional[float] = None
        self._last_flush = time.monotonic()
        self._thread = threading.Thread(target=self._consume,
                                        name="elle-stream-check",
                                        daemon=True)
        self._thread.start()

    # -- event-loop side --------------------------------------------------
    def feed(self, op: Op) -> None:
        """HistoryRecorder listener: stamp the history position (EVERY
        recorded op consumes one — the post-hoc pairer enumerates the
        full history, nemesis rows included, so positions must match),
        then enqueue."""
        pos = self._pos
        self._pos += 1
        if op.process == "nemesis":
            return
        self._q.put((pos, op))

    def finish_input(self) -> None:
        """The run is over; the consumer exits once the queue drains.
        Idempotent."""
        self._run_live.clear()
        if not self._done_sent:
            self._done_sent = True
            self._q.put(_DONE)

    def enable_eager_flush(self, interval_s: float = 0.5) -> None:
        """Fail-fast mode: re-check the grown graph after ~interval_s of
        feed idleness even when a full elle_stream_flush batch never
        accumulates, so a quiet anomalous run still trips the abort."""
        self._eager_flush_s = float(interval_s)

    def falsified(self) -> bool:
        """True once an incremental re-check found any anomaly — the
        --fail-fast trigger (sound: elle edges only accumulate, so a
        prefix anomaly is a full-history anomaly)."""
        return self._falsified

    # -- consumer thread --------------------------------------------------
    def _consume(self) -> None:
        while True:
            try:
                item = self._q.get(timeout=self._eager_flush_s)
            except queue.Empty:
                try:
                    if self._broken is None and self._since_flush \
                            and time.monotonic() - self._last_flush \
                            >= (self._eager_flush_s or 0.5):
                        self._recheck()
                except Exception as e:
                    self._broken = f"{type(e).__name__}: {e}"
                    log.exception("elle stream eager re-check crashed; "
                                  "falling back to post-hoc")
                continue
            if item is _DONE:
                return
            if self._broken is not None:
                continue   # drain cheaply; post-hoc owns the check now
            pos, op = item
            try:
                self._feed_one(pos, op)
            except Exception as e:
                # Malformed pairing / non-txn shapes — exactly what the
                # post-hoc checker will report on the same history; and
                # an unexplained crash must never kill the thread
                # silently either way.
                self._broken = f"{type(e).__name__}: {e}"
                log.warning("elle streaming check abandoned: %s",
                            self._broken)

    def _feed_one(self, pos: int, op: Op) -> None:
        from ..checkers.elle import TxnEncodeError

        if op.f != "txn":
            raise TxnEncodeError(f"non-txn op {op.f!r} in txn history")
        if op.type == "invoke":
            if op.process in self._pending:
                raise TxnEncodeError(
                    f"process {op.process} double-invoke")
            self._pending[op.process] = (pos, op)
            return
        if op.type not in ("ok", "fail", "info"):
            return
        got = self._pending.pop(op.process, None)
        if got is None:
            raise TxnEncodeError(f"completion without invoke: {op}")
        inv_pos, inv = got
        self._graph.add_txn(
            inv.value, op.type,
            op.value if op.type == "ok" else inv.value, inv_pos, pos)
        self._txns += 1
        if self._run_live.is_set():
            self._txns_live += 1
        obs.get_metrics().counter("elle.stream_txns").add(1)
        self._since_flush += 1
        if self._since_flush >= limits().elle_stream_flush:
            self._recheck()

    def _recheck(self) -> None:
        """One incremental falsification probe over the graph-so-far:
        refreshed direct anomalies, then cycle presence of the full
        edge set through the routed closure (diagonal-only fetch)."""
        self._since_flush = 0
        self._last_flush = time.monotonic()
        if self._falsified:
            return             # sticky — the verdict can only stay bad
        t0 = time.monotonic()
        g = self._graph
        bad = any(v for v in g.direct_anomalies().values())
        if not bad and g.oks:
            ww, wr, rw = g.edge_matrices()
            full = ww | wr | rw
            if self.checker.realtime:
                rt = g.rt_matrix()
                if rt is not None:
                    full = full | rt
            bad = bool(cycles.cycle_mask(full).any())
        self._rechecks += 1
        self._recheck_s += time.monotonic() - t0
        obs.get_metrics().counter("elle.stream_rechecks").add(1)
        if bad:
            self._falsified = True
            obs.get_tracer().event("stream.falsified", key="elle",
                                   txns=self._txns)

    # -- check-phase side -------------------------------------------------
    def finalize(self) -> Optional[dict]:
        """Join the consumer, resolve still-open invokes as :info, and
        run the shared finalization path. Returns
        ``{"elle": result}`` (the opts["stream_results"] shape), or
        None when the session abandoned streaming. Idempotent."""
        if self._results is not None:
            return self._results or None
        self.finish_input()
        self._thread.join()
        results: dict = {}
        if self._broken is None:
            try:
                for inv_pos, inv in self._pending.values():
                    self._graph.add_txn(inv.value, "info", inv.value,
                                        inv_pos, -1)
                self._pending.clear()
                t0 = time.monotonic()
                res = self.checker._check_graph(self._graph)
                self._recheck_s += time.monotonic() - t0
                res["streamed"] = True
                results["elle"] = res
            except Exception:
                log.exception("elle stream finalize failed; post-hoc "
                              "takes over")
                results = {}
        overlap = self._txns_live / self._txns if self._txns else 0.0
        obs.get_metrics().gauge("stream.overlap_ratio").set(overlap)
        self._stats = {
            "overlap_ratio": round(overlap, 4),
            "txns": self._txns,
            "txns_overlapped": self._txns_live,
            "rechecks": self._rechecks,
            "recheck_s": round(self._recheck_s, 4),
            "failfast_aborted": self.aborted,
        }
        if self._broken:
            self._stats["fallback"] = self._broken
        self._results = results
        return results or None

    def stats(self) -> dict:
        """The results.json ``stream`` record (finalize() must have
        run)."""
        stats = dict(getattr(self, "_stats", {}))
        stats["failfast_aborted"] = self.aborted
        return stats


def find_elle_checker(checker):
    """The first ElleChecker instance in a checker topology (walking
    nested Compose trees — the runner composes the workload checker
    under {perf, indep}) — the streamable shape. ElleRwChecker is
    excluded: the rw-register inference derives version orders
    globally, so it stays post-hoc. Keyed (IndependentChecker)
    topologies are not walked — the elle checkers consume whole txn
    histories, never (key, value) splits."""
    from ..checkers.compose import Compose
    from ..checkers.elle import ElleChecker, ElleRwChecker

    if isinstance(checker, ElleChecker) \
            and not isinstance(checker, ElleRwChecker):
        return checker
    if isinstance(checker, Compose):
        for sub in checker.checkers.values():
            found = find_elle_checker(sub)
            if found is not None:
                return found
    return None
