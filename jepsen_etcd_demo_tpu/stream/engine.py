"""Streaming check engine: stable-prefix chunk dispatch against the live run.

The harness used to run strictly ``run -> check``: the full history was
recorded, then the check phase encoded and swept it from scratch, so
end-to-end wall clock was run_time + check_time even though the WGL
chunked kernels are resumable (the frontier carry chains across chunk
launches). Lowe's P-compositionality / just-in-time linearization
observation applies here exactly: the sweep only ever needs a CLOSED
prefix of the history, and the prefix closes continuously while the run
is still going. This module streams it:

  * **Watermark** (ops/encode.py IncrementalEncoder): events become
    stable once their position precedes every still-open invoke — an
    op that will crash pins the watermark from its invoke until its
    ``:info`` completion is recorded, then is encoded pending-forever
    per WGL semantics. Ordering keys on the recorder's monotonic
    per-entry ``seq``, never wall clock.
  * **Incremental encoder**: stable events append to the packed rows /
    running slot-table snapshot instead of re-encoding the history.
  * **Chunk dispatcher** (KeyStream): every ``limits().stream_flush_ops``
    stable return steps form one chunk fed into the SAME resumable
    dense chunk kernel the post-hoc long sweep uses
    (wgl3._cached_chunk_run — donated carry, async dispatch), so the
    device pipelines chunk N+1's transfer behind chunk N, double-
    buffered against the live run on the host side by the consumer
    thread. The frontier's death flag is polled every
    ``limits().stream_max_lag_chunks`` chunks — the fail-fast bound.
  * **Geometry restarts**: the dense table's shape depends on
    (max_pending, max_value), which only GROW as the run proceeds.
    When a flush would outgrow the current DenseConfig, the engine
    re-derives the geometry and re-dispatches the (still cheap, early)
    stable prefix from scratch — O(log) restarts per run, after which
    the kernel shape is stable and every key shares the same compiled
    ``(cfg, chunk)`` entry through the wgl3 kernel cache (the sched
    engine's bucket discipline applied to streams).
  * **Multiplex** (StreamSession keyed mode): independent-key histories
    split per key incrementally (exactly checkers/independent.py
    split_by_key) and share the dispatcher thread + compiled chunk
    kernels.

Verdicts are BIT-IDENTICAL to the post-hoc path by construction: the
stable rows equal the post-hoc encoding's prefix (IncrementalEncoder
contract), chunk boundaries don't change the scan semantics (the carry
chains exactly; pads contribute nothing), and dead carries are sticky
(post-death chunks add zero configs), so survived / dead_step /
max_frontier / configs_explored all match the chunked dense sweep.
tests/test_stream.py pins this on golden + fuzz histories, crashed-op
pinning, fail-fast teardown, and a corpus multiplex.

The runner (runner/core.py) wires it end to end under
``--check-mode stream``: the recorder's listener feeds the session, the
check phase becomes drain + finalize, and valid streamed verdicts
settle their keys in the checkers (checkers/linearizable.py /
independent.py) — invalid keys re-run post-hoc for witness
reconstruction, so counterexample artifacts are unchanged.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Any, Optional

import numpy as np

from .. import obs
from ..models.base import Model
from ..ops.encode import (EV_INVOKE, EV_RETURN, EncodeError,
                          IncrementalEncoder, EncodedHistory,
                          encode_return_steps)
from ..ops.limits import limits
from ..ops.op import INVOKE, Op

log = logging.getLogger(__name__)

_DONE = object()   # input-exhausted sentinel on the session queue

# The streamed chunk rung's kernel name (results / bench / web).
STREAM_KERNEL = "wgl3-dense-stream-chunked"


class KeyStream:
    """One key's streaming check: incremental encoder + running slot
    snapshot + chunk dispatch into a resumable dense frontier carry."""

    def __init__(self, model: Model, key: Any, k_slots: int = 32):
        self.model = model
        self.key = key
        self.k0 = k_slots
        self.encoder = IncrementalEncoder(model)
        self.cfg = None                # current DenseConfig (None = not yet)
        self.carry = None
        self.parts = None              # device-side partial sums [3]
        self.steps_done = 0            # return steps dispatched this epoch
        self.real_dispatched = 0       # real (non-pad) steps this epoch
        self.live_high = 0             # high-water of real steps dispatched live
        self.total_high = 0            # high-water of real steps dispatched
        self.chunks = 0
        self.restarts = 0
        self.dispatch_s = 0.0
        self._since_poll = 0
        self.last_flush = time.monotonic()
        self.dead = False
        self.infeasible: Optional[str] = None
        # Running slot snapshot over the stable rows, at a growable
        # capacity (snapshot semantics are width-independent: slots
        # beyond max_pending are inactive zeros).
        self._tab = np.zeros((8, 4), np.int32)
        self._act = np.zeros((8,), bool)
        # Buffered return steps awaiting a full chunk:
        # (tab snapshot [cap,4], active [cap], target slot).
        self._buf: list[tuple[np.ndarray, np.ndarray, int]] = []

    # -- feeding ----------------------------------------------------------
    def feed(self, op: Op, live: bool) -> None:
        self._advance(self.encoder.append(op), live)

    def _advance(self, rows, live: bool) -> None:
        if self.infeasible or not rows:
            return
        for kind, slot, f, a1, a2, rv in rows:
            if slot >= self._act.shape[0]:
                grow = max(8, slot + 1 - self._act.shape[0])
                self._tab = np.concatenate(
                    [self._tab, np.zeros((grow, 4), np.int32)])
                self._act = np.concatenate(
                    [self._act, np.zeros((grow,), bool)])
            if kind == EV_INVOKE:
                self._tab[slot] = (f, a1, a2, rv)
                self._act[slot] = True
            elif kind == EV_RETURN:
                # Snapshot just BEFORE processing the return: the
                # returning op itself counts active (encode.py
                # encode_return_steps contract). A dead frontier is
                # sticky — post-death steps would be no-op chunks, so
                # stop buffering them (the verdict is already final).
                if not self.dead:
                    self._buf.append((self._tab.copy(), self._act.copy(),
                                      int(slot)))
                self._act[slot] = False
        chunk = limits().stream_flush_ops
        while len(self._buf) >= chunk and not self.dead \
                and self.infeasible is None:
            if not self._ensure_geometry(live):
                return
            chunk = limits().stream_flush_ops   # _restart may consume buf
            if len(self._buf) < chunk:
                break
            steps, self._buf = self._buf[:chunk], self._buf[chunk:]
            self._dispatch(steps, live, pad_to=chunk)

    def flush_partial(self, live: bool) -> None:
        """Dispatch the buffered tail as one PADDED chunk without waiting
        for a full stream_flush_ops accumulation, then poll death
        immediately — the fail-fast lag bound for keys the workload has
        retired (their buffers would otherwise sit unswept until the
        final drain, so at production chunk sizes a falsified key could
        never trigger the abort). Bit-safe: pad steps are no-ops in the
        scan (make_step_fn3 gates every effect on target >= 0) and chunk
        indexing keys on real_dispatched, so later real steps keep their
        post-hoc indices."""
        if self.dead or self.infeasible or not self._buf:
            return
        if not self._ensure_geometry(live):
            return
        chunk = limits().stream_flush_ops
        while len(self._buf) >= chunk and not self.dead:
            steps, self._buf = self._buf[:chunk], self._buf[chunk:]
            self._dispatch(steps, live, pad_to=chunk)
        if self._buf and not self.dead:
            steps, self._buf = self._buf, []
            self._dispatch(steps, live, pad_to=chunk)
        self._poll_death()

    # -- geometry ---------------------------------------------------------
    def _needed_cfg(self):
        from ..ops import wgl3

        k = wgl3.tight_k_for_pending(self.encoder.max_pending)
        if self.cfg is not None:
            k = max(k, self.cfg.k_slots)
        return wgl3.dense_config(self.model, k, self.encoder.max_value,
                                 budget=limits().dense_cell_budget_chunked)

    def _ensure_geometry(self, live: bool) -> bool:
        """True when the current cfg covers the stable rows; restarts the
        sweep under a bigger geometry when they outgrew it; False (and
        marks infeasible) when no dense geometry serves them — the key
        falls back to the post-hoc ladder untouched."""
        need = self._needed_cfg()
        if need is None:
            self.infeasible = (
                f"dense geometry infeasible (max_pending="
                f"{self.encoder.max_pending}, max_value="
                f"{self.encoder.max_value})")
            self._buf = []
            return False
        if need != self.cfg:
            self._restart(need, live)
        return True

    def _restart(self, cfg, live: bool) -> None:
        """Re-derive the sweep under a new geometry: rebuild return steps
        from the stable rows (vectorized), reset the carry, re-dispatch
        the full chunks, re-buffer the tail. Cheap by construction —
        geometries only grow O(log) times, all early in a run."""
        from ..ops import wgl3

        if self.cfg is not None:
            self.restarts += 1
        self.cfg = cfg
        self.carry = wgl3._init_carry3(self.model, cfg)
        self.parts = None
        self.steps_done = 0
        self.real_dispatched = 0
        self.chunks = 0
        self._since_poll = 0
        self.dead = False
        rows = self.encoder.rows
        enc = EncodedHistory(
            events=np.asarray(rows, np.int32).reshape(-1, 6),
            n_events=len(rows), n_ops=self.encoder.n_ops,
            k_slots=cfg.k_slots, max_pending=self.encoder.max_pending,
            max_value=self.encoder.max_value)
        rs = encode_return_steps(enc)
        chunk = limits().stream_flush_ops
        full = rs.n_steps // chunk * chunk
        self._buf = [(rs.slot_tabs[i], rs.slot_active[i],
                      int(rs.targets[i])) for i in range(full, rs.n_steps)]
        for c0 in range(0, full, chunk):
            self._dispatch_arrays(
                rs.slot_tabs[c0:c0 + chunk], rs.slot_active[c0:c0 + chunk],
                rs.targets[c0:c0 + chunk], live=live, real=chunk)

    # -- dispatch ---------------------------------------------------------
    def _dispatch(self, steps, live: bool, pad_to: int) -> None:
        K = self.cfg.k_slots
        tabs = np.zeros((pad_to, K, 4), np.int32)
        act = np.zeros((pad_to, K), bool)
        tgt = np.full((pad_to,), -1, np.int32)
        for i, (t, a, s) in enumerate(steps):
            w = min(K, t.shape[0])
            tabs[i, :w] = t[:w]
            act[i, :w] = a[:w]
            tgt[i] = s
        self._dispatch_arrays(tabs, act, tgt, live, real=len(steps))

    def _dispatch_arrays(self, tabs, act, tgt, live: bool,
                         real: int) -> None:
        import jax.numpy as jnp

        chunk = tgt.shape[0]
        # Through the KernelPlan layer (plan/dispatch.py): always the
        # PLAIN (no-canonicalization) wgl3 chunk family — the frontier
        # dedup pass (ops/canon.py) needs to know which pending ops
        # never return in the REMAINING history, and a live stream
        # cannot know its future — an op pending now may still complete
        # later. Post-hoc sweeps of the same key run canon-free too for
        # short histories (batched kernels), so streamed and post-hoc
        # metrics stay bit-identical (plan_stream_chunk docstring).
        from .. import plan as kplan

        run = kplan.resolve(
            kplan.plan_stream_chunk(self.model, self.cfg, chunk))
        t0 = time.monotonic()
        with obs.get_tracer().span("stream.chunk", key=str(self.key),
                                   steps=real, live=bool(live)):
            # Chunks index by REAL steps dispatched, not padded: pad
            # steps are scan no-ops, so a padded partial chunk (eager
            # fail-fast flush) mid-stream leaves every later real step's
            # dead_step index exactly where the post-hoc encoding puts
            # it.
            self.carry, part = run(
                self.carry, jnp.asarray(tabs), jnp.asarray(act),
                jnp.asarray(tgt), jnp.int32(self.real_dispatched))
        self.dispatch_s += time.monotonic() - t0
        self.last_flush = t0
        # A successful chunk dispatch is a free backend-health proof
        # (obs/health.py): the consumer thread is one of the supervisor's
        # passive signal sources.
        obs.health.get_supervisor().note_ok(source="stream.dispatch")
        self.parts = part if self.parts is None else self.parts + part
        self.steps_done += chunk
        self.real_dispatched += real
        self.total_high = max(self.total_high, self.real_dispatched)
        if live:
            self.live_high = max(self.live_high, self.real_dispatched)
        self.chunks += 1
        self._since_poll += 1
        if self._since_poll >= limits().stream_max_lag_chunks:
            self._poll_death()

    def _poll_death(self) -> None:
        """Fetch the frontier's death flag; a dead carry is sticky, so
        buffered post-death steps are dropped (zero-config no-ops)."""
        self._since_poll = 0
        if self.carry is not None and not self.dead \
                and bool(np.asarray(self.carry.dead)):
            self.dead = True
            self._buf = []   # post-death chunks are no-ops; skip them

    # -- finalize ---------------------------------------------------------
    def finalize(self) -> Optional[dict]:
        """Drain + fetch: the streamed check result in the chunked dense
        sweep's schema (plus ``model`` / ``streamed`` / ``_enc``), or
        None when this key abandoned streaming (post-hoc takes over)."""
        from ..ops import wgl3
        from ..ops.wgl import verdict

        self._advance(self.encoder.finalize(), live=False)
        enc = self.encoder.encoded_history(self.k0)
        if self.infeasible is not None:
            return None
        if enc.n_events == 0:
            return {"valid": True, "op_count": 0, "model": self.model.name,
                    "streamed": True, "_enc": enc}
        if not self._ensure_geometry(live=False):
            return None
        if self._buf and not self.dead:
            chunk = limits().stream_flush_ops
            steps, self._buf = self._buf, []
            self._dispatch(steps, live=False,
                           pad_to=max(chunk, len(steps)))
        import jax.numpy as jnp

        parts = self.parts if self.parts is not None \
            else jnp.zeros((3,), jnp.float32)
        # The streamed chunks ran wgl3's resumable chunk kernel, so the
        # fetch row is 3 verdict fields + ITS declared partial layout.
        # jtflow: partials-from wgl3._chunk_fn
        packed = np.asarray(jnp.concatenate([
            jnp.stack([jnp.where(self.carry.dead, 0, 1),
                       self.carry.dead_step, self.carry.max_frontier]),
            jnp.clip(parts, 0, 2**31 - 1).astype(jnp.int32)]))
        out = {
            "survived": bool(packed[0]),
            "overflow": False,
            "dead_step": int(packed[1]),
            "max_frontier": int(packed[2]),
            "configs_explored": int(packed[3]),
        }
        out["sweep"] = wgl3.sweep_summary(self.cfg, live_sum=float(packed[4]),
                                          real_steps=int(packed[5]))
        out["live_tile_ratio"] = out["sweep"]["live_tile_ratio"]
        out["valid"] = verdict(out)
        obs.record_check_result(out)
        out.update(op_count=enc.n_ops, kernel=STREAM_KERNEL,
                   model=self.model.name,
                   table_cells=self.cfg.n_states * self.cfg.n_masks,
                   streamed=True)
        out["_enc"] = enc
        return out


class StreamSession:
    """The run-facing half: a queue + consumer thread multiplexing the
    recorder's live op feed into per-key KeyStreams.

    ``feed`` (the HistoryRecorder listener) is O(enqueue); all encoding
    and device work happens on the consumer thread, concurrently with
    the event loop's workers — that concurrency IS the overlap. The
    check phase calls :meth:`finalize` (drain + fetch); ``--fail-fast``
    polls :meth:`falsified` from the runner."""

    def __init__(self, model: Model, keyed: bool, k_slots: int = 32):
        self.model = model
        self.keyed = keyed
        self.k0 = k_slots
        # Fail-fast abort latch. An Event, not a bare bool: the runner's
        # watcher (event-loop thread) sets it while the consumer thread
        # is mid-dispatch — the consumer and the finalize path both key
        # off it to STOP dispatching (see finalize: an aborted session
        # must not launch its buffered tails).
        self._abort = threading.Event()
        self._q: "queue.SimpleQueue" = queue.SimpleQueue()
        self._streams: dict[Any, KeyStream] = {}
        self._key_of_process: dict[Any, Any] = {}
        self._falsified: dict[Any, int] = {}
        self._broken: Optional[str] = None
        self._run_live = threading.Event()
        self._run_live.set()
        self._done_sent = False
        self._eager_flush_s: Optional[float] = None
        self._fed = 0
        self._lag_max = 0
        self._encode_s = 0.0
        self._results: Optional[dict] = None
        self._thread = threading.Thread(target=self._consume,
                                        name="stream-check", daemon=True)
        self._thread.start()

    @property
    def aborted(self) -> bool:
        return self._abort.is_set()

    @aborted.setter
    def aborted(self, value: bool) -> None:
        if value:
            self._abort.set()
        else:
            self._abort.clear()

    # -- event-loop side --------------------------------------------------
    def feed(self, op: Op) -> None:
        """HistoryRecorder listener: enqueue and return."""
        if op.process == "nemesis":
            return
        self._q.put(op)

    def finish_input(self) -> None:
        """The run is over: anything dispatched after this no longer
        counts as overlap, and the consumer thread exits once the queue
        drains. Idempotent."""
        self._run_live.clear()
        if not self._done_sent:
            self._done_sent = True
            self._q.put(_DONE)

    def enable_eager_flush(self, interval_s: float = 0.5) -> None:
        """Fail-fast mode (runner/core.py): partial-flush any key whose
        buffer has sat idle for interval_s, so a falsified key the
        workload already rotated away from still triggers the abort
        within ~interval_s instead of waiting for a full
        stream_flush_ops chunk that will never arrive. Costs at most
        one padded chunk launch per key per interval; verdicts stay
        bit-identical (KeyStream.flush_partial)."""
        self._eager_flush_s = float(interval_s)

    def falsified(self) -> bool:
        """True once any key's streamed frontier died — the --fail-fast
        trigger (detection lag is bounded by stream_max_lag_chunks
        chunks of stream_flush_ops steps; with eager flush enabled,
        additionally by ~the flush interval for idle keys)."""
        return bool(self._falsified)

    # -- consumer thread --------------------------------------------------
    def _consume(self) -> None:
        while True:
            try:
                op = self._q.get(timeout=self._eager_flush_s)
            except queue.Empty:
                # Idle with eager flush on: sweep stale key buffers so a
                # quiet (or rotated-away) falsified key still trips the
                # fail-fast watcher.
                try:
                    self._flush_stale(live=self._run_live.is_set())
                except Exception as e:
                    self._broken = f"{type(e).__name__}: {e}"
                    log.exception("streaming eager flush crashed; "
                                  "falling back to post-hoc")
                continue
            if op is _DONE:
                return
            if self._broken is not None:
                continue   # drain cheaply; post-hoc owns the check now
            if self._abort.is_set():
                # Fail-fast already fired: the verdict is decided and
                # the runner is tearing the workers down. Dispatching
                # the still-queued tail would launch more chunks whose
                # spans land after the run span closed — and an abort
                # landing mid-dispatch used to leave the final partial
                # chunk's span in exactly that orphaned state. Drain
                # cheaply instead; post-hoc owns every verdict now.
                continue
            t0 = time.monotonic()
            try:
                self._feed_one(op, live=self._run_live.is_set())
            except (EncodeError, ValueError) as e:
                # A shape streaming can't handle (malformed pairing, a
                # non-(key, value) independent op): abandon the WHOLE
                # session — the post-hoc checker will see the same
                # history and fail (or cope) exactly as it does today.
                self._broken = f"{type(e).__name__}: {e}"
                log.warning("streaming check abandoned: %s", self._broken)
            except Exception as e:   # never let the checker thread die silently
                self._broken = f"{type(e).__name__}: {e}"
                log.exception("streaming check crashed; falling back "
                              "to post-hoc")
                # An unexplained dispatch-path crash is a backend health
                # signal (a wedged tunnel surfaces as arbitrary jax
                # errors here); the supervisor decides whether it
                # accumulates to degraded/wedged.
                obs.health.get_supervisor().note_failure(
                    self._broken, source="stream.consumer")
            finally:
                self._encode_s += time.monotonic() - t0
                self._fed += 1
                # Rate-limited active probe from the consumer thread —
                # the long-running-daemon hook (no-op inside the first
                # probe interval, so short runs never pay it).
                obs.health.get_supervisor().maybe_probe(
                    source="stream.consumer")
        # not reached

    def _feed_one(self, op: Op, live: bool) -> None:
        if self.keyed:
            routed = self._route(op)
            if routed is None:
                return
            key, sub = routed
        else:
            key, sub = None, op
        ks = self._streams.get(key)
        if ks is None:
            ks = self._streams[key] = KeyStream(self.model, key, self.k0)
        ks.feed(sub, live)
        lag = ks.encoder.lag()
        self._lag_max = max(self._lag_max, lag)
        obs.get_metrics().gauge("stream.watermark_lag").set(lag)
        self._note_dead(key, ks)
        if self._eager_flush_s is not None:
            self._flush_stale(live)

    def _flush_stale(self, live: bool) -> None:
        """Eager-flush keys whose buffers sat idle past the interval
        (enable_eager_flush); O(keys) per sweep, each stale key costs at
        most one padded chunk launch per interval."""
        if self._eager_flush_s is None or self._abort.is_set():
            return
        cutoff = time.monotonic() - self._eager_flush_s
        for key, ks in self._streams.items():
            if ks._buf and ks.last_flush < cutoff:
                ks.flush_partial(live)
                self._note_dead(key, ks)

    def _note_dead(self, key, ks: KeyStream) -> None:
        if ks.dead and key not in self._falsified:
            self._falsified[key] = int(np.asarray(ks.carry.dead_step)) \
                if ks.carry is not None else -1
            obs.get_tracer().event("stream.falsified", key=str(key),
                                   dead_step=self._falsified[key])

    def _route(self, op: Op):
        """checkers/independent.py split_by_key, one op at a time."""
        if op.type == INVOKE:
            if not (isinstance(op.value, tuple) and len(op.value) == 2):
                raise ValueError(
                    f"independent history op without (key, value) tuple: "
                    f"{op}")
            k, v = op.value
            self._key_of_process[op.process] = k
        else:
            k = self._key_of_process.pop(op.process, None)
            if k is None:
                return None
            v = op.value[1] if (isinstance(op.value, tuple)
                                and len(op.value) == 2) else op.value
        return k, Op(type=op.type, f=op.f, value=v, process=op.process,
                     time=op.time, index=op.index, error=op.error,
                     seq=op.seq)

    # -- check-phase side -------------------------------------------------
    def finalize(self) -> Optional[dict]:
        """Join the consumer, finalize every key stream, publish the
        telemetry gauges. Returns {key: streamed result} (None when the
        session abandoned streaming entirely). Idempotent."""
        if self._results is not None:
            return self._results or None
        self.finish_input()
        self._thread.join()
        metrics = obs.get_metrics()
        results: dict[Any, dict] = {}
        if self._abort.is_set():
            # Fail-fast teardown (ISSUE 15 satellite): the run was
            # aborted because some key's streamed frontier died — the
            # post-hoc checker re-checks the recorded history whole, so
            # per-key finalize work here is pure waste. Worse than
            # waste: every key with a buffered tail would dispatch one
            # more padded chunk, emitting a telemetry span AFTER the
            # run span closed (the abort routinely lands mid-dispatch),
            # and a campaign's thousands of aborted runs turned those
            # orphan spans into tracer-cap truncation-footer noise.
            # Abandon every tail instead: no further dispatches, no new
            # spans — and a partial-prefix sweep must not settle a key
            # as valid anyway (the prefix proves nothing about the
            # whole history), so returning NO streamed results is the
            # only sound choice. tests/test_campaign.py pins both the
            # no-new-spans and the no-settle halves.
            self._finalize_stats(metrics, abandoned=len(self._streams))
            self._results = {}
            return None
        if self._broken is None:
            for key, ks in self._streams.items():
                t0 = time.monotonic()
                try:
                    res = ks.finalize()
                except Exception as e:
                    log.exception("stream finalize failed for key %r", key)
                    res = None
                self._encode_s += time.monotonic() - t0
                if res is not None:
                    results[key] = res
                    enc = res.get("_enc")
                    if enc is not None and enc.n_events \
                            and res.get("valid") is True:
                        # The post-hoc encode these keys skipped (web's
                        # check-eps column derives event counts from
                        # encode.event_bytes). Only VALID verdicts
                        # settle (checkers/linearizable._stream_result);
                        # invalid keys re-run post-hoc, whose
                        # encode_events counts the same history itself.
                        metrics.counter("encode.event_bytes").add(
                            int(enc.events[: enc.n_events].nbytes))
                        metrics.counter("encode.histories").add(1)
        self._finalize_stats(metrics, streamed_keys=len(results))
        self._results = results
        return results or None

    def _finalize_stats(self, metrics, streamed_keys: int = 0,
                        abandoned: int = 0) -> None:
        """Publish the session gauges + build the results.json stream
        record — shared by the normal and the aborted finalize paths.
        The consumer-thread wall minus the time spent inside chunk
        dispatches (those already land in wgl.compile_s/execute_s via
        instrument_kernel) is the honest host-encode share."""
        dispatch_s = sum(ks.dispatch_s for ks in self._streams.values())
        encode_s = max(0.0, self._encode_s - dispatch_s)
        metrics.counter("encode.encode_s").add(encode_s)
        self._encode_host_s = encode_s
        total = sum(ks.total_high for ks in self._streams.values())
        live = sum(ks.live_high for ks in self._streams.values())
        overlap = live / total if total else 0.0
        metrics.gauge("stream.overlap_ratio").set(overlap)
        self._stats = {
            "overlap_ratio": round(overlap, 4),
            "keys": len(self._streams),
            "streamed_keys": streamed_keys,
            "chunks": sum(ks.chunks for ks in self._streams.values()),
            "restarts": sum(ks.restarts for ks in self._streams.values()),
            "steps_total": int(total),
            "steps_overlapped": int(live),
            "watermark_lag_max": int(self._lag_max),
            "encode_s": round(encode_s, 4),
            "dispatch_s": round(dispatch_s, 4),
            "failfast_aborted": self.aborted,
        }
        if abandoned:
            # How many keys' buffered tails the abort abandoned — the
            # fail-fast accounting the campaign report surfaces.
            self._stats["abandoned_keys"] = abandoned
        if self._broken:
            self._stats["fallback"] = self._broken

    def stats(self) -> dict:
        """The results.json ``stream`` record (finalize() must have run)."""
        stats = dict(getattr(self, "_stats", {}))
        stats["failfast_aborted"] = self.aborted
        return stats


def session_for_test(test: dict):
    """Build the streaming session for a composed test, or None when its
    checker topology is not streamable (no jax Linearizable or elle
    checker, or a model whose prepare_history rewrites the history
    statefully — the stream feeds RAW ops, so only identity-translation
    models qualify). The caller falls back to post-hoc checking, with
    zero behavior change. Transactional topologies (an ElleChecker in
    the tree) stream through the incremental dependency-graph session
    (stream/elle.py) instead of the WGL chunk dispatcher."""
    found = _find_streamable(test.get("checker"))
    if found is None:
        from .elle import ElleStreamSession, find_elle_checker

        elle = find_elle_checker(test.get("checker"))
        if elle is not None:
            return ElleStreamSession(elle)
        return None
    lin, keyed = found
    if type(lin.model).prepare_history is not Model.prepare_history:
        log.info("check-mode stream: model %r translates histories; "
                 "falling back to post-hoc", lin.model.name)
        return None
    return StreamSession(lin.model, keyed=keyed, k_slots=lin.k_slots)


def _find_streamable(checker) -> Optional[tuple]:
    """Walk the checker tree for the first jax-backed Linearizable:
    (lin, keyed) — keyed when it sits under an IndependentChecker."""
    from ..checkers.compose import Compose
    from ..checkers.independent import IndependentChecker
    from ..checkers.linearizable import Linearizable

    if isinstance(checker, Linearizable):
        return (checker, False) if checker.backend == "jax" else None
    if isinstance(checker, IndependentChecker):
        sub = _find_streamable(checker.sub_checker)
        return (sub[0], True) if sub is not None else None
    if isinstance(checker, Compose):
        for sub in checker.checkers.values():
            found = _find_streamable(sub)
            if found is not None:
                return found
    return None
