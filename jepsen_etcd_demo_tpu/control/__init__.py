"""Remote-control plane: run commands on cluster nodes.

Equivalent of jepsen.control + control.util (reference call sites
src/jepsen/etcdemo.clj:36-60: c/su, c/exec, cu/install-archive!,
cu/start-daemon!, cu/stop-daemon!). The transport is the system `ssh`
binary driven over subprocess (the reference uses clj-ssh/jsch,
jepsen.etcdemo.iml:21,38); a LocalRunner runs the same command surface
against localhost for hermetic tests (SURVEY.md §4
"distributed-without-cluster").
"""

from .runner import (  # noqa: F401
    CommandResult, CommandError, Runner, LocalRunner, SSHRunner, shellquote,
)
from .daemon import (  # noqa: F401
    install_archive, start_daemon, stop_daemon, daemon_running,
)
