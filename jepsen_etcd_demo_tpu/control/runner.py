"""Command runners: SSH to a node, or localhost subprocess."""

from __future__ import annotations

import asyncio
import os
import shlex
from dataclasses import dataclass
from typing import Optional, Sequence


def shellquote(arg) -> str:
    return shlex.quote(str(arg))


@dataclass
class CommandResult:
    argv: list[str]
    returncode: int
    stdout: str
    stderr: str

    @property
    def ok(self) -> bool:
        return self.returncode == 0


class CommandError(Exception):
    def __init__(self, result: CommandResult):
        self.result = result
        super().__init__(
            f"command {' '.join(result.argv)!r} exited "
            f"{result.returncode}: {result.stderr[-500:]}")


class Runner:
    """Run shell commands 'on a node'. su=True wraps with sudo
    (c/su, reference src/jepsen/etcdemo.clj:36)."""

    node: str = "local"

    async def run(self, cmd: str, su: bool = False, check: bool = True,
                  timeout_s: float = 120.0) -> CommandResult:
        raise NotImplementedError

    async def exec(self, *argv, su: bool = False, check: bool = True,
                   timeout_s: float = 120.0) -> CommandResult:
        """c/exec equivalent: argv-style, auto-quoted."""
        cmd = " ".join(shellquote(a) for a in argv)
        return await self.run(cmd, su=su, check=check, timeout_s=timeout_s)

    async def upload(self, local_path: str, remote_path: str
                     ) -> CommandResult:
        """Copy a file onto the node (cu/install-archive! transport leg)."""
        raise NotImplementedError

    async def download(self, remote_path: str, local_path: str,
                       check: bool = False) -> CommandResult:
        """Copy a file off the node (db/LogFiles collection,
        reference src/jepsen/etcdemo.clj:62-64)."""
        raise NotImplementedError

    async def _spawn(self, argv: Sequence[str], check: bool,
                     timeout_s: float,
                     env: Optional[dict] = None) -> CommandResult:
        proc = await asyncio.create_subprocess_exec(
            *argv,
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.PIPE,
            env=env)
        try:
            out, err = await asyncio.wait_for(proc.communicate(), timeout_s)
        except asyncio.TimeoutError:
            proc.kill()
            await proc.wait()
            res = CommandResult(list(argv), -1, "", f"timeout after {timeout_s}s")
            if check:
                raise CommandError(res)
            return res
        res = CommandResult(list(argv), proc.returncode or 0,
                            out.decode(errors="replace"),
                            err.decode(errors="replace"))
        if check and not res.ok:
            raise CommandError(res)
        return res


class LocalRunner(Runner):
    """Run on this host — hermetic stand-in for a node (CI without a
    cluster). su is a no-op by default so tests never sudo."""

    def __init__(self, node: str = "local", allow_su: bool = False):
        self.node = node
        self.allow_su = allow_su

    async def run(self, cmd: str, su: bool = False, check: bool = True,
                  timeout_s: float = 120.0) -> CommandResult:
        if su and self.allow_su:
            cmd = f"sudo sh -c {shellquote(cmd)}"
        return await self._spawn(["sh", "-c", cmd], check, timeout_s)

    async def upload(self, local_path: str, remote_path: str
                     ) -> CommandResult:
        return await self._spawn(["cp", local_path, remote_path], True, 300.0)

    async def download(self, remote_path: str, local_path: str,
                       check: bool = False) -> CommandResult:
        return await self._spawn(["cp", remote_path, local_path], check,
                                 300.0)


class SSHRunner(Runner):
    """Drive a node over the system ssh binary.

    Equivalent transport role to the reference's clj-ssh/jsch sessions
    (jepsen.etcdemo.iml:21,38): one logical session per node, command
    assembly with quoting, sudo wrapping."""

    def __init__(self, node: str, username: str = "root",
                 port: int = 22, private_key: Optional[str] = None,
                 password: Optional[str] = None,
                 strict_host_key_checking: bool = False,
                 connect_timeout_s: int = 10):
        self.node = node
        self.username = username
        self.port = port
        self.private_key = private_key
        self.password = password
        self.strict = strict_host_key_checking
        self.connect_timeout_s = connect_timeout_s

    def _common_opts(self) -> list[str]:
        # Password auth (jepsen's --password, the jsch password session)
        # rides sshpass: OpenSSH refuses passwords on argv/stdin by
        # design, and BatchMode=yes would disable the prompt sshpass
        # answers — so BatchMode only guards the key-auth mode.
        opts = (["-o", "NumberOfPasswordPrompts=1"] if self.password
                else ["-o", "BatchMode=yes"])
        if not self.strict:
            opts += ["-o", "StrictHostKeyChecking=no",
                     "-o", "UserKnownHostsFile=/dev/null",
                     "-o", "LogLevel=ERROR"]
        if self.private_key:
            opts += ["-i", self.private_key]
        return opts

    def _transport(self, argv: list[str]) -> tuple[list[str], Optional[dict]]:
        """Final (argv, env) for one ssh/scp invocation. The password is
        handed to sshpass through the environment (`-e`/SSHPASS), never
        on argv — argv is visible to every local `ps`."""
        if not self.password:
            return argv, None
        import shutil

        if shutil.which("sshpass") is None:
            # Fail with the remedy, not a FileNotFoundError five frames
            # deep in asyncio's spawn path.
            raise RuntimeError(
                "--password auth rides the sshpass binary (OpenSSH "
                "refuses passwords on argv by design) and sshpass is "
                "not on PATH; install it or use --private-key")
        env = dict(os.environ, SSHPASS=self.password)
        return ["sshpass", "-e"] + argv, env

    def _ssh_argv(self, cmd: str) -> list[str]:
        return (["ssh", "-p", str(self.port),
                 "-o", f"ConnectTimeout={self.connect_timeout_s}"]
                + self._common_opts()
                + [f"{self.username}@{self.node}", cmd])

    async def run(self, cmd: str, su: bool = False, check: bool = True,
                  timeout_s: float = 120.0) -> CommandResult:
        if su and self.username != "root":
            cmd = f"sudo sh -c {shellquote(cmd)}"
        argv, env = self._transport(self._ssh_argv(cmd))
        return await self._spawn(argv, check, timeout_s, env)

    async def upload(self, local_path: str, remote_path: str) -> CommandResult:
        argv, env = self._transport(
            ["scp", "-P", str(self.port)] + self._common_opts()
            + [local_path, f"{self.username}@{self.node}:{remote_path}"])
        return await self._spawn(argv, True, 300.0, env)

    async def download(self, remote_path: str, local_path: str,
                       check: bool = False) -> CommandResult:
        argv, env = self._transport(
            ["scp", "-P", str(self.port)] + self._common_opts()
            + [f"{self.username}@{self.node}:{remote_path}", local_path])
        return await self._spawn(argv, check, 300.0, env)


def runner_for(test: dict, node: str) -> Runner:
    """Build the control-plane runner a test's config asks for."""
    if test.get("local_mode"):
        return LocalRunner(node)
    ssh = test.get("ssh", {})
    return SSHRunner(node,
                     username=ssh.get("username", "root"),
                     port=ssh.get("port", 22),
                     private_key=ssh.get("private_key"),
                     password=ssh.get("password"),
                     strict_host_key_checking=ssh.get("strict", False))
