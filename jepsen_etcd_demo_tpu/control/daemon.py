"""Daemon + archive helpers — jepsen.control.util equivalents.

Reference call sites: cu/install-archive! (download + unpack a release
tarball, src/jepsen/etcdemo.clj:37-40), cu/start-daemon! (daemonize with
pidfile + logfile + chdir, :42-54), cu/stop-daemon! (kill by pidfile, :59).
"""

from __future__ import annotations

from typing import Optional, Sequence

from .runner import Runner, shellquote


async def install_archive(r: Runner, url: str, dest_dir: str,
                          su: bool = True) -> None:
    """Download `url` (tar.gz) and unpack into dest_dir, stripping the
    top-level directory like cu/install-archive! does."""
    tmp = f"/tmp/jepsen-archive-{abs(hash(url)) % 10**8}.tar.gz"
    await r.run(
        f"mkdir -p {shellquote(dest_dir)} && "
        f"([ -f {shellquote(tmp)} ] || "
        f" wget -q -O {shellquote(tmp)} {shellquote(url)} || "
        f" curl -fsSL -o {shellquote(tmp)} {shellquote(url)}) && "
        f"tar xzf {shellquote(tmp)} -C {shellquote(dest_dir)} "
        f"--strip-components=1",
        su=su, timeout_s=600.0)


async def start_daemon(r: Runner, binary: str, args: Sequence,
                       logfile: str, pidfile: str, chdir: str,
                       su: bool = True) -> None:
    """Start `binary args...` as a daemon: nohup + setsid, stdout/stderr to
    logfile, pid recorded. Idempotent: a live pidfile means already running
    (cu/start-daemon! semantics)."""
    argstr = " ".join(shellquote(a) for a in args)
    await r.run(
        f"if [ -f {shellquote(pidfile)} ] && "
        f"kill -0 $(cat {shellquote(pidfile)}) 2>/dev/null; then "
        f"  echo already-running; "
        f"else "
        f"  cd {shellquote(chdir)} && "
        f"  setsid nohup {shellquote(binary)} {argstr} "
        f"  >> {shellquote(logfile)} 2>&1 < /dev/null & "
        f"  echo $! > {shellquote(pidfile)}; "
        f"fi",
        su=su, timeout_s=60.0)


async def stop_daemon(r: Runner, pidfile: str, su: bool = True) -> None:
    """Kill the daemon by pidfile (SIGKILL like cu/stop-daemon!), then
    remove the pidfile. Idempotent."""
    await r.run(
        f"if [ -f {shellquote(pidfile)} ]; then "
        f"  kill -9 $(cat {shellquote(pidfile)}) 2>/dev/null || true; "
        f"  rm -f {shellquote(pidfile)}; "
        f"fi",
        su=su, check=False, timeout_s=60.0)


async def daemon_running(r: Runner, pidfile: str) -> bool:
    res = await r.run(
        f"[ -f {shellquote(pidfile)} ] && "
        f"kill -0 $(cat {shellquote(pidfile)}) 2>/dev/null",
        check=False)
    return res.ok
