"""minietcd — an etcd-argv-compatible single-member v2 server.

VERDICT r4 missing #1: everything real-cluster-shaped in this tree was
verified by argv assembly and HTTP stubs, because the image cannot run
the Go etcd binary. This module is the promotion of that stub to a REAL
spawnable process, so the full product path — CLI `test` → SSH transport
→ `cu/install-archive!`-style tarball install → daemon lifecycle
(control/daemon.py) → live HTTP clients → store artifact + verdict —
executes end to end on this image, leaving nothing argv-only.

What it is: a faithful single-member implementation of the etcd **v2
keys API** surface the framework uses (the verschlimmbesserung 5-call
surface plus the in-order-keys queue recipe — clients/etcd.py, reference
src/jepsen/etcdemo.clj:79-98):

  GET    /v2/keys/<k>[?quorum=true]        value + modifiedIndex; dir
                                           listing with ?recursive&sorted
  PUT    /v2/keys/<k> value=v              set; ?prevValue/?prevIndex CAS
                                           (errorCode 101 on mismatch)
  POST   /v2/keys/<dir> value=v            in-order key creation
  DELETE /v2/keys/<k>[?prevIndex=i]        compare-and-delete

with etcd's errorCode 100 (key not found) / 101 (compare failed)
semantics, a global modifiedIndex, write-through persistence to
--data-dir, and mutation atomicity under concurrent clients (one lock —
a single-member etcd is exactly a linearizable single-copy register,
which is what makes a valid verdict against it meaningful).

What it is NOT: raft. One process is one one-member cluster; the
multi-node replication story is the real etcd binary's, and pointing
several minietcds at each other yields independent stores (the flag
parser accepts --initial-cluster for argv compatibility but only ever
serves its own member). Runs that need true replication semantics use a
real etcd via $ETCD_BIN, same as before.

It accepts the exact flag surface EtcdDB passes (db/etcd.py:66-74) plus
--data-dir/--enable-v2/--version, binds the peer port (so topology
mistakes conflict loudly, like real etcd), and `make_release_tarball`
packages it in the release-tarball shape `install_archive` unpacks — so
EtcdDB drives it with ZERO special-casing via the
JEPSEN_TPU_ETCD_TARBALL override.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import sys
import tarfile
import tempfile
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

VERSION = "2.3.8-minietcd"   # v2-era version string: _etcd_version probes
#                              parse it as (2,3) => v2 API default-on

# Campaign fault plane (ISSUE 15 satellite; nemesis/cluster_faults.py
# DiskFaultNemesis): persistence faults a KeyStore can be told to
# inject. ENV-GATED: `fault_mode` is honored only while this variable is
# set truthy, so a production minietcd can never be bent by a stray
# attribute write — the nemesis sets both, scoped to its fault window.
FAULT_HOOK_ENV = "JEPSEN_TPU_MINIETCD_FAULT_HOOK"
FAULT_DISK_FULL = "disk-full"        # acked writes never reach the disk
FAULT_CORRUPT_WRITE = "corrupt-write"  # snapshot garbles the last value


def fault_hook_enabled() -> bool:
    return os.environ.get(FAULT_HOOK_ENV, "").lower() \
        in ("1", "true", "yes", "on")


def _garble(value: str) -> str:
    """Deterministic on-disk corruption that stays in the op language:
    numeric register values bump by one (guaranteed != the acked value,
    still encodable by the checker), anything else reverses."""
    try:
        return str(int(value) + 1)
    except (TypeError, ValueError):
        return value[::-1] if value else "corrupt"


class KeyStore:
    """The single-copy store: key -> (value, modifiedIndex), one global
    index, one lock. Every compound read-check-write below holds the
    lock for its whole critical section — CAS atomicity under the
    ThreadingHTTPServer's per-request threads is what makes this a
    linearizable register rather than a data race with an HTTP port."""

    def __init__(self, data_dir: str | None = None):
        self.data: dict[str, tuple[str, int]] = {}
        self.index = 0
        self.lock = threading.Lock()
        self.path = (os.path.join(data_dir, "minietcd.json")
                     if data_dir else None)
        # Campaign fault plane (env-gated, see FAULT_HOOK_ENV): which
        # persistence fault to inject, and how many writes it has bent —
        # the DiskFaultNemesis's observability counter.
        self.fault_mode: str | None = None
        self.faults_injected = 0
        if self.path and os.path.exists(self.path):
            with open(self.path) as f:
                snap = json.load(f)
            self.index = snap["index"]
            self.data = {k: (v, i) for k, (v, i) in snap["keys"].items()}

    def _persist_locked(self) -> None:
        if not self.path:
            return
        mode = self.fault_mode if fault_hook_enabled() else None
        if mode == FAULT_DISK_FULL:
            # The seeded bug: a server that swallows ENOSPC — the write
            # is acked and served from memory but never reaches the
            # disk, so a crash-restart from the snapshot silently loses
            # it (the lost-acked-write the checker falsifies after the
            # nemesis's restart leg).
            self.faults_injected += 1
            return
        data = self.data
        if mode == FAULT_CORRUPT_WRITE:
            # Corrupt-on-write: the snapshot garbles the most recently
            # modified key's value on its way to disk; the in-memory
            # copy stays correct, so the corruption surfaces only after
            # a restart reloads it (an invented read the checker
            # falsifies).
            data = dict(self.data)
            latest = max(data, key=lambda k: data[k][1], default=None)
            if latest is not None:
                v, idx = data[latest]
                data[latest] = (_garble(v), idx)
                self.faults_injected += 1
        # Atomic replace: a daemon kill -9 (the KillNemesis) must never
        # leave a torn snapshot — either the old state or the new one.
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(self.path))
        with os.fdopen(fd, "w") as f:
            json.dump({"index": self.index,
                       "keys": {k: list(v) for k, v in data.items()}},
                      f)
        os.replace(tmp, self.path)

    # Each method returns (status, body) in etcd v2 wire shape.

    def get(self, key: str, quorum: bool = False):
        # `quorum` is part of the store-frontend protocol (the handler
        # forwards the client's ?quorum=true): the single-copy KeyStore
        # is linearizable either way, but frontends that bend reads
        # (campaign/cluster._MemberStore's lease plane) must see it to
        # honor etcd's q=true bypass.
        with self.lock:
            children = sorted(
                (idx, k, v) for k, (v, idx) in self.data.items()
                if k.startswith(key + "/"))
            if key not in self.data and not children:
                return 404, {"errorCode": 100, "message": "Key not found",
                             "cause": f"/{key}", "index": self.index}
            if children:
                return 200, {"action": "get", "node": {
                    "key": f"/{key}", "dir": True,
                    "nodes": [{"key": f"/{k}", "value": v,
                               "modifiedIndex": idx, "createdIndex": idx}
                              for idx, k, v in children]}}
            v, idx = self.data[key]
            return 200, {"action": "get",
                         "node": {"key": f"/{key}", "value": v,
                                  "modifiedIndex": idx,
                                  "createdIndex": idx}}

    def _write_conflict_locked(self, key: str, creating_dir: bool):
        """etcd forbids file/dir conflicts at WRITE time (errorCode 102
        "Not a file" writing a file over a dir, 104 "Not a directory"
        writing under — or in-order-posting to — an existing file); the
        store used to resolve the ambiguity silently at read time, which
        let a workload whose register key collided with a queue dir
        prefix behave differently here than on real etcd (ADVICE.md
        round 5). Caller holds the lock."""
        if not creating_dir and any(k.startswith(key + "/")
                                    for k in self.data):
            return 403, {"errorCode": 102, "message": "Not a file",
                         "cause": f"/{key}", "index": self.index}
        if creating_dir and key in self.data:
            return 400, {"errorCode": 104, "message": "Not a directory",
                         "cause": f"/{key}", "index": self.index}
        parts = key.split("/")
        for i in range(1, len(parts)):
            ancestor = "/".join(parts[:i])
            if ancestor in self.data:
                return 400, {"errorCode": 104,
                             "message": "Not a directory",
                             "cause": f"/{ancestor}", "index": self.index}
        return None

    def put(self, key: str, value: str, prev_value: str | None,
            prev_index: int | None):
        with self.lock:
            conflict = self._write_conflict_locked(key, creating_dir=False)
            if conflict is not None:
                return conflict
            if prev_value is not None or prev_index is not None:
                if key not in self.data:
                    return 404, {"errorCode": 100,
                                 "message": "Key not found",
                                 "cause": f"/{key}", "index": self.index}
                cur, idx = self.data[key]
                if ((prev_value is not None and prev_value != cur)
                        or (prev_index is not None and prev_index != idx)):
                    return 412, {"errorCode": 101,
                                 "message": "Compare failed",
                                 "cause": f"[{prev_value} != {cur}]",
                                 "index": self.index}
            self.index += 1
            self.data[key] = (value, self.index)
            self._persist_locked()
            return 200, {"action": "set",
                         "node": {"key": f"/{key}", "value": value,
                                  "modifiedIndex": self.index,
                                  "createdIndex": self.index}}

    def post(self, key: str, value: str):
        with self.lock:
            conflict = self._write_conflict_locked(key, creating_dir=True)
            if conflict is not None:
                return conflict
            self.index += 1
            # Zero-padded index name: lexicographic sort == creation
            # order (etcd's in-order keys are ordered by createdIndex;
            # the padding makes the string sort agree).
            node = f"{key}/{self.index:020d}"
            self.data[node] = (value, self.index)
            self._persist_locked()
            return 201, {"action": "create",
                         "node": {"key": f"/{node}", "value": value,
                                  "modifiedIndex": self.index,
                                  "createdIndex": self.index}}

    def delete(self, key: str, prev_index: int | None):
        with self.lock:
            if key not in self.data:
                return 404, {"errorCode": 100, "message": "Key not found",
                             "cause": f"/{key}", "index": self.index}
            v, idx = self.data[key]
            if prev_index is not None and prev_index != idx:
                return 412, {"errorCode": 101, "message": "Compare failed",
                             "cause": f"[{prev_index} != {idx}]",
                             "index": self.index}
            del self.data[key]
            self._persist_locked()
            return 200, {"action": "delete",
                         "node": {"key": f"/{key}", "value": v,
                                  "modifiedIndex": idx,
                                  "createdIndex": idx}}


def _handler_for(store: KeyStore):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):   # request log -> stdout noise; the
            pass                     # daemon logfile gets lifecycle lines

        def _key(self) -> str:
            return urlparse(self.path).path[len("/v2/keys/"):].strip("/")

        def _params(self) -> dict:
            return {k: v[0]
                    for k, v in parse_qs(urlparse(self.path).query).items()}

        def _form(self) -> dict:
            length = int(self.headers.get("Content-Length", 0))
            return {k: v[0] for k, v in
                    parse_qs(self.rfile.read(length).decode()).items()}

        def _reply(self, status: int, body: dict):
            payload = json.dumps(body).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("X-Etcd-Index", str(store.index))
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

        def do_GET(self):
            if urlparse(self.path).path in ("/health", "/version"):
                self._reply(200, {"etcdserver": VERSION,
                                  "health": "true"})
                return
            # Forward q=true: the plain KeyStore ignores it, but the
            # campaign's leased cluster frontends serve non-quorum
            # reads from an expired lease snapshot — a quorum read must
            # bypass that (etcd's q=true semantics).
            self._reply(*store.get(
                self._key(),
                quorum=self._params().get("quorum") == "true"))

        def do_PUT(self):
            # Real etcd v2 accepts the payload fields in EITHER location
            # (urlencoded form body or query string); merging both (form
            # wins on collision, like etcd's form parse shadowing the
            # URL's) keeps wire drift between our client and server from
            # silently degrading a CAS to an unconditional PUT — the
            # client sends value in the form and prevValue/prevIndex in
            # the query today, but a drifted client using the other
            # location must hit the same semantics (ADVICE.md round 5).
            merged = {**self._params(), **self._form()}
            prev_index = merged.get("prevIndex")
            self._reply(*store.put(
                self._key(), merged.get("value", ""),
                merged.get("prevValue"),
                int(prev_index) if prev_index is not None else None))

        def do_POST(self):
            merged = {**self._params(), **self._form()}
            self._reply(*store.post(self._key(), merged.get("value", "")))

        def do_DELETE(self):
            # Same either-location merge as do_PUT: a drifted client
            # sending prevIndex in the body must not silently get an
            # UNCONDITIONAL delete (compare-and-delete is the queue
            # recipe's claim guard).
            merged = {**self._params(), **self._form()}
            prev_index = merged.get("prevIndex")
            self._reply(*store.delete(
                self._key(),
                int(prev_index) if prev_index is not None else None))

    return Handler


def _url_port(url: str, default: int) -> tuple[str, int]:
    u = urlparse(url if "//" in url else f"http://{url}")
    return u.hostname or "127.0.0.1", u.port or default


def build_parser() -> argparse.ArgumentParser:
    """The etcd flag surface EtcdDB passes (db/etcd.py:66-74), plus the
    handful the integration fixture uses. Unknown flags are rejected
    like real etcd rejects them (parse_args, not parse_known_args) —
    argv drift in EtcdDB should fail loudly here."""
    p = argparse.ArgumentParser(prog="minietcd")
    p.add_argument("--name", default="default")
    p.add_argument("--data-dir", default=None)
    p.add_argument("--listen-client-urls", default="http://127.0.0.1:2379")
    p.add_argument("--advertise-client-urls", default=None)
    p.add_argument("--listen-peer-urls", default="http://127.0.0.1:2380")
    p.add_argument("--initial-advertise-peer-urls", default=None)
    p.add_argument("--initial-cluster", default=None)
    p.add_argument("--initial-cluster-state", default="new")
    p.add_argument("--log-output", default=None)
    p.add_argument("--enable-v2", nargs="?", const="true", default="true")
    p.add_argument("--version", action="store_true")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.version:
        # The real binary's shape: test_integration._etcd_version greps
        # the "Version:" line to decide whether --enable-v2 is needed.
        print(f"etcd Version: {VERSION}\nGit SHA: none\n"
              f"Go Version: none (python stand-in)")
        return 0
    # Real etcd defaults its data dir to <name>.etcd under the working
    # directory; matching it means EtcdDB's argv (which passes no
    # --data-dir, reference :42-54) gets DURABLE state under the install
    # dir — a kill-nemesis restart must not lose acked writes, and
    # teardown's rm -rf of the install dir wipes it exactly like the
    # reference's teardown.
    data_dir = args.data_dir or f"{args.name}.etcd"
    os.makedirs(data_dir, exist_ok=True)
    t_start = time.monotonic()
    store = KeyStore(data_dir)
    host, port = _url_port(args.listen_client_urls, 2379)
    peer_host, peer_port = _url_port(args.listen_peer_urls, 2380)
    # Hold the peer port like real etcd does: a second member pointed at
    # the same host fails at bind time instead of silently forking an
    # unrelated store.
    peer_sock = socket.socket()
    peer_sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    peer_sock.bind((peer_host, peer_port))
    peer_sock.listen(1)
    server = ThreadingHTTPServer((host, port), _handler_for(store))
    server.daemon_threads = True
    # shutdown() joins the serve_forever loop, and the signal handler
    # runs ON the serving (main) thread — calling it inline deadlocks.
    signal.signal(signal.SIGTERM, lambda *a: threading.Thread(
        target=server.shutdown, daemon=True).start())
    # Start timing in the daemon log (obs satellite of the telemetry PR):
    # the harness-side db.start span ends at start_daemon's pidfile
    # check, so snapshot-load + bind cost is only visible HERE.
    ready_ms = (time.monotonic() - t_start) * 1e3
    print(f"minietcd {VERSION} member {args.name}: serving client "
          f"requests on http://{host}:{port} (peer {peer_port}, "
          f"data-dir {data_dir}, ready in {ready_ms:.1f} ms, "
          f"{len(store.data)} keys restored)", flush=True)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.server_close()
        peer_sock.close()
    return 0


# --- packaging: the release-tarball shape install_archive expects ----------

LAUNCHER = """#!/bin/sh
# minietcd launcher — etcd-argv-compatible stand-in (single member, v2).
PYTHONPATH={pkg_root}${{PYTHONPATH:+:$PYTHONPATH}} \\
  exec {python} -m jepsen_etcd_demo_tpu.db.minietcd "$@"
"""


def write_launcher(dest: str) -> str:
    """Write an executable `etcd` shim at `dest` that execs this module
    with the invoking interpreter and this package importable."""
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    with open(dest, "w") as f:
        f.write(LAUNCHER.format(pkg_root=pkg_root, python=sys.executable))
    os.chmod(dest, 0o755)
    return dest


def make_release_tarball(dest: str, version: str = "v3.1.5") -> str:
    """Build `etcd-<version>-linux-amd64/etcd` inside a tar.gz at `dest`
    — the exact layout the google-storage release tarball has
    (db/etcd.py tarball_url), so install_archive's --strip-components=1
    lands the launcher at <dir>/etcd."""
    top = f"etcd-{version}-linux-amd64"
    with tempfile.TemporaryDirectory() as td:
        launcher = write_launcher(os.path.join(td, "etcd"))
        with tarfile.open(dest, "w:gz") as tar:
            tar.add(launcher, arcname=f"{top}/etcd")
    return dest


if __name__ == "__main__":
    sys.exit(main())
