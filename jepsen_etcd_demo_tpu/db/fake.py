"""No-op DB for hermetic runs over the in-process FakeKVStore."""

from __future__ import annotations

from ..control.runner import Runner
from .base import DB


class FakeDB(DB):
    async def setup(self, test: dict, r: Runner, node: str) -> None:
        pass

    async def teardown(self, test: dict, r: Runner, node: str) -> None:
        pass
