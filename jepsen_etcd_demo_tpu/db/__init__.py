"""DB orchestration layer — jepsen.db protocol equivalents.

Reference: the db/DB + db/LogFiles reify at src/jepsen/etcdemo.clj:30-65.
"""

from .base import DB  # noqa: F401
from .etcd import EtcdDB, node_url, peer_url, client_url, initial_cluster  # noqa: F401
from .fake import FakeDB  # noqa: F401
from .debian import debian_setup  # noqa: F401
