"""etcd cluster orchestration + topology helpers.

Mirrors the reference's db reify (src/jepsen/etcdemo.clj:25-65) and
support.clj URL builders (src/jepsen/etcdemo/support.clj:4-26): install the
release tarball, start the daemon with full static-cluster flags, wait for
convergence; teardown kills and wipes; etcd.log is the collectable log.
"""

from __future__ import annotations

import asyncio
import logging

from ..control.runner import Runner
from ..control.daemon import install_archive, start_daemon, stop_daemon
from .base import DB

log = logging.getLogger(__name__)

DIR = "/opt/etcd"                       # reference :25
BINARY = "etcd"                         # :26
LOGFILE = f"{DIR}/etcd.log"             # :27
PIDFILE = f"{DIR}/etcd.pid"             # :28

PEER_PORT = 2380                        # support.clj:9-12
CLIENT_PORT = 2379                      # support.clj:14-17

DEFAULT_VERSION = "v3.1.5"              # reference :162


def node_url(node: str, port: int) -> str:
    """HTTP url for connecting to a node on a port (support.clj:4-7)."""
    return f"http://{node}:{port}"


def peer_url(node: str) -> str:
    return node_url(node, PEER_PORT)


def client_url(node: str) -> str:
    return node_url(node, CLIENT_PORT)


def initial_cluster(nodes: list[str]) -> str:
    """node=peer-url pairs joined by commas (support.clj:19-26)."""
    return ",".join(f"{n}={peer_url(n)}" for n in nodes)


def tarball_url(version: str) -> str:
    """Release tarball location (reference :37-40)."""
    return (f"https://storage.googleapis.com/etcd/{version}/"
            f"etcd-{version}-linux-amd64.tar.gz")


class EtcdDB(DB):
    def __init__(self, version: str = DEFAULT_VERSION,
                 settle_s: float = 10.0):
        self.version = version
        self.settle_s = settle_s  # convergence wait (reference :55)

    async def setup(self, test: dict, r: Runner, node: str) -> None:
        log.info("installing etcd %s on %s", self.version, node)
        await install_archive(r, tarball_url(self.version), DIR)
        nodes = test["nodes"]
        await start_daemon(
            r, f"{DIR}/{BINARY}",
            ["--log-output", "stderr",
             "--name", node,
             "--listen-peer-urls", peer_url(node),
             "--listen-client-urls", client_url(node),
             "--advertise-client-urls", client_url(node),
             "--initial-cluster-state", "new",
             "--initial-advertise-peer-urls", peer_url(node),
             "--initial-cluster", initial_cluster(nodes)],
            logfile=LOGFILE, pidfile=PIDFILE, chdir=DIR)
        await asyncio.sleep(self.settle_s)

    async def teardown(self, test: dict, r: Runner, node: str) -> None:
        log.info("tearing down etcd on %s", node)
        await stop_daemon(r, PIDFILE)
        await r.run(f"rm -rf {DIR}", su=True, check=False)

    def log_files(self, test: dict, node: str) -> list[str]:
        return [LOGFILE]
