"""etcd cluster orchestration + topology helpers.

Mirrors the reference's db reify (src/jepsen/etcdemo.clj:25-65) and
support.clj URL builders (src/jepsen/etcdemo/support.clj:4-26): install the
release tarball, start the daemon with full static-cluster flags, wait for
convergence; teardown kills and wipes; etcd.log is the collectable log.
"""

from __future__ import annotations

import asyncio
import logging
import os

from .. import obs
from ..control.runner import Runner
from ..control.daemon import install_archive, start_daemon, stop_daemon
from .base import DB

log = logging.getLogger(__name__)

# JEPSEN_TPU_ETCD_DIR: hermetic runs (the in-image minietcd integration
# lane) relocate the install under a scratch dir; the default is the
# reference's path. Resolved at import: the env travels to the CLI
# subprocess, not across a long-lived interpreter.
DIR = os.environ.get("JEPSEN_TPU_ETCD_DIR", "/opt/etcd")   # reference :25
BINARY = "etcd"                         # :26
LOGFILE = f"{DIR}/etcd.log"             # :27
PIDFILE = f"{DIR}/etcd.pid"             # :28

# Port env overrides exist for the same hermetic lane (several runs on
# one host must not fight over fixed ports); the defaults are etcd's.
PEER_PORT = int(os.environ.get(
    "JEPSEN_TPU_ETCD_PEER_PORT", "2380"))       # support.clj:9-12
CLIENT_PORT = int(os.environ.get(
    "JEPSEN_TPU_ETCD_CLIENT_PORT", "2379"))     # support.clj:14-17


def _parse_port_map(raw: str) -> dict[str, tuple[int, int]]:
    """JEPSEN_TPU_ETCD_PORT_MAP="n1=2379/2380,n2=2479/2480": per-NODE
    client/peer ports, for multi-node runs where several daemons share
    one host (every "node" resolving to localhost). Real multi-host
    clusters never need this — one port per host, the reference's model."""
    out = {}
    for part in raw.split(","):
        if not part.strip():
            continue
        node, ports = part.split("=")
        c, p = ports.split("/")
        out[node.strip()] = (int(c), int(p))
    return out


PORT_MAP = _parse_port_map(os.environ.get("JEPSEN_TPU_ETCD_PORT_MAP", ""))


def client_port_for(node: str) -> int:
    return PORT_MAP.get(node, (CLIENT_PORT, PEER_PORT))[0]


def peer_port_for(node: str) -> int:
    return PORT_MAP.get(node, (CLIENT_PORT, PEER_PORT))[1]


def pidfile_for(node: str) -> str:
    """Co-hosted nodes (PORT_MAP) each need their own pidfile — a shared
    one makes the second start_daemon see 'already-running'. Off the
    map, the reference's single path."""
    return f"{DIR}/etcd-{node}.pid" if node in PORT_MAP else PIDFILE


def logfile_for(node: str) -> str:
    return f"{DIR}/etcd-{node}.log" if node in PORT_MAP else LOGFILE

DEFAULT_VERSION = "v3.1.5"              # reference :162


def node_url(node: str, port: int) -> str:
    """HTTP url for connecting to a node on a port (support.clj:4-7)."""
    return f"http://{node}:{port}"


def peer_url(node: str) -> str:
    return node_url(node, peer_port_for(node))


def client_url(node: str) -> str:
    return node_url(node, client_port_for(node))


def initial_cluster(nodes: list[str]) -> str:
    """node=peer-url pairs joined by commas (support.clj:19-26)."""
    return ",".join(f"{n}={peer_url(n)}" for n in nodes)


def tarball_url(version: str) -> str:
    """Release tarball location (reference :37-40).
    JEPSEN_TPU_ETCD_TARBALL overrides it wholesale (any scheme curl
    speaks, file:// included) — how the in-image lane feeds the minietcd
    release tarball through the UNCHANGED install path."""
    override = os.environ.get("JEPSEN_TPU_ETCD_TARBALL")
    if override:
        return override
    return (f"https://storage.googleapis.com/etcd/{version}/"
            f"etcd-{version}-linux-amd64.tar.gz")


class EtcdDB(DB):
    def __init__(self, version: str = DEFAULT_VERSION,
                 settle_s: float | None = None):
        self.version = version
        # Convergence wait (reference :55); a single-member stand-in
        # settles instantly, so the hermetic lane shrinks it by env.
        self.settle_s = (settle_s if settle_s is not None else float(
            os.environ.get("JEPSEN_TPU_ETCD_SETTLE_S", "10.0")))
        # Serializes co-hosted installs: PORT_MAP nodes share one host,
        # one tarball tmp path and one DIR; concurrent setup_one tasks
        # would race the download/extraction (real multi-host nodes never
        # contend — each installs on its own machine). Keyed by the
        # RUNNING loop, not cached once: an asyncio.Lock binds to the
        # loop that first awaits it, and `--test-count >= 2` runs each
        # test under its own asyncio.run — a lock surviving the first run
        # raises "bound to a different event loop" in the second
        # (ADVICE.md round 5). One entry per run; the dict dies with the
        # instance.
        self._install_locks: dict[asyncio.AbstractEventLoop,
                                  asyncio.Lock] = {}

    def _install_lock(self) -> asyncio.Lock:
        loop = asyncio.get_running_loop()
        lock = self._install_locks.get(loop)
        if lock is None:
            lock = self._install_locks[loop] = asyncio.Lock()
        return lock

    async def setup(self, test: dict, r: Runner, node: str) -> None:
        log.info("installing etcd %s on %s", self.version, node)
        with obs.get_tracer().span("db.install", node=node,
                                   version=self.version):
            if node in PORT_MAP:
                async with self._install_lock():
                    await install_archive(r, tarball_url(self.version), DIR)
            else:
                await install_archive(r, tarball_url(self.version), DIR)
        await self.start(test, r, node)

    async def start(self, test: dict, r: Runner, node: str) -> None:
        """Start (or restart) the daemon against the EXISTING install and
        data dir — the restart leg the kill nemesis drives; a reinstall
        would waste the kill window and is not what jepsen's db/start!
        does."""
        nodes = test["nodes"]
        with obs.get_tracer().span("db.start", node=node):
            await start_daemon(
                r, f"{DIR}/{BINARY}",
                ["--log-output", "stderr",
                 "--name", node,
                 "--listen-peer-urls", peer_url(node),
                 "--listen-client-urls", client_url(node),
                 "--advertise-client-urls", client_url(node),
                 "--initial-cluster-state", "new",
                 "--initial-advertise-peer-urls", peer_url(node),
                 "--initial-cluster", initial_cluster(nodes)],
                logfile=logfile_for(node), pidfile=pidfile_for(node),
                chdir=DIR)
            await asyncio.sleep(self.settle_s)

    async def kill(self, test: dict, r: Runner, node: str) -> None:
        """SIGKILL by pidfile; install and data dir stay (db/kill!)."""
        with obs.get_tracer().span("db.kill", node=node):
            await stop_daemon(r, pidfile_for(node))

    async def teardown(self, test: dict, r: Runner, node: str) -> None:
        log.info("tearing down etcd on %s", node)
        with obs.get_tracer().span("db.teardown", node=node):
            await stop_daemon(r, pidfile_for(node))
            if node in PORT_MAP:
                # Co-hosted: DIR is shared, and node teardowns run
                # concurrently — a whole-DIR wipe here would delete a
                # peer's pidfile before ITS stop_daemon runs (leaking the
                # daemon) and its log before collection. Wipe only this
                # node's state.
                await r.run(
                    f"rm -rf {DIR}/{node}.etcd {pidfile_for(node)} "
                    f"{logfile_for(node)}", su=True, check=False)
            else:
                await r.run(f"rm -rf {DIR}", su=True, check=False)

    def log_files(self, test: dict, node: str) -> list[str]:
        return [logfile_for(node)]
