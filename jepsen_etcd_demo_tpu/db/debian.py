"""OS preparation — jepsen.os.debian equivalent (reference
src/jepsen/etcdemo.clj:20,161): make sure basic tooling for archive install
and fault injection exists on each node."""

from __future__ import annotations

import logging

from ..control.runner import Runner

log = logging.getLogger(__name__)

PACKAGES = ["curl", "wget", "tar", "iptables", "procps"]


async def debian_setup(r: Runner, node: str) -> None:
    res = await r.run("command -v apt-get", check=False)
    if not res.ok:
        log.info("%s: no apt-get; skipping OS prep", node)
        return
    missing = []
    for p in PACKAGES:
        have = await r.run(f"command -v {p}", check=False)
        if not have.ok:
            missing.append(p)
    if missing:
        log.info("%s: installing %s", node, missing)
        # Refresh package lists first — a fresh node's cache is usually
        # stale/empty and the install would 404 (jepsen.os.debian does the
        # same update-then-install dance [dep]).
        await r.run("DEBIAN_FRONTEND=noninteractive apt-get -y update",
                    su=True, check=False, timeout_s=600.0)
        await r.run(
            "DEBIAN_FRONTEND=noninteractive apt-get -y install "
            + " ".join(missing),
            su=True, check=False, timeout_s=600.0)
