"""DB lifecycle protocol — mirror of jepsen.db/DB + db/LogFiles.

The reference reifies both at src/jepsen/etcdemo.clj:30-65: setup! installs
and starts the database on one node, teardown! stops and wipes it, log-files
names remote logs to collect into the store."""

from __future__ import annotations

import abc

from ..control.runner import Runner


class DB(abc.ABC):
    @abc.abstractmethod
    async def setup(self, test: dict, r: Runner, node: str) -> None:
        ...

    @abc.abstractmethod
    async def teardown(self, test: dict, r: Runner, node: str) -> None:
        ...

    def log_files(self, test: dict, node: str) -> list[str]:
        return []
