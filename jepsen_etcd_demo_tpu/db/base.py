"""DB lifecycle protocol — mirror of jepsen.db/DB + db/LogFiles.

The reference reifies both at src/jepsen/etcdemo.clj:30-65: setup! installs
and starts the database on one node, teardown! stops and wipes it, log-files
names remote logs to collect into the store."""

from __future__ import annotations

import abc

from ..control.runner import Runner


class DB(abc.ABC):
    @abc.abstractmethod
    async def setup(self, test: dict, r: Runner, node: str) -> None:
        ...

    @abc.abstractmethod
    async def teardown(self, test: dict, r: Runner, node: str) -> None:
        ...

    async def start(self, test: dict, r: Runner, node: str) -> None:
        """Restart a stopped daemon WITHOUT reinstalling (the restart leg
        of jepsen's db/kill! cycle — the binary and data dir are still on
        the node). Default falls back to full setup for DBs that don't
        distinguish."""
        await self.setup(test, r, node)

    async def kill(self, test: dict, r: Runner, node: str) -> None:
        """Kill the daemon process, leaving install + data in place (the
        kill leg of jepsen's db/kill!; start() is its inverse). The kill
        nemesis drives BOTH legs through the DB protocol, so a subclass
        must override this (or inherit an implementation) before
        KillNemesis can target it."""
        raise NotImplementedError(
            f"{type(self).__name__} does not implement kill(); the kill "
            f"nemesis needs both db.kill and db.start")

    def log_files(self, test: dict, node: str) -> list[str]:
        return []
