"""jepsen_etcd_demo_tpu — a TPU-native distributed-systems correctness harness.

Brand-new framework with the capabilities of the Jepsen etcd tutorial demo
(reference: /root/reference, `dovidio/jepsen-etcd-demo`): orchestrate a real
etcd cluster, drive concurrent read/write/CAS and grow-only-set workloads
through composable operation generators while a nemesis injects network
partitions, record the full concurrent history, and verify it — linearizability
against a CAS-register model, set durability, perf charts, HTML timeline —
persisting every run to a browsable store.

The defining difference from the reference: the linearizability checker's
Wing–Gong state-space search runs as a vmapped, mesh-shardable JAX/XLA kernel
(see `ops.wgl3`, `ops.wgl3_pallas`, and `parallel/`) instead of knossos's JVM search, behind the
same pluggable Checker seam (reference seam: jepsen.checker/Checker, invoked
at src/jepsen/etcdemo.clj:115-119).

Layout (see SURVEY.md §7 for the build plan; subpackages land in this order):
  ops/        history core: op records, pairing, tensor encoding, JAX WGL kernel
  models/     state-machine models (register, cas-register, grow-only set)
  checkers/   Checker protocol + linearizable / set / perf / timeline / compose / independent
  parallel/   device mesh, batched + frontier-sharded checker execution
  generators/ pure operation-scheduling combinators (mix/stagger/limit/phases/...)
  clients/    Client protocol, etcd v2 HTTP client, hermetic in-memory KV
  db/         DB lifecycle protocol, etcd daemon orchestration, fake DB
  nemesis/    fault injection (partition-random-halves, fake partitions)
  control/    remote control plane (SSH runner, local runner, daemon helpers)
  runner/     the core run loop (workers, history recorder, phases)
  store/      on-disk run persistence (store/<name>/<ts>/ + latest/current)
  cli/        command line entry (test / analyze / serve)
  web/        HTTP browser over the store
  utils/      clocks, logging, misc
"""

__version__ = "0.1.0"
