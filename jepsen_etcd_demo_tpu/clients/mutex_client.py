"""Mutex-workload client: acquire / release a distributed lock.

No reference-demo counterpart (the demo ships register and set workloads,
src/jepsen/etcdemo.clj:128-131) — this drives the mutex MODEL from
knossos's family (models/mutex.py). The lock is a CAS register on the
backing store (acquire = cas 0->1, release = cas 1->0 — exactly the
translation the model applies), so the same etcd/fake connections serve.

Error mapping follows the reference client (src/jepsen/etcdemo.clj:
100-105): a CAS that returned false is :fail (definitely didn't happen);
a timeout is :info (the lock MAY have been taken/released — the model's
pending-forever semantics carry it).
"""

from __future__ import annotations

from ..ops.op import Op
from .base import ConnClient, ClientError, NotFound, Timeout, completed

LOCK_KEY = "a-lock"
UNLOCKED, LOCKED = "0", "1"


class MutexClient(ConnClient):
    """conn_factory(test, node) -> an object with async get/reset/cas."""

    async def setup(self, test: dict) -> None:
        # Initialize-and-verify: setup must succeed even against a backend
        # with injected lost-write bugs (the run's assertions are about the
        # RUN, not setup).
        for _ in range(16):
            await self.conn.reset(LOCK_KEY, UNLOCKED)
            if await self.conn.get(LOCK_KEY, quorum=True) is not None:
                return
        raise RuntimeError("MutexClient.setup could not initialize the lock")

    async def invoke(self, test: dict, op: Op) -> Op:
        try:
            if op.f == "acquire":
                ok = await self.conn.cas(LOCK_KEY, UNLOCKED, LOCKED)
            elif op.f == "release":
                ok = await self.conn.cas(LOCK_KEY, LOCKED, UNLOCKED)
            else:
                raise ValueError(f"unknown op f={op.f!r}")
            return completed(op, "ok" if ok else "fail")
        except Timeout:
            return completed(op, "info", error="timeout")
        except NotFound:
            return completed(op, "fail", error="not-found")
        except ClientError as e:
            return completed(op, "fail", error=str(e))
