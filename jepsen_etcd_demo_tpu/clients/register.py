"""Register-workload client: read / write / cas over a KV connection.

Mirror of the reference's Client record (src/jepsen/etcdemo.clj:76-108),
including the load-bearing error mapping:
  * timeout on read        -> :fail (:error :timeout)     [:100-102]
  * timeout on write/cas   -> :info (indeterminate!)      [:100-102]
  * key-missing (etcd 100) -> :fail (:error :not-found)   [:104-105]
  * cas returned false     -> :fail                       [:95-98]
  * connection refused     -> :fail (determinate — the request never
    left; clients/base.py ConnectionRefused, via the ClientError arm)

Values are (key, value) independent-tuples (reference :84,:90); reads parse
the stored string to an int, None surviving for missing keys (:71-74,:87-90).
"""

from __future__ import annotations

from typing import Optional

from ..ops.op import Op
from .base import ConnClient, ClientError, NotFound, Timeout, completed


def parse_long(s: Optional[str]):
    """nil-passing string→int (reference parse-long, :71-74)."""
    return None if s is None else int(s)


class RegisterClient(ConnClient):
    """conn_factory(test, node) -> an object with async get/reset/cas
    (FakeKV bound connection or EtcdClient)."""

    async def invoke(self, test: dict, op: Op) -> Op:
        k, v = op.value
        try:
            if op.f == "read":
                raw = await self.conn.get(str(k),
                                          quorum=bool(test.get("quorum")))
                return completed(op, "ok", value=(k, parse_long(raw)))
            if op.f == "write":
                await self.conn.reset(str(k), str(v))
                return completed(op, "ok")
            if op.f == "cas":
                old, new = v
                ok = await self.conn.cas(str(k), str(old), str(new))
                return completed(op, "ok" if ok else "fail")
            raise ValueError(f"unknown op f={op.f!r}")
        except Timeout:
            if op.f == "read":
                return completed(op, "fail", error="timeout")
            return completed(op, "info", error="timeout")
        except NotFound:
            return completed(op, "fail", error="not-found")
        except ClientError as e:
            return completed(op, "fail", error=str(e))


class MultiRegisterClient(ConnClient):
    """Whole-store client for the multi-register workload: ops address
    register i of a small register file — read (i, None)->(i, v) /
    write (i, v) — mapped onto KV keys "r<i>". Unlike RegisterClient the
    values are NOT independent-key tuples: the whole run is ONE history
    checked against the multi-register model (models/multi_register.py),
    so cross-register ordering violations are visible to the checker.
    Error mapping identical to RegisterClient (reference
    src/jepsen/etcdemo.clj:100-105)."""

    async def invoke(self, test: dict, op: Op) -> Op:
        i, v = op.value
        try:
            if op.f == "read":
                raw = await self.conn.get(f"r{i}",
                                          quorum=bool(test.get("quorum")))
                return completed(op, "ok", value=(i, parse_long(raw)))
            if op.f == "write":
                await self.conn.reset(f"r{i}", str(v))
                return completed(op, "ok")
            raise ValueError(f"unknown op f={op.f!r}")
        except Timeout:
            if op.f == "read":
                return completed(op, "fail", error="timeout")
            return completed(op, "info", error="timeout")
        except NotFound:
            return completed(op, "fail", error="not-found")
        except ClientError as e:
            return completed(op, "fail", error=str(e))


class _BoundFakeConn:
    """FakeKVStore bound to one node, presenting async get/reset/cas/swap."""

    def __init__(self, store, node: str):
        self.store = store
        self.node = node

    async def get(self, key, quorum=False):
        return await self.store.get(self.node, key, quorum=quorum)

    async def reset(self, key, value):
        return await self.store.reset(self.node, key, value)

    async def cas(self, key, old, new):
        return await self.store.cas(self.node, key, old, new)

    async def swap(self, key, fn):
        return await self.store.swap(self.node, key, fn)

    async def txn(self, mops):
        return await self.store.txn(self.node, mops)

    async def txn_register(self, mops):
        return await self.store.txn_register(self.node, mops)

    async def enqueue(self, key, value):
        return await self.store.enqueue(self.node, key, value)

    async def dequeue(self, key):
        return await self.store.dequeue(self.node, key)


def fake_conn_factory(store):
    def factory(test, node):
        return _BoundFakeConn(store, node)
    return factory
