"""Txn client for the elle list-append workload.

No direct reference-demo counterpart (the demo never drives elle, it only
ships it as a dependency — jepsen.etcdemo.iml:46); the client follows the
same 5-method protocol and error mapping shape as the register client
(reference src/jepsen/etcdemo.clj:76-108): a timeout on a txn that may
have written is indeterminate -> :info; a pure-read txn can safely
:fail.
"""

from __future__ import annotations

from ..ops.op import Op
from .base import ConnClient, ClientError, Timeout, completed


class TxnClient(ConnClient):
    """conn_factory(test, node) -> connection exposing the transactional
    method named by `method`: txn(mops) for list-append (micro-op
    "append"), txn_register(mops) for rw-register (micro-op "w")."""

    def __init__(self, conn_factory, conn=None, method: str = "txn"):
        # (conn_factory, conn) positional order matches ConnClient's
        # open() clone call.
        super().__init__(conn_factory, conn)
        self.method = method

    async def open(self, test: dict, node: str) -> "TxnClient":
        c = await super().open(test, node)
        c.method = self.method
        return c

    def _check_conn(self, conn) -> None:
        if not hasattr(conn, self.method):
            # Fail fast at setup, not with an AttributeError mid-run: the
            # etcd v2 API has no transactions, so the txn workloads only
            # run against transactional stores (e.g. --fake).
            raise RuntimeError(
                "txn workload requires a transactional connection "
                f"(conn {type(conn).__name__!r} has no {self.method}()); "
                "use --fake or a store with multi-key transactions")

    async def invoke(self, test: dict, op: Op) -> Op:
        if op.f != "txn":
            raise ValueError(f"unknown op f={op.f!r}")
        try:
            done = await getattr(self.conn, self.method)(list(op.value))
            return completed(op, "ok", value=done)
        except Timeout:
            writes = any(m[0] in ("append", "w") for m in op.value)
            return completed(op, "info" if writes else "fail",
                             error="timeout")
        except ClientError as e:
            return completed(op, "fail", error=str(e))

