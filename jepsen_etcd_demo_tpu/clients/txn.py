"""Txn client for the elle list-append workload.

No direct reference-demo counterpart (the demo never drives elle, it only
ships it as a dependency — jepsen.etcdemo.iml:46); the client follows the
same 5-method protocol and error mapping shape as the register client
(reference src/jepsen/etcdemo.clj:76-108): a timeout on a txn that may
have written is indeterminate -> :info; a pure-read txn can safely
:fail.
"""

from __future__ import annotations

from ..ops.op import Op
from .base import ConnClient, ClientError, Timeout, completed


class TxnClient(ConnClient):
    """conn_factory(test, node) -> connection exposing async txn(mops)."""

    def _check_conn(self, conn) -> None:
        if not hasattr(conn, "txn"):
            # Fail fast at setup, not with an AttributeError mid-run: the
            # etcd v2 API has no transactions, so the append workload only
            # runs against transactional stores (e.g. --fake).
            raise RuntimeError(
                "append workload requires a transactional connection "
                f"(conn {type(conn).__name__!r} has no txn()); "
                "use --fake or a store with multi-key transactions")

    async def invoke(self, test: dict, op: Op) -> Op:
        if op.f != "txn":
            raise ValueError(f"unknown op f={op.f!r}")
        try:
            done = await self.conn.txn(list(op.value))
            return completed(op, "ok", value=done)
        except Timeout:
            writes = any(m[0] == "append" for m in op.value)
            return completed(op, "info" if writes else "fail",
                             error="timeout")
        except ClientError as e:
            return completed(op, "fail", error=str(e))

