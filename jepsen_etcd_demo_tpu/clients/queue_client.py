"""Queue-workload client: enqueue / dequeue over independent per-key queues.

No reference-demo counterpart (the demo ships register and set workloads,
src/jepsen/etcdemo.clj:128-131) — this drives the fifo/unordered-queue
MODELS that mirror the rest of the knossos model family the reference
depends on (knossos 0.3.7, jepsen.etcdemo.iml:58; models/queues.py).

Error mapping follows the reference client's logic (src/jepsen/etcdemo.clj:
100-105) adapted to queue semantics:
  * enqueue timeout       -> :info (indeterminate, like a register write)
  * dequeue timeout       -> :fail — sound because both backends raise it
    only when no removal can have been attempted (before any claim is
    sent/applied)
  * IndeterminateDequeue  -> :info carrying the CLAIMED value (a lost
    compare-and-delete response after the node vanished) — the one shape
    of indeterminate dequeue the encoder accepts (models/queues.py)
  * empty queue           -> :fail :empty (definitely no effect)
"""

from __future__ import annotations

from ..ops.op import Op
from .base import (ConnClient, ClientError, IndeterminateDequeue,
                   NotFound, Timeout, completed)


class QueueClient(ConnClient):
    """conn_factory(test, node) -> an object with async enqueue/dequeue."""

    async def invoke(self, test: dict, op: Op) -> Op:
        k, v = op.value
        try:
            if op.f == "enqueue":
                await self.conn.enqueue(str(k), v)
                return completed(op, "ok")
            if op.f == "dequeue":
                got = await self.conn.dequeue(str(k))
                return completed(op, "ok", value=(k, got))
            raise ValueError(f"unknown op f={op.f!r}")
        except IndeterminateDequeue as e:
            return completed(op, "info", value=(k, e.value),
                             error="timeout")
        except Timeout:
            if op.f == "dequeue":
                return completed(op, "fail", error="timeout")
            return completed(op, "info", error="timeout")
        except NotFound:
            return completed(op, "fail", error="empty")
        except ClientError as e:
            return completed(op, "fail", error=str(e))
