"""Grow-only-set client: concurrent adds to one key, one final read.

Mirror of the reference SetClient (src/jepsen/etcdemo/set.clj:10-40): a single
fixed key holds a serialized set; setup initializes it to the empty set
(:15-16); :add conj's via the connection's atomic swap (read-modify-write CAS
retry loop, :26-31); :read parses the stored serialization (:21-24).

Serialization: JSON sorted list (the reference stores Clojure EDN "#{}" —
same idea, host-language-native encoding)."""

from __future__ import annotations

import json
from ..ops.op import Op
from .base import ConnClient, ClientError, NotFound, Timeout, completed

SET_KEY = "a-set"


def _dumps(s: set) -> str:
    return json.dumps(sorted(s))


def _loads(raw: str) -> set:
    return set(json.loads(raw))


class SetClient(ConnClient):


    async def setup(self, test: dict) -> None:
        # Initialize, then read back and retry: setup must succeed even
        # against a backend with injected lost-write bugs (the workload's
        # assertions are about the RUN, not about setup).
        for _ in range(16):
            await self.conn.reset(SET_KEY, _dumps(set()))
            if await self.conn.get(SET_KEY, quorum=True) is not None:
                return
        raise RuntimeError("SetClient.setup could not initialize the set key")

    async def invoke(self, test: dict, op: Op) -> Op:
        try:
            if op.f == "read":
                raw = await self.conn.get(SET_KEY,
                                          quorum=bool(test.get("quorum")))
                if raw is None:
                    return completed(op, "fail", error="not-found")
                return completed(op, "ok", value=sorted(_loads(raw)))
            if op.f == "add":
                await self.conn.swap(
                    SET_KEY, lambda raw: _dumps(_loads(raw) | {op.value}))
                return completed(op, "ok")
            raise ValueError(f"unknown op f={op.f!r}")
        except Timeout:
            if op.f == "read":
                return completed(op, "fail", error="timeout")
            return completed(op, "info", error="timeout")
        except NotFound:
            return completed(op, "fail", error="not-found")
        except ClientError as e:
            return completed(op, "fail", error=str(e))
