"""Client lifecycle protocol — mirror of jepsen.client/Client.

Five methods, same seam as the reference implements at
src/jepsen/etcdemo.clj:78-108: open! / setup! / invoke! / close! / teardown!.
`invoke` is async (workers are asyncio tasks, the analogue of jepsen's worker
threads) and returns the *completed* op.

Completion semantics the whole checker stack depends on (reference
src/jepsen/etcdemo.clj:100-105):
  * A definite failure completes :fail (op did not happen).
  * An INDETERMINATE failure (e.g. timeout on a write/cas) completes :info —
    the op may have taken effect; the checker must keep it open forever.
  * Reads may complete :fail on timeout because an unobserved read never
    constrains the model (reference :100-102 maps reads to :fail).
"""

from __future__ import annotations

import abc
from typing import Any

from ..ops.op import Op


class ClientError(Exception):
    """Definite failure: the op did not take effect."""


class NotFound(ClientError):
    """Key absent — the reference's etcd errorCode 100 edge
    (src/jepsen/etcdemo.clj:104-105)."""


class RetriesExhausted(ClientError):
    """A client-side retry loop (swap!'s CAS loop) burned its whole budget
    on DETERMINATE failures — every attempt observably did not apply, so
    the op as a whole definitely did not take effect. A :fail, not an
    :info: mapping this to Timeout (round 2 did) was sound but needlessly
    pessimistic — every spurious open-forever op multiplies the checker's
    search space (VERDICT r2 weak #6). Any genuinely indeterminate attempt
    inside the loop raises Timeout out of it directly instead."""


class ConnectionRefused(ClientError):
    """TCP connect failed before any request byte was transmitted — a
    DETERMINATE failure (the op cannot have taken effect), so every
    client's generic ClientError arm maps it to :fail. Distinguishing it
    from the indeterminate Timeout -> :info matters operationally: under
    a kill nemesis every op in the dead window is refused, and mapping
    those to :info would flood the history with forever-pending slots
    (~rate x window of them) the linearizability search must then carry
    — measured r5: a 6 s kill window at rate 20 adds ~100 pending ops
    and pushes the check toward its wall-clock budget for nothing."""


class Timeout(Exception):
    """Indeterminate: the op may or may not have taken effect
    (SocketTimeoutException edge, src/jepsen/etcdemo.clj:100-102)."""


class IndeterminateDequeue(Timeout):
    """A dequeue timed out AFTER its claim was sent/applied: the removal
    is indeterminate forever. Unlike a plain Timeout the CLAIMED value is
    known, which is exactly what makes the op encodable as a
    pending-forever dequeue (models/queues.py). Raised by both queue
    backends (clients/etcd.py compare-and-delete, clients/fake_kv.py)."""

    def __init__(self, value):
        super().__init__(f"indeterminate dequeue of {value!r}")
        self.value = value


class Client(abc.ABC):
    """Per-process client. The runner calls open() to get a fresh connected
    instance per logical process, setup() once per run for data-plane init,
    then invoke() per op; close()/teardown() on the way down."""

    async def open(self, test: dict, node: str) -> "Client":
        """Return a client connected to `node` (may be self)."""
        return self

    async def setup(self, test: dict) -> None:
        pass

    @abc.abstractmethod
    async def invoke(self, test: dict, op: Op) -> Op:
        """Execute op, return its completion (type ok/fail/info)."""

    async def close(self, test: dict) -> None:
        pass

    async def teardown(self, test: dict) -> None:
        pass


class ConnClient(Client):
    """Client whose per-process state is one connection from
    conn_factory(test, node). Shares the open/close lifecycle every
    concrete client repeats; subclasses implement invoke() (and setup()
    when the workload needs data-plane init)."""

    def __init__(self, conn_factory, conn=None):
        self.conn_factory = conn_factory
        self.conn = conn

    async def open(self, test: dict, node: str) -> "ConnClient":
        conn = self.conn_factory(test, node)
        if hasattr(conn, "__await__"):
            conn = await conn
        self._check_conn(conn)
        return type(self)(self.conn_factory, conn)

    def _check_conn(self, conn) -> None:
        """Hook: fail fast on an incompatible connection (e.g. the txn
        client against a non-transactional store)."""

    async def close(self, test: dict) -> None:
        close = getattr(self.conn, "close", None)
        if close is not None:
            res = close()
            if hasattr(res, "__await__"):
                await res


def completed(op: Op, type_: str, value: Any = None, error: Any = None) -> Op:
    """Build the completion record for an invocation."""
    return Op(type=type_, f=op.f,
              value=op.value if value is None else value,
              process=op.process, error=error)
