"""Hermetic in-process KV "cluster" with injectable consistency bugs.

The reference has no hermetic backend at all — every run needs a real 5-node
etcd cluster (SURVEY.md §4). This build adds one so the full pipeline
(generator → client → history → checker) runs in CI: a fake replicated
register store exposing the same 5-call surface the demo uses through
verschlimmbesserung (connect/get/reset/cas/swap — reference
src/jepsen/etcdemo.clj:79-98, set.clj:13-29), plus fault hooks the fake
nemesis drives.

Fault model:
  * Partition: the store tracks a set of "isolated" nodes. A client bound to
    an isolated node gets Timeout on every op (indeterminate — the op is
    counted as possibly-applied with probability `partial_apply_prob`,
    exercising the :info open-forever path end to end).
  * Injectable bugs (to prove the checkers DETECT badness, SURVEY.md §4):
      stale_read_prob      — non-quorum reads may return a stale snapshot
                             (quorum reads are always linearizable, matching
                             etcd's q=true semantics the -q flag toggles,
                             reference src/jepsen/etcdemo.clj:88,179)
      lost_write_prob      — acked writes that never took effect
      duplicate_cas_prob   — a failed CAS that actually applied (acked :fail
                             but took effect), the inverse indeterminacy
"""

from __future__ import annotations

import asyncio
import random
from typing import Any, Optional

from .base import (IndeterminateDequeue, NotFound, RetriesExhausted,
                   Timeout)


class FakeKVStore:
    """The simulated cluster: one logical linearizable register map, plus a
    bounded history of past snapshots for stale reads."""

    def __init__(self, nodes: Optional[list[str]] = None,
                 seed: int = 0,
                 stale_read_prob: float = 0.0,
                 lost_write_prob: float = 0.0,
                 duplicate_cas_prob: float = 0.0,
                 reorder_prob: float = 0.0,
                 duplicate_delivery_prob: float = 0.0,
                 partial_apply_prob: float = 0.5,
                 op_delay_s: float = 0.0):
        self.nodes = nodes or ["n1", "n2", "n3", "n4", "n5"]
        self.data: dict[str, Any] = {}
        self.queues: dict[str, list[Any]] = {}
        self.snapshots: list[dict[str, Any]] = []
        self.isolated: set[str] = set()
        self.rng = random.Random(seed)
        self.stale_read_prob = stale_read_prob
        self.lost_write_prob = lost_write_prob
        self.duplicate_cas_prob = duplicate_cas_prob
        self.reorder_prob = reorder_prob
        self.duplicate_delivery_prob = duplicate_delivery_prob
        self.partial_apply_prob = partial_apply_prob
        self.op_delay_s = op_delay_s
        # jtlint: disable=JTL202 -- lifetime argument: a FakeKVStore is
        # built per test iteration (compose.fake_test constructs a fresh
        # one inside each cmd_test loop turn), so this lock never
        # survives into a second asyncio.run. If the fake ever becomes
        # long-lived, key it by running loop like db/etcd._install_lock.
        self.lock = asyncio.Lock()

    # -- fault hooks (driven by the fake nemesis) -------------------------
    def isolate(self, nodes: set[str]):
        self.isolated = set(nodes)

    def heal(self):
        self.isolated = set()

    def _snapshot(self):
        self.snapshots.append(dict(self.data))
        if len(self.snapshots) > 64:
            self.snapshots.pop(0)

    async def _enter(self, node: str):
        if self.op_delay_s:
            await asyncio.sleep(self.op_delay_s * self.rng.random())
        if node in self.isolated:
            # Partitioned node: the op MAY still land (it raced the
            # partition). Apply-then-timeout gives the checker real
            # indeterminacy to reason about.
            raise Timeout(f"node {node} partitioned")

    # -- the 5-call surface ----------------------------------------------
    async def get(self, node: str, key: str, quorum: bool = False) -> Any:
        await self._enter(node)
        async with self.lock:
            if (not quorum and self.snapshots
                    and self.rng.random() < self.stale_read_prob):
                snap = self.rng.choice(self.snapshots)
                return snap.get(key)
            return self.data.get(key)

    async def reset(self, node: str, key: str, value: Any) -> None:
        maybe_timeout = node in self.isolated
        if maybe_timeout and self.rng.random() >= self.partial_apply_prob:
            raise Timeout(f"node {node} partitioned")
        async with self.lock:
            self._snapshot()
            if self.rng.random() >= self.lost_write_prob:
                self.data[key] = value
        if maybe_timeout:
            raise Timeout(f"node {node} partitioned (op applied)")
        if self.op_delay_s:
            await asyncio.sleep(self.op_delay_s * self.rng.random())

    async def cas(self, node: str, key: str, old: Any, new: Any) -> bool:
        maybe_timeout = node in self.isolated
        if maybe_timeout and self.rng.random() >= self.partial_apply_prob:
            raise Timeout(f"node {node} partitioned")
        async with self.lock:
            if key not in self.data:
                raise NotFound(key)
            applied = self.data[key] == old
            if applied:
                self._snapshot()
                # Lost-update bug: ack success but drop the update.
                if self.rng.random() >= self.lost_write_prob:
                    self.data[key] = new
            elif self.rng.random() < self.duplicate_cas_prob:
                self._snapshot()
                self.data[key] = new  # bug: acked :fail but applied
        if maybe_timeout:
            raise Timeout(f"node {node} partitioned (op applied)")
        if self.op_delay_s:
            await asyncio.sleep(self.op_delay_s * self.rng.random())
        return applied

    async def txn(self, node: str, mops: list) -> list:
        """Atomic multi-key transaction over micro-ops (elle's list-append
        workload; no reference-demo counterpart — the fake cluster stands
        in for a transactional store so the elle checker has an end-to-end
        path). Micro-ops: ("append", k, v) appends v to the list under k;
        ("r", k, None) reads the list. Returns completed micro-ops with
        reads filled in. Injected bugs: lost_write_prob drops an acked
        append; stale_read_prob serves a read from an old snapshot (both
        elle-detectable anomalies)."""
        maybe_timeout = node in self.isolated
        if maybe_timeout and self.rng.random() >= self.partial_apply_prob:
            raise Timeout(f"node {node} partitioned")
        out = []
        written: set = set()
        async with self.lock:
            self._snapshot()
            for mop in mops:
                f, k, v = mop
                if f == "append":
                    if self.rng.random() >= self.lost_write_prob:
                        cur = self.data.get(k)
                        cur = () if not isinstance(cur, tuple) else cur
                        self.data[k] = cur + (v,)
                    written.add(k)
                    out.append(("append", k, v))
                elif f == "r":
                    src = self.data
                    # Stale reads never hide the txn's OWN earlier append
                    # (read-your-writes inside a txn is assumed even by
                    # the buggy store, so the checker's :internal anomaly
                    # never fires on fake runs — it is golden-tested).
                    if (k not in written and self.snapshots
                            and self.rng.random() < self.stale_read_prob):
                        src = self.rng.choice(self.snapshots)
                    cur = src.get(k)
                    cur = () if not isinstance(cur, tuple) else cur
                    out.append(("r", k, cur))
                else:
                    raise ValueError(f"unknown micro-op {f!r}")
        if maybe_timeout:
            raise Timeout(f"node {node} partitioned (txn applied)")
        if self.op_delay_s:
            await asyncio.sleep(self.op_delay_s * self.rng.random())
        return out

    async def txn_register(self, node: str, mops: list) -> list:
        """Atomic multi-key REGISTER transaction (elle's rw-register
        workload — checkers/elle.py ElleRwChecker). Micro-ops:
        ("w", k, v) writes register k; ("r", k, None) reads it (None =
        the initial nil). Same injected bugs as txn(): lost_write_prob
        drops an acked write, stale_read_prob serves an old snapshot —
        both surface as elle anomalies (G-single-realtime and friends)."""
        maybe_timeout = node in self.isolated
        if maybe_timeout and self.rng.random() >= self.partial_apply_prob:
            raise Timeout(f"node {node} partitioned")
        out = []
        overlay: dict = {}   # own writes, so read-your-writes holds even
        #                      when the store LOSES the write (same
        #                      contract as txn(): :internal never fires
        #                      on fake runs, it is golden-tested)
        async with self.lock:
            self._snapshot()
            for mop in mops:
                f, k, v = mop
                if f == "w":
                    if self.rng.random() >= self.lost_write_prob:
                        self.data[k] = v
                    overlay[k] = v
                    out.append(("w", k, v))
                elif f == "r":
                    if k in overlay:
                        out.append(("r", k, overlay[k]))
                        continue
                    src = self.data
                    if (self.snapshots
                            and self.rng.random() < self.stale_read_prob):
                        src = self.rng.choice(self.snapshots)
                    out.append(("r", k, src.get(k)))
                else:
                    raise ValueError(f"unknown register micro-op {f!r}")
        if maybe_timeout:
            raise Timeout(f"node {node} partitioned (txn applied)")
        if self.op_delay_s:
            await asyncio.sleep(self.op_delay_s * self.rng.random())
        return out

    # -- queue surface (queue workload; no reference counterpart — the
    # fifo/unordered-queue MODELS mirror knossos's model family) ----------
    async def enqueue(self, node: str, key: str, value: Any) -> None:
        """Append to the queue under `key`. Same indeterminacy model as
        reset(): on a partitioned node the op may land and then time out."""
        maybe_timeout = node in self.isolated
        if maybe_timeout and self.rng.random() >= self.partial_apply_prob:
            raise Timeout(f"node {node} partitioned")
        async with self.lock:
            self.queues.setdefault(key, []).append(value)
        if maybe_timeout:
            raise Timeout(f"node {node} partitioned (op applied)")
        if self.op_delay_s:
            await asyncio.sleep(self.op_delay_s * self.rng.random())

    async def dequeue(self, node: str, key: str) -> Any:
        """Pop the queue head. Under partition the same indeterminacy
        protocol as the real etcd client (clients/etcd.py): with
        partial_apply_prob the pop HAPPENS and the ack is lost —
        IndeterminateDequeue carrying the claimed element (the one
        encodable indeterminate-dequeue shape, models/queues.py) — else a
        plain Timeout before any effect. Injectable bugs:
          reorder_prob            — pops a random position, not the head
                                    (FIFO violation)
          duplicate_delivery_prob — returns the head without removing it
                                    (element delivered twice)"""
        maybe_timeout = node in self.isolated
        if maybe_timeout and self.rng.random() >= self.partial_apply_prob:
            raise Timeout(f"node {node} partitioned")
        async with self.lock:
            q = self.queues.get(key)
            if not q:
                if maybe_timeout:
                    raise Timeout(f"node {node} partitioned")
                raise NotFound(key)
            i = (self.rng.randrange(len(q))
                 if self.rng.random() < self.reorder_prob else 0)
            if self.rng.random() < self.duplicate_delivery_prob:
                got = q[i]
            else:
                got = q.pop(i)
        if maybe_timeout:
            raise IndeterminateDequeue(got)
        if self.op_delay_s:
            await asyncio.sleep(self.op_delay_s * self.rng.random())
        return got

    async def swap(self, node: str, key: str, fn) -> Any:
        """Atomic read-modify-write retry loop — verschlimmbesserung's swap!
        (reference set.clj:26-31 uses it for set adds)."""
        for _ in range(64):
            await self._enter(node)
            async with self.lock:
                if key not in self.data:
                    raise NotFound(key)
                cur = self.data[key]
            new = fn(cur)
            try:
                if await self.cas(node, key, cur, new):
                    return new
            except NotFound:
                raise
        raise RetriesExhausted("swap retry budget exhausted: 64 determinate CAS failures")


