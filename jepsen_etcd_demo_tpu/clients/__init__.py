"""Client layer: the 5-method lifecycle protocol + concrete clients.

Equivalent of jepsen.client's Client protocol as implemented by the reference
demo (register client: src/jepsen/etcdemo.clj:76-108; set client:
src/jepsen/etcdemo/set.clj:10-40).
"""

from .base import (Client, ClientError, ConnectionRefused,  # noqa: F401
                   NotFound, Timeout)
from .fake_kv import FakeKVStore  # noqa: F401
from .queue_client import QueueClient  # noqa: F401
from .register import RegisterClient  # noqa: F401
from .set_client import SetClient  # noqa: F401
from .etcd import EtcdClient, EtcdError  # noqa: F401
