"""etcd v2 HTTP API client — the verschlimmbesserung 5-call surface.

The reference speaks etcd's v2 keys API through verschlimmbesserung
(connect/get/reset!/cas!/swap!, reference src/jepsen/etcdemo.clj:79-98,
set.clj:13-29) with a 5000 ms timeout (:79). Same surface here over httpx:

  GET /v2/keys/<k>[?quorum=true]            -> value | NotFound(code 100)
  PUT /v2/keys/<k> value=v                  -> reset
  PUT /v2/keys/<k> prevValue=old value=new  -> cas (False on code 101)
  swap(k, fn): get-with-index + prevIndex CAS retry loop

Error mapping at this layer is value-level only; the op-level completion
mapping (timeout→info etc.) lives in RegisterClient/SetClient, exactly like
the reference splits verschlimmbesserung from the Client record.
"""

from __future__ import annotations

from typing import Any, Optional

import httpx

from .base import (ClientError, ConnectionRefused, IndeterminateDequeue,
                   NotFound, RetriesExhausted, Timeout)

ETCD_KEY_MISSING = 100   # etcd v2 errorCode for absent key (reference :104)
ETCD_CAS_FAILED = 101    # compare failed


class EtcdError(ClientError):
    def __init__(self, code: int, message: str):
        super().__init__(f"etcd error {code}: {message}")
        self.code = code


class EtcdClient:
    """One connection to one node's client port (2379,
    reference support.clj:14-17)."""

    def __init__(self, base_url: str, timeout_s: float = 5.0):
        self.base_url = base_url.rstrip("/")
        self.http = httpx.AsyncClient(timeout=timeout_s)

    @classmethod
    def connect(cls, node: str, port: int = 2379,
                timeout_s: float = 5.0) -> "EtcdClient":
        return cls(f"http://{node}:{port}", timeout_s=timeout_s)

    async def close(self):
        await self.http.aclose()

    def _url(self, key: str) -> str:
        return f"{self.base_url}/v2/keys/{key}"

    @staticmethod
    def _raise_for(body: dict):
        code = body.get("errorCode")
        if code == ETCD_KEY_MISSING:
            raise NotFound(body.get("message", "key not found"))
        if code is not None and code != ETCD_CAS_FAILED:
            raise EtcdError(code, body.get("message", ""))

    async def _request(self, method: str, url: str, **kw) -> dict:
        try:
            resp = await self.http.request(method, url, **kw)
            return resp.json()
        except httpx.ConnectError as e:
            # No TCP connection ever formed: the request was never
            # transmitted, so the failure is DETERMINATE (:fail), unlike
            # the indeterminate cases below. ConnectTimeout is excluded
            # on purpose — a SYN that got no reply proves nothing about
            # what the peer received.
            raise ConnectionRefused(str(e)) from e
        except (httpx.TimeoutException, httpx.ReadError, httpx.WriteError,
                httpx.CloseError, httpx.RemoteProtocolError) as e:
            # Includes WriteError/CloseError: a reused keep-alive
            # connection to a just-killed server fails on SEND
            # (EPIPE/ECONNRESET) — bytes may have been transmitted, so
            # these stay indeterminate, and mapping them here keeps them
            # out of the runner's crash arm (which would also burn a
            # logical process on reincarnation).
            raise Timeout(str(e)) from e

    # -- the 5-call surface ----------------------------------------------
    async def get(self, key: str, quorum: bool = False) -> Optional[str]:
        params = {"quorum": "true"} if quorum else {}
        body = await self._request("GET", self._url(key), params=params)
        if body.get("errorCode") == ETCD_KEY_MISSING:
            return None
        self._raise_for(body)
        return body["node"]["value"]

    async def get_with_index(self, key: str,
                             quorum: bool = False) -> tuple[str, int]:
        params = {"quorum": "true"} if quorum else {}
        body = await self._request("GET", self._url(key), params=params)
        self._raise_for(body)
        node = body["node"]
        return node["value"], node["modifiedIndex"]

    async def reset(self, key: str, value: Any) -> None:
        body = await self._request("PUT", self._url(key),
                                   data={"value": str(value)})
        self._raise_for(body)

    async def cas(self, key: str, old: Any, new: Any) -> bool:
        body = await self._request(
            "PUT", self._url(key),
            data={"value": str(new)}, params={"prevValue": str(old)})
        if body.get("errorCode") == ETCD_CAS_FAILED:
            return False
        self._raise_for(body)
        return True

    # -- queue surface (etcd v2 atomic in-order keys) ---------------------
    async def enqueue(self, key: str, value: Any) -> None:
        """Append via etcd's in-order-keys recipe: POST to the queue dir
        creates a node named by creation index, giving a total order.
        Timeouts are indeterminate exactly like writes (the node may have
        been created) — QueueClient maps them to :info."""
        body = await self._request("POST", self._url(key),
                                   data={"value": str(value)})
        self._raise_for(body)

    async def dequeue(self, key: str) -> str:
        """Claim the queue head: quorum-read the dir sorted by creation
        order, compare-and-delete the first node (prevIndex); a lost race
        (another consumer claimed it) retries on the next head.

        Indeterminacy protocol (the part linearizability checking depends
        on, models/queues.py): once the compare-and-delete has been SENT,
        a timeout is unconditionally indeterminate — the in-flight DELETE
        can commit arbitrarily later, so even observing the node still
        present proves nothing. IndeterminateDequeue carries the claimed
        value (QueueClient maps it :info, pending forever); timeouts
        BEFORE any claim attempt stay plain Timeouts (no effect
        possible)."""
        for _ in range(64):
            body = await self._request(
                "GET", self._url(key),
                params={"recursive": "true", "sorted": "true",
                        "quorum": "true"})
            if body.get("errorCode") == ETCD_KEY_MISSING:
                raise NotFound(key)
            self._raise_for(body)
            nodes = body.get("node", {}).get("nodes") or []
            if not nodes:
                raise NotFound(key)
            head = nodes[0]
            value, idx = head["value"], head["modifiedIndex"]
            node_url = f"{self.base_url}/v2/keys{head['key']}"
            try:
                del_body = await self._request(
                    "DELETE", node_url, params={"prevIndex": str(idx)})
            except Timeout as e:
                raise IndeterminateDequeue(value) from e
            if del_body.get("errorCode") in (ETCD_KEY_MISSING,
                                             ETCD_CAS_FAILED):
                continue   # lost the race to another consumer
            self._raise_for(del_body)
            return value
        # Every retry lost its claim DETERMINATELY (compare-and-delete
        # observed missing/stale); an indeterminate delete raised
        # IndeterminateDequeue above. Same determinate-:fail reasoning as
        # swap's exhaustion.
        raise RetriesExhausted(
            "dequeue retry budget exhausted: 64 determinate claim losses")

    async def swap(self, key: str, fn) -> str:
        """Atomic read-modify-write via prevIndex CAS retries — the client-
        side loop verschlimmbesserung's swap! runs (reference set.clj:26-31)."""
        for _ in range(64):
            cur, idx = await self.get_with_index(key, quorum=True)
            # str() BEFORE returning, not just before sending: the store
            # holds strings, so the value this call reports must be the
            # value a subsequent get() observes (caught by the live
            # five-call integration test when fn returns an int).
            new = str(fn(cur))
            body = await self._request(
                "PUT", self._url(key),
                data={"value": new}, params={"prevIndex": str(idx)})
            if body.get("errorCode") == ETCD_CAS_FAILED:
                continue
            self._raise_for(body)
            return new
        raise RetriesExhausted("swap retry budget exhausted: 64 determinate CAS failures")


def etcd_conn_factory(port: Optional[int] = None, timeout_s: float = 5.0):
    """Per-node connections. port=None (default) resolves each node's
    client port through the DB layer (db/etcd.py client_port_for — the
    env-overridable default, or the per-node PORT_MAP when several
    daemons share one host); a fixed port pins every node."""
    def factory(test, node):
        if port is None:
            from ..db.etcd import client_port_for

            return EtcdClient.connect(node, port=client_port_for(node),
                                      timeout_s=timeout_s)
        return EtcdClient.connect(node, port=port, timeout_s=timeout_s)
    return factory
