"""Backend health supervisor: healthy -> degraded -> wedged, and back.

BENCH_r05 recorded the incident this module exists for: a wedged remote
TPU tunnel turned every backend touch into an uninterruptible hang, and
the only thing that caught it was the bench's ad-hoc trivial-jit probe
in a subprocess. ROADMAP item 1 (checking-as-a-service) needs that
probe as a *reusable state machine* the daemon can attach its CPU
failover to — this is it.

State machine (one supervisor per process, :func:`get_supervisor`):

  healthy --[consecutive failures >= fail_degraded]--> degraded
  degraded --[consecutive failures >= fail_wedged]--> wedged
  * --[probe TIMEOUT]--> wedged          (a hang IS the wedged signature)
  * --[any success]--> healthy           (recovery is immediate: the
                                          backend either completes a
                                          trivial jit or it doesn't)

Signals come from two directions:

  * **passive** — the hot paths report outcomes they already have:
    every successful kernel dispatch in stream/engine.py's consumer and
    sched/engine.py's bucket launcher is a free health proof
    (:meth:`~BackendSupervisor.note_ok`, a few ns), and a dispatch
    exception is a failure (:meth:`~BackendSupervisor.note_failure`).
  * **active** — :meth:`~BackendSupervisor.maybe_probe` runs the
    trivial-jit subprocess probe (:func:`probe_backend`, the exact
    probe bench.py ships) when `probe_interval_s` has elapsed,
    rate-limited so the runner check phase / stream consumer can call
    it every pass for free. A fresh supervisor starts its interval
    clock at construction, so short-lived test processes never pay the
    subprocess.

Transitions are recorded as obs events (`health.transition`) and the
`health.state` gauge (0 healthy / 1 degraded / 2 wedged) when a capture
is active, and carry last-transition provenance (when, why, which
caller) — exposed verbatim by `/healthz` (web/server.py) and stamped
into every bench record.

Env knobs (doc/telemetry.md "Backend health"):
  JEPSEN_TPU_HEALTH_PROBE_TIMEOUT_S   subprocess probe timeout (240)
  JEPSEN_TPU_HEALTH_PROBE_INTERVAL_S  active-probe rate limit (300)
  JEPSEN_TPU_HEALTH_FAIL_DEGRADED     consecutive failures -> degraded (1)
  JEPSEN_TPU_HEALTH_FAIL_WEDGED       consecutive failures -> wedged (3)
  JEPSEN_TPU_HEALTH_PROBE=0           disable ACTIVE probing entirely
                                      (passive signals still drive the
                                      state machine)
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Callable, Optional

HEALTHY = "healthy"
DEGRADED = "degraded"
WEDGED = "wedged"
# State -> numeric level: the health.state gauge and the /metrics
# jepsen_tpu_health_state series share this one mapping.
STATE_LEVEL = {HEALTHY: 0, DEGRADED: 1, WEDGED: 2}
_STATE_LEVEL = STATE_LEVEL

PROBE_TIMEOUT_S = 240.0
PROBE_INTERVAL_S = 300.0
# The probe-timeout reason's marker phrase. Single source of truth for
# the wedged-tunnel signature: probe_backend composes its timeout
# reason with it, and consumers that only have the reason STRING (the
# bench's monkeypatch-stable (ok, reason) probe wrapper) classify by
# it — editing the wording here cannot desync them.
TIMEOUT_MARKER = "remote TPU tunnel down/wedged?"


def _env_float(var: str, default: float) -> float:
    try:
        return float(os.environ.get(var, ""))
    except ValueError:
        return default


def _env_int(var: str, default: int) -> int:
    try:
        return int(os.environ.get(var, ""))
    except ValueError:
        return default


def probe_backend(timeout_s: float = PROBE_TIMEOUT_S,
                  platforms: Optional[str] = None
                  ) -> tuple[bool, str, bool]:
    """Probe the default JAX backend in a SUBPROCESS with a hard
    timeout: a wedged remote-TPU tunnel hangs backend init indefinitely
    and un-interruptibly from within the process (observed live,
    BENCH_r05), so the probe must be killable from outside. Returns
    (ok, reason, timed_out): a timeout and a fast crash are DIFFERENT
    failures — a timeout is the wedged signature, a crash is a
    diagnosable error (reason carries the stderr tail). The probe
    enables the same persistent compile cache production runs use, so
    on a healthy machine it costs one trivial cached compile (~1-2 s
    warm; ~20-40 s only the very first time ever)."""
    import subprocess

    code = ("from jepsen_etcd_demo_tpu.cli.main import "
            "_honor_platform_env, enable_compilation_cache; "
            # JAX_PLATFORMS must steer the PROBE too (the sitecustomize
            # pre-import otherwise dials the default tunnel even under
            # JAX_PLATFORMS=cpu — the exact trap cli/main works around).
            "_honor_platform_env(); enable_compilation_cache(); "
            "import numpy, jax, jax.numpy as jnp; "
            "numpy.asarray(jax.jit(lambda a: a + 1)(jnp.zeros(4))); "
            "print('BACKEND_OK')")
    env = dict(os.environ)
    if platforms is not None:
        env["JAX_PLATFORMS"] = platforms
    try:
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True,
                             env=env, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return False, (f"trivial jit round trip exceeded {timeout_s:.0f}s "
                       f"— {TIMEOUT_MARKER}"), True
    except OSError as e:
        return False, f"could not spawn the probe: {e}", False
    if "BACKEND_OK" in out.stdout:
        return True, "", False
    return False, (f"probe exited {out.returncode} without completing a "
                   f"trivial jit; stderr tail: {out.stderr[-500:]}"), False


class BackendSupervisor:
    """The healthy/degraded/wedged state machine. Thread-safe: passive
    notes come from the stream consumer thread, the asyncio event loop,
    and sched's caller concurrently."""

    def __init__(self, probe: Optional[Callable] = None,
                 fail_degraded: Optional[int] = None,
                 fail_wedged: Optional[int] = None,
                 probe_timeout_s: Optional[float] = None,
                 probe_interval_s: Optional[float] = None):
        self.fail_degraded = fail_degraded if fail_degraded is not None \
            else max(1, _env_int("JEPSEN_TPU_HEALTH_FAIL_DEGRADED", 1))
        self.fail_wedged = fail_wedged if fail_wedged is not None \
            else max(self.fail_degraded,
                     _env_int("JEPSEN_TPU_HEALTH_FAIL_WEDGED", 3))
        self.probe_timeout_s = probe_timeout_s if probe_timeout_s is not None \
            else _env_float("JEPSEN_TPU_HEALTH_PROBE_TIMEOUT_S",
                            PROBE_TIMEOUT_S)
        self.probe_interval_s = probe_interval_s \
            if probe_interval_s is not None \
            else _env_float("JEPSEN_TPU_HEALTH_PROBE_INTERVAL_S",
                            PROBE_INTERVAL_S)
        from .sync import maybe_wrap

        self._probe = probe or (
            lambda: probe_backend(timeout_s=self.probe_timeout_s))
        self._lock = maybe_wrap(threading.Lock(),
                                "obs.health.BackendSupervisor._lock")
        self.state = HEALTHY
        self._since_wall = time.time()
        self._consecutive_failures = 0
        self._ok_total = 0
        self._fail_total = 0
        self._probes_run = 0
        self._last_failure_reason: Optional[str] = None
        self._last_transition: Optional[dict] = None
        # The interval clock starts NOW: a fresh supervisor never
        # active-probes until probe_interval_s has elapsed, so
        # short-lived processes (the tier-1 suite) pay nothing.
        self._last_probe_mono = time.monotonic()

    # -- signals ----------------------------------------------------------

    def note_ok(self, source: str = "passive") -> None:
        """A backend interaction succeeded (a kernel dispatch, a probe).
        Recovery is immediate: any success proves the backend answers."""
        with self._lock:
            self._ok_total += 1
            self._consecutive_failures = 0
            if self.state != HEALTHY:
                self._transition(HEALTHY, f"backend interaction succeeded "
                                          f"({source})", source)

    def note_failure(self, reason: str, source: str = "passive",
                     wedged: bool = False) -> None:
        """A backend interaction failed. `wedged=True` (a probe timeout
        — the hung-tunnel signature) escalates straight to wedged;
        otherwise consecutive failures walk the thresholds."""
        with self._lock:
            self._fail_total += 1
            self._consecutive_failures += 1
            self._last_failure_reason = reason
            if wedged:
                if self.state != WEDGED:
                    self._transition(WEDGED, reason, source)
                return
            n = self._consecutive_failures
            if n >= self.fail_wedged and self.state != WEDGED:
                self._transition(
                    WEDGED, f"{n} consecutive failures "
                            f"(>= fail_wedged={self.fail_wedged}): "
                            f"{reason}", source)
            elif n >= self.fail_degraded and self.state == HEALTHY:
                self._transition(
                    DEGRADED, f"{n} consecutive failure(s) "
                              f"(>= fail_degraded={self.fail_degraded}): "
                              f"{reason}", source)

    def probe(self, source: str = "probe") -> bool:
        """Run the trivial-jit probe NOW and fold the outcome in."""
        with self._lock:
            self._probes_run += 1
            self._last_probe_mono = time.monotonic()
        ok, reason, timed_out = self._probe()
        if ok:
            self.note_ok(source=f"{source}:probe-ok")
        else:
            self.note_failure(reason, source=source, wedged=timed_out)
        return ok

    def maybe_probe(self, source: str = "periodic") -> Optional[bool]:
        """Rate-limited active probe: runs only when probe_interval_s
        has elapsed since the last probe (or construction) and active
        probing isn't disabled (JEPSEN_TPU_HEALTH_PROBE=0). Returns the
        probe outcome, or None when skipped — the shape the runner
        check phase / stream consumer call on every pass (the interval
        check comes first, so the common skip path is one lock + one
        clock read)."""
        with self._lock:
            if time.monotonic() - self._last_probe_mono \
                    < self.probe_interval_s:
                return None
        if os.environ.get("JEPSEN_TPU_HEALTH_PROBE", "1").lower() \
                in ("0", "false", "no", "off"):
            return None
        return self.probe(source=source)

    # -- state ------------------------------------------------------------

    def _transition(self, to: str, reason: str, source: str) -> None:
        """Record a state change (caller holds the lock)."""
        frm = self.state
        self.state = to
        self._since_wall = time.time()
        self._last_transition = {
            "from": frm, "to": to, "reason": reason, "source": source,
            "at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        }
        # Observability of the observer: transitions land in the active
        # capture as an event + gauge (no-ops outside a capture).
        from . import get_metrics, get_tracer

        get_tracer().event("health.transition", **self._last_transition)
        get_metrics().gauge("health.state").set(_STATE_LEVEL[to])

    def snapshot(self) -> dict:
        """The /healthz + bench-record view: current state with
        last-transition provenance and signal counters."""
        with self._lock:
            return {
                "state": self.state,
                "since": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                       time.gmtime(self._since_wall)),
                "consecutive_failures": self._consecutive_failures,
                "ok_total": self._ok_total,
                "fail_total": self._fail_total,
                "probes_run": self._probes_run,
                "last_failure": self._last_failure_reason,
                "last_transition": dict(self._last_transition)
                if self._last_transition else None,
                "thresholds": {"fail_degraded": self.fail_degraded,
                               "fail_wedged": self.fail_wedged,
                               "probe_timeout_s": self.probe_timeout_s,
                               "probe_interval_s": self.probe_interval_s},
            }


_supervisor_lock = threading.Lock()
_supervisor: Optional[BackendSupervisor] = None


def get_supervisor() -> BackendSupervisor:
    """The process-wide supervisor (created on first use — env knobs
    are read then)."""
    global _supervisor
    with _supervisor_lock:
        if _supervisor is None:
            _supervisor = BackendSupervisor()
        return _supervisor


def reset_supervisor(sup: Optional[BackendSupervisor] = None
                     ) -> Optional[BackendSupervisor]:
    """Swap (or clear) the process supervisor; returns the previous one.
    Tests install fake-probe supervisors through this."""
    global _supervisor
    with _supervisor_lock:
        prev, _supervisor = _supervisor, sup
        return prev
