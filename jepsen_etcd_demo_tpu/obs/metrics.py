"""Metrics registry: counters, gauges, histograms (stdlib only).

The aggregate half of the telemetry subsystem (the tracer in
obs/trace.py is the per-occurrence half): bounded-memory running
aggregates, serialized per run as `metrics.json`. Every instrument is a
fixed-size record — a counter is one float, a gauge tracks
last/min/max, a histogram tracks count/sum/min/max — so instrumenting
hot paths (per-op dispatch, per-kernel-launch) costs one lock + a few
float ops and can never grow with workload size.

Naming convention (dotted, lowercase): `<layer>.<what>[_<unit>]`, e.g.
`wgl.compile_s`, `runner.ops_ok`, `encode.event_bytes`. The suffix
carries the unit. The well-known keys the bench/e2e contract depends on
are pre-registered at zero by obs.capture() so consumers never see an
absent key ("zeros permitted, never absent").

snapshot() schema (metrics.json is {"metrics": snapshot(), ...}):
  counter   {"type": "counter", "value": f}
  gauge     {"type": "gauge", "last": f|null, "min": f|null, "max": f|null,
             "n": int}
  histogram {"type": "histogram", "count": int, "sum": f, "min": f|null,
             "max": f|null, "avg": f|null}
"""

from __future__ import annotations

import json
import threading
from pathlib import Path
from typing import Optional


class Counter:
    __slots__ = ("_lock", "value")

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self.value = 0.0

    def add(self, n: float = 1.0) -> None:
        with self._lock:
            self.value += n

    def snapshot(self) -> dict:
        return {"type": "counter", "value": self.value}


class Gauge:
    __slots__ = ("_lock", "last", "min", "max", "n")

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self.last: Optional[float] = None
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self.n = 0

    def set(self, v: float) -> None:
        with self._lock:
            v = float(v)
            self.last = v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)
            self.n += 1

    def snapshot(self) -> dict:
        return {"type": "gauge", "last": self.last, "min": self.min,
                "max": self.max, "n": self.n}


class Histogram:
    __slots__ = ("_lock", "count", "sum", "min", "max")

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None

    def observe(self, v: float) -> None:
        with self._lock:
            v = float(v)
            self.count += 1
            self.sum += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)

    def snapshot(self) -> dict:
        return {"type": "histogram", "count": self.count,
                "sum": self.sum, "min": self.min, "max": self.max,
                "avg": (self.sum / self.count) if self.count else None}


class _NullInstrument:
    """Accepts every instrument method, stores nothing — what the
    disabled registry hands out so call sites never branch."""

    def add(self, n: float = 1.0) -> None:
        pass

    def set(self, v: float) -> None:
        pass

    def observe(self, v: float) -> None:
        pass


_NULL_INSTRUMENT = _NullInstrument()


class MetricsRegistry:
    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self._lock = threading.Lock()
        self._metrics: dict[str, object] = {}

    def _get(self, name: str, cls):
        if not self.enabled:
            return _NULL_INSTRUMENT
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(self._lock)
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, requested {cls.__name__}")
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str) -> Histogram:
        return self._get(name, Histogram)

    # -- reading ----------------------------------------------------------

    def snapshot(self) -> dict[str, dict]:
        with self._lock:
            items = list(self._metrics.items())
        return {name: m.snapshot() for name, m in sorted(items)}

    def value(self, name: str, default: float = 0.0) -> float:
        """Scalar view for consumers that just want a number: a counter's
        value, a gauge's last, a histogram's sum."""
        with self._lock:
            m = self._metrics.get(name)
        if isinstance(m, Counter):
            return m.value
        if isinstance(m, Gauge):
            return m.last if m.last is not None else default
        if isinstance(m, Histogram):
            return m.sum
        return default

    def to_json(self) -> str:
        return json.dumps({"metrics": self.snapshot()}, indent=2) + "\n"

    def write(self, path: str | Path) -> None:
        Path(path).write_text(self.to_json())


def read_metrics(path: str | Path) -> dict[str, dict]:
    """Load a metrics.json back into its snapshot dict."""
    return json.loads(Path(path).read_text())["metrics"]
