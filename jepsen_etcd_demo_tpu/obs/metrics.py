"""Metrics registry: counters, gauges, histograms (stdlib only).

The aggregate half of the telemetry subsystem (the tracer in
obs/trace.py is the per-occurrence half): bounded-memory running
aggregates, serialized per run as `metrics.json`. Every instrument is a
fixed-size record — a counter is one float, a gauge tracks
last/min/max, a histogram tracks count/sum/min/max plus a fixed
log-bucket sketch for quantiles — so instrumenting hot paths (per-op
dispatch, per-kernel-launch) costs one lock + a few float ops and can
never grow with workload size.

Naming convention (dotted, lowercase): `<layer>.<what>[_<unit>]`, e.g.
`wgl.compile_s`, `runner.ops_ok`, `encode.event_bytes`. The suffix
carries the unit. The well-known keys the bench/e2e contract depends on
are pre-registered at zero by obs.capture() so consumers never see an
absent key ("zeros permitted, never absent").

snapshot() schema (metrics.json is {"metrics": snapshot(), ...}):
  counter   {"type": "counter", "value": f}
  gauge     {"type": "gauge", "last": f|null, "min": f|null, "max": f|null,
             "n": int}
  histogram {"type": "histogram", "count": int, "sum": f, "min": f|null,
             "max": f|null, "avg": f|null,
             "p50": f|null, "p95": f|null, "p99": f|null}

The quantiles come from a fixed-geometry log-bucket sketch (base 1.1,
so ~5% relative error): observations land in bucket
floor(log(v)/log(1.1)), clamped to a bounded index range, so the
sketch's memory is bounded by the VALUE RANGE (a few hundred buckets at
most), never by the observation count. p* keys are additive — every
pre-quantile consumer of count/sum/min/max/avg keeps working.

Every instrument also notes its name in the registry's dirty set on
update; `drain_dirty()` hands the live-export bus (obs/export.py) the
changed-since-last-drain subset without a full snapshot per tick.
"""

from __future__ import annotations

import json
import math
import threading
from pathlib import Path
from typing import Optional

from .sync import maybe_wrap

# Log-bucket geometry for histogram quantiles: base 1.1 gives ~±4.9%
# relative error; indices clamped so memory stays bounded for any input
# (index 400 covers up to ~5e16, -400 down to ~2e-17).
_LN_BASE = math.log(1.1)
_BUCKET_LO, _BUCKET_HI = -400, 400
QUANTILES = (0.5, 0.95, 0.99)


class Counter:
    __slots__ = ("_lock", "_dirty", "name", "value")

    def __init__(self, lock: threading.Lock, name: str = "",
                 dirty: Optional[set] = None):
        # The registry's ONE lock, injected so a snapshot pass and the
        # writers serialize on the same object.
        # jtsan: alias-of=obs.metrics.MetricsRegistry._lock
        self._lock = lock
        self._dirty = dirty
        self.name = name
        self.value = 0.0

    def add(self, n: float = 1.0) -> None:
        with self._lock:
            self.value += n
            if self._dirty is not None:
                self._dirty.add(self.name)

    def snapshot(self) -> dict:
        # Snapshot-under-lock: /metrics scrapes run on web handler
        # threads while kernel/serve threads write — an unlocked read
        # here was jtsan JTL501's first real finding.
        with self._lock:
            return {"type": "counter", "value": self.value}


class Gauge:
    __slots__ = ("_lock", "_dirty", "name", "last", "min", "max", "n")

    def __init__(self, lock: threading.Lock, name: str = "",
                 dirty: Optional[set] = None):
        # jtsan: alias-of=obs.metrics.MetricsRegistry._lock
        self._lock = lock
        self._dirty = dirty
        self.name = name
        self.last: Optional[float] = None
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self.n = 0

    def set(self, v: float) -> None:
        with self._lock:
            v = float(v)
            self.last = v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)
            self.n += 1
            if self._dirty is not None:
                self._dirty.add(self.name)

    def snapshot(self) -> dict:
        # Snapshot-under-lock (see Counter.snapshot): a torn
        # last/min/max triple would mix two updates on one row.
        with self._lock:
            return {"type": "gauge", "last": self.last, "min": self.min,
                    "max": self.max, "n": self.n}


class Histogram:
    __slots__ = ("_lock", "_dirty", "name", "count", "sum", "min", "max",
                 "_buckets", "_nonpos")

    def __init__(self, lock: threading.Lock, name: str = "",
                 dirty: Optional[set] = None):
        # jtsan: alias-of=obs.metrics.MetricsRegistry._lock
        self._lock = lock
        self._dirty = dirty
        self.name = name
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self._buckets: dict[int, int] = {}
        self._nonpos = 0

    def observe(self, v: float) -> None:
        with self._lock:
            v = float(v)
            self.count += 1
            self.sum += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)
            if v > 0.0:
                i = int(math.floor(math.log(v) / _LN_BASE))
                i = min(_BUCKET_HI, max(_BUCKET_LO, i))
                self._buckets[i] = self._buckets.get(i, 0) + 1
            else:
                self._nonpos += 1
            if self._dirty is not None:
                self._dirty.add(self.name)

    def _quantile(self, q: float) -> Optional[float]:
        """Sketch estimate for quantile q (caller holds the lock)."""
        if self.count == 0:
            return None
        target = max(1, math.ceil(q * self.count))
        cum = self._nonpos
        if cum >= target:
            # The quantile falls among the <=0 observations; min is the
            # best (and only) order statistic kept for them.
            return self.min
        for i in sorted(self._buckets):
            cum += self._buckets[i]
            if cum >= target:
                rep = math.exp((i + 0.5) * _LN_BASE)   # geometric mid
                rep = max(rep, self.min) if self.min is not None else rep
                rep = min(rep, self.max) if self.max is not None else rep
                return rep
        return self.max

    def snapshot(self) -> dict:
        with self._lock:
            out = {"type": "histogram", "count": self.count,
                   "sum": self.sum, "min": self.min, "max": self.max,
                   "avg": (self.sum / self.count) if self.count else None}
            for q in QUANTILES:
                out[f"p{int(q * 100)}"] = self._quantile(q)
        return out


class _NullInstrument:
    """Accepts every instrument method, stores nothing — what the
    disabled registry hands out so call sites never branch."""

    def add(self, n: float = 1.0) -> None:
        pass

    def set(self, v: float) -> None:
        pass

    def observe(self, v: float) -> None:
        pass


_NULL_INSTRUMENT = _NullInstrument()


class MetricsRegistry:
    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self._lock = maybe_wrap(threading.Lock(),
                                "obs.metrics.MetricsRegistry._lock")
        self._metrics: dict[str, object] = {}
        self._dirty: set[str] = set()

    def _get(self, name: str, cls):
        if not self.enabled:
            return _NULL_INSTRUMENT
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(self._lock, name=name,
                                              dirty=self._dirty)
                self._dirty.add(name)
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, requested {cls.__name__}")
            return m

    # jtsan: returns=Counter
    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    # jtsan: returns=Gauge
    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    # jtsan: returns=Histogram
    def histogram(self, name: str) -> Histogram:
        return self._get(name, Histogram)

    # -- reading ----------------------------------------------------------

    def snapshot(self) -> dict[str, dict]:
        with self._lock:
            items = list(self._metrics.items())
        return {name: m.snapshot() for name, m in sorted(items)}

    def drain_dirty(self) -> dict[str, dict]:
        """Snapshot of every instrument updated since the last drain,
        clearing the dirty set — the live-export bus's incremental view
        (obs/export.py publishes these as `metric` records)."""
        with self._lock:
            names = [n for n in self._dirty if n in self._metrics]
            insts = [self._metrics[n] for n in names]
            self._dirty.clear()
        return {n: m.snapshot() for n, m in zip(names, insts)}

    def value(self, name: str, default: float = 0.0) -> float:
        """Scalar view for consumers that just want a number: a counter's
        value, a gauge's last, a histogram's sum. Read under the shared
        lock — the instruments write under the same one (jtsan's
        snapshot-under-lock discipline)."""
        with self._lock:
            m = self._metrics.get(name)
            if isinstance(m, Counter):
                return m.value
            if isinstance(m, Gauge):
                return m.last if m.last is not None else default
            if isinstance(m, Histogram):
                return m.sum
        return default

    def to_json(self) -> str:
        return json.dumps({"metrics": self.snapshot()}, indent=2) + "\n"

    def write(self, path: str | Path) -> None:
        Path(path).write_text(self.to_json())


def read_metrics(path: str | Path) -> dict[str, dict]:
    """Load a metrics.json back into its snapshot dict."""
    return json.loads(Path(path).read_text())["metrics"]
