"""Span tracer: the harness's own observability substrate (stdlib only).

The post-hoc checkers (checkers/perf.py charts, checkers/timeline.py
swimlanes) observe the *op history*; this module observes the HARNESS —
where wall time goes across setup / generator-interpret / teardown /
check / store, which kernel compiled when, when each fault fired. One
tracer instance collects one run's records and serializes them as
`telemetry.jsonl` next to the other store artifacts (obs/__init__.py
capture()).

Design constraints, in order:
  * near-zero cost when disabled (the library default): every public
    entry point is a single attribute check before bailing;
  * thread- AND async-safe: span parentage rides a contextvars.ContextVar,
    which is per-thread and per-asyncio-task (create_task copies the
    context, so the runner's worker tasks inherit the "run" span as
    parent exactly like jepsen's worker threads nest under run!);
    record appends take one lock;
  * monotonic-ns timestamps (never wall clock deltas): spans survive
    clock-skew nemeses by construction. One wall-clock anchor is
    recorded in the meta line for human correlation.

Record schema (one JSON object per line, completion order):
  {"kind": "meta",  "wall_start": iso8601, "clock": "monotonic_ns", ...}
  {"kind": "span",  "id": n, "parent": n|null, "name": str,
   "t0_ns": n, "t1_ns": n, "status": "ok"|"error", "attrs": {...}}
  {"kind": "event", "id": n, "span": n|null, "name": str,
   "t_ns": n, "attrs": {...}}
  {"kind": "footer", "truncated": true, "records": n, "dropped": n}
     (only when max_records truncated the capture)

t*_ns are offsets from the tracer's birth (the meta anchor), so files
are small and diffable; span ids are unique within one tracer.
"""

from __future__ import annotations

import contextvars
import json
import threading
import time
from contextlib import contextmanager
from datetime import datetime, timezone
from pathlib import Path
from typing import Any, Iterator, Optional


class SpanHandle:
    """What `with tracer.span(...) as sp` yields: lets the body annotate
    the span after the fact (sp.set(valid=True, kernel="wgl3-dense"))."""

    __slots__ = ("id", "attrs")

    def __init__(self, span_id: Optional[int], attrs: dict):
        self.id = span_id
        self.attrs = attrs

    def set(self, **attrs: Any) -> None:
        self.attrs.update(attrs)


_NULL_HANDLE = SpanHandle(None, {})


class Tracer:
    """Collects spans + events for ONE run (or bench invocation).

    `max_records` bounds memory for pathological workloads (a span per
    client op at high rate): past the cap, records are dropped and
    counted — the meta line reports `dropped` so truncation is never
    silent."""

    def __init__(self, enabled: bool = True, max_records: int = 200_000):
        from .sync import maybe_wrap

        self.enabled = enabled
        self.max_records = max_records
        self._lock = maybe_wrap(threading.Lock(),
                                "obs.trace.Tracer._lock")
        self._records: list[dict] = []
        self._dropped = 0
        self._next_id = 1
        self._current: contextvars.ContextVar[Optional[int]] = \
            contextvars.ContextVar("jepsen_tpu_span", default=None)
        self._t0_ns = time.monotonic_ns()
        self._wall_start = datetime.now(timezone.utc).isoformat()
        # Live-export hooks (obs/export.py / obs.capture): `listener`
        # receives each appended record (called under the tracer lock,
        # so subscribers observe exact append order); `drop_counter` is
        # the pre-registered trace.dropped_records metric, incremented
        # the moment a record is dropped so truncation surfaces live,
        # not only in the final artifact.
        self.listener: Optional[object] = None
        self.drop_counter: Optional[object] = None

    # -- recording --------------------------------------------------------

    def _now(self) -> int:
        return time.monotonic_ns() - self._t0_ns

    def _append(self, rec: dict) -> None:
        with self._lock:
            if len(self._records) >= self.max_records:
                self._dropped += 1
                drop = self.drop_counter
                lst = None
            else:
                self._records.append(rec)
                drop = None
                lst = self.listener
            if drop is not None:
                drop.add(1)
            if lst is not None:
                lst(rec)

    @contextmanager
    def span(self, name: str, **attrs: Any) -> Iterator[SpanHandle]:
        """Context manager timing one phase; nests via contextvars (safe
        across threads and asyncio tasks). Exceptions mark the span
        status "error" and re-raise."""
        if not self.enabled:
            yield _NULL_HANDLE
            return
        with self._lock:
            sid = self._next_id
            self._next_id += 1
        parent = self._current.get()
        token = self._current.set(sid)
        handle = SpanHandle(sid, dict(attrs))
        t0 = self._now()
        status = "ok"
        try:
            yield handle
        except BaseException:
            status = "error"
            raise
        finally:
            self._current.reset(token)
            self._append({"kind": "span", "id": sid, "parent": parent,
                          "name": name, "t0_ns": t0, "t1_ns": self._now(),
                          "status": status, "attrs": handle.attrs})

    def event(self, name: str, **attrs: Any) -> None:
        """Point-in-time record, correlated to the enclosing span (if any)
        via its id — how nemesis fault firings tie back to the phase and
        nemesis-op spans they happened under."""
        if not self.enabled:
            return
        with self._lock:
            eid = self._next_id
            self._next_id += 1
        self._append({"kind": "event", "id": eid,
                      "span": self._current.get(), "name": name,
                      "t_ns": self._now(), "attrs": attrs})

    def current_span_id(self) -> Optional[int]:
        return self._current.get()

    # -- serialization ----------------------------------------------------

    def records(self) -> list[dict]:
        with self._lock:
            return list(self._records)

    def tail(self, n: int) -> list[dict]:
        """The most recent `n` records, copied under the lock — the
        /live SSE init seed. Copies n records, not the whole buffer
        (records() duplicates up to max_records entries per call, which
        a reconnecting SSE client would pay on every connect)."""
        if n <= 0:
            # [-0:] would degenerate to the WHOLE buffer — the exact
            # copy this method exists to avoid.
            return []
        with self._lock:
            return self._records[-n:]

    def to_jsonl(self) -> str:
        with self._lock:
            recs = list(self._records)
            dropped = self._dropped
        meta = {"kind": "meta", "wall_start": self._wall_start,
                "clock": "monotonic_ns", "records": len(recs),
                "dropped": dropped}
        lines = [json.dumps(meta)]
        lines.extend(json.dumps(r, default=str) for r in recs)
        if dropped:
            # Truncation footer: a tail reader (or a consumer that never
            # parses the meta line) still learns the file is INCOMPLETE
            # — the telemetry page renders this as a warning banner
            # instead of presenting a truncated span tree as complete.
            lines.append(json.dumps({"kind": "footer", "truncated": True,
                                     "records": len(recs),
                                     "dropped": dropped}))
        return "\n".join(lines) + "\n"

    def write(self, path: str | Path) -> None:
        Path(path).write_text(self.to_jsonl())


def read_jsonl(path: str | Path) -> list[dict]:
    """Parse a telemetry.jsonl back into records (meta line included);
    tolerates a trailing partial line from an interrupted run."""
    out = []
    for line in Path(path).read_text().splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            out.append(json.loads(line))
        except ValueError:
            break
    return out
