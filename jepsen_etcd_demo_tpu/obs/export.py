"""Live telemetry export: Prometheus text exposition + subscription bus.

PR 1 made every run observable *after* the fact (telemetry.jsonl /
metrics.json artifacts); this module makes the process observable
*while it runs* — the layer ROADMAP item 1's checking-as-a-service
daemon stands on:

  * :func:`render_prometheus` — the active MetricsRegistry as
    Prometheus text exposition (text/plain; version=0.0.4): counters
    and gauges under stable ``jepsen_tpu_*`` names, histograms as
    summaries with p50/p95/p99 quantile lines (the obs/metrics.py
    log-bucket sketch), per-kernel/per-knob metric families split into
    a label instead of exploding the name space. Served by
    ``web/server.py`` at ``/metrics``.
  * :func:`subscribe` — an in-process bus streaming span/event/metric
    records AS THEY ARE APPENDED, so the web layer's ``/live`` SSE page
    (and the future daemon) consume telemetry without polling files.
    Trace records are published synchronously from the tracer's append
    (exact append order); metric updates are coalesced by a pump thread
    that drains the registry's dirty set a few times per second —
    streaming every ``counter.add`` on a hot kernel path would cost
    more than the kernels.

Zero-overhead discipline: with no subscribers, publish() is one
attribute check; with telemetry disabled (JEPSEN_TPU_TELEMETRY=0) the
null tracer never publishes at all and /metrics renders an empty
registry. Everything is stdlib-only.
"""

from __future__ import annotations

import json
import queue
import re
import threading
import time
from typing import Iterable, Optional

from .metrics import MetricsRegistry

PROM_PREFIX = "jepsen_tpu_"
PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

# Dotted metric families whose LAST component is an open-ended (but
# statically bounded — kernel names, knob names) member set: exported
# as one Prometheus family with a label instead of one metric name per
# member. The exported family name gains a `_by_<label>` suffix so it
# can NEVER collide with a plain metric of the same prefix (the
# `wgl.compile_s` counter and the `wgl.compile_s.<kernel>` histograms
# must be distinct Prometheus families — one name with two types is an
# invalid exposition). `wgl.compile_s.wgl3-chunk` ->
# `jepsen_tpu_wgl_compile_s_by_kernel{kernel="wgl3-chunk"}`.
LABELED_FAMILIES = {
    "wgl.compile_s": "kernel",
    "wgl.execute_s": "kernel",
    "wgl.kernel_flops": "kernel",
    "wgl.kernel_bytes": "kernel",
    "tune.probe_s": "knob",
    "tune.chosen": "knob",
    # Scaling-ledger per-bucket cumulative seconds (obs/ledger.py
    # BUCKETS — a closed 8-member set): `ledger.bucket_s.padding_s` ->
    # `jepsen_tpu_ledger_bucket_s_by_bucket{bucket="padding_s"}`.
    "ledger.bucket_s": "bucket",
}

_NAME_SUB = re.compile(r"[^a-zA-Z0-9_:]")
_QUANTILE_KEYS = (("p50", "0.5"), ("p95", "0.95"), ("p99", "0.99"))


def sanitize_metric_name(name: str) -> str:
    """Dotted registry name -> Prometheus metric name body: every
    character outside [a-zA-Z0-9_:] becomes '_', and a leading digit is
    prefixed so the result always matches the exposition grammar."""
    out = _NAME_SUB.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def sanitize_label_value(value: str) -> str:
    """Escape a label value per the exposition format (backslash,
    double-quote, newline)."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _family_of(name: str) -> tuple[str, Optional[str], Optional[str]]:
    """(exported family, label name, label value) — label parts None
    for plain (unlabeled) metrics; labeled families export under a
    `_by_<label>` name so they never collide with a plain metric."""
    for fam, label in LABELED_FAMILIES.items():
        if name.startswith(fam + ".") and len(name) > len(fam) + 1:
            return f"{fam}_by_{label}", label, name[len(fam) + 1:]
    return name, None, None


def _fmt(v) -> str:
    if v is None:
        return "NaN"
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def render_prometheus(snapshot: dict[str, dict],
                      extra_lines: Iterable[str] = ()) -> str:
    """A MetricsRegistry snapshot as Prometheus text exposition.

    Counters render as-is, gauges render their `last` (0 when never
    set — the pre-registered contract keys stay visible), histograms
    render as summaries: quantile lines from the log-bucket sketch plus
    `_sum` / `_count`. One `# TYPE` line per family, families sorted
    for a stable (diffable, goldenable) output. `extra_lines` lets the
    web layer append process-level series (health state, up)."""
    families: dict[str, dict] = {}   # prom name -> {type, lines: [...]}
    for name, rec in sorted(snapshot.items()):
        fam, label, member = _family_of(name)
        prom = PROM_PREFIX + sanitize_metric_name(fam)
        kind = rec.get("type")
        lbl = (f'{{{label}="{sanitize_label_value(member)}"}}'
               if label is not None else "")
        if kind == "counter":
            f = families.setdefault(prom, {"type": "counter", "lines": []})
            f["lines"].append(f"{prom}{lbl} {_fmt(rec.get('value', 0))}")
        elif kind == "gauge":
            f = families.setdefault(prom, {"type": "gauge", "lines": []})
            f["lines"].append(
                f"{prom}{lbl} {_fmt(rec.get('last') or 0)}")
        elif kind == "histogram":
            f = families.setdefault(prom, {"type": "summary", "lines": []})
            for key, q in _QUANTILE_KEYS:
                qlbl = (lbl[:-1] + f',quantile="{q}"}}') if lbl \
                    else f'{{quantile="{q}"}}'
                f["lines"].append(f"{prom}{qlbl} {_fmt(rec.get(key))}")
            f["lines"].append(f"{prom}_sum{lbl} {_fmt(rec.get('sum', 0))}")
            f["lines"].append(
                f"{prom}_count{lbl} {_fmt(rec.get('count', 0))}")
    out: list[str] = []
    for prom in sorted(families):
        out.append(f"# TYPE {prom} {families[prom]['type']}")
        out.extend(families[prom]["lines"])
    out.extend(extra_lines)
    return "\n".join(out) + "\n"


# -- subscription bus ------------------------------------------------------

class Subscription:
    """One subscriber's bounded record queue. Records are dicts with a
    "kind" key: span / event (tracer records, exact append order) and
    metric ({"kind": "metric", "name": ..., "metric": snapshot}). A
    full queue drops the oldest-unread records' successors and counts
    them (`dropped`) — a slow consumer must never backpressure the
    harness."""

    def __init__(self, kinds: Optional[set] = None, maxsize: int = 4096):
        self.kinds = set(kinds) if kinds else None
        self.dropped = 0
        self.closed = False
        self._q: queue.Queue = queue.Queue(maxsize=maxsize)

    def _offer(self, rec: dict) -> None:
        if self.closed or (self.kinds and rec.get("kind") not in self.kinds):
            return
        try:
            self._q.put_nowait(rec)
        except queue.Full:
            self.dropped += 1

    def get(self, timeout: Optional[float] = None) -> Optional[dict]:
        """Next record, or None on timeout / after close."""
        try:
            return self._q.get(timeout=timeout) if timeout is not None \
                else self._q.get_nowait()
        except queue.Empty:
            return None

    def close(self) -> None:
        self.closed = True
        _BUS.unsubscribe(self)


# jtlint: disable=JTL505 -- the pump thread is self-terminating by
# design: _pump_metrics exits (and clears self._pump) the moment the
# last subscriber closes, and it is daemon=True — a module-global bus
# has no shutdown path to join it from, and needs none.
class _Bus:
    """Module-global publish/subscribe fan-out. `publish` is called
    from the tracer's append path (under the tracer lock), so the
    no-subscriber fast path must stay one attribute check."""

    def __init__(self):
        from .sync import maybe_wrap

        self._lock = maybe_wrap(threading.Lock(),
                                "obs.export._Bus._lock")
        self._subs: tuple[Subscription, ...] = ()
        self._pump: Optional[threading.Thread] = None
        self.pump_interval_s = 0.25

    @property
    def active(self) -> bool:
        with self._lock:
            return bool(self._subs)

    def subscribe(self, kinds: Optional[set] = None,
                  maxsize: int = 4096) -> Subscription:
        sub = Subscription(kinds=kinds, maxsize=maxsize)
        with self._lock:
            self._subs = self._subs + (sub,)
            if self._pump is None or not self._pump.is_alive():
                self._pump = threading.Thread(
                    target=self._pump_metrics, name="obs-metric-pump",
                    daemon=True)
                self._pump.start()
        return sub

    def unsubscribe(self, sub: Subscription) -> None:
        with self._lock:
            self._subs = tuple(s for s in self._subs if s is not sub)

    def publish(self, rec: dict) -> None:
        # Deliberate lock-free fast path: _subs is an IMMUTABLE tuple
        # swapped under the bus lock, publish runs inside the tracer's
        # append (every span on every thread) and must stay one
        # attribute check when nobody subscribed. A reader sees either
        # the old or the new tuple — both are safe to fan out to.
        # jtlint: disable=JTL501 -- lock-free by design: immutable
        # tuple swap (writers hold the bus lock), benign stale read;
        # taking the lock here would serialize every traced span
        # against subscribe/unsubscribe.
        subs = self._subs
        if not subs:
            return
        for s in subs:
            s._offer(rec)

    def _pump_metrics(self) -> None:
        """Coalesced metric streaming: while any subscriber exists,
        drain the ACTIVE registry's dirty set every pump_interval_s and
        publish one `metric` record per changed instrument. Exits when
        the last subscriber closes (a later subscribe restarts it)."""
        from . import get_metrics   # late: obs package is initialized

        while True:
            with self._lock:
                if not self._subs:
                    self._pump = None
                    return
            reg = get_metrics()
            if isinstance(reg, MetricsRegistry) and reg.enabled:
                try:
                    for name, snap in sorted(reg.drain_dirty().items()):
                        self.publish({"kind": "metric", "name": name,
                                      "metric": snap})
                except Exception:   # pragma: no cover - never kill the pump
                    pass
            time.sleep(self.pump_interval_s)


_BUS = _Bus()


def subscribe(kinds: Optional[set] = None,
              maxsize: int = 4096) -> Subscription:
    """Subscribe to the live telemetry stream. `kinds` filters record
    kinds ({"span", "event", "metric"}); None receives everything.
    Close the subscription when done — an abandoned one just fills its
    bounded queue and counts drops, but costs a fan-out check per
    record while registered."""
    return _BUS.subscribe(kinds=kinds, maxsize=maxsize)


def bus_publish(rec: dict) -> None:
    """The tracer listener obs.capture() installs: forward one appended
    trace record to the bus (no-op without subscribers)."""
    _BUS.publish(rec)


def bus_active() -> bool:
    return _BUS.active


# -- SSE helpers -----------------------------------------------------------

def sse_message(data, event: Optional[str] = None) -> bytes:
    """One Server-Sent-Events message: `data` is JSON-encoded (unless
    already a string); multi-line data is framed per the SSE spec."""
    if not isinstance(data, str):
        data = json.dumps(data, default=str)
    out = []
    if event:
        out.append(f"event: {event}")
    out.extend(f"data: {line}" for line in data.split("\n"))
    return ("\n".join(out) + "\n\n").encode("utf-8")
