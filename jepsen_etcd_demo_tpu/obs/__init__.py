"""obs — harness-wide telemetry: span tracing + metrics + per-run artifacts.

The harness used to be a black box: the only observability was post-hoc
charts over the op history, so a wedged backend (BENCH_r05's
`wgl_check_throughput = 0`) had nothing to say about where time went.
This package is the in-band answer — stdlib-only, near-zero-cost when
idle:

  * obs/trace.py   — span tracer (context-manager API, monotonic-ns,
                     thread/async-safe) -> `telemetry.jsonl`
  * obs/metrics.py — counters/gauges/histograms -> `metrics.json`
  * this module    — the capture stack wiring instrumentation points to
                     the active run, kernel compile/execute attribution,
                     and the env-gated jax.profiler trace.

Usage pattern: layers call `get_tracer()` / `get_metrics()` at the
point of instrumentation; both return no-op singletons unless a
`capture()` is active, so library use (imports, ad-hoc checker calls)
records nothing and pays one list-index per call. The runner
(runner/core.py run_test) and the bench (bench.py) open captures; the
runner's capture writes `telemetry.jsonl` + `metrics.json` into the run
dir next to history.jsonl/results.json.

Env vars:
  JEPSEN_TPU_TELEMETRY=0   disable capture entirely (spans/metrics
                           become no-ops; no artifacts are written)
  JEPSEN_TPU_JAX_TRACE=1   additionally capture a jax.profiler trace of
                           the check phase into <run_dir>/jax_trace/
                           (view with tensorboard/xprof)
  JEPSEN_TPU_KERNEL_COST=0 disable the per-kernel XLA cost_analysis /
                           device-memory capture on first calls
                           (kernel_phases flops/bytes stay zero)

Live export (obs/export.py): `obs.subscribe()` streams span/event/
metric records as they are appended (the web layer's /live SSE feed),
`obs.render_prometheus(...)` renders a registry snapshot as Prometheus
text for /metrics. Backend health (obs/health.py):
`health.get_supervisor()` is the process-wide healthy/degraded/wedged
state machine behind /healthz and the bench record.

Well-known metric keys (pre-registered at zero by capture(), so they
are never absent from metrics.json or the bench's kernel_phases):
  wgl.compile_s      summed first-call wall of each compiled kernel
                     geometry (jit tracing+compilation is synchronous on
                     the first call, so this is compile-dominated)
  wgl.execute_s      summed steady-state kernel call wall (dispatch +
                     any in-call fetch; a lower bound on device time for
                     async backends)
  encode.encode_s    host-side history->tensor encoding seconds
  wgl.frontier_peak  gauge; max over checks of the search's live-config
                     high-water mark (kernel_phases reports its max)
"""

from __future__ import annotations

import os
import threading
import time
from contextlib import contextmanager
from pathlib import Path
from typing import Callable, Iterator, Optional

from .metrics import MetricsRegistry, read_metrics
from .trace import Tracer, read_jsonl
from . import export                               # noqa: E402
from . import health                               # noqa: E402
from . import ledger                               # noqa: E402
from .export import render_prometheus, subscribe   # noqa: F401
from .ledger import Ledger                         # noqa: F401

TELEMETRY_FILE = "telemetry.jsonl"
METRICS_FILE = "metrics.json"
KERNEL_COST_ENV = "JEPSEN_TPU_KERNEL_COST"

# The bench/e2e contract keys: pre-registered at zero on every capture.
# The jtflow "metrics preregistered" hooks below declare the
# pre-registration set to the flow pass (JTL405): a key the snapshot
# readers (kernel_phases / sched_stats / sweep_stats) fetch but no
# capture pre-registers would be ABSENT — not zero — on quiet runs,
# breaking the "zeros permitted, never absent" artifact contract.
# jtflow: metrics preregistered
PHASE_COUNTERS = ("wgl.compile_s", "wgl.execute_s", "encode.encode_s")
# jtflow: metrics preregistered
PHASE_GAUGE = "wgl.frontier_peak"
# Corpus-scheduler accounting (sched/): padded-vs-real step counters
# behind the bench's padding_waste field and the kernel-LRU hit/miss
# counters behind cache_hit_rate — pre-registered so the artifacts carry
# zeros, never absences, even for runs that never launch a batch.
# jtflow: metrics preregistered
SCHED_COUNTERS = ("sched.steps_real", "sched.steps_padded",
                  "sched.cache_hits", "sched.cache_misses",
                  "encode.cache_hits", "encode.cache_misses")
# Sparse active-tile sweep engine (ops/wgl3_sparse.py) accounting:
# per-mode step counters plus the live-tile occupancy gauge — pre-
# registered so every dense-kernel run's metrics.json carries them
# (zeros permitted, never absent; the web UI renders both).
# jtflow: metrics preregistered
SWEEP_COUNTERS = ("wgl.sweep_steps_sparse", "wgl.sweep_steps_dense",
                  "wgl.sweep_checks_sparse", "wgl.sweep_checks_dense",
                  "wgl.sweep_checks_mixed",
                  # ISSUE 10: configs removed by frontier
                  # canonicalization (ops/canon.py) and the previously-
                  # silent work-list-overflow dense rounds
                  # (ops/wgl3_sparse.py).
                  "wgl.configs_pruned", "wgl.sparse_overflow_rounds")
# jtflow: metrics preregistered
SWEEP_GAUGE = "wgl.live_tile_ratio"
# Frontier-dedup effectiveness: pruned / pre-canon configs over the
# canon-applied steps of a check (ops/canon.py; zeros-never-absent like
# every sweep key).
# jtflow: metrics preregistered
DEDUP_GAUGE = "wgl.frontier_dedup_ratio"
# Elle transitive-closure engine (ops/cycles.py / ops/cycles_tiled.py /
# stream/elle.py, ISSUE 11): per-route graph counts (dense squaring /
# vmapped batch / tiled work-list / host-oracle fallback), launch and
# tiled-round accounting, and the streaming session's txn/re-check
# counters — pre-registered so every capture's metrics.json carries
# them (zeros permitted, never absent; elle_stats() is the bench/web
# reader).
# jtflow: metrics preregistered
ELLE_COUNTERS = ("elle.graphs_dense", "elle.graphs_batched",
                 "elle.graphs_tiled", "elle.graphs_oracle",
                 "elle.closure_launches", "elle.tiled_rounds_sparse",
                 "elle.tiled_rounds_dense", "elle.stream_txns",
                 "elle.stream_rechecks")
# Batched-launch fill ratio (real graphs / padded batch) and the tiled
# kernel's last eligible-product density — the elle engine's occupancy
# telemetry.
# jtflow: metrics preregistered
ELLE_GAUGES = ("elle.batch_fill", "elle.tile_density")
# Streaming check engine (stream/engine.py): fraction of return steps
# swept while the run was still live, and the watermark's lag behind
# the recorder (history entries recorded but not yet stable) — pre-
# registered so every run's metrics.json carries them (zeros permitted,
# never absent; a post-hoc run simply records zeros).
# jtflow: metrics preregistered
STREAM_GAUGES = ("stream.overlap_ratio", "stream.watermark_lag")
# Checking-as-a-service daemon (serve/, ISSUE 13): request/batch/
# admission accounting of the continuous-batching scheduler — requests
# admitted, coalesced batch launches, requests that shared a batch with
# another request, work shed to the CPU oracle path while degraded,
# rejections (admission bound / wedged backend), webhook deliveries —
# pre-registered so every capture's metrics.json carries them (zeros
# permitted, never absent; serve_stats() is the bench/web reader).
# jtflow: metrics preregistered
SERVE_COUNTERS = ("serve.requests", "serve.batches",
                  "serve.coalesced_requests", "serve.shed_cpu",
                  "serve.rejected_inflight", "serve.rejected_wedged",
                  "serve.webhooks")
# Queue depth at dispatch time and the coalesced batch's fill (requests
# per batch over serve_max_batch) — the serve daemon's occupancy
# telemetry on /metrics and the /live page.
# jtflow: metrics preregistered
SERVE_GAUGES = ("serve.queue_depth", "serve.batch_fill")
# End-to-end request latency (submit -> verdict, seconds) across every
# tenant; the exporter renders p50/p95/p99 quantile lines.
# jtflow: metrics preregistered
SERVE_HISTOGRAM = "serve.request_latency_s"
# Deep kernel attribution (ISSUE 8): XLA cost_analysis totals captured
# by instrument_kernel at lower time, plus the device-memory high-water
# mark — behind kernel_phases' flops / bytes / device_mem_peak fields.
# Tracer truncation (trace.dropped_records) rides along so a truncated
# telemetry.jsonl is visible in metrics too, not only the footer.
# jtflow: metrics preregistered
COST_COUNTERS = ("wgl.flops", "wgl.bytes_accessed",
                 "trace.dropped_records")
# jtflow: metrics preregistered
COST_GAUGE = "wgl.device_mem_peak"
# Backend health supervisor (obs/health.py): 0 healthy / 1 degraded /
# 2 wedged, set on every transition.
# jtflow: metrics preregistered
HEALTH_GAUGE = "health.state"
# Runtime lock-order sanitizer (obs/sync.py, JEPSEN_TPU_SYNC_TRACE=1):
# wrapped-lock acquisitions and distinct witnessed order edges, folded
# in by sync.publish_metrics() — zeros (sanitizer off) permitted, never
# absent.
# jtflow: metrics preregistered
SYNC_COUNTERS = ("sync.lock_acquisitions", "sync.order_edges")
# Scenario factory (campaign/, ISSUE 15): executed specs, fail-fast
# aborted live runs, per-key checks, falsifying runs, ddmin shrinker
# candidate checks + batched launches, banked minimal witnesses, and
# the regression-corpus replay accounting — pre-registered so every
# capture's metrics.json carries them (zeros permitted, never absent;
# campaign_stats() is the bench/web reader).
# jtflow: metrics preregistered
CAMPAIGN_COUNTERS = ("campaign.specs", "campaign.aborted_runs",
                     "campaign.keys_checked",
                     "campaign.keys_skipped_hard",
                     "campaign.runs_falsified",
                     "campaign.shrink_checks", "campaign.shrink_launches",
                     "campaign.banked", "campaign.replayed",
                     "campaign.replay_failures")
# Occupancy/effectiveness gauges: distinct anomaly signatures the last
# triage pass produced, the last shrink's minimal/original op ratio,
# and end-to-end scenario throughput.
# jtflow: metrics preregistered
CAMPAIGN_GAUGES = ("campaign.unique_signatures", "campaign.shrink_ratio",
                   "campaign.specs_per_sec")
# Scaling ledger (obs/ledger.py, ISSUE 16): launch-level time
# attribution folded live into the capture's registry — launches,
# per-bucket seconds (encode / H2D / compile / useful execute / bucket
# padding / straggler wait / host dispatch gap) and H2D bytes — behind
# obs.ledger_stats(), the bench record's `ledger` object and the
# /metrics jepsen_tpu_ledger_* families. Pre-registered so the
# artifacts carry zeros, never absences, even for runs that never
# launch (the degraded bench paths included).
# jtflow: metrics preregistered
LEDGER_COUNTERS = ("ledger.launches", "ledger.encode_s", "ledger.h2d_s",
                   "ledger.h2d_bytes", "ledger.compile_s",
                   "ledger.execute_s", "ledger.padding_s",
                   "ledger.straggler_s", "ledger.dispatch_gap_s",
                   "ledger.spill_read_s", "ledger.spill_write_s")
# Last-launch occupancy: real/padded step fill and real/padded batch
# fill of the most recent decomposed launch.
# jtflow: metrics preregistered
LEDGER_GAUGES = ("ledger.step_fill", "ledger.batch_fill")
# Serve SLO gauges (obs/ledger.py RollingWindow): rolling-window
# p50/p99 request latency and the burn rate (breach fraction over the
# error budget) — the /live SLO cells and ledger_stats' slo_* fields.
# jtflow: metrics preregistered
SLO_GAUGES = ("serve.slo_p50_s", "serve.slo_p99_s",
              "serve.slo_burn_rate")
# Fleet router (serve/router.py + serve/fleet.py, ISSUE 18): requests
# admitted by the shape-affine router, spillover re-routes past an
# unavailable replica, upstream forward failures, no-replica-available
# rejections, and completed zero-downtime restarts — pre-registered so
# every capture's metrics.json carries them (zeros permitted, never
# absent; fleet_stats() is the bench/web reader).
# jtflow: metrics preregistered
FLEET_COUNTERS = ("fleet.requests", "fleet.spillover",
                  "fleet.replica_errors", "fleet.rejected",
                  "fleet.restarts")
# Fleet occupancy: replicas registered with the router and how many of
# them are currently routable (ready + not degraded/wedged/down).
# jtflow: metrics preregistered
FLEET_GAUGES = ("fleet.replicas", "fleet.replicas_ready")
# Out-of-core spill tier (store/spill.py + store/encode_cache.py GC,
# ISSUE 20): disk-tier transfer counts and bytes in each direction,
# in-RAM window evictions, encode-cache LRU collections — pre-
# registered so every capture's metrics.json carries them (zeros
# permitted, never absent; longhaul_stats() is the bench/web reader).
# jtflow: metrics preregistered
SPILL_COUNTERS = ("spill.writes", "spill.reads",
                  "spill.bytes_written", "spill.bytes_read",
                  "spill.evictions", "encode.cache_evictions")
# Spill-tier occupancy: last measured checkpoint compression ratio
# (raw packed bytes / stored bytes; >1 means the canon-quotient codec
# beat raw) and the long-haul lane's peak RSS growth in MiB.
# jtflow: metrics preregistered
SPILL_GAUGES = ("spill.compress_ratio", "spill.peak_rss_mb")

_NULL_TRACER = Tracer(enabled=False)
_NULL_METRICS = MetricsRegistry(enabled=False)
_NULL_LEDGER = Ledger(enabled=False)


class Capture:
    """One active telemetry scope: a tracer + registry pair, optionally
    bound to an output directory the artifacts land in on exit."""

    def __init__(self, out_dir: Optional[str | Path] = None,
                 enabled: bool = True, with_ledger: bool = True):
        self.enabled = enabled
        self.out_dir = Path(out_dir) if out_dir is not None else None
        self.tracer = Tracer(enabled=enabled)
        self.metrics = MetricsRegistry(enabled=enabled)
        # The scaling ledger (obs/ledger.py): in-memory always; file-
        # backed (ledger-<proc>.jsonl next to telemetry.jsonl, via a
        # writer thread joined on write()) when the capture has a run
        # dir. `with_ledger=False` is the bench's overhead-control arm.
        self.ledger = Ledger(out_dir=self.out_dir, metrics=self.metrics,
                             enabled=enabled and with_ledger)
        if enabled:
            for name in PHASE_COUNTERS + SCHED_COUNTERS + SWEEP_COUNTERS \
                    + COST_COUNTERS + ELLE_COUNTERS + SERVE_COUNTERS \
                    + SYNC_COUNTERS + CAMPAIGN_COUNTERS \
                    + LEDGER_COUNTERS + FLEET_COUNTERS \
                    + SPILL_COUNTERS:
                self.metrics.counter(name)
            for name in ELLE_GAUGES + SERVE_GAUGES + CAMPAIGN_GAUGES \
                    + LEDGER_GAUGES + SLO_GAUGES + FLEET_GAUGES \
                    + SPILL_GAUGES:
                self.metrics.gauge(name)
            self.metrics.histogram(SERVE_HISTOGRAM)
            self.metrics.gauge(PHASE_GAUGE)
            self.metrics.gauge(SWEEP_GAUGE)
            self.metrics.gauge(DEDUP_GAUGE)
            self.metrics.gauge(COST_GAUGE)
            self.metrics.gauge(HEALTH_GAUGE)
            for name in STREAM_GAUGES:
                self.metrics.gauge(name)
            # Live-export wiring (obs/export.py): appended trace records
            # stream to bus subscribers in exact append order, and a
            # dropped record increments trace.dropped_records the moment
            # it happens (the tracer's meta/footer carry the final
            # count; the metric makes truncation visible live).
            self.tracer.listener = export.bus_publish
            self.tracer.drop_counter = \
                self.metrics.counter("trace.dropped_records")

    def write(self) -> None:
        # Join the ledger writer thread first (idempotent) so
        # ledger-<proc>.jsonl is complete before anyone merges it.
        self.ledger.close()
        if not self.enabled or self.out_dir is None:
            return
        try:
            self.out_dir.mkdir(parents=True, exist_ok=True)
            self.tracer.write(self.out_dir / TELEMETRY_FILE)
            self.metrics.write(self.out_dir / METRICS_FILE)
        except OSError:
            # Telemetry is an observability aid, never a failure mode:
            # a read-only or vanished store dir must not fail the run.
            pass


_lock = threading.Lock()
_stack: list[Capture] = []


def telemetry_enabled() -> bool:
    return os.environ.get("JEPSEN_TPU_TELEMETRY", "1").lower() \
        not in ("0", "false", "no", "off")


# jtsan: returns=Tracer
def get_tracer() -> Tracer:
    """The active capture's tracer, or a no-op singleton."""
    stack = _stack
    return stack[-1].tracer if stack else _NULL_TRACER


# jtsan: returns=MetricsRegistry
def get_metrics() -> MetricsRegistry:
    """The active capture's metrics registry, or a no-op singleton."""
    stack = _stack
    return stack[-1].metrics if stack else _NULL_METRICS


# jtsan: returns=Ledger
def get_ledger() -> Ledger:
    """The active capture's scaling ledger, or a no-op singleton."""
    stack = _stack
    return stack[-1].ledger if stack else _NULL_LEDGER


def capture_active() -> bool:
    """True while some capture is installed (a run is in flight) — the
    /healthz `run_in_flight` field."""
    return bool(_stack)


@contextmanager
def capture(out_dir: Optional[str | Path] = None, *,
            with_ledger: bool = True) -> Iterator[Capture]:
    """Install a fresh tracer+registry as the active telemetry sinks;
    on exit, restore the previous ones and (when `out_dir` is given)
    write telemetry.jsonl + metrics.json + ledger-<proc>.jsonl there.
    Nesting shadows: the innermost capture receives the records (one
    capture per run). `with_ledger=False` disables only the scaling
    ledger — the bench's ledger-overhead control arm."""
    cap = Capture(out_dir, enabled=telemetry_enabled(),
                  with_ledger=with_ledger)
    if not cap.enabled:
        yield cap
        return
    with _lock:
        _stack.append(cap)
    try:
        yield cap
    finally:
        with _lock:
            if cap in _stack:
                _stack.remove(cap)
        cap.write()


# -- kernel phase attribution ----------------------------------------------

def kernel_cost_enabled() -> bool:
    return os.environ.get(KERNEL_COST_ENV, "1").lower() \
        not in ("0", "false", "no", "off")


def _capture_kernel_cost(name: str, fn: Callable, args, kwargs,
                         m: MetricsRegistry) -> None:
    """Deep attribution for one about-to-compile kernel geometry: lower
    the jitted callable (tracing only — no XLA compile, no execution,
    donation-safe because nothing runs) and fold its
    ``cost_analysis()`` flops / bytes-accessed estimates into the
    registry, then note the backend's device-memory high-water mark.
    Pure observability: ANY failure (a non-jit callable, a backend
    without cost analysis, a CPU without memory_stats) is swallowed and
    the pre-registered zeros stand."""
    try:
        lowered = fn.lower(*args, **kwargs)
        ca = lowered.cost_analysis()
        if isinstance(ca, (list, tuple)):   # older jax: one per device
            ca = ca[0] if ca else {}
        flops = float(ca.get("flops", 0.0) or 0.0)
        nbytes = float(ca.get("bytes accessed", 0.0) or 0.0)
        if flops > 0:
            m.counter("wgl.flops").add(flops)
            # jtlint: disable=JTL107 -- bounded family: kernel names are
            # the fixed static set of instrument_kernel call sites; the
            # exporter folds them into one labeled Prometheus family.
            m.gauge(f"wgl.kernel_flops.{name}").set(flops)
        if nbytes > 0:
            m.counter("wgl.bytes_accessed").add(nbytes)
            # jtlint: disable=JTL107 -- bounded family: kernel names are
            # a fixed static set (same argument as wgl.kernel_flops).
            m.gauge(f"wgl.kernel_bytes.{name}").set(nbytes)
    except Exception:
        pass
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats() or {}
        peak = float(stats.get("peak_bytes_in_use",
                               stats.get("bytes_in_use", 0)) or 0)
        if peak > 0:
            m.gauge(COST_GAUGE).set(peak)
    except Exception:
        pass


def instrument_kernel(name: str, fn: Callable) -> Callable:
    """Wrap a jit-compiled kernel callable for compile/execute
    attribution. The FIRST call of a jitted function runs tracing + XLA
    compilation synchronously before dispatch, so its wall time is
    compile-dominated; later calls are steady-state dispatch. The
    wrapper's first-call flag lives with the wrapped fn in the kernel
    caches (ops/wgl2.py / wgl3.py / wgl3_pallas.py _CACHE), so the
    granularity is exactly one flag per compiled geometry — and a
    capture opened after the geometry warmed correctly records only
    execute time (the compile happened outside the run).

    Steady-state times are dispatch wall, NOT device time: kernels
    dispatch asynchronously and callers rely on that (the chunked
    sweeps pipeline windows), so the wrapper never blocks on results.
    Device-true timings are the env-gated jax.profiler trace's job."""
    state = {"first": True}

    def wrapped(*args, **kwargs):
        first = state["first"]
        m = get_metrics()
        if first and m.enabled and kernel_cost_enabled():
            # Deep attribution BEFORE the call (donated operands are
            # still alive): XLA cost_analysis flops/bytes + device
            # memory peak, outside the timed region so compile_s keeps
            # meaning "the first call's wall".
            _capture_kernel_cost(name, fn, args, kwargs, m)
        t0_ns = time.monotonic_ns()
        out = fn(*args, **kwargs)
        t1_ns = time.monotonic_ns()
        dt = (t1_ns - t0_ns) / 1e9
        if first:
            state["first"] = False
            m.counter("wgl.compile_s").add(dt)
            m.counter("wgl.compile_calls").add(1)
            # jtlint: disable=JTL107 -- bounded family: kernel names are
            # the fixed static set of instrument_kernel call sites.
            m.histogram(f"wgl.compile_s.{name}").observe(dt)
            get_tracer().event("wgl.compile", kernel=name,
                               seconds=round(dt, 6))
        else:
            m.counter("wgl.execute_s").add(dt)
            m.counter("wgl.execute_calls").add(1)
            # jtlint: disable=JTL107 -- bounded family: kernel names are
            # the fixed static set of instrument_kernel call sites.
            m.histogram(f"wgl.execute_s.{name}").observe(dt)
        # Scaling ledger (obs/ledger.py): the launch record, enriched
        # by whatever launch_context the call site opened (plan
        # identity, bucket shape, padding, shard layout).
        get_ledger().record_launch(name,
                                   "compile" if first else "execute",
                                   t0_ns, t1_ns)
        return out

    wrapped.__name__ = f"instrumented_{name}"
    return wrapped


def record_check_result(res: dict) -> None:
    """Fold one WGL check result's search metrics into the registry:
    frontier occupancy high-water mark and configs explored (the §5.1
    unit of search work)."""
    m = get_metrics()
    try:
        mf = float(res.get("max_frontier"))
    except (TypeError, ValueError):
        mf = -1.0
    if mf >= 0:
        m.gauge(PHASE_GAUGE).set(mf)
    try:
        cfgs = float(res.get("configs_explored"))
    except (TypeError, ValueError):
        cfgs = 0.0
    if cfgs > 0:
        m.counter("wgl.configs_explored").add(cfgs)
    # Sparse-sweep telemetry (ops/wgl3_sparse.py): live-tile occupancy
    # of the converged tables and which sweep mode the steps ran under.
    # Batched launches report the occupancy column but always sweep
    # dense; the long sweeps report exact per-mode step counts.
    try:
        ratio = float(res.get("live_tile_ratio"))
    except (TypeError, ValueError):
        ratio = -1.0
    if ratio >= 0:
        m.gauge(SWEEP_GAUGE).set(ratio)
    sweep = res.get("sweep")
    if isinstance(sweep, dict):
        mode = sweep.get("mode")
        if mode in ("sparse", "dense", "mixed"):
            # jtlint: disable=JTL107 -- bounded family: mode is checked
            # against the closed {sparse, dense, mixed} set on the line
            # above; all three names are pre-registered by capture().
            m.counter(f"wgl.sweep_checks_{mode}").add(1)
        for key in ("steps_sparse", "steps_dense"):
            try:
                v = int(sweep.get(key, 0))
            except (TypeError, ValueError):
                v = 0
            if v > 0:
                # jtlint: disable=JTL107 -- bounded family: key iterates
                # the closed two-element tuple above; both names are
                # pre-registered by capture().
                m.counter(f"wgl.sweep_{key}").add(v)
        try:
            ovf = int(sweep.get("overflow_rounds", 0))
        except (TypeError, ValueError):
            ovf = 0
        if ovf > 0:
            # The previously-silent sparse fallback (ISSUE 10): rounds
            # where work-list overflow forced a dense sweep.
            m.counter("wgl.sparse_overflow_rounds").add(ovf)
    elif ratio >= 0:
        # A dense batched launch: no sweep record, but the measured
        # occupancy proves it ran the dense kernels.
        m.counter("wgl.sweep_checks_dense").add(1)
    # Frontier canonicalization accounting (ops/canon.py): configs
    # removed by the symmetry-reduction pass and its effectiveness
    # ratio over the canon-applied steps.
    dedup = res.get("dedup")
    if isinstance(dedup, dict):
        try:
            pruned = int(dedup.get("configs_pruned", 0))
        except (TypeError, ValueError):
            pruned = 0
        if pruned > 0:
            m.counter("wgl.configs_pruned").add(pruned)
        try:
            dr = float(dedup.get("frontier_dedup_ratio"))
        except (TypeError, ValueError):
            dr = -1.0
        if dr >= 0:
            m.gauge(DEDUP_GAUGE).set(dr)


def active_profile_hash() -> str:
    """The active tuning profile's short hash (tune/profile.py), or
    "default". Never initializes a jax backend (the profile key resolves
    only when jax is already imported) and never raises — safe to stamp
    on the bench's degraded/unreachable-backend records."""
    try:
        from ..tune import profile

        return profile.profile_hash()
    except Exception:
        return "default"


def kernel_phases(metrics: Optional[MetricsRegistry] = None) -> dict:
    """The bench's kernel-phase breakdown, from a registry snapshot.
    With no registry (backend unreachable, telemetry disabled) every
    timing field is zero — the contract is "zeros permitted, never
    absent". `profile_hash` identifies the tuning profile the process
    resolved (ISSUE 4: every bench record names its profile, the
    degraded path included — "default" when none applies). ISSUE 8
    grew the deep-attribution fields: `flops` / `bytes` (summed XLA
    cost_analysis estimates over every kernel geometry compiled under
    the capture) and `device_mem_peak` (the backend allocator's
    peak-bytes-in-use high-water mark) — zeros on backends that report
    neither, never absent."""
    out = {"compile_s": 0.0, "execute_s": 0.0, "encode_s": 0.0,
           "frontier_peak": 0, "flops": 0.0, "bytes": 0.0,
           "device_mem_peak": 0, "profile_hash": active_profile_hash()}
    if metrics is None or not metrics.enabled:
        return out
    snap = metrics.snapshot()

    def counter_value(key: str) -> float:
        rec = snap.get(key)
        return round(rec["value"], 4) if rec \
            and rec.get("type") == "counter" else 0.0

    out["compile_s"] = counter_value("wgl.compile_s")
    out["execute_s"] = counter_value("wgl.execute_s")
    out["encode_s"] = counter_value("encode.encode_s")
    out["flops"] = counter_value("wgl.flops")
    out["bytes"] = counter_value("wgl.bytes_accessed")
    fp = snap.get(PHASE_GAUGE)
    if fp and fp.get("max") is not None:
        out["frontier_peak"] = int(fp["max"])
    mem = snap.get(COST_GAUGE)
    if mem and mem.get("max") is not None:
        out["device_mem_peak"] = int(mem["max"])
    return out


def sched_stats(metrics: Optional[MetricsRegistry] = None) -> dict:
    """The corpus scheduler's bench contract fields, from a registry
    snapshot: padding_waste (padded/real steps over every scheduled
    launch in the capture) and cache_hit_rate (kernel-LRU hits over
    lookups). Zeros when no registry / no launches — like
    kernel_phases, the contract is "zeros permitted, never absent"."""
    out = {"padding_waste": 0.0, "cache_hit_rate": 0.0}
    if metrics is None or not metrics.enabled:
        return out
    snap = metrics.snapshot()

    def counter_value(key: str) -> float:
        rec = snap.get(key)
        return rec["value"] if rec \
            and rec.get("type") == "counter" else 0.0

    real = counter_value("sched.steps_real")
    padded = counter_value("sched.steps_padded")
    if real:
        out["padding_waste"] = round(padded / real, 4)
    hits = counter_value("sched.cache_hits")
    lookups = hits + counter_value("sched.cache_misses")
    if lookups:
        out["cache_hit_rate"] = round(hits / lookups, 4)
    return out


def sweep_stats(metrics: Optional[MetricsRegistry] = None) -> dict:
    """The sparse-sweep engine's bench/web contract fields, from a
    registry snapshot: the live-tile-ratio gauge (last/min/max) and the
    per-mode step/check counters. Zeros when no registry / no dense runs
    — the contract is "zeros permitted, never absent"."""
    out = {"live_tile_ratio": 0.0, "steps_sparse": 0, "steps_dense": 0,
           "checks_sparse": 0, "checks_dense": 0, "checks_mixed": 0,
           "configs_pruned": 0, "sparse_overflow_rounds": 0,
           "frontier_dedup_ratio": 0.0}
    if metrics is None or not metrics.enabled:
        return out
    snap = metrics.snapshot()

    def counter_value(key: str) -> int:
        rec = snap.get(key)
        return int(rec["value"]) if rec \
            and rec.get("type") == "counter" else 0

    out["steps_sparse"] = counter_value("wgl.sweep_steps_sparse")
    out["steps_dense"] = counter_value("wgl.sweep_steps_dense")
    out["checks_sparse"] = counter_value("wgl.sweep_checks_sparse")
    out["checks_dense"] = counter_value("wgl.sweep_checks_dense")
    out["checks_mixed"] = counter_value("wgl.sweep_checks_mixed")
    out["configs_pruned"] = counter_value("wgl.configs_pruned")
    out["sparse_overflow_rounds"] = \
        counter_value("wgl.sparse_overflow_rounds")
    g = snap.get(SWEEP_GAUGE)
    if g and g.get("last") is not None:
        out["live_tile_ratio"] = round(float(g["last"]), 4)
    g = snap.get(DEDUP_GAUGE)
    if g and g.get("last") is not None:
        out["frontier_dedup_ratio"] = round(float(g["last"]), 4)
    return out


def elle_stats(metrics: Optional[MetricsRegistry] = None) -> dict:
    """The elle closure engine's bench/web contract fields, from a
    registry snapshot: per-route graph counts, launch/round accounting,
    the streamed-session counters, and the occupancy gauges. Zeros when
    no registry / no elle checks — like every reader here, the contract
    is "zeros permitted, never absent"."""
    out = {"graphs_dense": 0, "graphs_batched": 0, "graphs_tiled": 0,
           "graphs_oracle": 0, "closure_launches": 0,
           "tiled_rounds_sparse": 0, "tiled_rounds_dense": 0,
           "stream_txns": 0, "stream_rechecks": 0,
           "batch_fill": 0.0, "tile_density": 0.0}
    if metrics is None or not metrics.enabled:
        return out
    snap = metrics.snapshot()

    def counter_value(key: str) -> int:
        rec = snap.get(key)
        return int(rec["value"]) if rec \
            and rec.get("type") == "counter" else 0

    out["graphs_dense"] = counter_value("elle.graphs_dense")
    out["graphs_batched"] = counter_value("elle.graphs_batched")
    out["graphs_tiled"] = counter_value("elle.graphs_tiled")
    out["graphs_oracle"] = counter_value("elle.graphs_oracle")
    out["closure_launches"] = counter_value("elle.closure_launches")
    out["tiled_rounds_sparse"] = counter_value("elle.tiled_rounds_sparse")
    out["tiled_rounds_dense"] = counter_value("elle.tiled_rounds_dense")
    out["stream_txns"] = counter_value("elle.stream_txns")
    out["stream_rechecks"] = counter_value("elle.stream_rechecks")
    g = snap.get("elle.batch_fill")
    if g and g.get("last") is not None:
        out["batch_fill"] = round(float(g["last"]), 4)
    g = snap.get("elle.tile_density")
    if g and g.get("last") is not None:
        out["tile_density"] = round(float(g["last"]), 4)
    return out


def serve_stats(metrics: Optional[MetricsRegistry] = None) -> dict:
    """The serve daemon's bench/web contract fields (serve/, ISSUE 13),
    from a registry snapshot: request/batch/admission counters, the
    queue-depth/batch-fill occupancy gauges, and the request-latency
    quantiles. Zeros when no registry / no served requests — like every
    reader here, the contract is "zeros permitted, never absent"."""
    out = {"requests": 0, "batches": 0, "coalesced_requests": 0,
           "shed_cpu": 0, "rejected_inflight": 0, "rejected_wedged": 0,
           "webhooks": 0, "queue_depth": 0, "batch_fill": 0.0,
           "latency_p50_s": 0.0, "latency_p99_s": 0.0}
    if metrics is None or not metrics.enabled:
        return out
    snap = metrics.snapshot()

    def counter_value(key: str) -> int:
        rec = snap.get(key)
        return int(rec["value"]) if rec \
            and rec.get("type") == "counter" else 0

    out["requests"] = counter_value("serve.requests")
    out["batches"] = counter_value("serve.batches")
    out["coalesced_requests"] = counter_value("serve.coalesced_requests")
    out["shed_cpu"] = counter_value("serve.shed_cpu")
    out["rejected_inflight"] = counter_value("serve.rejected_inflight")
    out["rejected_wedged"] = counter_value("serve.rejected_wedged")
    out["webhooks"] = counter_value("serve.webhooks")
    g = snap.get("serve.queue_depth")
    if g and g.get("last") is not None:
        out["queue_depth"] = int(g["last"])
    g = snap.get("serve.batch_fill")
    if g and g.get("last") is not None:
        out["batch_fill"] = round(float(g["last"]), 4)
    h = snap.get("serve.request_latency_s")
    if h and h.get("p50") is not None:
        out["latency_p50_s"] = round(float(h["p50"]), 6)
        out["latency_p99_s"] = round(float(h.get("p99") or 0.0), 6)
    return out


def fleet_stats(metrics: Optional[MetricsRegistry] = None) -> dict:
    """The fleet router's bench/web contract fields (serve/router.py,
    ISSUE 18), from a registry snapshot: routed/spillover/error/reject
    counters, completed zero-downtime restarts, and the replica
    occupancy gauges. Zeros when no registry / no router — like every
    reader here, the contract is "zeros permitted, never absent"."""
    out = {"requests": 0, "spillover": 0, "replica_errors": 0,
           "rejected": 0, "restarts": 0, "replicas": 0,
           "replicas_ready": 0}
    if metrics is None or not metrics.enabled:
        return out
    snap = metrics.snapshot()
    for key, name in (("requests", "fleet.requests"),
                      ("spillover", "fleet.spillover"),
                      ("replica_errors", "fleet.replica_errors"),
                      ("rejected", "fleet.rejected"),
                      ("restarts", "fleet.restarts")):
        rec = snap.get(name)
        if rec and rec.get("type") == "counter":
            out[key] = int(rec["value"])
    for key, name in (("replicas", "fleet.replicas"),
                      ("replicas_ready", "fleet.replicas_ready")):
        g = snap.get(name)
        if g and g.get("last") is not None:
            out[key] = int(g["last"])
    return out


def longhaul_stats(metrics: Optional[MetricsRegistry] = None) -> dict:
    """The out-of-core spill tier's bench/web contract fields
    (store/spill.py + the encode-cache GC, ISSUE 20), from a registry
    snapshot: disk-tier transfer counts/bytes both directions, window
    and cache evictions, the last measured checkpoint compression
    ratio, and the long-haul lane's peak RSS growth. Zeros when no
    registry / nothing spilled — like every reader here, the contract
    is "zeros permitted, never absent"."""
    out = {"spill_writes": 0, "spill_reads": 0,
           "spill_bytes_written": 0, "spill_bytes_read": 0,
           "spill_evictions": 0, "cache_evictions": 0,
           "compress_ratio": 0.0, "peak_rss_mb": 0.0}
    if metrics is None or not metrics.enabled:
        return out
    snap = metrics.snapshot()
    for key, name in (("spill_writes", "spill.writes"),
                      ("spill_reads", "spill.reads"),
                      ("spill_bytes_written", "spill.bytes_written"),
                      ("spill_bytes_read", "spill.bytes_read"),
                      ("spill_evictions", "spill.evictions"),
                      ("cache_evictions", "encode.cache_evictions")):
        rec = snap.get(name)
        if rec and rec.get("type") == "counter":
            out[key] = int(rec["value"])
    for key, name in (("compress_ratio", "spill.compress_ratio"),
                      ("peak_rss_mb", "spill.peak_rss_mb")):
        g = snap.get(name)
        if g and g.get("last") is not None:
            out[key] = round(float(g["last"]), 6)
    return out


def ledger_stats(metrics: Optional[MetricsRegistry] = None) -> dict:
    """The scaling ledger's bench/web contract fields (obs/ledger.py,
    ISSUE 16), from a registry snapshot: launch count, the per-bucket
    second totals (useful execute vs padding/straggler waste, encode,
    H2D, compile, host dispatch gap), H2D bytes, the last launch's
    fill gauges, and the serve daemon's rolling-window SLO gauges.
    Zeros when no registry / no launches — like every reader here, the
    contract is "zeros permitted, never absent"."""
    out = {"launches": 0, "encode_s": 0.0, "h2d_s": 0.0, "h2d_bytes": 0,
           "compile_s": 0.0, "execute_s": 0.0, "padding_s": 0.0,
           "straggler_s": 0.0, "dispatch_gap_s": 0.0,
           "spill_read_s": 0.0, "spill_write_s": 0.0,
           "step_fill": 0.0, "batch_fill": 0.0,
           "slo_p50_s": 0.0, "slo_p99_s": 0.0, "slo_burn_rate": 0.0}
    if metrics is None or not metrics.enabled:
        return out
    snap = metrics.snapshot()

    def counter_value(key: str) -> float:
        rec = snap.get(key)
        return round(rec["value"], 6) if rec \
            and rec.get("type") == "counter" else 0.0

    out["launches"] = int(counter_value("ledger.launches"))
    out["encode_s"] = counter_value("ledger.encode_s")
    out["h2d_s"] = counter_value("ledger.h2d_s")
    out["h2d_bytes"] = int(counter_value("ledger.h2d_bytes"))
    out["compile_s"] = counter_value("ledger.compile_s")
    out["execute_s"] = counter_value("ledger.execute_s")
    out["padding_s"] = counter_value("ledger.padding_s")
    out["straggler_s"] = counter_value("ledger.straggler_s")
    out["dispatch_gap_s"] = counter_value("ledger.dispatch_gap_s")
    out["spill_read_s"] = counter_value("ledger.spill_read_s")
    out["spill_write_s"] = counter_value("ledger.spill_write_s")
    for key, name in (("step_fill", "ledger.step_fill"),
                      ("batch_fill", "ledger.batch_fill"),
                      ("slo_p50_s", "serve.slo_p50_s"),
                      ("slo_p99_s", "serve.slo_p99_s"),
                      ("slo_burn_rate", "serve.slo_burn_rate")):
        g = snap.get(name)
        if g and g.get("last") is not None:
            out[key] = round(float(g["last"]), 6)
    return out


def campaign_stats(metrics: Optional[MetricsRegistry] = None) -> dict:
    """The scenario factory's bench/web contract fields (campaign/,
    ISSUE 15), from a registry snapshot: spec/abort/check/falsification
    counters, shrinker accounting, bank and replay counters, and the
    signature/ratio/throughput gauges. Zeros when no registry / no
    campaign ran — like every reader here, the contract is "zeros
    permitted, never absent"."""
    out = {"specs": 0, "aborted_runs": 0, "keys_checked": 0,
           "keys_skipped_hard": 0, "runs_falsified": 0,
           "shrink_checks": 0, "shrink_launches": 0,
           "banked": 0, "replayed": 0, "replay_failures": 0,
           "unique_signatures": 0, "shrink_ratio": 0.0,
           "specs_per_sec": 0.0}
    if metrics is None or not metrics.enabled:
        return out
    snap = metrics.snapshot()

    def counter_value(key: str) -> int:
        rec = snap.get(key)
        return int(rec["value"]) if rec \
            and rec.get("type") == "counter" else 0

    out["specs"] = counter_value("campaign.specs")
    out["aborted_runs"] = counter_value("campaign.aborted_runs")
    out["keys_checked"] = counter_value("campaign.keys_checked")
    out["keys_skipped_hard"] = \
        counter_value("campaign.keys_skipped_hard")
    out["runs_falsified"] = counter_value("campaign.runs_falsified")
    out["shrink_checks"] = counter_value("campaign.shrink_checks")
    out["shrink_launches"] = counter_value("campaign.shrink_launches")
    out["banked"] = counter_value("campaign.banked")
    out["replayed"] = counter_value("campaign.replayed")
    out["replay_failures"] = counter_value("campaign.replay_failures")
    g = snap.get("campaign.unique_signatures")
    if g and g.get("last") is not None:
        out["unique_signatures"] = int(g["last"])
    g = snap.get("campaign.shrink_ratio")
    if g and g.get("last") is not None:
        out["shrink_ratio"] = round(float(g["last"]), 4)
    g = snap.get("campaign.specs_per_sec")
    if g and g.get("last") is not None:
        out["specs_per_sec"] = round(float(g["last"]), 2)
    return out


# -- env-gated jax.profiler capture ----------------------------------------

def jax_trace_enabled() -> bool:
    return os.environ.get("JEPSEN_TPU_JAX_TRACE", "").lower() \
        in ("1", "true", "yes", "on")


@contextmanager
def maybe_jax_trace(out_dir: Optional[str | Path]) -> Iterator[None]:
    """jax.profiler.trace into <out_dir>/jax_trace when the env gate
    (JEPSEN_TPU_JAX_TRACE=1) is set and a run dir exists; a plain no-op
    otherwise — including when jax itself is unimportable or the
    profiler refuses (profiling is never a failure mode)."""
    if out_dir is None or not jax_trace_enabled():
        yield
        return
    ctx = None
    try:
        import jax

        ctx = jax.profiler.trace(str(Path(out_dir) / "jax_trace"))
        ctx.__enter__()
    except Exception:
        ctx = None
    try:
        yield
    finally:
        if ctx is not None:
            try:
                ctx.__exit__(None, None, None)
            except Exception:
                pass
