"""Runtime lock-order sanitizer — the dynamic half of jtsan.

The static model (analysis/flow/sync.py) predicts which lock orders
*may* happen; this module records which orders *do* happen, so the two
can be cross-validated in tier-1 (tests/test_jtsan.py): every witnessed
acquisition order must be an edge the static model predicted, and no
pair may be witnessed in both directions (a live inversion — the
deadlock JTL502 exists to prevent). Disagreement in either direction is
a failure: an unpredicted witness means the static resolution went
blind somewhere (fix the model before trusting its race verdicts); a
witnessed inversion means the tree has the bug.

Zero-cost discipline: wrapping is decided at LOCK CONSTRUCTION time by
``maybe_wrap(lock, name)`` — with ``JEPSEN_TPU_SYNC_TRACE`` unset (the
default, production included) it returns the raw lock untouched, so the
hot paths pay exactly one env check per lock *created*, never per
acquisition. With ``JEPSEN_TPU_SYNC_TRACE=1`` each wrapped lock records,
per acquisition, an ordered edge (held-lock -> acquired-lock) into a
process-global witness table keyed by the same canonical names the
static model derives (``serve.scheduler.CoalescingScheduler._lock``),
plus held-while-blocking events when a wrapped Condition is waited on
with other wrapped locks held.

The witness table is plain dicts under one RAW ``threading.Lock`` (never
itself wrapped — recording an acquisition must not recurse into
recording) with a per-thread held stack in ``threading.local``.

``publish_metrics()`` folds the table into the active obs capture
(``sync.lock_acquisitions`` / ``sync.order_edges`` counters, pre-
registered like every contract key) — called by the cross-validation
test and at serve-daemon shutdown; doc/telemetry.md documents the
records and the env gate.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

SYNC_TRACE_ENV = "JEPSEN_TPU_SYNC_TRACE"

_table_lock = threading.Lock()          # raw on purpose (see docstring)
_held = threading.local()
# (outer name, inner name) -> count of witnessed acquisitions in that
# order; _acquisitions counts every wrapped acquisition; _blocking holds
# (held name, event label) pairs witnessed while blocked.
_edges: dict[tuple[str, str], int] = {}
_acquisitions = 0
_blocking: dict[tuple[str, str], int] = {}


def sync_trace_enabled() -> bool:
    return os.environ.get(SYNC_TRACE_ENV, "").lower() \
        in ("1", "true", "yes", "on")


def reset_witness() -> None:
    """Clear the witness table (test isolation)."""
    global _acquisitions
    with _table_lock:
        _edges.clear()
        _blocking.clear()
        _acquisitions = 0


def witnessed_edges() -> dict[tuple[str, str], int]:
    with _table_lock:
        return dict(_edges)


def witnessed_blocking() -> dict[tuple[str, str], int]:
    with _table_lock:
        return dict(_blocking)


def witness_summary() -> dict:
    """The telemetry view: counts + the edge list, JSON-shaped."""
    with _table_lock:
        return {
            "acquisitions": _acquisitions,
            "edges": sorted([a, b] for a, b in _edges),
            "held_while_blocking": sorted(
                [h, w] for h, w in _blocking),
        }


def _stack() -> list:
    st = getattr(_held, "stack", None)
    if st is None:
        st = _held.stack = []
    return st


def _note_acquired(name: str) -> None:
    global _acquisitions
    st = _stack()
    with _table_lock:
        _acquisitions += 1
        for outer in st:
            if outer != name:
                key = (outer, name)
                _edges[key] = _edges.get(key, 0) + 1
    st.append(name)


def _note_released(name: str) -> None:
    st = _stack()
    # Release order can legitimately differ from reverse-acquisition
    # (lock juggling); remove the most recent matching entry.
    for i in range(len(st) - 1, -1, -1):
        if st[i] == name:
            del st[i]
            break


def _note_blocking(name: str, what: str) -> None:
    st = _stack()
    held = [h for h in st if h != name]
    if not held:
        return
    with _table_lock:
        for h in held:
            key = (h, what)
            _blocking[key] = _blocking.get(key, 0) + 1


class TracingLock:
    """Proxy over a Lock/RLock/Condition recording acquisition order.
    Context-manager use, acquire/release, and the Condition surface
    (wait/notify/notify_all) are instrumented; everything else
    delegates. ``wait`` keeps the lock on the held stack — the
    condition reacquires before returning, so the thread's held set is
    unchanged from the model's point of view."""

    __slots__ = ("_inner", "name")

    def __init__(self, inner, name: str):
        self._inner = inner
        self.name = name

    # -- lock surface -----------------------------------------------------
    def acquire(self, *a, **kw):
        ok = self._inner.acquire(*a, **kw)
        if ok:
            _note_acquired(self.name)
        return ok

    def release(self):
        _note_released(self.name)
        return self._inner.release()

    def __enter__(self):
        self._inner.__enter__()
        _note_acquired(self.name)
        return self

    def __exit__(self, *exc):
        _note_released(self.name)
        return self._inner.__exit__(*exc)

    def locked(self):
        return self._inner.locked()

    # -- condition surface ------------------------------------------------
    def wait(self, timeout: Optional[float] = None):
        _note_blocking(self.name, "Condition.wait")
        return self._inner.wait(timeout)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        _note_blocking(self.name, "Condition.wait")
        return self._inner.wait_for(predicate, timeout)

    def notify(self, n: int = 1):
        return self._inner.notify(n)

    def notify_all(self):
        return self._inner.notify_all()

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def __repr__(self):
        return f"TracingLock({self.name!r}, {self._inner!r})"


def maybe_wrap(lock, name: str):
    """Wrap `lock` for witness recording when JEPSEN_TPU_SYNC_TRACE is
    set; return it untouched otherwise. `name` must be the canonical id
    the static model derives for this lock
    (``<module>.<Class>.<attr>`` under the package root) — JTL506
    verifies the literal against the model, so a rename cannot leave a
    stale witness name behind."""
    if not sync_trace_enabled():
        return lock
    return TracingLock(lock, name)


def cross_validate(predicted: set) -> list[str]:
    """Compare the witness table against the static model's edge set.
    Returns a list of human-readable problems (empty = the halves
    agree): witnessed-but-unmodeled edges, and pairs witnessed in BOTH
    directions (a live lock-order inversion — the runtime counterpart
    of a JTL502 cycle)."""
    problems: list[str] = []
    witnessed = witnessed_edges()
    for (a, b), n in sorted(witnessed.items()):
        if (a, b) not in predicted:
            problems.append(
                f"witnessed lock order {a} -> {b} ({n}x) is not an edge "
                f"the static model predicts — the jtsan resolution is "
                f"blind to this path")
        if (b, a) in witnessed and a < b:
            problems.append(
                f"lock-order inversion witnessed live: {a} -> {b} AND "
                f"{b} -> {a} — two threads taking opposite ends deadlock")
    return problems


def publish_metrics() -> dict:
    """Fold the witness table into the active obs capture (pre-
    registered ``sync.lock_acquisitions`` / ``sync.order_edges``) and
    return the summary dict."""
    from . import get_metrics

    summary = witness_summary()
    m = get_metrics()
    m.counter("sync.lock_acquisitions").add(summary["acquisitions"])
    m.counter("sync.order_edges").add(len(summary["edges"]))
    return summary
