"""obs/ledger.py — the scaling ledger: launch-level time attribution.

ROADMAP item 1 names the problem this module answers: the 8-device
dryrun measures ``efficiency_vs_single: 0.14`` and nothing in the
system can say which of encode / H2D / compile / padding /
straggler-wait / dispatch-gap eats the other 86%. The ledger is the
instrument: every dispatch through the KernelPlan spine emits a
:class:`LaunchRecord` (plan ``cache_key()``, bucket shape,
real-vs-padded steps and batch fill, phase wall, and the host-side gap
since the previous instrumented event), encode and H2D staging emit
sibling event records, and :func:`attribute` decomposes a measured
wall-clock window into named loss buckets that must account for >=95%
of it.

Layering: stdlib-only, imported BY ``obs/__init__`` (never the other
way at module scope). Emission is two-layered so call sites stay
decoupled from the spine:

  * ``instrument_kernel`` (obs/__init__) emits the launch record — it
    already wraps every compiled kernel, so every dispatch is covered.
  * callers that KNOW the launch economics (sched/engine.py bucket
    launches, parallel/dense.py sharded launches, plan/dispatch.py's
    choke point) open a :func:`launch_context` around the call; the
    emission layer folds the context's plan identity / padding / shard
    fields into the record without any plumbing through the kernel
    caches.

Per-process artifacts: a file-backed ledger streams records to
``ledger-<proc>.jsonl`` next to the store artifacts via a writer
thread (joined on close). The first line is a clock handshake —
``time.monotonic_ns()`` and ``time.time()`` sampled back to back — so
:func:`merge_ledgers` can fold a pod's per-process files into one
wall-clock timeline without trusting any cross-host monotonic
relationship (skew between processes shifts that process's records
coherently; ordering within a process is always exact).

Loss-bucket decomposition (doc/telemetry.md "Scaling ledger" chapter):
per execute/fetch record with padding context, ``fill = steps_real /
steps_padded`` splits the span into useful and waste; the waste splits
into straggler wait (the mesh idling behind its slowest shard:
``D * max(shard_real) - sum(shard_real)`` of the padded-step excess)
and pure bucket padding. Host time not covered by any instrumented
span inside the window is the dispatch gap; wall outside the
instrumented window is ``other_s``.
"""

from __future__ import annotations

import contextvars
import json
import os
import queue
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Iterator, Optional

from .sync import maybe_wrap

LEDGER_FILE_PREFIX = "ledger-"
LEDGER_SCHEMA = "ledger/1"
PROC_ENV = "JEPSEN_TPU_PROC"
LEDGER_ENV = "JEPSEN_TPU_LEDGER"

# The closed loss-bucket set every attribution reports (zeros
# permitted, never absent — the bench/report contract). execute_s is
# the USEFUL share of device-facing spans; padding_s / straggler_s are
# the waste carved out of them; dispatch_gap_s is host time inside the
# window no instrumented span covers; other_s is wall outside the
# instrumented window.
BUCKETS = ("encode_s", "h2d_s", "compile_s", "execute_s",
           "padding_s", "straggler_s", "dispatch_gap_s",
           "spill_read_s", "spill_write_s", "other_s")

# Record kinds whose spans carry padding context and decompose into
# useful/padding/straggler (dispatch wall + the blocking result fetch).
_DEVICE_KINDS = ("execute", "fetch")

# Host disk-tier spans of the out-of-core checking tier (store/spill.py):
# each maps 1:1 onto its same-named `_s` bucket above.
_SPILL_KINDS = ("spill_read", "spill_write")


def ledger_enabled() -> bool:
    return os.environ.get(LEDGER_ENV, "1").lower() \
        not in ("0", "false", "no", "off")


def process_index() -> int:
    """This process's ledger index (the <proc> in ledger-<proc>.jsonl).
    Multi-process launchers export JEPSEN_TPU_PROC; single-process runs
    are proc 0."""
    try:
        return int(os.environ.get(PROC_ENV, "0"))
    except ValueError:
        return 0


# -- launch context ---------------------------------------------------------
# Call sites that know the launch economics (bucket shape, padding,
# shard layout, plan identity) publish them here; the emission layer
# (instrument_kernel / record_fetch) folds them into the record. A
# contextvar so nested captures, threads and the serve daemon's
# dispatch thread each see their own context.

_CTX: contextvars.ContextVar[Optional[dict]] = \
    contextvars.ContextVar("jepsen_tpu_ledger_ctx", default=None)


@contextmanager
def launch_context(**fields: Any) -> Iterator[None]:
    """Annotate every ledger record emitted inside the block with these
    launch fields (plan cache_key/family/label, steps_real/padded,
    batch_real/padded, shard_real, n_shards). Nesting merges — inner
    fields win."""
    cur = _CTX.get()
    tok = _CTX.set({**cur, **fields} if cur else dict(fields))
    try:
        yield
    finally:
        _CTX.reset(tok)


def current_context() -> Optional[dict]:
    return _CTX.get()


def plan_context(plan: Any) -> dict:
    """The launch-context fields a KernelPlan contributes to its
    records: cache identity, family/label, and the mesh's shard
    count/shape."""
    fields: dict[str, Any] = {
        "cache_key": str(plan.cache_key()),
        "plan_family": plan.family,
        "label": plan.label,
    }
    mesh = getattr(plan, "mesh", None)
    if mesh is not None:
        fields["n_shards"] = int(mesh.total)
        fields["mesh_shape"] = list(mesh.shape)
    else:
        fields["n_shards"] = 1
    return fields


def shard_real_steps(step_counts: list[int], n_shards: int) -> list[int]:
    """Per-shard real step totals for a contiguous [B]-axis partition
    of a padded batch (the sharded routes split the batch into
    n_shards equal contiguous blocks)."""
    b = len(step_counts)
    if n_shards <= 1 or b % n_shards:
        return [int(sum(step_counts))]
    per = b // n_shards
    return [int(sum(step_counts[i * per:(i + 1) * per]))
            for i in range(n_shards)]


# -- records ----------------------------------------------------------------

_CTX_FIELDS = ("cache_key", "plan_family", "label", "mesh_shape",
               "n_shards", "batch_real", "batch_padded",
               "steps_real", "steps_padded", "shard_real",
               "shard_packed")


@dataclass
class LaunchRecord:
    """One ledger line: an instrumented span plus its launch context.
    kind is the phase — "compile" / "execute" (instrument_kernel),
    "fetch" (the blocking device->host result wait), "encode" (host
    history->tensor encoding) or "h2d" (host->device staging, with
    bytes)."""

    kind: str
    kernel: str = ""
    t0_ns: int = 0
    t1_ns: int = 0
    gap_s: float = 0.0
    bytes: int = 0
    ctx: dict = field(default_factory=dict)

    @property
    def dur_s(self) -> float:
        return max(0, self.t1_ns - self.t0_ns) / 1e9

    def as_line(self) -> dict:
        out = {"kind": self.kind, "t0_ns": self.t0_ns,
               "t1_ns": self.t1_ns, "dur_s": round(self.dur_s, 6)}
        if self.kernel:
            out["kernel"] = self.kernel
        if self.gap_s > 0:
            out["gap_s"] = round(self.gap_s, 6)
        if self.bytes:
            out["bytes"] = int(self.bytes)
        for k in _CTX_FIELDS:
            v = self.ctx.get(k)
            if v is not None:
                out[k] = v
        return out


def _decompose(rec: dict) -> tuple[float, float, float]:
    """Split one device-facing span into (useful_s, padding_s,
    straggler_s). fill = steps_real/steps_padded is the useful share;
    of the waste, the straggler share is the padded-step excess the
    mesh paid waiting for its slowest shard: D*max(shard_real) -
    sum(shard_real) over (steps_padded - steps_real) — provably <= 1
    since D*max(shard_real) <= steps_padded."""
    dur = float(rec.get("dur_s", 0.0) or 0.0)
    sp = int(rec.get("steps_padded") or 0)
    sr = int(rec.get("steps_real") or 0)
    if sp <= 0 or sr <= 0 or sr >= sp:
        return dur, 0.0, 0.0
    waste = dur * (1.0 - sr / sp)
    strag = 0.0
    shards = rec.get("shard_real")
    if isinstance(shards, list) and len(shards) > 1:
        mx = max(shards)
        lag = len(shards) * mx - sum(shards)
        if lag > 0:
            strag = waste * min(1.0, lag / (sp - sr))
    return dur - waste, waste - strag, strag


# -- the ledger -------------------------------------------------------------

class Ledger:
    """One capture's launch ledger: an in-memory record list, the
    running metric fold (ledger.* counters on the capture's registry),
    and — when bound to an output directory — a writer thread
    streaming ``ledger-<proc>.jsonl`` (joined on close; a dead store
    dir degrades to dropped lines, never a failed run)."""

    MAX_RECORDS = 100_000

    def __init__(self, out_dir: Optional[str | Path] = None,
                 metrics: Any = None, enabled: bool = True,
                 proc: Optional[int] = None):
        self.enabled = enabled and ledger_enabled()
        self.proc = process_index() if proc is None else proc
        self._metrics = metrics
        # The clock handshake: monotonic origin + wall clock sampled
        # back to back. Merge maps t_ns -> wall via this pair.
        self.mono_ns = time.monotonic_ns()
        self.wall_s = time.time()
        self._records: list[dict] = []
        self._bucket_totals: dict[str, float] = {}
        self.dropped = 0
        self._last_end_ns = 0
        self._lock = maybe_wrap(threading.Lock(),
                                "obs.ledger.Ledger._lock")
        self._queue: Optional[queue.SimpleQueue] = None
        self._thread: Optional[threading.Thread] = None
        self.path: Optional[Path] = None
        if self.enabled and out_dir is not None:
            self.path = Path(out_dir) / \
                f"{LEDGER_FILE_PREFIX}{self.proc}.jsonl"
            self._queue = queue.SimpleQueue()
            self._thread = threading.Thread(
                target=self._drain, name="ledger-writer", daemon=True)
            self._thread.start()

    # -- writer thread ------------------------------------------------------

    def _drain(self) -> None:
        try:
            self.path.parent.mkdir(parents=True, exist_ok=True)
            fh = open(self.path, "w", encoding="utf-8")
        except OSError:
            # Observability is never a failure mode: drain the queue to
            # nowhere so record() keeps not blocking.
            fh = None
        try:
            if fh is not None:
                meta = {"kind": "meta", "schema": LEDGER_SCHEMA,
                        "proc": self.proc, "pid": os.getpid(),
                        "mono_ns": self.mono_ns, "wall_s": self.wall_s}
                fh.write(json.dumps(meta) + "\n")
            while True:
                line = self._queue.get()
                if line is None:
                    break
                if fh is not None:
                    try:
                        fh.write(line + "\n")
                    except OSError:
                        fh = None
        finally:
            if fh is not None:
                try:
                    fh.close()
                except OSError:
                    pass

    def close(self) -> None:
        """Flush and join the writer thread (idempotent). File-backed
        ledgers MUST be closed before the file is read or merged."""
        if self._thread is None:
            return
        self._queue.put(None)
        self._thread.join(timeout=10.0)
        self._thread = None

    # -- emission -----------------------------------------------------------

    def _emit(self, rec: LaunchRecord) -> None:
        line = rec.as_line()
        with self._lock:
            if len(self._records) >= self.MAX_RECORDS:
                self.dropped += 1
                return
            self._records.append(line)
            gap_ns = rec.t0_ns - self._last_end_ns \
                if self._last_end_ns else 0
            self._last_end_ns = max(self._last_end_ns, rec.t1_ns)
        if gap_ns > 0:
            rec.gap_s = line["gap_s"] = round(gap_ns / 1e9, 6)
        self._fold(rec, line)
        if self._queue is not None:
            self._queue.put(json.dumps(line))

    def _fold(self, rec: LaunchRecord, line: dict) -> None:
        """Running ledger.* metric totals on the capture's registry —
        the zeros-never-absent bench surface (obs.ledger_stats) and the
        /metrics ledger families."""
        m = self._metrics
        if m is None or not getattr(m, "enabled", False):
            return
        if rec.kind == "encode":
            m.counter("ledger.encode_s").add(rec.dur_s)
            self._bucket(m, "encode_s", rec.dur_s)
        elif rec.kind == "h2d":
            m.counter("ledger.h2d_s").add(rec.dur_s)
            m.counter("ledger.h2d_bytes").add(rec.bytes)
            self._bucket(m, "h2d_s", rec.dur_s)
        elif rec.kind == "compile":
            m.counter("ledger.launches").add(1)
            m.counter("ledger.compile_s").add(rec.dur_s)
            self._bucket(m, "compile_s", rec.dur_s)
        elif rec.kind == "spill_read":
            m.counter("ledger.spill_read_s").add(rec.dur_s)
            self._bucket(m, "spill_read_s", rec.dur_s)
        elif rec.kind == "spill_write":
            m.counter("ledger.spill_write_s").add(rec.dur_s)
            self._bucket(m, "spill_write_s", rec.dur_s)
        else:
            if rec.kind == "execute":
                m.counter("ledger.launches").add(1)
            useful, pad, strag = _decompose(line)
            m.counter("ledger.execute_s").add(useful)
            self._bucket(m, "execute_s", useful)
            if pad > 0:
                m.counter("ledger.padding_s").add(pad)
                self._bucket(m, "padding_s", pad)
            if strag > 0:
                m.counter("ledger.straggler_s").add(strag)
                self._bucket(m, "straggler_s", strag)
            sp = int(line.get("steps_padded") or 0)
            if sp > 0:
                m.gauge("ledger.step_fill").set(
                    round(int(line.get("steps_real") or 0) / sp, 4))
            bp = int(line.get("batch_padded") or 0)
            if bp > 0:
                m.gauge("ledger.batch_fill").set(
                    round(int(line.get("batch_real") or 0) / bp, 4))
        if rec.gap_s > 0:
            m.counter("ledger.dispatch_gap_s").add(rec.gap_s)
            self._bucket(m, "dispatch_gap_s", rec.gap_s)

    def _bucket(self, m: Any, name: str, dt: float) -> None:
        """Cumulative per-bucket seconds as a labeled gauge family
        (/metrics renders jepsen_tpu_ledger_bucket_s{bucket=...})."""
        with self._lock:
            total = self._bucket_totals.get(name, 0.0) + dt
            self._bucket_totals[name] = total
        # jtlint: disable=JTL107 -- bounded family: name comes from the
        # closed BUCKETS tuple above; the exporter folds the members
        # into one labeled Prometheus family (ledger.bucket_s).
        m.gauge(f"ledger.bucket_s.{name}").set(round(total, 6))

    def record_launch(self, kernel: str, phase: str, t0_ns: int,
                      t1_ns: int) -> None:
        """One instrumented kernel call (instrument_kernel's hook).
        phase is "compile" (first call of a geometry) or "execute"."""
        if not self.enabled:
            return
        self._emit(LaunchRecord(kind=phase, kernel=kernel, t0_ns=t0_ns,
                                t1_ns=t1_ns,
                                ctx=current_context() or {}))

    def record_fetch(self, t0_ns: int, t1_ns: int,
                     ctx: Optional[dict] = None) -> None:
        """The blocking device->host result wait of one launch — on
        async backends this is where device time actually surfaces, so
        it decomposes under the same padding context as its launch."""
        if not self.enabled:
            return
        self._emit(LaunchRecord(kind="fetch", t0_ns=t0_ns, t1_ns=t1_ns,
                                ctx=ctx if ctx is not None
                                else (current_context() or {})))

    def record_encode(self, dur_s: float,
                      t1_ns: Optional[int] = None) -> None:
        """Host-side history->tensor encoding seconds (the existing
        encode.encode_s sites feed this with their measured interval)."""
        if not self.enabled or dur_s <= 0:
            return
        t1 = time.monotonic_ns() if t1_ns is None else t1_ns
        self._emit(LaunchRecord(kind="encode", t0_ns=t1 - int(dur_s * 1e9),
                                t1_ns=t1))

    def record_h2d(self, nbytes: int, t0_ns: int, t1_ns: int) -> None:
        """Host->device staging: bytes moved + the enqueue wall (a
        lower bound — async backends overlap the copy with dispatch)."""
        if not self.enabled:
            return
        self._emit(LaunchRecord(kind="h2d", bytes=int(nbytes),
                                t0_ns=t0_ns, t1_ns=t1_ns,
                                ctx=current_context() or {}))

    def record_spill(self, kind: str, nbytes: int, t0_ns: int,
                     t1_ns: int) -> None:
        """One disk-tier transfer of the out-of-core checker
        (store/spill.py): kind is "spill_read" or "spill_write", bytes
        is the on-disk payload size. These decompose into their own
        first-class buckets so scaling_report shows where the
        disk-seconds go."""
        if not self.enabled:
            return
        assert kind in _SPILL_KINDS, kind
        self._emit(LaunchRecord(kind=kind, bytes=int(nbytes),
                                t0_ns=t0_ns, t1_ns=t1_ns,
                                ctx=current_context() or {}))

    # -- reading ------------------------------------------------------------

    def records(self) -> list[dict]:
        with self._lock:
            return list(self._records)

    def wall_records(self) -> list[dict]:
        """Records with absolute wall-clock spans (t0_s/t1_s), mapped
        through this ledger's clock handshake."""
        return [_to_wall(r, self.mono_ns, self.wall_s, self.proc)
                for r in self.records()]

    def attribution(self, t0_ns: Optional[int] = None,
                    t1_ns: Optional[int] = None,
                    wall_s: Optional[float] = None) -> dict:
        """Decompose this ledger's records over a measured window (ns
        anchors from the caller's own monotonic_ns samples, or a plain
        wall_s length). See :func:`attribute`."""
        if t0_ns is not None and t1_ns is not None and wall_s is None:
            wall_s = max(0, t1_ns - t0_ns) / 1e9
        recs = self.wall_records()
        w0 = None
        if t0_ns is not None:
            w0 = self.wall_s + (t0_ns - self.mono_ns) / 1e9
        return attribute(recs, wall_s=wall_s, window_start_s=w0)


# -- attribution ------------------------------------------------------------

def empty_attribution() -> dict:
    """The zeros-never-absent ledger attribution shape (degraded bench
    paths, runs that never launched)."""
    return {"wall_s": 0.0, "window_s": 0.0, "coverage": 0.0,
            "launches": 0, "h2d_bytes": 0, "overlap_s": 0.0,
            "buckets": {k: 0.0 for k in BUCKETS}, "top_losses": []}


def _union_len(spans: list[tuple[float, float]]) -> float:
    total = 0.0
    end = None
    for a, b in sorted(spans):
        if end is None or a > end:
            total += b - a
            end = b
        elif b > end:
            total += b - end
            end = b
    return total


def attribute(records: list[dict], wall_s: Optional[float] = None,
              window_start_s: Optional[float] = None) -> dict:
    """Decompose a record timeline into the named loss buckets.

    The instrumented window is [first span start, last span end]; the
    in-window time no span covers is ``dispatch_gap_s`` (host-side
    scheduling/partitioning/drain logic); wall outside the window is
    ``other_s``. Concurrent spans overlap — ``overlap_s`` reports the
    double-booked seconds so buckets-minus-overlap ties back to the
    window exactly. ``coverage`` is the explained share of wall: every
    bucket except other_s, capped at 1.0 (overlap can push the raw sum
    past the wall)."""
    out = empty_attribution()
    spans = [(float(r["t0_s"]), float(r["t1_s"])) for r in records
             if r.get("t1_s", 0) > r.get("t0_s", 0)]
    if not spans:
        if wall_s:
            out["wall_s"] = round(wall_s, 6)
            out["buckets"]["other_s"] = round(wall_s, 6)
        return out
    b = out["buckets"]
    for r in records:
        kind = r.get("kind")
        dur = float(r.get("dur_s", 0.0) or 0.0)
        if kind == "encode":
            b["encode_s"] += dur
        elif kind == "h2d":
            b["h2d_s"] += dur
            out["h2d_bytes"] += int(r.get("bytes") or 0)
        elif kind == "compile":
            out["launches"] += 1
            b["compile_s"] += dur
        elif kind == "spill_read":
            b["spill_read_s"] += dur
        elif kind == "spill_write":
            b["spill_write_s"] += dur
        elif kind in _DEVICE_KINDS:
            if kind == "execute":
                out["launches"] += 1
            useful, pad, strag = _decompose(r)
            b["execute_s"] += useful
            b["padding_s"] += pad
            b["straggler_s"] += strag
    lo = min(a for a, _ in spans)
    hi = max(bb for _, bb in spans)
    if window_start_s is not None:
        lo = min(lo, window_start_s)
    union = _union_len(spans)
    window = hi - lo
    b["dispatch_gap_s"] = max(0.0, window - union)
    if wall_s is None:
        wall_s = window
    b["other_s"] = max(0.0, wall_s - window)
    out["wall_s"] = wall_s
    out["window_s"] = window
    out["overlap_s"] = max(0.0, sum(bb - a for a, bb in spans) - union)
    explained = sum(v for k, v in b.items() if k != "other_s")
    out["coverage"] = min(1.0, explained / wall_s) if wall_s > 0 else 0.0
    for k in b:
        b[k] = round(b[k], 6)
    for k in ("wall_s", "window_s", "overlap_s", "coverage"):
        out[k] = round(out[k], 6)
    out["top_losses"] = sorted(
        ([k, v] for k, v in b.items() if k != "execute_s" and v > 0),
        key=lambda kv: -kv[1])
    return out


def by_plan(records: list[dict]) -> list[dict]:
    """Per-plan roll-up of device-facing spans: launches, seconds,
    useful/waste split — the report's "where the chip-seconds went by
    kernel" table."""
    agg: dict[str, dict] = {}
    for r in records:
        if r.get("kind") not in ("compile",) + _DEVICE_KINDS:
            continue
        key = r.get("label") or r.get("kernel") or "?"
        a = agg.setdefault(key, {"label": key, "launches": 0,
                                 "seconds": 0.0, "useful_s": 0.0,
                                 "waste_s": 0.0})
        dur = float(r.get("dur_s", 0.0) or 0.0)
        a["seconds"] += dur
        if r.get("kind") == "compile":
            a["launches"] += 1
            a["useful_s"] += dur
            continue
        if r.get("kind") == "execute":
            a["launches"] += 1
        useful, pad, strag = _decompose(r)
        a["useful_s"] += useful
        a["waste_s"] += pad + strag
    out = sorted(agg.values(), key=lambda a: -a["seconds"])
    for a in out:
        for k in ("seconds", "useful_s", "waste_s"):
            a[k] = round(a[k], 6)
    return out


def straggler_table(records: list[dict]) -> list[dict]:
    """Per-launch shard imbalance rows for ragged corpora: the bucket
    the whole mesh paid vs each shard's real steps — the "corpus
    ragged 17" smoking gun, quantified."""
    rows = []
    for r in records:
        shards = r.get("shard_real")
        if r.get("kind") not in _DEVICE_KINDS \
                or not isinstance(shards, list) or len(shards) < 2:
            continue
        _, _, strag = _decompose(r)
        if strag <= 0:
            continue
        rows.append({"label": r.get("label") or r.get("kernel") or "?",
                     "steps_padded": int(r.get("steps_padded") or 0),
                     "shard_real": [int(s) for s in shards],
                     "shard_packed": bool(r.get("shard_packed")),
                     "straggler_s": round(strag, 6)})
    return sorted(rows, key=lambda x: -x["straggler_s"])


# -- per-process files and the pod merge ------------------------------------

def read_ledger(path: str | Path) -> tuple[Optional[dict], list[dict],
                                           list[str]]:
    """One ledger-<proc>.jsonl -> (meta, records, warnings). Truncated
    or partially-written files (a killed process) degrade to the parsed
    prefix plus a counted warning — never an exception."""
    path = Path(path)
    warnings: list[str] = []
    meta: Optional[dict] = None
    records: list[dict] = []
    try:
        text = path.read_text(encoding="utf-8")
    except OSError as e:
        return None, [], [f"{path.name}: unreadable ({e})"]
    lines = text.splitlines()
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            # A killed writer leaves a partial trailing line; anything
            # after it is untrustworthy. Keep the parsed prefix.
            warnings.append(
                f"{path.name}: truncated at line {i + 1} "
                f"({len(lines) - i} line(s) dropped)")
            break
        if rec.get("kind") == "meta":
            meta = rec
        else:
            records.append(rec)
    if meta is None:
        warnings.append(f"{path.name}: missing clock handshake meta "
                        f"line; records skipped")
        return None, [], warnings
    return meta, records, warnings


def _to_wall(rec: dict, mono_ns: int, wall_s: float, proc: int) -> dict:
    out = dict(rec)
    out["proc"] = proc
    out["t0_s"] = wall_s + (rec.get("t0_ns", 0) - mono_ns) / 1e9
    out["t1_s"] = wall_s + (rec.get("t1_ns", 0) - mono_ns) / 1e9
    return out


def ledger_paths(run_dir: str | Path) -> list[Path]:
    return sorted(Path(run_dir).glob(f"{LEDGER_FILE_PREFIX}*.jsonl"))


def merge_ledgers(paths: list[str | Path]) -> dict:
    """Fold per-process ledger files into one wall-ordered pod
    timeline. Each file's clock handshake maps its monotonic spans to
    wall clock independently — cross-process wall skew shifts one
    process's records coherently but can never reorder records WITHIN
    a process (skew-tolerant by construction). Returns {"records",
    "procs", "warnings"}."""
    merged: list[dict] = []
    procs: list[int] = []
    warnings: list[str] = []
    for p in paths:
        meta, records, warns = read_ledger(p)
        warnings.extend(warns)
        if meta is None:
            continue
        proc = int(meta.get("proc", 0))
        procs.append(proc)
        mono = int(meta.get("mono_ns", 0))
        wall = float(meta.get("wall_s", 0.0))
        merged.extend(_to_wall(r, mono, wall, proc) for r in records)
    merged.sort(key=lambda r: (r["t0_s"], r["proc"]))
    return {"records": merged, "procs": sorted(procs),
            "warnings": warnings}


# -- span-tree critical path ------------------------------------------------

def critical_path(trace_records: list[dict]) -> list[dict]:
    """The longest root-to-leaf chain through a telemetry.jsonl span
    tree (runner/serve paths), with per-span self time (duration minus
    the union of its children) — the "what would speeding X up actually
    buy" view."""
    spans = [r for r in trace_records
             if r.get("kind") == "span" and r.get("t1_ns") is not None]
    if not spans:
        return []
    children: dict[Any, list[dict]] = {}
    by_id = {}
    for s in spans:
        by_id[s.get("id")] = s
        children.setdefault(s.get("parent"), []).append(s)
    roots = [s for s in spans
             if s.get("parent") not in by_id or s.get("parent") is None]
    if not roots:
        return []

    def dur(s: dict) -> int:
        return max(0, int(s["t1_ns"]) - int(s["t0_ns"]))

    path = []
    cur = max(roots, key=dur)
    while cur is not None:
        kids = children.get(cur.get("id"), [])
        child_union = _union_len(
            [(int(k["t0_ns"]) / 1e9, int(k["t1_ns"]) / 1e9)
             for k in kids])
        path.append({"name": cur.get("name", "?"),
                     "dur_s": round(dur(cur) / 1e9, 6),
                     "self_s": round(max(0.0, dur(cur) / 1e9
                                         - child_union), 6)})
        cur = max(kids, key=dur) if kids else None
    return path


# -- rolling-window SLO gauges ----------------------------------------------

def slo_target_s() -> float:
    """The serve SLO latency target (p99 threshold) in seconds."""
    try:
        return float(os.environ.get("JEPSEN_TPU_SERVE_SLO_P99_S", "1.0"))
    except ValueError:
        return 1.0


def slo_budget() -> float:
    """The SLO error budget: the tolerated breach fraction (burn rate
    1.0 means breaches exactly consume the budget)."""
    try:
        return float(os.environ.get("JEPSEN_TPU_SERVE_SLO_BUDGET",
                                    "0.01"))
    except ValueError:
        return 0.01


class RollingWindow:
    """A time-bounded latency window for the serve daemon's live SLO
    gauges: p50/p99 over the last window_s seconds (the cumulative
    request histogram can't forget, so a recovered daemon would wear
    its worst minute forever) plus the burn rate — the breach fraction
    over the error budget."""

    def __init__(self, window_s: float = 60.0, maxlen: int = 4096):
        self.window_s = window_s
        self.maxlen = maxlen
        self._items: list[tuple[float, float]] = []

    def observe(self, value: float, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        self._items.append((now, float(value)))
        self._prune(now)

    def _prune(self, now: float) -> None:
        cut = now - self.window_s
        i = 0
        n = len(self._items)
        while i < n and self._items[i][0] < cut:
            i += 1
        if i or n > self.maxlen:
            self._items = self._items[max(i, n - self.maxlen):]

    def values(self, now: Optional[float] = None) -> list[float]:
        self._prune(time.monotonic() if now is None else now)
        return [v for _, v in self._items]

    def quantiles(self, now: Optional[float] = None) \
            -> tuple[float, float]:
        vals = sorted(self.values(now))
        if not vals:
            return 0.0, 0.0

        def q(p: float) -> float:
            return vals[min(len(vals) - 1, int(p * len(vals)))]

        return q(0.50), q(0.99)

    def burn_rate(self, slo_s: Optional[float] = None,
                  budget: Optional[float] = None,
                  now: Optional[float] = None) -> float:
        vals = self.values(now)
        if not vals:
            return 0.0
        slo = slo_target_s() if slo_s is None else slo_s
        bud = slo_budget() if budget is None else budget
        breach = sum(1 for v in vals if v > slo) / len(vals)
        return round(breach / bud, 4) if bud > 0 else 0.0


# -- report rendering -------------------------------------------------------

def render_waterfall(att: dict, width: int = 40) -> list[str]:
    """The where-did-the-chip-seconds-go waterfall as text lines:
    every bucket, ranked, with its share bar of the measured wall."""
    wall = att.get("wall_s") or 0.0
    lines = [f"wall {wall:.3f}s  coverage "
             f"{100.0 * att.get('coverage', 0.0):.1f}%  "
             f"launches {att.get('launches', 0)}"]
    buckets = att.get("buckets") or {}
    ranked = sorted(buckets.items(), key=lambda kv: -kv[1])
    for name, sec in ranked:
        frac = sec / wall if wall > 0 else 0.0
        bar = "#" * max(0, min(width, int(round(frac * width))))
        lines.append(f"  {name:<15} {sec:>9.3f}s {100 * frac:>5.1f}% "
                     f"|{bar}")
    if att.get("overlap_s"):
        lines.append(f"  (overlap {att['overlap_s']:.3f}s of concurrent "
                     f"spans double-booked above)")
    return lines
