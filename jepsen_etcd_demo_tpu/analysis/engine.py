"""jtlint engine: walk files, run applicable rules, fold in baseline.

This module is the library API (``run_lint``) behind both the
``jepsen-tpu lint`` CLI verb and the tier-1 wiring (tests/test_lint.py
self-clean assertion). It is deliberately jax-free and fast: linting
the whole package is AST parsing + pure-Python rule passes, well under
the 5 s tier-1 budget.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from pathlib import Path
from typing import Optional, Sequence

from .baseline import Baseline
from .core import (ModuleSource, ProjectRule, Rule, all_rules,
                   PACKAGE_NAME, _relpath)
from .findings import Finding, fingerprint_findings

# Directories never worth descending into (linting a checkout root must
# not crawl virtualenvs/build output — foreign code, minutes of wall).
_SKIP_DIRS = {".git", "__pycache__", ".pytest_cache", "node_modules",
              ".xla-cache", ".venv", "venv", ".tox", ".eggs",
              "site-packages", "build", "dist"}


@dataclass
class LintResult:
    findings: list[Finding] = field(default_factory=list)   # unbaselined
    baselined: list[Finding] = field(default_factory=list)
    suppressed: list[Finding] = field(default_factory=list)
    stale_baseline: list[str] = field(default_factory=list)
    files: int = 0
    parse_errors: list[Finding] = field(default_factory=list)
    # Justified suppression comments that suppressed NOTHING this run
    # (every id they name was executed): stale suppressions, surfaced by
    # tools/lint_report.py. {path, line, ids, justification} records.
    unused_suppressions: list[dict] = field(default_factory=list)

    @property
    def all_findings(self) -> list[Finding]:
        return self.findings + self.baselined

    def ok(self) -> bool:
        """Clean under --strict: nothing unbaselined (parse errors are
        findings too — rule JTL000) and no stale baseline entries."""
        return not self.findings and not self.stale_baseline


def iter_python_files(paths: Sequence[Path]) -> list[Path]:
    """Python files under the given paths, deduped by resolved path
    (overlapping arguments must not double-lint a file — the duplicate
    would take occurrence+1 and invalidate its baseline fingerprint).
    _SKIP_DIRS applies only to directories BELOW each argument: a
    checkout that happens to live under .../venv/... — or the package
    installed into site-packages and passed explicitly — still lints."""
    out: list[Path] = []
    seen: set[Path] = set()

    def add(f: Path) -> None:
        r = f.resolve()
        if r not in seen:
            seen.add(r)
            out.append(f)

    for p in paths:
        p = Path(p)
        if p.is_dir():
            for f in sorted(p.rglob("*.py")):
                below = f.relative_to(p).parts[:-1]
                if not any(part in _SKIP_DIRS for part in below):
                    add(f)
        elif p.suffix == ".py":
            add(p)
    return out


def find_repo_root(start: Path) -> Path:
    """Nearest ancestor holding the package (or a .git/pyproject.toml);
    relpaths and the default baseline location anchor here so
    fingerprints are machine-independent."""
    cur = start.resolve()
    if cur.is_file():
        cur = cur.parent
    for cand in (cur, *cur.parents):
        if ((cand / PACKAGE_NAME).is_dir() or (cand / ".git").exists()
                or (cand / "pyproject.toml").is_file()):
            return cand
    return cur


class ProjectContext:
    """Shared state for one lint invocation, handed to every
    ProjectRule: the modules this run already parsed (parse once, share
    the AST across all rules) and the lazily built cross-module
    FlowIndex the JTL4xx rules + contracts extraction all ride — built
    at most ONCE per invocation, seeded with the scanned modules so the
    flow pass re-parses nothing."""

    def __init__(self, root: Path, modules: dict[str, ModuleSource]):
        self.root = Path(root)
        self.modules = modules
        self._flow = None

    def flow_index(self):
        if self._flow is None:
            from .flow.index import FlowIndex

            self._flow = FlowIndex.build(self.root,
                                         preloaded=self.modules)
        return self._flow

    def module_for(self, relpath: str) -> Optional[ModuleSource]:
        """A parsed module by repo-relative path — from this run's scan
        or, for project-rule findings on unscanned files, the flow
        index (without forcing one to exist)."""
        mod = self.modules.get(relpath)
        if mod is None and self._flow is not None:
            mod = self._flow.modules.get(relpath)
        return mod


def run_lint(paths: Sequence[Path | str],
             rules: Optional[dict[str, Rule]] = None,
             root: Optional[Path] = None,
             baseline: Optional[Baseline] = None,
             project_rules: bool = True) -> LintResult:
    """Lint `paths` (files or directories) and return a LintResult.

    `rules` defaults to the full registry; pass a subset for targeted
    runs (fixture tests). Project-level rules (the doc lint, the flow
    rules) run once against `root` unless disabled — they are skipped
    automatically when `rules` was narrowed to exclude them."""
    from .flow.index import load_module_cached

    paths = [Path(p) for p in paths]
    if root is None:
        root = find_repo_root(paths[0] if paths else Path.cwd())
    rules = all_rules() if rules is None else rules
    res = LintResult()
    raw: list[Finding] = []
    sup_raw: list[tuple[Finding, ModuleSource]] = []
    mods: dict[str, ModuleSource] = {}
    # relpath -> suppression-comment lines that suppressed something.
    used_sup: dict[str, set[int]] = {}

    def suppress(mod: ModuleSource, f: Finding) -> bool:
        hit = mod.suppression_line(f.rule, f.line)
        if hit is None and f.anchor and f.anchor != f.line:
            hit = mod.suppression_line(f.rule, f.anchor)
        if hit is None:
            return False
        used_sup.setdefault(mod.relpath, set()).add(hit)
        sup_raw.append((f, mod))
        return True

    module_rules = [r for r in rules.values()
                    if not isinstance(r, ProjectRule)]
    covered: set[str] = set()
    for path in iter_python_files(paths):
        res.files += 1
        covered.add(_relpath(path, root))
        try:
            mod = load_module_cached(path, root)
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            lineno = getattr(e, "lineno", 1) or 1
            # Repo-relative like every finding: the fingerprint must be
            # machine-independent so a parse error is baselinable.
            pe = Finding(rule="JTL000", path=_relpath(path, root),
                         line=lineno,
                         message=f"file does not parse: "
                                 f"{type(e).__name__}: {e}",
                         hint="jtlint only checks parseable modules")
            res.parse_errors.append(pe)
            raw.append(pe)
            continue
        mods[mod.relpath] = mod
        # Unjustified suppression comments are findings themselves
        # (JTL001) and do NOT suppress — including stale bare disables
        # on lines where no rule fires anymore.
        for ln, (ids, justified) in sorted(mod.suppressions.items()):
            if not justified:
                raw.append(Finding(
                    rule="JTL001", path=mod.relpath, line=ln,
                    message=f"suppression of {', '.join(sorted(ids))} "
                            f"without a justification — a suppression "
                            f"is an argument, not an off switch (and "
                            f"this one does not suppress)",
                    hint="append ` -- <why this is safe/bounded>` to "
                         "the jtlint: disable comment",
                    snippet=mod.line(ln)))
        for rule in module_rules:
            if not rule.applies_to(mod):
                continue
            for f in rule.check(mod):
                if not suppress(mod, f):
                    raw.append(f)

    ctx = ProjectContext(root, mods)
    if project_rules:
        for rule in rules.values():
            if isinstance(rule, ProjectRule):
                for f in rule.check_project(root, ctx):
                    # Project-rule findings (the flow rules land on
                    # module lines) honor the same inline-suppression
                    # contract as module rules.
                    fmod = ctx.module_for(f.path)
                    if fmod is None or not suppress(fmod, f):
                        raw.append(f)
                covered.update(rule.covered_paths(root))

    # ONE fingerprint pass over kept + suppressed findings together:
    # occurrence indices (the identical-line disambiguator) must not
    # shift when a sibling finding gets suppressed — a baseline entry
    # may only go stale when the flagged code itself changes.
    fingerprint_findings(raw + [f for f, _ in sup_raw])
    res.suppressed = [f for f, _ in sup_raw]
    if baseline is None:
        baseline = Baseline()
    # The engine-emitted rules (JTL000 parse errors, JTL001 unjustified
    # suppressions) always run, so their entries are always in scope
    # for staleness. Project rules count as "ran" only when they
    # actually did — a project_rules=False run (the --changed
    # clean-graph fast path) must not judge JTL3xx/4xx baseline entries
    # or suppressions it never re-derived.
    ran_rules = {rid for rid, r in rules.items()
                 if project_rules or not isinstance(r, ProjectRule)} \
        | {"JTL000", "JTL001"}
    # A baseline entry whose file was deleted outright would never go
    # stale by fingerprint alone (the path is no longer scanned);
    # deletion is global truth, so such entries always prune.
    missing = {ent.get("path") for ent in baseline.entries.values()
               if ent.get("path") and not (root / ent["path"]).exists()}
    res.findings, res.baselined, res.stale_baseline = baseline.split(
        raw, covered_paths=covered, ran_rules=ran_rules,
        missing_paths=missing)
    # Stale-suppression accounting: a justified disable that suppressed
    # nothing, counted only when every rule it names actually ran (a
    # --rules-narrowed or project_rules=False run must not report other
    # rules' suppressions as stale; `disable=all` is checkable only
    # when the WHOLE registry executed).
    full_run = ran_rules >= set(all_rules())
    for rel, mod in sorted(mods.items()):
        used = used_sup.get(rel, set())
        for ln, (ids, justified) in sorted(mod.suppressions.items()):
            if not justified or ln in used:
                continue
            if "all" in ids:
                if not full_run:
                    continue
            elif not ids <= ran_rules:
                continue
            res.unused_suppressions.append({
                "path": rel, "line": ln, "ids": sorted(ids),
                "justification": mod.suppression_notes.get(ln, "")})
    return res
