"""Shared AST plumbing for jtlint rules.

Everything here is stdlib-``ast`` only — the lint layer must never
import jax (it runs in tier-1's fast path; tests/test_lint.py asserts
the no-jax property in a subprocess).

The helpers encode the repo's import idioms once so rules don't each
re-derive them: ``ImportMap`` resolves local names to dotted origins
(``from jax import jit as j`` -> ``j`` means ``jax.jit``), ``dotted``
renders attribute chains (``self.carry.dead`` -> that string), and the
enclosing-scope walkers answer "is this node inside a loop / which
function owns it" without each rule re-threading parent links.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, Optional

# Shared name heuristics — ONE definition so paired rules can never
# diverge: JTL201's lock identity and JTL203's under-lock exemption
# must recognize the same lock-like names; likewise the cache-store
# checks in JTL101 and JTL105.
LOCKISH_RE = re.compile(r"lock$|^lock|mutex", re.I)
CACHE_NAME_RE = re.compile(r"cache", re.I)


def parse_module(text: str, filename: str = "<lint>") -> ast.Module:
    """Parse + annotate every node with ``.jt_parent`` (None at root).

    The annotation pass visits every node in ``ast.walk`` (BFS) order
    anyway, so it doubles as the flattening pass: the sequence is
    stored as the tree's ``walk_cached`` entry and every later
    full-module walk (ImportMap, ``ModuleSource.walk_nodes``, rules)
    reads the list instead of re-traversing."""
    tree = ast.parse(text, filename=filename)
    tree.jt_parent = None  # type: ignore[attr-defined]
    nodes: list[ast.AST] = [tree]
    for node in nodes:     # grows while iterating — exactly BFS order
        for child in ast.iter_child_nodes(node):
            child.jt_parent = node  # type: ignore[attr-defined]
            nodes.append(child)
    tree._jt_walk = tuple(nodes)  # type: ignore[attr-defined]
    return tree


def parent(node: ast.AST) -> Optional[ast.AST]:
    return getattr(node, "jt_parent", None)


def ancestors(node: ast.AST) -> Iterator[ast.AST]:
    cur = parent(node)
    while cur is not None:
        yield cur
        cur = parent(cur)


def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain; None for anything else."""
    parts: list[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return None


class ImportMap:
    """Local-name -> dotted-origin resolution from a module's imports.

    ``import jax`` maps ``jax`` -> ``jax``; ``from jax import jit as j``
    maps ``j`` -> ``jax.jit``; ``from ..obs import instrument_kernel``
    maps the name -> ``obs.instrument_kernel`` (relative dots dropped —
    rules match on suffixes).
    """

    def __init__(self, tree: ast.Module):
        self.names: dict[str, str] = {}
        for node in walk_cached(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.names[a.asname or a.name.split(".")[0]] = a.name
            elif isinstance(node, ast.ImportFrom):
                mod = (node.module or "").lstrip(".")
                for a in node.names:
                    if a.name == "*":
                        continue
                    origin = f"{mod}.{a.name}" if mod else a.name
                    self.names[a.asname or a.name] = origin

    def resolve(self, node: ast.AST) -> Optional[str]:
        """Dotted origin of a Name/Attribute expression, imports applied:
        ``jax.jit`` stays ``jax.jit``; an aliased ``j`` becomes
        ``jax.jit``."""
        d = dotted(node)
        if d is None:
            return None
        head, _, rest = d.partition(".")
        origin = self.names.get(head, head)
        return f"{origin}.{rest}" if rest else origin

    def is_call_to(self, call: ast.Call, *suffixes: str) -> bool:
        """True when the call's resolved function name equals or ends
        with any of the given dotted suffixes."""
        origin = self.resolve(call.func)
        if origin is None:
            return False
        return any(origin == s or origin.endswith("." + s)
                   for s in suffixes)


def enclosing_function(node: ast.AST):
    """Nearest enclosing FunctionDef/AsyncFunctionDef, or None."""
    for a in ancestors(node):
        if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return a
    return None


def enclosing_class(node: ast.AST):
    for a in ancestors(node):
        if isinstance(a, ast.ClassDef):
            return a
    return None


def in_loop(node: ast.AST) -> bool:
    """Inside a for/while body within the SAME function scope (loops in
    an outer function don't count — the inner def is its own unit).
    Comprehensions don't count as loops here: rules that care about
    per-iteration host work mean statement loops."""
    for a in ancestors(node):
        if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            return False
        if isinstance(a, (ast.For, ast.AsyncFor, ast.While)):
            return True
    return False


def walk_cached(node: ast.AST) -> tuple:
    """``ast.walk`` flattened once and memoized on the node. Lint trees
    are immutable after ``parse_module``, yet every rule re-walks the
    same module/function subtrees — the repeated generator traversal is
    the single hottest path in the strict-lint budget. The cache rides
    the node itself (like ``jt_parent``) so its lifetime matches the
    tree's and ``ast.iter_fields`` never sees it."""
    cached = getattr(node, "_jt_walk", None)
    if cached is None:
        cached = tuple(ast.walk(node))
        try:
            node._jt_walk = cached  # type: ignore[attr-defined]
        except AttributeError:
            pass
    return cached


def walk_same_scope(node: ast.AST) -> tuple:
    """Descendants of `node` WITHOUT crossing into nested function /
    lambda bodies: a `with lock:` inside a deferred callback defined
    here runs later, under different held state, and must not count as
    nested under this scope's locks (same boundary in_loop respects).

    Memoized like ``walk_cached`` — the donation/flow/sync rules each
    re-scan the same function and with-block scopes."""
    cached = getattr(node, "_jt_walk_ss", None)
    if cached is None:
        out: list[ast.AST] = []
        stack = list(ast.iter_child_nodes(node))
        while stack:
            n = stack.pop()
            out.append(n)
            if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                stack.extend(ast.iter_child_nodes(n))
        cached = tuple(out)
        try:
            node._jt_walk_ss = cached  # type: ignore[attr-defined]
        except AttributeError:
            pass
    return cached


def ancestors_same_scope(node: ast.AST) -> Iterator[ast.AST]:
    """Ancestors up to (excluding) the nearest enclosing function/
    lambda — the dual of walk_same_scope."""
    for a in ancestors(node):
        if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            return
        yield a


def call_args_source(node: ast.AST, text: str = "") -> str:
    """Approximate source text of a node. Uses ast.unparse (pure AST —
    ast.get_source_segment would rescan the file per node, O(n^2) over
    a module) so the whole-package lint stays inside tier-1's budget."""
    try:
        return ast.unparse(node)
    except Exception:
        return ""


def assigned_names(target: ast.AST) -> set[str]:
    """Every dotted name bound by an assignment target (tuple targets
    flattened; subscripted/starred bases included by their base chain:
    ``self.carry, p = ...`` binds {"self.carry", "p"})."""
    out: set[str] = set()
    for n in ast.walk(target):
        if isinstance(n, (ast.Name, ast.Attribute)):
            d = dotted(n)
            if d is not None:
                out.add(d)
    return out


def statement_of(node: ast.AST) -> ast.stmt:
    """The statement a node belongs to (the node itself if a stmt)."""
    cur: ast.AST = node
    while not isinstance(cur, ast.stmt):
        p = parent(cur)
        if p is None:
            break
        cur = p
    return cur  # type: ignore[return-value]


def decorator_names(fn, imports: ImportMap) -> set[str]:
    out = set()
    for d in fn.decorator_list:
        target = d.func if isinstance(d, ast.Call) else d
        origin = imports.resolve(target)
        if origin:
            out.add(origin)
    return out
