"""Checked-in lint baseline: accepted findings by stable fingerprint.

The baseline file (default ``.jtlint-baseline.json`` at the repo root)
maps finding fingerprints to a record carrying the rule, path, and a
REQUIRED human justification note — the file is reviewed like code, so
every accepted finding carries its argument. ``--strict`` fails on any
finding NOT in the baseline; stale entries (fingerprints no longer
produced — the flagged code changed or was fixed) are reported so the
file never accretes dead weight.

Fingerprints are line-drift tolerant (analysis/findings.py), so the
baseline survives edits elsewhere in a file and goes stale exactly when
the flagged line itself changes.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterable, Optional

from .findings import Finding

BASELINE_VERSION = 1
DEFAULT_BASELINE = ".jtlint-baseline.json"


@dataclass
class Baseline:
    path: Optional[Path] = None
    entries: dict[str, dict] = field(default_factory=dict)

    @classmethod
    def load(cls, path: Path) -> "Baseline":
        data = json.loads(Path(path).read_text(encoding="utf-8"))
        if data.get("version") != BASELINE_VERSION:
            raise ValueError(
                f"{path}: unsupported baseline version "
                f"{data.get('version')!r} (want {BASELINE_VERSION})")
        entries = data.get("findings")
        if not isinstance(entries, dict):
            raise ValueError(f"{path}: baseline 'findings' must be a "
                             f"fingerprint -> record object")
        return cls(path=Path(path), entries=entries)

    @classmethod
    def load_or_empty(cls, path: Optional[Path]) -> "Baseline":
        if path is None or not Path(path).is_file():
            return cls(path=Path(path) if path else None)
        return cls.load(path)

    def save(self, path: Optional[Path] = None) -> Path:
        path = Path(path or self.path or DEFAULT_BASELINE)
        payload = {"version": BASELINE_VERSION, "findings": dict(
            sorted(self.entries.items()))}
        path.write_text(json.dumps(payload, indent=2) + "\n",
                        encoding="utf-8")
        self.path = path
        return path

    def split(self, findings: Iterable[Finding],
              covered_paths: Optional[set[str]] = None,
              ran_rules: Optional[set[str]] = None,
              missing_paths: Optional[set[str]] = None
              ) -> tuple[list[Finding], list[Finding], list[str]]:
        """(new, baselined, stale-fingerprints): findings not covered by
        the baseline, findings it accepts, and entries no longer
        produced by the lint run.

        Staleness is judged only against `covered_paths` (repo-relative,
        the files this run actually scanned) and `ran_rules` (rule ids
        this run executed): a partial run — ``lint --strict <subdir>``
        or ``--rules JTL101`` — must not report entries for unscanned
        files / un-run rules as "fixed" (nor let --write-baseline prune
        them). None = everything was in scope.

        `missing_paths` are entry paths that no longer EXIST on disk (a
        file deleted outright). Fingerprint staleness alone never
        catches those — the deleted file is no longer scanned, so its
        entries looked permanently out of scope and accreted forever.
        Deletion is global truth: such entries are stale regardless of
        the scanned-path / ran-rule scoping."""
        new: list[Finding] = []
        baselined: list[Finding] = []
        seen: set[str] = set()
        for f in findings:
            if f.fingerprint in self.entries:
                baselined.append(f)
                seen.add(f.fingerprint)
            else:
                new.append(f)
        missing = missing_paths or set()
        stale = [fp for fp, ent in self.entries.items()
                 if fp not in seen
                 and (ent.get("path") in missing
                      or ((covered_paths is None
                           or ent.get("path") in covered_paths)
                          and (ran_rules is None
                               or ent.get("rule") in ran_rules)))]
        return new, baselined, stale

    def extend(self, findings: Iterable[Finding],
               note: str = "TODO: justify this accepted finding") -> None:
        """Accept findings into the baseline, preserving any existing
        entry's note (the human-authored part)."""
        for f in findings:
            prev = self.entries.get(f.fingerprint, {})
            self.entries[f.fingerprint] = {
                "rule": f.rule, "path": f.path, "line": f.line,
                "message": f.message,
                "note": prev.get("note", note)}
