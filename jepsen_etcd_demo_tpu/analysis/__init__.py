"""jtlint — a JAX-aware static analysis suite for this harness.

Five PRs in, the expensive bug classes moved from checker math into
harness hygiene: recompile storms from unstable jit-cache keys, donated
buffers read after donation, host syncs hidden in chunk loops, and the
thread/event-loop races ADVICE r5 and BENCH_r05 already bit us with
(ISSUE 7). Every one is statically detectable; this package detects
them — AST-only, jax-free, fast enough for tier-1.

Library API:

    from jepsen_etcd_demo_tpu import analysis
    result = analysis.run_lint(["jepsen_etcd_demo_tpu"])
    result.findings            # unbaselined Finding rows
    analysis.all_rules()       # id -> rule (docs, hints, scopes)

CLI: ``jepsen-tpu lint [--strict] [paths...]`` (analysis/cli.py), also
``python -m jepsen_etcd_demo_tpu.analysis``. Rule reference, the
suppression syntax, and how to add a rule: doc/analysis.md.
"""

from .baseline import Baseline, DEFAULT_BASELINE
from .core import (CONCURRENCY_SCOPES, KERNEL_SCOPES, ModuleSource,
                   ProjectRule, Rule, all_rules, resolve_rules)
from .engine import LintResult, find_repo_root, run_lint
from .findings import Finding, fingerprint_findings, format_json, \
    format_sarif, format_text
from .flow import (CONTRACTS_FILE, FlowIndex, extract_contracts,
                   render_contracts)

__all__ = [
    "Baseline", "DEFAULT_BASELINE", "CONCURRENCY_SCOPES",
    "KERNEL_SCOPES", "ModuleSource", "ProjectRule", "Rule", "all_rules",
    "resolve_rules", "LintResult", "find_repo_root", "run_lint",
    "Finding", "fingerprint_findings", "format_json", "format_sarif",
    "format_text", "CONTRACTS_FILE", "FlowIndex", "extract_contracts",
    "render_contracts",
]
