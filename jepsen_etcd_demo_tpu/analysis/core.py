"""jtlint rule framework: ModuleSource, Rule base classes, registry.

A rule is a small class with an id, a human name, the package scopes it
applies to, a rationale (citing the incident that motivated it — see
doc/analysis.md), and a fix hint. Module rules get a parsed
``ModuleSource`` and yield :class:`~.findings.Finding` rows; project
rules run once per lint invocation against the repo root (the doc lint
lives there). Registration is import-time via the :func:`register`
decorator — ``analysis/rules/__init__.py`` imports every rule module,
so ``all_rules()`` is the complete suite.

Suppression syntax (matched on the finding's line or the line above):

    # jtlint: disable=JTL103 -- bounded death poll, see doc/perf.md

The justification after ``--`` is REQUIRED by convention (doc/
analysis.md): a suppression is an argument, not an off switch. Multiple
ids comma-separate; ``disable=all`` silences every rule for that line.
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterator, Optional

from .astutil import ImportMap, parse_module
from .findings import Finding

# Top-level package directories each rule family runs over (ISSUE 7):
# kernel hygiene = the jit/device hot paths; concurrency = everything
# with threads or event loops. "" means top-level modules (compose.py).
KERNEL_SCOPES = ("ops", "parallel", "sched", "stream", "tune")
CONCURRENCY_SCOPES = ("runner", "stream", "sched", "db", "web", "clients",
                      "control", "serve", "campaign")

PACKAGE_NAME = "jepsen_etcd_demo_tpu"

_SUPPRESS_RE = re.compile(
    r"#\s*jtlint:\s*disable=([A-Za-z0-9_,\s]+?)(?:\s*--\s*(.*))?$")


@dataclass
class ModuleSource:
    """One parsed file, handed to every applicable module rule."""

    path: Path                 # absolute
    relpath: str               # repo-relative, posix separators
    text: str
    tree: ast.Module
    imports: ImportMap
    scope: Optional[str]       # package subdir ("ops", "", ...) or None
                               # when the file is outside the package —
                               # then every rule applies (lint fixtures)
    lines: list[str] = field(default_factory=list)
    # line -> (rule ids, has a ` -- justification`); see load().
    suppressions: dict[int, tuple[set[str], bool]] = field(
        default_factory=dict)
    # line -> the justification text after ` -- ` (the human argument).
    # Captured by the ONE suppression grammar (_SUPPRESS_RE) so the
    # stale-suppression ledger (engine/tools/lint_report.py) can never
    # drift from what the engine considers justified.
    suppression_notes: dict[int, str] = field(default_factory=dict)
    # line -> real COMMENT text on that line (from the tokenizer, so
    # comment syntax QUOTED inside strings/docstrings — the suppression
    # examples in this very module, the jtflow grammar in
    # analysis/flow/facts.py — never parses as a live directive, while
    # a real trailing comment after a multiline string's closing quote
    # still does).
    comments: dict[int, str] = field(default_factory=dict, repr=False)
    # Lazy flat ast.walk snapshot: the flow extractors (analysis/flow/)
    # make many typed passes over each module; walking the generator
    # per pass was the dominant cost of the whole lint run. Cached here
    # so it also amortizes across run_lint invocations (ModuleSource
    # objects are parse-cached process-wide, flow/index.py).
    _walked: Optional[list] = field(default=None, repr=False)

    def walk_nodes(self) -> list:
        if self._walked is None:
            from .astutil import walk_cached
            self._walked = walk_cached(self.tree)
        return self._walked

    @classmethod
    def load(cls, path: Path, root: Path) -> "ModuleSource":
        text = path.read_text(encoding="utf-8")
        tree = parse_module(text, filename=str(path))
        lines = text.splitlines()
        comments = _comment_lines(text)
        # line -> (rule ids, has a `--` justification). Only JUSTIFIED
        # suppressions suppress (the engine reports bare ones as JTL001
        # — "a suppression is an argument, not an off switch" is
        # enforced here, not just in a test). Directives parse from
        # REAL comment tokens only: `# jtlint:` quoted inside a
        # docstring example is prose, not a directive — it must neither
        # suppress nor count as stale.
        sup: dict[int, tuple[set[str], bool]] = {}
        notes: dict[int, str] = {}
        for i, ln in sorted(comments.items()):
            m = _SUPPRESS_RE.search(ln)
            if m:
                ids = {s.strip() for s in m.group(1).split(",") if s.strip()}
                note = (m.group(2) or "").strip()
                sup[i] = (ids, bool(note))
                if note:
                    notes[i] = note
        return cls(path=path, relpath=_relpath(path, root), text=text,
                   tree=tree, imports=ImportMap(tree),
                   scope=_scope_of(path), lines=lines, suppressions=sup,
                   suppression_notes=notes, comments=comments)

    def line(self, n: int) -> str:
        return self.lines[n - 1] if 1 <= n <= len(self.lines) else ""

    def suppressed(self, rule_id: str, line: int) -> bool:
        """A `# jtlint: disable=` on the finding's line, or anywhere in
        the contiguous comment block directly above it, silences it —
        so a multi-line justification reads naturally:

            # jtlint: disable=JTL103 -- bounded death poll: fetch every
            # long_scan_poll chunks is the documented fail-fast contract.
            if bool(np.asarray(carry.dead)):
        """
        return self.suppression_line(rule_id, line) is not None

    def suppression_line(self, rule_id: str, line: int) -> Optional[int]:
        """The comment line whose justified disable covers a finding at
        `line`, or None — the engine uses the matched line for the
        unused-suppression accounting behind tools/lint_report.py."""
        def hit(n: int) -> bool:
            ids, justified = self.suppressions.get(n, (set(), False))
            return justified and (rule_id in ids or "all" in ids)

        if hit(line):
            return line
        n = line - 1
        while n >= 1 and self.line(n).lstrip().startswith("#"):
            if hit(n):
                return n
            n -= 1
        return None

    def finding(self, rule: "Rule", node_or_line, message: str,
                hint: Optional[str] = None) -> Finding:
        from .astutil import statement_of

        if isinstance(node_or_line, int):
            line = anchor = node_or_line
        else:
            line = getattr(node_or_line, "lineno", 1)
            # The enclosing statement's first line: a suppression above
            # the statement must keep covering a flagged call that a
            # line-length wrap pushed onto a continuation line.
            anchor = getattr(statement_of(node_or_line), "lineno", line)
        return Finding(rule=rule.id, path=self.relpath, line=line,
                       message=message,
                       hint=rule.hint if hint is None else hint,
                       snippet=self.line(line), anchor=anchor)


def _comment_lines(text: str) -> dict[int, str]:
    """line -> comment text, from the tokenizer (never from strings).
    Falls back to a plain line scan if tokenization fails — the text
    already parsed as a module, so that path is near-unreachable."""
    out: dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(text).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string
    except (tokenize.TokenError, IndentationError, SyntaxError):
        for i, ln in enumerate(text.splitlines(), start=1):
            if "#" in ln:
                out[i] = ln[ln.index("#"):]
    return out


def _scope_of(path: Path) -> Optional[str]:
    """Package subdir a file belongs to: "ops" for
    .../jepsen_etcd_demo_tpu/ops/wgl3.py, "" for a top-level module,
    None when the file is outside the package entirely."""
    parts = path.parts
    for i in range(len(parts) - 1, -1, -1):
        if parts[i] == PACKAGE_NAME:
            rest = parts[i + 1:-1]
            return rest[0] if rest else ""
    return None


def _relpath(path: Path, root: Path) -> str:
    try:
        return path.resolve().relative_to(root.resolve()).as_posix()
    except ValueError:
        return path.as_posix()


class Rule:
    """Base module rule. Subclasses set the class attributes and
    implement :meth:`check`."""

    id: str = ""
    name: str = ""
    scopes: Optional[tuple[str, ...]] = None   # None = whole package
    rationale: str = ""
    hint: str = ""

    def applies_to(self, mod: ModuleSource) -> bool:
        if mod.scope is None:          # outside the package: fixtures,
            return True                # explicit file targets
        if self.scopes is None:
            return True
        return mod.scope in self.scopes

    def check(self, mod: ModuleSource) -> Iterator[Finding]:
        raise NotImplementedError


class ProjectRule(Rule):
    """A rule that runs once per invocation against the repo root
    instead of per module (the KernelLimits doc lint, the JTL4xx flow
    rules). `ctx` — when the engine provides one — is the shared
    ProjectContext carrying the already-parsed modules and the lazily
    built cross-module FlowIndex, so every project rule rides ONE parse
    of the tree instead of re-reading it per rule."""

    def check(self, mod: ModuleSource) -> Iterator[Finding]:
        return iter(())

    def check_project(self, root: Path, ctx=None) -> list[Finding]:
        raise NotImplementedError

    def covered_paths(self, root: Path) -> list[str]:
        """Repo-relative paths this rule's findings land on — baseline
        entries for them count as in-scope (and can go stale) whenever
        the rule runs, even when the rule currently emits nothing."""
        return []


_REGISTRY: dict[str, Rule] = {}


def register(cls):
    """Class decorator: instantiate + register a rule by id."""
    inst = cls()
    assert inst.id and inst.id not in _REGISTRY, f"bad rule id {inst.id!r}"
    _REGISTRY[inst.id] = inst
    return cls


def all_rules() -> dict[str, Rule]:
    """id -> rule instance for the full registered suite (importing
    analysis.rules as a side effect)."""
    from . import rules  # noqa: F401  (imports register the suite)

    return dict(_REGISTRY)


def resolve_rules(spec: Optional[str]) -> dict[str, Rule]:
    """Comma-separated rule ids/names -> registry subset; None = all.
    Unknown names raise ValueError naming the valid ids."""
    rules = all_rules()
    if not spec:
        return rules
    by_name = {r.name: r for r in rules.values()}
    out: dict[str, Rule] = {}
    for tok in (t.strip() for t in spec.split(",")):
        if not tok:
            continue
        if tok in rules:
            out[tok] = rules[tok]
        elif tok in by_name:
            out[by_name[tok].id] = by_name[tok]
        else:
            raise ValueError(
                f"unknown rule {tok!r}; valid: "
                + ", ".join(f"{i} ({r.name})"
                            for i, r in sorted(rules.items())))
    return out
