"""Finding: the one record every jtlint rule (and the doc lint) emits.

One findings format for the whole analysis layer (ISSUE 7): AST rules
over the package, the KernelLimits doc lint (analysis/rules/limits_doc
— the refactored tools/check_limits_doc.py core), and any future
project-level check all produce ``Finding`` rows, so reporting,
suppression accounting, and the baseline mechanism are written once.

Fingerprints are LINE-DRIFT TOLERANT: they hash the rule id, the
repo-relative path, and the whitespace-normalized source line — not the
line number — plus an occurrence index to disambiguate identical lines.
A baseline therefore survives unrelated edits above a finding, and goes
stale exactly when the flagged code itself changes (which is when a
human should re-look anyway).
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass
from typing import Iterable


@dataclass
class Finding:
    rule: str              # rule id, e.g. "JTL103"
    path: str              # repo-relative posix path
    line: int              # 1-based
    message: str           # what is wrong, one sentence
    hint: str = ""         # how to fix it (the rule's fix-hint)
    snippet: str = ""      # the flagged source line (fingerprint input)
    fingerprint: str = ""  # filled by fingerprint_findings()
    anchor: int = 0        # first line of the enclosing STATEMENT (0 =
                           # same as line); a suppression comment above
                           # the statement covers findings on its
                           # continuation lines. Not serialized.

    def text(self) -> str:
        loc = f"{self.path}:{self.line}"
        out = f"{loc}: {self.rule} {self.message}"
        if self.hint:
            out += f"\n    hint: {self.hint}"
        if self.fingerprint:
            out += f"\n    fingerprint: {self.fingerprint}"
        return out

    def as_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message, "hint": self.hint,
                "fingerprint": self.fingerprint}


def _norm(snippet: str) -> str:
    return " ".join(snippet.split())


def fingerprint_findings(findings: Iterable[Finding]) -> list[Finding]:
    """Assign stable fingerprints in place (and return the list).

    sha1(rule | path | normalized snippet | occurrence)[:16], occurrence
    counted among findings sharing all three other components in line
    order — so two identical flagged lines in one file keep distinct,
    stable identities."""
    out = sorted(findings, key=lambda f: (f.path, f.line, f.rule))
    seen: dict[tuple, int] = {}
    for f in out:
        key = (f.rule, f.path, _norm(f.snippet))
        occ = seen.get(key, 0)
        seen[key] = occ + 1
        raw = "|".join((f.rule, f.path, _norm(f.snippet), str(occ)))
        f.fingerprint = hashlib.sha1(raw.encode()).hexdigest()[:16]
    return out


def format_text(findings: list[Finding]) -> str:
    return "\n".join(f.text() for f in findings)


def format_json(findings: list[Finding], **extra) -> str:
    return json.dumps({"findings": [f.as_dict() for f in findings],
                       **extra}, indent=2)


def format_sarif(findings: list[Finding], rules: dict) -> str:
    """SARIF 2.1.0 — the format CI annotators (GitHub code scanning)
    ingest to pin findings onto PR diffs. One run, one driver; the
    stable jtlint fingerprint rides along as a partial fingerprint so
    annotations dedupe across pushes the same way the baseline does."""
    rule_ids = sorted({f.rule for f in findings})
    driver_rules = []
    for rid in rule_ids:
        r = rules.get(rid)
        desc = getattr(r, "rationale", "") or rid
        driver_rules.append({
            "id": rid,
            "name": getattr(r, "name", rid) or rid,
            "shortDescription": {"text": getattr(r, "name", rid) or rid},
            "fullDescription": {"text": desc},
            "help": {"text": getattr(r, "hint", "") or desc},
        })
    results = []
    for f in findings:
        results.append({
            "ruleId": f.rule,
            "level": "error",
            "message": {"text": f.message
                        + (f"\nhint: {f.hint}" if f.hint else "")},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path,
                                         "uriBaseId": "SRCROOT"},
                    "region": {"startLine": max(1, f.line)},
                }}],
            "partialFingerprints": {"jtlint/v1": f.fingerprint},
        })
    doc = {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "jtlint",
                "informationUri": "doc/analysis.md",
                "rules": driver_rules,
            }},
            "results": results,
        }],
    }
    return json.dumps(doc, indent=2)
