"""`jepsen-tpu lint` — the jtlint CLI (also `python -m ...analysis`).

Exit codes: 0 clean (non-strict always exits 0 unless the run itself
errored), 1 = --strict with unbaselined findings or stale baseline
entries, 2 = usage error. The default target is the package itself;
the default baseline is <repo-root>/.jtlint-baseline.json when
present. This module imports nothing heavy — no jax, no kernel code —
so the tier-1 wiring stays well under its 5 s budget.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path
from typing import Optional, Sequence

from .baseline import Baseline, DEFAULT_BASELINE
from .core import PACKAGE_NAME, resolve_rules
from .engine import find_repo_root, run_lint
from .findings import format_json, format_text


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="jepsen-tpu lint",
        description="jtlint: JAX kernel hygiene + concurrency "
                    "discipline static analysis (doc/analysis.md)")
    p.add_argument("paths", nargs="*",
                   help="files/directories to lint (default: the "
                        "jepsen_etcd_demo_tpu package)")
    p.add_argument("--strict", action="store_true",
                   help="exit 1 on any unbaselined finding or stale "
                        "baseline entry (the tier-1 gate)")
    p.add_argument("--json", action="store_true",
                   help="emit one JSON object instead of text")
    p.add_argument("--rules", default=None, metavar="IDS",
                   help="comma-separated rule ids/names to run "
                        "(default: all)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule reference (id, name, scopes, "
                        "rationale) and exit")
    p.add_argument("--baseline", default=None, metavar="FILE",
                   help=f"baseline file (default: <repo-root>/"
                        f"{DEFAULT_BASELINE} when it exists)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline file")
    p.add_argument("--write-baseline", action="store_true",
                   help="accept every current finding into the "
                        "baseline file (notes on existing entries are "
                        "preserved; new entries get a TODO note to "
                        "justify)")
    p.add_argument("--no-project-rules", action="store_true",
                   help="skip project-level rules (the doc lint)")
    return p


def _list_rules(rules) -> str:
    out = []
    for rid in sorted(rules):
        r = rules[rid]
        scopes = ", ".join(r.scopes) if r.scopes else "whole package"
        out.append(f"{rid} {r.name}  [{scopes}]\n"
                   f"    {r.rationale}\n    fix: {r.hint}")
    return "\n".join(out)


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    try:
        rules = resolve_rules(args.rules)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.list_rules:
        print(_list_rules(rules))
        return 0
    if args.no_baseline and args.write_baseline:
        # Writing "ignore the baseline" INTO the checked-in baseline
        # file would clobber it with every current finding.
        print("error: --no-baseline and --write-baseline conflict",
              file=sys.stderr)
        return 2

    if args.paths:
        paths = [Path(p) for p in args.paths]
        missing = [p for p in paths if not p.exists()]
        if missing:
            # A typo'd CI path must not read as a clean lint.
            print("error: no such path(s): "
                  + ", ".join(str(p) for p in missing), file=sys.stderr)
            return 2
        root = find_repo_root(paths[0])
    else:
        root = find_repo_root(Path(__file__))
        paths = [root / PACKAGE_NAME]
        if not paths[0].is_dir():
            print(f"error: cannot locate the {PACKAGE_NAME} package "
                  f"from {root}; pass explicit paths", file=sys.stderr)
            return 2

    # One loading path for --baseline and the repo default: a corrupt /
    # wrong-version baseline must be the documented exit-2 usage error
    # on BOTH (the default path is the tier-1 invocation), never a raw
    # traceback.
    try:
        if args.no_baseline:
            baseline = Baseline()
        elif args.baseline:
            bp = Path(args.baseline)
            baseline = (Baseline.load(bp) if bp.is_file()
                        else Baseline(path=bp))
        else:
            baseline = Baseline.load_or_empty(root / DEFAULT_BASELINE)
    except (ValueError, OSError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    res = run_lint(paths, rules=rules, root=root, baseline=baseline,
                   project_rules=not args.no_project_rules)
    if res.files == 0:
        # Nothing scanned can never read as a clean lint (a green that
        # checked nothing is the worst CI outcome).
        print(f"error: no Python files found under "
              f"{', '.join(str(p) for p in paths)}", file=sys.stderr)
        return 2

    if args.write_baseline:
        baseline.extend(res.findings)
        # Prune what this run proved stale (scoped to scanned paths):
        # the stale-entry message names --write-baseline as the fix, so
        # it must actually remove them or --strict stays red forever.
        for fp in res.stale_baseline:
            baseline.entries.pop(fp, None)
        path = baseline.save(baseline.path or root / DEFAULT_BASELINE)
        print(f"baseline: {len(res.findings)} finding(s) accepted, "
              f"{len(res.stale_baseline)} stale entr"
              f"{'y' if len(res.stale_baseline) == 1 else 'ies'} pruned "
              f"-> {path} — add a justification note per entry")
        return 0

    if args.json:
        print(format_json(
            res.findings, files=res.files,
            suppressed=len(res.suppressed), baselined=len(res.baselined),
            stale_baseline=res.stale_baseline, strict=args.strict,
            ok=res.ok()))
    else:
        if res.findings:
            print(format_text(res.findings))
        for fp in res.stale_baseline:
            ent = baseline.entries.get(fp, {})
            print(f"stale baseline entry {fp} "
                  f"({ent.get('rule', '?')} {ent.get('path', '?')}): the "
                  f"flagged code changed or was fixed — remove the "
                  f"entry (or re-run --write-baseline)")
        print(f"jtlint: {res.files} file(s), "
              f"{len(res.findings)} finding(s), "
              f"{len(res.suppressed)} suppressed, "
              f"{len(res.baselined)} baselined"
              + (f", {len(res.stale_baseline)} stale baseline entr"
                 f"{'y' if len(res.stale_baseline) == 1 else 'ies'}"
                 if res.stale_baseline else ""))
    if args.strict and not res.ok():
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
