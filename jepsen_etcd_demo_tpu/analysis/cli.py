"""`jepsen-tpu lint` — the jtlint CLI (also `python -m ...analysis`).

Exit codes: 0 clean (non-strict always exits 0 unless the run itself
errored), 1 = --strict with unbaselined findings or stale baseline
entries, 2 = usage error. The default target is the package itself;
the default baseline is <repo-root>/.jtlint-baseline.json when
present. This module imports nothing heavy — no jax, no kernel code —
so the tier-1 wiring stays well under its 5 s budget.
"""

from __future__ import annotations

import argparse
import subprocess
import sys
from pathlib import Path
from typing import Optional, Sequence

from .baseline import Baseline, DEFAULT_BASELINE
from .core import PACKAGE_NAME, resolve_rules
from .engine import find_repo_root, run_lint
from .findings import format_json, format_sarif, format_text


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="jepsen-tpu lint",
        description="jtlint: JAX kernel hygiene + concurrency "
                    "discipline static analysis (doc/analysis.md)")
    p.add_argument("paths", nargs="*",
                   help="files/directories to lint (default: the "
                        "jepsen_etcd_demo_tpu package)")
    p.add_argument("--strict", action="store_true",
                   help="exit 1 on any unbaselined finding or stale "
                        "baseline entry (the tier-1 gate)")
    p.add_argument("--json", action="store_true",
                   help="emit one JSON object instead of text "
                        "(alias for --format json)")
    p.add_argument("--format", default=None, metavar="FMT",
                   choices=("text", "json", "sarif"),
                   help="report format: text (default), json, or sarif "
                        "(SARIF 2.1.0 — CI PR annotation)")
    p.add_argument("--changed", default=None, metavar="REF",
                   help="incremental mode: lint only Python files "
                        "changed vs the git base REF (plus untracked). "
                        "Project/flow rules still run full-project "
                        "whenever a changed file dirties the package's "
                        "contract graph, and are skipped otherwise")
    p.add_argument("--contracts", action="store_true",
                   help="print the extracted kernel-contract spec "
                        "(contracts.json content) and exit")
    p.add_argument("--write-contracts", action="store_true",
                   help="regenerate <repo-root>/contracts.json from the "
                        "tree and exit (the JTL406 sync gate's fix)")
    p.add_argument("--rules", default=None, metavar="IDS",
                   help="comma-separated rule ids/names to run "
                        "(default: all)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule reference (id, name, scopes, "
                        "rationale) and exit")
    p.add_argument("--baseline", default=None, metavar="FILE",
                   help=f"baseline file (default: <repo-root>/"
                        f"{DEFAULT_BASELINE} when it exists)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline file")
    p.add_argument("--write-baseline", action="store_true",
                   help="accept every current finding into the "
                        "baseline file (notes on existing entries are "
                        "preserved; new entries get a TODO note to "
                        "justify)")
    p.add_argument("--no-project-rules", action="store_true",
                   help="skip project-level rules (the doc lint)")
    return p


def _list_rules(rules) -> str:
    out = []
    for rid in sorted(rules):
        r = rules[rid]
        scopes = ", ".join(r.scopes) if r.scopes else "whole package"
        out.append(f"{rid} {r.name}  [{scopes}]\n"
                   f"    {r.rationale}\n    fix: {r.hint}")
    return "\n".join(out)


def _git_changed_files(root: Path, ref: str
                       ) -> Optional[tuple[list[str], list[Path]]]:
    """(all changed relpaths, existing changed Python files) vs `ref`
    (working tree diff + untracked). The RAW list keeps deletions and
    non-.py changes — the package-dirty decision must see a deleted
    kernel module or an edited contracts.json/doc file even though
    there is nothing to module-lint in them. None = git unavailable/
    failed (caller falls back to a full-project lint rather than a
    silent partial one)."""
    def run(*cmd: str) -> Optional[list[str]]:
        try:
            out = subprocess.run(cmd, cwd=root, capture_output=True,
                                 text=True, timeout=30)
        except (OSError, subprocess.TimeoutExpired):
            return None
        if out.returncode != 0:
            return None
        return [ln for ln in out.stdout.split("\0") if ln.strip()]

    # --relative: paths come back relative to `root` (the lint root),
    # not the git toplevel — in a monorepo where the project is nested
    # inside a larger repo, toplevel-relative paths would never resolve
    # under root and every change would be silently dropped.
    diff = run("git", "diff", "--name-only", "--relative", "-z", ref)
    if diff is None:
        return None
    untracked = run("git", "ls-files", "--others", "--exclude-standard",
                    "-z") or []
    raw = list(dict.fromkeys(diff + untracked))
    files = [root / rel for rel in raw
             if (root / rel).suffix == ".py" and (root / rel).is_file()]
    return raw, files


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    try:
        rules = resolve_rules(args.rules)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.list_rules:
        print(_list_rules(rules))
        return 0
    fmt = args.format or ("json" if args.json else "text")
    if args.contracts or args.write_contracts:
        from .flow.contracts import (CONTRACTS_FILE, extract_contracts,
                                     render_contracts)

        root = find_repo_root(Path(args.paths[0]) if args.paths
                              else Path(__file__))
        text = render_contracts(extract_contracts(root))
        if args.write_contracts:
            out = root / CONTRACTS_FILE
            out.write_text(text, encoding="utf-8")
            print(f"contracts: wrote {out}")
        else:
            print(text, end="")
        return 0
    if args.no_baseline and args.write_baseline:
        # Writing "ignore the baseline" INTO the checked-in baseline
        # file would clobber it with every current finding.
        print("error: --no-baseline and --write-baseline conflict",
              file=sys.stderr)
        return 2

    if args.paths:
        paths = [Path(p) for p in args.paths]
        missing = [p for p in paths if not p.exists()]
        if missing:
            # A typo'd CI path must not read as a clean lint.
            print("error: no such path(s): "
                  + ", ".join(str(p) for p in missing), file=sys.stderr)
            return 2
        root = find_repo_root(paths[0])
    else:
        root = find_repo_root(Path(__file__))
        paths = [root / PACKAGE_NAME]
        if not paths[0].is_dir():
            print(f"error: cannot locate the {PACKAGE_NAME} package "
                  f"from {root}; pass explicit paths", file=sys.stderr)
            return 2

    project_rules = not args.no_project_rules
    changed_no_modules = False
    if args.changed is not None:
        changed = _git_changed_files(root, args.changed)
        if changed is None:
            # A bad ref / missing git must not silently lint nothing —
            # fall back to the full run the CI gate expects.
            print(f"warning: git diff vs {args.changed!r} failed; "
                  f"falling back to a full lint", file=sys.stderr)
        else:
            raw, py_files = changed
            scope = [p.resolve() for p in paths]
            sel = [f for f in py_files
                   if any(s == f.resolve() or s in f.resolve().parents
                          for s in scope)]
            # Contract-graph dirtiness judges the RAW change list —
            # deleted package modules and non-.py inputs the project
            # rules read (contracts.json for JTL406, doc/ for JTL301)
            # must re-trigger the full-project pass even though there
            # is no surviving .py file to module-lint.
            dirty = any(
                rel.split("/")[0] in (PACKAGE_NAME, "doc")
                or rel == "contracts.json" for rel in raw)
            if not sel and not (dirty and project_rules):
                # The quiet no-op must still honor the output contract:
                # a CI consumer parsing --format json/sarif gets an
                # empty findings document, never prose on stdout.
                if fmt == "json":
                    print(format_json([], files=0, suppressed=0,
                                      baselined=0, stale_baseline=[],
                                      strict=args.strict, ok=True))
                elif fmt == "sarif":
                    print(format_sarif([], rules))
                else:
                    print(f"jtlint: nothing changed vs {args.changed} "
                          f"under {', '.join(str(p) for p in paths)} — "
                          f"nothing to lint")
                return 0
            paths = sel
            changed_no_modules = not sel
            if project_rules and not dirty:
                # Project/flow rules read the whole contract graph; when
                # no changed file touches it, their full-project pass is
                # provably unchanged — skip it. ANY package/doc/
                # contracts change dirties the graph and falls back to
                # the full flow pass.
                project_rules = False

    # One loading path for --baseline and the repo default: a corrupt /
    # wrong-version baseline must be the documented exit-2 usage error
    # on BOTH (the default path is the tier-1 invocation), never a raw
    # traceback.
    try:
        if args.no_baseline:
            baseline = Baseline()
        elif args.baseline:
            bp = Path(args.baseline)
            baseline = (Baseline.load(bp) if bp.is_file()
                        else Baseline(path=bp))
        else:
            baseline = Baseline.load_or_empty(root / DEFAULT_BASELINE)
    except (ValueError, OSError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    res = run_lint(paths, rules=rules, root=root, baseline=baseline,
                   project_rules=project_rules)
    if res.files == 0 and not changed_no_modules:
        # Nothing scanned can never read as a clean lint (a green that
        # checked nothing is the worst CI outcome). Exception: a
        # --changed run whose only changes are project-rule inputs
        # (contracts.json, doc/, a deleted module) legitimately scans
        # zero modules — the project rules above were the point.
        print(f"error: no Python files found under "
              f"{', '.join(str(p) for p in paths)}", file=sys.stderr)
        return 2

    if args.write_baseline:
        baseline.extend(res.findings)
        # Prune what this run proved stale (scoped to scanned paths):
        # the stale-entry message names --write-baseline as the fix, so
        # it must actually remove them or --strict stays red forever.
        for fp in res.stale_baseline:
            baseline.entries.pop(fp, None)
        path = baseline.save(baseline.path or root / DEFAULT_BASELINE)
        print(f"baseline: {len(res.findings)} finding(s) accepted, "
              f"{len(res.stale_baseline)} stale entr"
              f"{'y' if len(res.stale_baseline) == 1 else 'ies'} pruned "
              f"-> {path} — add a justification note per entry")
        return 0

    if fmt == "json":
        print(format_json(
            res.findings, files=res.files,
            suppressed=len(res.suppressed), baselined=len(res.baselined),
            stale_baseline=res.stale_baseline, strict=args.strict,
            ok=res.ok()))
    elif fmt == "sarif":
        print(format_sarif(res.findings, rules))
    else:
        if res.findings:
            print(format_text(res.findings))
        for fp in res.stale_baseline:
            ent = baseline.entries.get(fp, {})
            print(f"stale baseline entry {fp} "
                  f"({ent.get('rule', '?')} {ent.get('path', '?')}): the "
                  f"flagged code changed or was fixed — remove the "
                  f"entry (or re-run --write-baseline)")
        print(f"jtlint: {res.files} file(s), "
              f"{len(res.findings)} finding(s), "
              f"{len(res.suppressed)} suppressed, "
              f"{len(res.baselined)} baselined"
              + (f", {len(res.stale_baseline)} stale baseline entr"
                 f"{'y' if len(res.stale_baseline) == 1 else 'ies'}"
                 if res.stale_baseline else ""))
    if args.strict and not res.ok():
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
