"""Contract extraction: ``# jtflow:`` annotations + whole-program facts.

The flow rules (JTL401-405) and the contracts.json artifact both
consume one ``FlowFacts`` object extracted from a ``FlowIndex`` — the
extraction runs ONCE per lint invocation and is shared (the engine's
parse-once discipline extended to the cross-module layer).

Most facts are extracted from the code itself (packed-field tuples,
``jnp.stack`` widths, ``donate_argnums``, NamedTuple carries, mesh
constructions, collective axis names, metric-name literals). Where the
code cannot carry the contract — a bare integer literal that *means*
"the pack width", a tuple constant that *means* "pre-registered metric
set" — a small declarative annotation ties the literal to the contract
so drift becomes machine-checkable:

    # jtflow: packs wgl3.PACKED_FIELDS_XLA          (producer function)
    # jtflow: unpacks wgl3.PACKED_FIELDS_XLA        (consumer function)
    # jtflow: packed wgl3.PACKED_FIELDS_XLA         (declares a kernel's
                                                     packed result schema)
    # jtflow: packed-width=5 wgl3.PACKED_FIELDS     (this statement's
                                                     literal 5 IS the width)
    # jtflow: partials configs,live_tile_sum,real_steps
    # jtflow: partials-from wgl3._chunk_fn
    # jtflow: mesh-axes slice,batch
    # jtflow: table-word-bits=5
    # jtflow: metrics preregistered

An annotation binds to the next statement (or the statement on its own
line), exactly like a jtlint suppression. Annotations that fail to
bind, reference an unknown schema, or disagree with the code they
annotate are themselves JTL401 findings — a stale annotation is drift.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Optional

from ..astutil import dotted, walk_cached
from ..core import ModuleSource
from .index import FlowIndex

_ANNOT_RE = re.compile(r"#\s*jtflow:\s*(.+?)\s*$")

# Collective / sharding call suffixes whose axis argument names a mesh
# axis (positional index of the axis arg; kw axis_name also accepted).
COLLECTIVES = {"lax.psum": 1, "lax.pmax": 1, "lax.pmin": 1,
               "lax.pmean": 1, "lax.ppermute": 1, "lax.all_gather": 1,
               "lax.axis_index": 0, "lax.psum_scatter": 1}

_METRIC_METHODS = ("counter", "gauge", "histogram")


@dataclass
class Annotation:
    mod: ModuleSource
    line: int                    # the comment line
    directive: str
    arg: str
    node: Optional[ast.stmt]     # bound statement (None = failed to bind)


@dataclass
class SchemaDecl:
    ref: str                     # "wgl3.PACKED_FIELDS_XLA"
    module: str                  # relpath
    name: str
    fields: tuple[str, ...]
    line: int

    @property
    def width(self) -> int:
        return len(self.fields)


@dataclass
class KernelDecl:
    name: str                    # instrument_kernel's literal name
    module: str
    factory: str                 # enclosing function ("" = module level)
    line: int
    donates: tuple[int, ...] = ()
    packed: Optional[str] = None     # schema ref from a packed/packs annot


@dataclass
class CarryDecl:
    name: str
    module: str
    fields: tuple[str, ...]
    line: int


@dataclass
class AxisUse:
    mod: ModuleSource
    line: int
    kind: str                    # "psum", "ppermute", "partition-spec", ...
    axis: str


@dataclass
class MetricWrite:
    mod: ModuleSource
    line: int
    method: str                  # counter/gauge/histogram
    name: Optional[str]          # literal (or const-resolved) name
    family: Optional[str]        # f-string family prefix, "." / "_" trimmed


@dataclass
class FlowFacts:
    index: FlowIndex
    annotations: list[Annotation] = field(default_factory=list)
    schemas: dict[str, SchemaDecl] = field(default_factory=dict)
    kernels: list[KernelDecl] = field(default_factory=list)
    carries: dict[str, CarryDecl] = field(default_factory=dict)
    # factory symbol ("wgl3._init_carry3") -> carry class name
    carry_factories: dict[str, str] = field(default_factory=dict)
    mesh_axes: dict[str, list[str]] = field(default_factory=dict)
    axis_uses: list[AxisUse] = field(default_factory=list)
    # (mod, line, shift literal) of `1 << (K|k_slots - N)` table-width math
    word_shifts: list[tuple[ModuleSource, int, int]] = field(
        default_factory=list)
    table_word_bits: Optional[tuple[int, str, int]] = None  # (N, mod, line)
    # metric facts
    # name -> (declaring module relpath, annotation line)
    preregistered: dict[str, tuple[str, int]] = field(default_factory=dict)
    prereg_modules: set[str] = field(default_factory=set)
    labeled_families: dict[str, str] = field(default_factory=dict)
    metric_writes: list[MetricWrite] = field(default_factory=list)
    snapshot_reads: list[tuple[ModuleSource, int, str]] = field(
        default_factory=list)
    # "stem.func" -> declared partial-sum field names
    partial_layouts: dict[str, tuple[str, ...]] = field(default_factory=dict)


def flow_facts(index: FlowIndex) -> FlowFacts:
    """Extract (and memoize on the index) the whole-program facts."""
    if index._facts is None:
        index._facts = _extract(index)
    return index._facts


# -- helpers ---------------------------------------------------------------

def _stem(mod: ModuleSource) -> str:
    stem = mod.path.stem
    if stem == "__init__":
        stem = mod.path.parent.name
    return stem


def _stmt_at(mod: ModuleSource, line: int) -> Optional[ast.stmt]:
    """The outermost statement starting exactly at `line`."""
    for node in mod.walk_nodes():       # BFS: outermost first
        if isinstance(node, ast.stmt) and node.lineno == line:
            return node
    return None


def _bind_line(mod: ModuleSource, line: int) -> Optional[int]:
    """The code line an annotation at `line` governs: the same line when
    code precedes the comment, else the first following non-comment,
    non-blank line."""
    text = mod.line(line)
    before = text.split("#", 1)[0]
    if before.strip():
        return line
    n = line + 1
    while n <= len(mod.lines):
        s = mod.line(n).strip()
        if s and not s.startswith("#"):
            return n
        n += 1
    return None


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _str_tuple(node: ast.AST) -> Optional[tuple[str, ...]]:
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            s = _const_str(e)
            if s is None:
                return None
            out.append(s)
        return tuple(out)
    return None


def _module_consts(mod: ModuleSource) -> dict[str, ast.AST]:
    out: dict[str, ast.AST] = {}
    for node in mod.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            out[node.targets[0].id] = node.value
    return out


def _resolve_fields(mod: ModuleSource, consts: dict[str, ast.AST],
                    node: ast.AST, depth: int = 0
                    ) -> Optional[tuple[str, ...]]:
    """A tuple-of-str constant, through one level of `BASE + (...)`."""
    if depth > 3:
        return None
    t = _str_tuple(node)
    if t is not None:
        return t
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        left = _resolve_fields(mod, consts, node.left, depth + 1)
        right = _resolve_fields(mod, consts, node.right, depth + 1)
        if left is not None and right is not None:
            return left + right
    if isinstance(node, ast.Name) and node.id in consts:
        return _resolve_fields(mod, consts, consts[node.id], depth + 1)
    return None


def enclosing_def_name(node: ast.AST) -> str:
    """The OUTERMOST enclosing function's name — contract layouts and
    kernel factories are addressed by the public factory
    (``wgl3._chunk_fn``), not the ubiquitous nested ``run``/``launch``
    defs the jit actually wraps."""
    from ..astutil import ancestors

    name = ""
    for a in ancestors(node):
        if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef)):
            name = a.name
    return name


def _param_default_node(fn: ast.AST, name: str) -> Optional[ast.AST]:
    """The default-value NODE of parameter `name` on a FunctionDef —
    matched to the parameter itself, never to a neighboring default."""
    if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return None
    a = fn.args
    pos = a.posonlyargs + a.args
    defaults = a.defaults
    for i, arg in enumerate(reversed(pos)):
        if arg.arg == name and i < len(defaults):
            return defaults[-1 - i]
    for arg, d in zip(a.kwonlyargs, a.kw_defaults):
        if arg.arg == name and d is not None:
            return d
    return None


def _param_default(fn: ast.AST, name: str) -> Optional[str]:
    """String default of parameter `name` on a FunctionDef."""
    d = _param_default_node(fn, name)
    return _const_str(d) if d is not None else None


class _AxisResolver:
    """Resolve an axis-argument expression to a string axis name:
    a literal, a module constant, a parameter default of the enclosing
    function, or — for defaultless parameters — the single consistent
    string every intra-project call site passes (one propagation hop,
    which resolves the `_build_local_step(..., axis, ...)` idiom)."""

    def __init__(self, index: FlowIndex):
        self.index = index
        self._call_sites: Optional[dict] = None  # fname -> [(mod, Call)]

    def _sites(self, fname: str) -> list:
        """All project call sites by bare callee name — indexed ONCE
        (the per-lookup whole-project walk was the flow pass's dominant
        cost)."""
        if self._call_sites is None:
            self._call_sites = {}
            for m in self.index.modules.values():
                for call in m.walk_nodes():
                    if not isinstance(call, ast.Call):
                        continue
                    callee = dotted(call.func)
                    if callee is None:
                        continue
                    self._call_sites.setdefault(
                        callee.split(".")[-1], []).append((m, call))
        return self._call_sites.get(fname, [])

    def resolve(self, mod: ModuleSource, node: ast.AST,
                depth: int = 0) -> Optional[str]:
        s = _const_str(node)
        if s is not None:
            return s
        if depth > 2 or not isinstance(node, ast.Name):
            return None
        from ..astutil import enclosing_function

        fn = enclosing_function(node)
        seen = set()
        while fn is not None and fn not in seen:      # closures walk out
            seen.add(fn)
            d = _param_default(fn, node.id)
            if d is not None:
                return d
            if any(a.arg == node.id
                   for a in fn.args.posonlyargs + fn.args.args
                   + fn.args.kwonlyargs):
                return self._from_call_sites(mod, fn, node.id, depth)
            fn = enclosing_function(fn)
        consts = _module_consts(mod)
        if node.id in consts:
            return _const_str(consts[node.id])
        return None

    def _from_call_sites(self, mod: ModuleSource, fn, param: str,
                         depth: int) -> Optional[str]:
        values: set[str] = set()
        pos = fn.args.posonlyargs + fn.args.args
        try:
            pidx = [a.arg for a in pos].index(param)
        except ValueError:
            pidx = None
        for m, call in self._sites(fn.name):
            arg = None
            for kw in call.keywords:
                if kw.arg == param:
                    arg = kw.value
            if arg is None and pidx is not None and pidx < len(call.args):
                arg = call.args[pidx]
            if arg is not None:
                v = self.resolve(m, arg, depth + 1)
                if v is None:
                    return None     # ambiguous: stay conservative
                values.add(v)
        return values.pop() if len(values) == 1 else None


# -- extraction ------------------------------------------------------------

def contract_modules(index: FlowIndex) -> list[ModuleSource]:
    """The modules the flow pass analyzes: everything indexed except the
    analysis layer itself — the lint sources quote ``# jtflow:`` syntax
    in docstrings and rationale strings constantly, and they declare no
    kernel contracts of their own."""
    return [m for m in index.modules.values() if m.scope != "analysis"]


def _extract(index: FlowIndex) -> FlowFacts:
    facts = FlowFacts(index=index)
    mods = contract_modules(index)
    for mod in mods:
        _extract_annotations(facts, mod)
    for mod in mods:
        _extract_schemas(facts, mod)
        _extract_carries(facts, mod)
        _extract_metrics(facts, mod)
        _extract_word_shifts(facts, mod)
    axis_res = _AxisResolver(index)
    for mod in mods:
        _extract_mesh_axes(facts, mod, axis_res)
        _extract_axis_uses(facts, mod, axis_res)
        _extract_kernels(facts, mod)
    _apply_annotations(facts)
    return facts


def _extract_annotations(facts: FlowFacts, mod: ModuleSource) -> None:
    # Real comment tokens only (mod.comments): jtflow grammar quoted in
    # a docstring is prose, but a trailing comment after a multiline
    # string's closing quote is live.
    for i, ln in sorted(mod.comments.items()):
        m = _ANNOT_RE.search(ln)
        if not m:
            continue
        body = m.group(1)
        head, _, rest = body.partition(" ")
        directive, _, inline = head.partition("=")
        arg = (inline + " " + rest).strip() if inline else rest.strip()
        target = _bind_line(mod, i)
        node = _stmt_at(mod, target) if target is not None else None
        facts.annotations.append(Annotation(
            mod=mod, line=i, directive=directive, arg=arg, node=node))


def _extract_schemas(facts: FlowFacts, mod: ModuleSource) -> None:
    consts = _module_consts(mod)
    stem = _stem(mod)
    for node in mod.tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        name = node.targets[0].id
        if "PACKED_FIELDS" not in name:
            continue
        fields = _resolve_fields(mod, consts, node.value)
        if fields is not None:
            ref = f"{stem}.{name}"
            facts.schemas[ref] = SchemaDecl(
                ref=ref, module=mod.relpath, name=name, fields=fields,
                line=node.lineno)


def _extract_carries(facts: FlowFacts, mod: ModuleSource) -> None:
    stem = _stem(mod)
    for node in mod.walk_nodes():
        if not isinstance(node, ast.ClassDef):
            continue
        bases = {dotted(b) or "" for b in node.bases}
        if not any(b.endswith("NamedTuple") for b in bases):
            continue
        if not node.name.lstrip("_").lower().startswith("carry"):
            continue
        fields = tuple(
            t.target.id for t in node.body
            if isinstance(t, ast.AnnAssign) and isinstance(t.target,
                                                           ast.Name))
        if fields:
            facts.carries[node.name] = CarryDecl(
                name=node.name, module=mod.relpath, fields=fields,
                line=node.lineno)
    # Factory mapping: functions whose return constructs a known carry.
    for fn in mod.walk_nodes():
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for ret in walk_cached(fn):
            if isinstance(ret, ast.Return) and isinstance(ret.value,
                                                          ast.Call):
                callee = dotted(ret.value.func)
                if callee in facts.carries:
                    facts.carry_factories[f"{stem}.{fn.name}"] = callee
                    break


def _extract_word_shifts(facts: FlowFacts, mod: ModuleSource) -> None:
    """`1 << (K - N)` / `1 << (cfg.k_slots - N)` sites: the packed-table
    word-width math whose literal N must agree with the declared
    table-word-bits everywhere (JTL403's shard-width half)."""
    for node in mod.walk_nodes():
        if not (isinstance(node, ast.BinOp)
                and isinstance(node.op, ast.LShift)
                and isinstance(node.left, ast.Constant)
                and node.left.value == 1
                and isinstance(node.right, ast.BinOp)
                and isinstance(node.right.op, ast.Sub)
                and isinstance(node.right.right, ast.Constant)
                and isinstance(node.right.right.value, int)):
            continue
        base = dotted(node.right.left) or ""
        if base == "K" or base.endswith("k_slots"):
            facts.word_shifts.append(
                (mod, node.lineno, node.right.right.value))


def _extract_mesh_axes(facts: FlowFacts, mod: ModuleSource,
                       axis_res: _AxisResolver) -> None:
    from ..astutil import enclosing_function

    def declare(axis: Optional[str]) -> None:
        if axis:
            facts.mesh_axes.setdefault(axis, [])
            if mod.relpath not in facts.mesh_axes[axis]:
                facts.mesh_axes[axis].append(mod.relpath)

    for node in mod.walk_nodes():
        # def make_mesh(..., axes=("batch",)) — the `axes` parameter's
        # OWN default declares (not any tuple default the function
        # happens to carry).
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            d = _param_default_node(node, "axes")
            if d is not None:
                for ax in _str_tuple(d) or ():
                    declare(ax)
            continue
        if not isinstance(node, ast.Call):
            continue
        callee = mod.imports.resolve(node.func) or ""
        if callee.endswith("make_mesh"):
            for kw in node.keywords:
                if kw.arg == "axes":
                    for ax in _str_tuple(kw.value) or ():
                        declare(ax)
        elif callee.split(".")[-1] == "Mesh" and len(node.args) >= 2:
            axes_arg = node.args[1]
            if isinstance(axes_arg, (ast.Tuple, ast.List)):
                for e in axes_arg.elts:
                    declare(axis_res.resolve(mod, e))
            _ = enclosing_function  # (kept for symmetry with uses)


def _extract_axis_uses(facts: FlowFacts, mod: ModuleSource,
                       axis_res: _AxisResolver) -> None:
    for node in mod.walk_nodes():
        if not isinstance(node, ast.Call):
            continue
        callee = mod.imports.resolve(node.func) or ""
        matched = None
        for suffix, pos in COLLECTIVES.items():
            if callee == suffix or callee.endswith("." + suffix):
                matched = (suffix.split(".")[-1], pos)
                break
        if matched is not None:
            kind, pos = matched
            arg = None
            for kw in node.keywords:
                if kw.arg in ("axis_name", "axis"):
                    arg = kw.value
            if arg is None and pos < len(node.args):
                arg = node.args[pos]
            axis = axis_res.resolve(mod, arg) if arg is not None else None
            if axis is not None:
                facts.axis_uses.append(AxisUse(mod, node.lineno, kind,
                                               axis))
            continue
        if callee.endswith("PartitionSpec"):
            for e in node.args:
                axis = None
                if _const_str(e) is not None or isinstance(e, ast.Name):
                    axis = axis_res.resolve(mod, e)
                if axis is not None:
                    facts.axis_uses.append(
                        AxisUse(mod, node.lineno, "partition-spec", axis))


def _extract_kernels(facts: FlowFacts, mod: ModuleSource) -> None:
    for node in mod.walk_nodes():
        if not isinstance(node, ast.Call):
            continue
        if not mod.imports.is_call_to(node, "instrument_kernel",
                                      "obs.instrument_kernel"):
            continue
        if not node.args:
            continue
        name = _const_str(node.args[0])
        if name is None:
            continue
        donates: tuple[int, ...] = ()
        if len(node.args) > 1:
            d = facts.index.donates(mod, node.args[-1])
            if d is not None:
                donates = d[0]
        facts.kernels.append(KernelDecl(
            name=name, module=mod.relpath,
            factory=enclosing_def_name(node), line=node.lineno,
            donates=donates))


def _extract_metrics(facts: FlowFacts, mod: ModuleSource) -> None:
    consts = _module_consts(mod)
    # LABELED_FAMILIES = {...} (obs/export.py or a fixture's stand-in).
    fam = consts.get("LABELED_FAMILIES")
    if isinstance(fam, ast.Dict):
        for k, v in zip(fam.keys, fam.values):
            ks, vs = _const_str(k), _const_str(v)
            if ks is not None:
                facts.labeled_families[ks] = vs or ""
    for node in mod.walk_nodes():
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _METRIC_METHODS and node.args):
            continue
        recv = node.func.value
        if isinstance(recv, ast.Name) and recv.id in mod.imports.names:
            continue            # np.histogram(...) — not an instrument
        arg = node.args[0]
        name = _const_str(arg)
        family = None
        if name is None and isinstance(arg, ast.Name) \
                and arg.id in consts:
            name = _const_str(consts[arg.id])
        if name is None and isinstance(arg, ast.JoinedStr) and arg.values:
            lead = arg.values[0]
            prefix = _const_str(lead)
            if prefix:
                family = prefix.rstrip("._")
        if name is not None or family is not None:
            facts.metric_writes.append(MetricWrite(
                mod=mod, line=node.lineno, method=node.func.attr,
                name=name, family=family))
    # Snapshot readers live with the pre-registration declarations
    # (obs/__init__.py in the real tree) — collected in
    # _apply_annotations once prereg_modules is known.


def _extract_snapshot_reads(facts: FlowFacts, mod: ModuleSource) -> None:
    from ..astutil import walk_same_scope

    consts = _module_consts(mod)
    for fn in mod.walk_nodes():
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        has_snapshot = any(
            isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
            and n.func.attr == "snapshot" for n in walk_cached(fn))
        if not has_snapshot:
            continue
        nested = {n.name for n in walk_same_scope(fn)
                  if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        for call in walk_cached(fn):
            if not (isinstance(call, ast.Call) and call.args):
                continue
            is_get = (isinstance(call.func, ast.Attribute)
                      and call.func.attr == "get")
            is_helper = (isinstance(call.func, ast.Name)
                         and call.func.id in nested)
            if not (is_get or is_helper):
                continue
            arg = call.args[0]
            name = _const_str(arg)
            if name is None and isinstance(arg, ast.Name) \
                    and arg.id in consts:
                name = _const_str(consts[arg.id])
            if name is not None and "." in name:
                facts.snapshot_reads.append((mod, call.lineno, name))


def _apply_annotations(facts: FlowFacts) -> None:
    """Fold annotation-declared facts into the registries (verification
    against the code happens in the flow rules, which own the finding
    format)."""
    for a in facts.annotations:
        if a.node is None:
            continue
        if a.directive == "mesh-axes":
            for ax in (s.strip() for s in a.arg.split(",")):
                if ax:
                    facts.mesh_axes.setdefault(ax, [])
                    if a.mod.relpath not in facts.mesh_axes[ax]:
                        facts.mesh_axes[ax].append(a.mod.relpath)
        elif a.directive == "table-word-bits":
            try:
                facts.table_word_bits = (int(a.arg), a.mod.relpath, a.line)
            except ValueError:
                pass        # malformed: JTL401 reports it
        elif a.directive == "metrics" and a.arg == "preregistered":
            names: tuple[str, ...] = ()
            if isinstance(a.node, ast.Assign):
                consts = _module_consts(a.mod)
                names = _resolve_fields(a.mod, consts, a.node.value) or ()
                if not names:
                    s = _const_str(a.node.value)
                    if s is not None:
                        names = (s,)
            for n in names:
                facts.preregistered.setdefault(n, (a.mod.relpath, a.line))
            facts.prereg_modules.add(a.mod.relpath)
        elif a.directive == "partials":
            names = tuple(s.strip() for s in a.arg.split(",") if s.strip())
            fname = (a.node.name
                     if isinstance(a.node, (ast.FunctionDef,
                                            ast.AsyncFunctionDef))
                     else enclosing_def_name(a.node))
            facts.partial_layouts[f"{_stem(a.mod)}.{fname}"] = names
        elif a.directive in ("packs", "packed"):
            # Attach the schema to kernels declared in the same factory.
            fname = enclosing_def_name(a.node)
            for k in facts.kernels:
                if k.module == a.mod.relpath and (
                        k.factory == fname
                        or (isinstance(a.node, ast.FunctionDef)
                            and a.node.name == k.factory)):
                    k.packed = a.arg
    # Snapshot-reader collection needs prereg_modules settled first.
    for rel in sorted(facts.prereg_modules):
        mod = facts.index.modules.get(rel)
        if mod is not None:
            _extract_snapshot_reads(facts, mod)
