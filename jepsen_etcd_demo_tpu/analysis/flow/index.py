"""FlowIndex: the whole-program module index behind the flow rules.

One parse of every in-scope module (reusing the engine's already-loaded
``ModuleSource`` objects when the lint run scanned them — the parse-
once contract of analysis/engine.py), plus the two resolution services
every flow rule needs:

  * **symbol resolution** — a dotted origin from a consumer module's
    ``ImportMap`` (``ops.wgl3._cached_chunk_run``,
    ``producer.cached_run``) resolved to (producing module, symbol);
  * **donation resolution** — the donated-operand positions of a
    callable resolved ACROSS modules, by chaining each module's
    intra-module resolver (analysis/rules/donation.py) through the
    import graph: ``stream/engine.py`` calling
    ``wgl3._cached_chunk_run`` resolves through wgl3's
    ``_CACHE[key] = instrument_kernel(..., _chunk_fn(...))`` store to
    ``jax.jit(run, donate_argnums=(0,))``.

Scope: when ``<root>/jepsen_etcd_demo_tpu`` exists the index covers the
package (the production contract graph); otherwise every ``*.py``
under the root (the flow-rule fixture mini-projects in
tests/lint_fixtures/). Parses are cached process-wide keyed by
(path, mtime_ns, size) so repeated lint runs — fixtures in one pytest
session, ``--changed`` full-project fallbacks — never re-parse an
unchanged file.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Optional

from ..core import ModuleSource, PACKAGE_NAME

# (resolved path, mtime_ns, size, root) -> ModuleSource. Bounded: the
# cache is cleared wholesale past the cap (a whole-repo lint is ~130
# files; the cap only guards pathological fixture churn).
_PARSE_CACHE: dict[tuple, ModuleSource] = {}
_PARSE_CACHE_CAP = 4096


def load_module_cached(path: Path, root: Path) -> ModuleSource:
    """ModuleSource.load with a process-wide stat-keyed cache."""
    rp = Path(path).resolve()
    try:
        st = rp.stat()
        key = (str(rp), st.st_mtime_ns, st.st_size, str(Path(root).resolve()))
    except OSError:
        return ModuleSource.load(path, root)
    mod = _PARSE_CACHE.get(key)
    if mod is None:
        if len(_PARSE_CACHE) > _PARSE_CACHE_CAP:
            _PARSE_CACHE.clear()
        mod = ModuleSource.load(path, root)
        _PARSE_CACHE[key] = mod
    return mod


class FlowIndex:
    """Parsed modules + cross-module resolution for one project root."""

    def __init__(self, root: Path, modules: dict[str, ModuleSource]):
        self.root = Path(root)
        self.modules = modules           # relpath -> ModuleSource
        self._resolvers: dict[str, object] = {}
        self._facts = None               # memoized FlowFacts (facts.py)
        # Dotted module name -> relpath ("jepsen_etcd_demo_tpu.ops.wgl3"
        # and its suffixes resolve; fixture files resolve by stem).
        self.dotted: dict[str, str] = {}
        for rel in modules:
            parts = Path(rel).with_suffix("").parts
            if parts and parts[-1] == "__init__":
                parts = parts[:-1]
            for i in range(len(parts)):
                self.dotted.setdefault(".".join(parts[i:]), rel)

    @classmethod
    def build(cls, root: Path,
              preloaded: Optional[dict[str, ModuleSource]] = None
              ) -> "FlowIndex":
        """Index the contract graph under `root`: the package when it
        exists, else every .py below root (fixture mini-projects).
        `preloaded` ModuleSources (the engine's current scan) are reused
        verbatim — no re-parse."""
        from ..core import _relpath
        from ..engine import iter_python_files

        root = Path(root)
        pkg = root / PACKAGE_NAME
        files = iter_python_files([pkg if pkg.is_dir() else root])
        preloaded = preloaded or {}
        modules: dict[str, ModuleSource] = {}
        for f in files:
            rel = _relpath(f, root)
            mod = preloaded.get(rel)
            if mod is None:
                try:
                    mod = load_module_cached(f, root)
                except (SyntaxError, UnicodeDecodeError, OSError):
                    continue        # JTL000 is the per-file engine's job
            modules[rel] = mod
        return cls(root, modules)

    # -- symbol resolution -------------------------------------------------

    def resolve_symbol(self, origin: Optional[str]
                       ) -> Optional[tuple[ModuleSource, str]]:
        """A dotted origin (import-resolved by the consumer module) ->
        (defining module, symbol name), or None. Tries the longest
        module prefix first so ``ops.wgl3._cached_chunk_run`` binds to
        ops/wgl3.py even when a top-level module named ``ops`` exists."""
        if not origin or "." not in origin:
            return None
        parts = origin.split(".")
        for i in range(len(parts) - 1, 0, -1):
            rel = self.dotted.get(".".join(parts[:i]))
            if rel is not None and i < len(parts):
                return self.modules[rel], ".".join(parts[i:])
        return None

    def module_of(self, mod_dotted: str) -> Optional[ModuleSource]:
        rel = self.dotted.get(mod_dotted)
        return self.modules[rel] if rel is not None else None

    # -- donation resolution ----------------------------------------------

    def _resolver(self, mod: ModuleSource):
        from ..rules.donation import _Resolver

        r = self._resolvers.get(mod.relpath)
        if r is None:
            r = self._resolvers[mod.relpath] = _Resolver(mod)
        return r

    def donates(self, mod: ModuleSource, node: ast.AST,
                depth: int = 0) -> Optional[tuple[tuple[int, ...], bool]]:
        """Donated positions of the callable `node` evaluates to, chasing
        imports across modules. Returns (indices, crossed_module) or
        None. ``crossed_module`` distinguishes the interprocedural
        findings (JTL402) from what the intra-module rule (JTL102)
        already reports."""
        if depth > 4:
            return None
        local = self._resolver(mod).expr(node)
        if local is not None:
            return local, False
        # Cross-module: a call (or bare name) whose origin lives in
        # another indexed module.
        target = None
        if isinstance(node, ast.Call):
            target = node.func
        elif isinstance(node, (ast.Name, ast.Attribute)):
            target = node
        if target is None:
            return None
        resolved = self.resolve_symbol(mod.imports.resolve(target))
        if resolved is None:
            return None
        tmod, sym = resolved
        if tmod is mod or "." in sym:
            return None
        d = self._resolver(tmod).function(sym)
        if d is None:
            return None
        return d, True
