"""contracts.json: the machine-readable kernel-interface spec.

This is the artifact the flow pass both *verifies against the tree*
(rules JTL401-405) and *emits for consumers*: a reviewed, diffable
statement of every cross-module kernel contract — exactly the explicit
interface set ROADMAP item 5's ``KernelPlan`` layer will be built on.
``jepsen-tpu lint --write-contracts`` regenerates it; a tier-1 check
(JTL406 + tests/test_lint.py) fails when the checked-in copy drifts
from the tree, the same regenerate-and-diff discipline as the
KernelLimits doc lint.

Sections (all extracted by analysis/flow/facts.py, deterministically —
sorted keys, repo-relative posix paths, no timestamps):

  * ``packed_schemas``   field tuple + column width per packed-result
                         schema (``wgl3.PACKED_FIELDS[_XLA]``)
  * ``kernels``          every ``instrument_kernel`` site: name, module,
                         factory, donated operand positions, packed
                         schema where declared
  * ``partials``         per-chunk partial-sum layouts (the f32[N]
                         accumulator rows consumers index into)
  * ``carries``          resumable-carry NamedTuple field sets + the
                         factories that build them
  * ``meshes``           declared mesh axis names -> declaring modules
  * ``collectives``      per-module collective/sharding axis uses
  * ``metrics``          pre-registered capture names, labeled export
                         families, snapshot-contract keys
  * ``sync``             the jtsan concurrency contract (analysis/flow/
                         sync.py): canonical lock ids, thread roots,
                         each shared structure's guarding lock + the
                         threads that touch it, and the may-happen
                         lock-order edge set the runtime sanitizer
                         (obs/sync.py) is validated against
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Optional

from .facts import FlowFacts, flow_facts
from .index import FlowIndex

CONTRACTS_VERSION = 1
CONTRACTS_FILE = "contracts.json"


def extract_contracts(root: Path,
                      index: Optional[FlowIndex] = None) -> dict:
    """The contracts dict for `root` (building a FlowIndex unless the
    caller shares one — the engine passes its ProjectContext index so
    the whole lint run parses each file once)."""
    if index is None:
        index = FlowIndex.build(Path(root))
    facts = flow_facts(index)
    return _assemble(facts)


def _sync_section(index: FlowIndex) -> dict:
    from .sync import sync_model

    return sync_model(index).contract_section()


def _assemble(facts: FlowFacts) -> dict:
    kernels: dict[str, dict] = {}
    for k in sorted(facts.kernels, key=lambda k: (k.name, k.module,
                                                  k.line)):
        ent = kernels.get(k.name)
        if ent is None:
            kernels[k.name] = ent = {
                "module": k.module, "factory": k.factory or None,
                "donates": sorted(k.donates)}
        else:
            # Same kernel name from two factories (wgl3-batch's packed
            # and dict forms): one entry, facts merged.
            ent["donates"] = sorted(set(ent["donates"]) | set(k.donates))
        if k.packed and not ent.get("packed"):
            ent["packed"] = k.packed

    collectives: dict[str, dict[str, list[str]]] = {}
    for use in facts.axis_uses:
        by_kind = collectives.setdefault(use.mod.relpath, {})
        axes = by_kind.setdefault(use.kind, [])
        if use.axis not in axes:
            axes.append(use.axis)
    for by_kind in collectives.values():
        for axes in by_kind.values():
            axes.sort()

    dynamic_families = sorted({
        w.family for w in facts.metric_writes if w.family})

    return {
        "version": CONTRACTS_VERSION,
        "generated_by": "jepsen-tpu lint --write-contracts",
        "packed_schemas": {
            ref: {"module": s.module, "fields": list(s.fields),
                  "width": s.width}
            for ref, s in sorted(facts.schemas.items())},
        "kernels": kernels,
        "partials": {key: list(names) for key, names
                     in sorted(facts.partial_layouts.items())},
        "carries": {
            name: {"module": c.module, "fields": list(c.fields),
                   "factories": sorted(
                       f for f, cls in facts.carry_factories.items()
                       if cls == name)}
            for name, c in sorted(facts.carries.items())},
        "meshes": {ax: sorted(mods)
                   for ax, mods in sorted(facts.mesh_axes.items())},
        "collectives": {rel: dict(sorted(by_kind.items()))
                        for rel, by_kind in sorted(collectives.items())},
        "table_word_bits": (facts.table_word_bits[0]
                            if facts.table_word_bits else None),
        "metrics": {
            "preregistered": sorted(facts.preregistered),
            "labeled_families": dict(sorted(
                facts.labeled_families.items())),
            "snapshot_keys": sorted({n for _, _, n
                                     in facts.snapshot_reads}),
            "dynamic_families": dynamic_families,
        },
        "sync": _sync_section(facts.index),
    }


def render_contracts(contracts: dict) -> str:
    return json.dumps(contracts, indent=2, sort_keys=False) + "\n"


def contracts_in_sync(root: Path,
                      index: Optional[FlowIndex] = None
                      ) -> tuple[bool, str]:
    """(in_sync, detail): compare the checked-in contracts.json against
    a fresh extraction. Missing file -> out of sync with a hint."""
    path = Path(root) / CONTRACTS_FILE
    fresh = render_contracts(extract_contracts(root, index=index))
    if not path.is_file():
        return False, (f"{CONTRACTS_FILE} missing — run `jepsen-tpu lint "
                       f"--write-contracts`")
    current = path.read_text(encoding="utf-8")
    if current == fresh:
        return True, ""
    try:
        cur, new = json.loads(current), json.loads(fresh)
        changed = sorted(
            k for k in set(cur) | set(new) if cur.get(k) != new.get(k))
        detail = f"sections out of sync: {', '.join(changed)}"
    except ValueError:
        detail = "checked-in file is not valid JSON"
    return False, (f"{CONTRACTS_FILE} is stale ({detail}) — regenerate "
                   f"with `jepsen-tpu lint --write-contracts` and review "
                   f"the diff")
