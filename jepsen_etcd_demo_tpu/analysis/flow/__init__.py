"""jtflow — interprocedural kernel-contract and dataflow analysis.

The per-file jtlint rules (ISSUE 7) cannot see *cross-module contract
drift*: PR 3 widened ``wgl3.PACKED_FIELDS`` from 5 to 6 columns and had
to hand-patch ``unpack_np``, ``parallel/dense.py``,
``parallel/multislice.py`` and the ``__graft_entry__`` shard-shape
assert; PR 7's ``/metrics`` family collision was the same shape of bug
in the obs layer. This package is the whole-program half of the
analysis layer (ISSUE 9):

  * ``index.py``      — FlowIndex: every package module parsed once,
                        with cross-module symbol + donation resolution
                        through the factory → ``_CACHE`` →
                        ``instrument_kernel`` idiom;
  * ``facts.py``      — ``# jtflow:`` annotation parsing and contract
                        extraction (packed-result schemas, donated
                        operand positions, resumable-carry field sets,
                        mesh/collective axis names, obs metric
                        contracts);
  * ``contracts.py``  — the machine-readable ``contracts.json``
                        artifact: the reviewed, diffable statement of
                        the kernel interfaces that ROADMAP item 5's
                        KernelPlan layer will consume.

Like the rest of ``analysis/``, everything here is stdlib-``ast`` only
and never imports jax — the flow pass rides the same tier-1 fast path
as the per-file rules (tests/test_lint.py keeps the whole strict run
under 5 s).
"""

from .index import FlowIndex                     # noqa: F401
from .facts import FlowFacts, flow_facts         # noqa: F401
from .contracts import (CONTRACTS_FILE,          # noqa: F401
                        extract_contracts, render_contracts)
