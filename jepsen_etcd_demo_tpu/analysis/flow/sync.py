"""jtsan's concurrency model: locks, threads, happens-before — statically.

The JTL5xx rules (analysis/rules/sync_rules.py) and the sync section of
contracts.json both consume one ``SyncModel`` extracted from a
``FlowIndex`` — the same parse-once discipline as the JTL4xx flow facts,
extended from *data* contracts (packed widths, donation sets) to
*synchronization* contracts (which lock guards which structure, which
thread reaches which method, which lock orders are possible).

What the model knows, and where it comes from:

  * **Locks** — ``self.X = threading.Lock()/RLock()/Condition()`` class
    attrs and module-level ``NAME = threading.Lock()`` globals, each
    with a canonical id (``serve.scheduler.CoalescingScheduler._lock``)
    the runtime sanitizer (obs/sync.py) shares, so witnessed and modeled
    edges compare by name. Constructions wrapped in
    ``obs.sync.maybe_wrap(inner, "name")`` are seen through (and the
    name literal is verified against the canonical id — JTL506). A lock
    attr assigned from a constructor *parameter* (obs/metrics.py's
    injected instrument lock) has no identity of its own; the
    ``# jtsan: alias-of=<lock-id>`` annotation unifies it with the lock
    its owner actually passes in.
  * **Threads** — ``threading.Thread(target=self.m)`` spawn sites,
    ``executor.submit(fn, ...)`` sites, and HTTP handler classes
    (anything whose base chain reaches ``*RequestHandler`` — each
    request runs the ``do_*`` methods on its own thread). Each is a
    *root*; the call-graph closure of a root is everything that thread
    may execute. Call edges placed after ``self.<thread>.join()`` in
    the same method are pruned from closure propagation — join IS the
    happens-before edge that makes post-join access single-threaded
    (StreamSession.finalize's shape).
  * **Locksets** — for every attribute access and call site, the set of
    modeled locks syntactically held (``with`` nesting, same scope). A
    private function whose every in-model call site holds lock L is
    credited with L ("callers always hold" — the RacerD ownership
    idiom obs/health.py's ``_transition`` uses).
  * **Lock order** — ``with a: with b:`` nesting plus the
    interprocedural edges: a call made while holding L contributes
    L -> every lock the callee's call-graph closure may acquire.
    This edge set is exactly what the runtime sanitizer's witnessed
    acquisition orders are validated against (tests/test_jtsan.py).

Resolution is deliberately conservative: a call the model cannot type
(a variable callable, a queue's internal machinery) contributes no
edges. Under-approximation is safe for the race/order rules (they stay
quiet) and is *tested* for the cross-validation contract — a witnessed
runtime edge the model failed to predict fails tier-1, which is the
mechanism that keeps the resolution honest as the tree grows.

``# jtsan:`` annotation grammar (bound to the next statement, same
binding rules as ``# jtflow:``; VERIFIED not trusted — a stale or
unresolvable annotation is a JTL506 finding):

    # jtsan: returns=MetricsRegistry      (call-result type for the
                                           call-graph: obs factories)
    # jtsan: alias-of=obs.metrics.MetricsRegistry._lock
                                          (an injected lock attr IS
                                           that lock)
    # jtsan: guarded-by=self._lock        (this attr's contract lock —
                                           JTL501 enforces every site)
    # jtsan: hb=self.done                 (accesses in this statement
                                           are ordered by that Event /
                                           Thread — excluded from the
                                           lockset intersection)
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterator, Optional

from ..astutil import ancestors_same_scope, dotted, walk_cached
from ..core import ModuleSource, PACKAGE_NAME
from .facts import _bind_line, _const_str, _stmt_at
from .index import FlowIndex

# Package scopes the concurrency model covers: everything with threads,
# handlers or locks in it. None (files outside the package — the
# fixture mini-projects) and "" (top-level modules) are always in.
# "campaign" joined in ISSUE 15: the scenario-factory executor spawns
# worker pools and in-process cluster serve threads — JTL505's
# join-on-shutdown discipline applies to all of them. The ISSUE 18
# fleet modules (serve/router.py, serve/fleet.py — the router's
# health-poller thread and both classes' membership locks) ride the
# existing "serve" scope; their locks/threads land in contracts.json's
# sync section like every other scoped module.
SYNC_SCOPES = ("serve", "stream", "sched", "runner", "web", "obs", "db",
               "clients", "control", "campaign")

_ANNOT_RE = re.compile(r"#\s*jtsan:\s*(.+?)\s*$")
_DIRECTIVES = ("returns", "alias-of", "guarded-by", "hb")

_LOCK_ORIGINS = {"threading.Lock": "lock", "threading.RLock": "rlock",
                 "threading.Condition": "condition"}
_SAFE_ORIGINS = {"queue.Queue", "queue.SimpleQueue", "queue.LifoQueue",
                 "queue.PriorityQueue", "threading.Event",
                 "threading.Semaphore", "threading.BoundedSemaphore",
                 "collections.deque", "contextvars.ContextVar"}
_THREAD_ORIGINS = {"threading.Thread", "Thread"}
_EXECUTOR_SUFFIX = "ThreadPoolExecutor"
_WRAP_SUFFIXES = ("maybe_wrap", "wrap_lock")

# Calls that block the calling thread. `.get`/`.wait` are matched only
# against receivers the model can type (a queue/Event attr) — bare
# dict.get must never count. Condition.wait on a HELD condition is the
# release idiom, not a block.
_BLOCKING_SUBPROC = {"run", "check_output", "check_call", "call"}
_BLOCKING_METHODS = {"result", "join"}          # future / thread


def mod_dotted(mod: ModuleSource) -> str:
    """Canonical dotted module path: package prefix and .py dropped,
    __init__ collapsed — ``serve.scheduler`` for
    jepsen_etcd_demo_tpu/serve/scheduler.py, ``engine`` for a fixture's
    engine.py."""
    parts = list(Path(mod.relpath).with_suffix("").parts)
    if parts and parts[0] == PACKAGE_NAME:
        parts = parts[1:]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts) if parts else Path(mod.relpath).stem


def in_sync_scope(mod: ModuleSource) -> bool:
    return mod.scope is None or mod.scope == "" or mod.scope in SYNC_SCOPES


@dataclass
class LockDecl:
    id: str                      # canonical ("serve.scheduler.Cls._lock")
    kind: str                    # lock / rlock / condition / injected
    mod: ModuleSource
    line: int
    wrap_name: Optional[str] = None   # literal passed to maybe_wrap


@dataclass
class Annotation:
    mod: ModuleSource
    line: int
    directive: str
    arg: str
    node: Optional[ast.stmt]


@dataclass
class Access:
    owner: str                   # class key
    attr: str
    write: bool
    mod: ModuleSource
    node: ast.AST
    fn: str                      # function key
    locks: frozenset
    in_init: bool
    after_join: bool
    hb: bool                     # statement carries a `# jtsan: hb=` edge


@dataclass
class BlockingCall:
    fn: str
    mod: ModuleSource
    node: ast.AST
    what: str                    # human label ("Queue.get", ".join()", …)
    locks: frozenset


@dataclass
class ClassInfo:
    key: str
    name: str
    mod: ModuleSource
    node: ast.ClassDef
    locks: dict[str, LockDecl] = field(default_factory=dict)
    alias: dict[str, str] = field(default_factory=dict)   # attr -> lock id
    safe_attrs: set[str] = field(default_factory=set)
    queue_attrs: set[str] = field(default_factory=set)    # queue.* only
    thread_attrs: dict[str, str] = field(default_factory=dict)  # attr->target
    executor_attrs: set[str] = field(default_factory=set)
    attr_types: dict[str, str] = field(default_factory=dict)  # attr->clskey
    elem_types: dict[str, str] = field(default_factory=dict)  # registry attr
    methods: dict[str, ast.AST] = field(default_factory=dict)
    bases: list[str] = field(default_factory=list)        # resolved origins
    handler: bool = False


@dataclass
class FuncInfo:
    key: str
    mod: ModuleSource
    node: ast.AST
    cls: Optional[str]           # owning class key
    acquires: set[str] = field(default_factory=set)
    # (callee key, locks held, after_join, call node)
    calls: list[tuple] = field(default_factory=list)
    returns_cls: Optional[str] = None
    join_line: Optional[int] = None
    ltypes: dict[str, str] = field(default_factory=dict)
    # Same-scope node list, computed ONCE per function and reused by
    # every pass (the repeated walk_same_scope generators were the
    # model's dominant cost against the tier-1 lint budget).
    nodes: list = field(default_factory=list)

    def same_scope(self) -> list:
        if not self.nodes:
            from ..astutil import walk_same_scope

            self.nodes = list(walk_same_scope(self.node))
        return self.nodes


class SyncModel:
    def __init__(self, index: FlowIndex):
        self.index = index
        self.classes: dict[str, ClassInfo] = {}
        self.functions: dict[str, FuncInfo] = {}
        self.module_locks: dict[str, LockDecl] = {}
        self.module_var_types: dict[str, dict[str, str]] = {}  # mod->name
        self.module_executors: dict[str, tuple] = {}  # name id -> (mod, line)
        self.annotations: list[Annotation] = []
        self.guarded: dict[tuple[str, str], tuple[str, int]] = {}
        self.hb_stmts: set[tuple[str, int]] = set()    # (relpath, stmt line)
        self.hb_decls: list[Annotation] = []
        self.accesses: list[Access] = []
        self.blocking: list[BlockingCall] = []
        # class-level annotated attrs awaiting the full class table:
        # (ClassInfo, attr, bare class name, mod)
        self._pending_attr_ann: list[tuple] = []
        self._value_class_memo: dict[tuple, Optional[str]] = {}
        self._blocks_memo: dict[str, bool] = {}
        self._acq_star: dict[str, set[str]] = {}
        # root id -> (entry fn key, multi-threaded?)
        self.roots: dict[str, tuple[str, bool]] = {}
        self.closures: dict[str, set[str]] = {}
        # (outer lock id, inner lock id) -> (mod, line, via_call) of the
        # first site; via_call distinguishes call-chain edges (JTL502's
        # exclusive jurisdiction) from direct with-nesting (which JTL201
        # also sees when intra-module).
        self.order_edges: dict[tuple[str, str],
                               tuple[ModuleSource, int, bool]] = {}
        self._build()

    # -- public views -------------------------------------------------------

    def lock_ids(self) -> dict[str, str]:
        out = {d.id: d.kind for d in self.module_locks.values()}
        for ci in self.classes.values():
            for d in ci.locks.values():
                out[d.id] = d.kind
        return out

    def lock_modules(self) -> dict[str, str]:
        """Lock id -> declaring module relpath, from the declarations
        themselves — parsing the module back out of the dotted id would
        mis-split module-level lock ids (no class component)."""
        out = {d.id: d.mod.relpath for d in self.module_locks.values()}
        for ci in self.classes.values():
            for d in ci.locks.values():
                out[d.id] = d.mod.relpath
        return out

    def edge_pairs(self) -> set[tuple[str, str]]:
        """The may-happen acquisition-order edge set, alias-unified —
        what the runtime sanitizer's witnessed orders validate against."""
        return set(self.order_edges)

    def sides_of(self, fn_key: str) -> set[str]:
        return {r for r, c in self.closures.items() if fn_key in c}

    # -- construction -------------------------------------------------------

    def _mods(self) -> list[ModuleSource]:
        return [m for m in self.index.modules.values()
                if in_sync_scope(m) and m.scope != "analysis"]

    def _build(self) -> None:
        mods = self._mods()
        for mod in mods:
            self._scan_annotations(mod)
        for mod in mods:
            self._scan_module_level(mod)
            for node in mod.tree.body:
                if isinstance(node, ast.ClassDef):
                    self._scan_class(mod, node)
        self._mark_handlers()
        for ci, attr, bare, mod in self._pending_attr_ann:
            cls = self._class_by_name(bare, mod)
            if cls is not None:
                ci.attr_types.setdefault(attr, cls)
        for mod in mods:
            self._scan_functions(mod)
        self._apply_annotations()
        self._build_roots()
        self._analyze_bodies()
        self._detect_blocking()
        self._propagate_caller_locks()
        self._build_closures()
        self._build_order_edges()

    # -- annotations --------------------------------------------------------

    def _scan_annotations(self, mod: ModuleSource) -> None:
        for i, ln in sorted(mod.comments.items()):
            m = _ANNOT_RE.search(ln)
            if not m:
                continue
            body = m.group(1)
            head, _, rest = body.partition(" ")
            directive, _, inline = head.partition("=")
            arg = (inline + " " + rest).strip() if inline else rest.strip()
            target = _bind_line(mod, i)
            node = _stmt_at(mod, target) if target is not None else None
            self.annotations.append(Annotation(
                mod=mod, line=i, directive=directive, arg=arg, node=node))

    def _apply_annotations(self) -> None:
        """Fold the resolvable annotations into the model; verification
        (unknown directive, failed binding, dangling reference) is
        JTL506's job — it re-walks self.annotations."""
        for a in self.annotations:
            if a.node is None:
                continue
            if a.directive == "returns":
                fn = self._enclosing_or_bound_def(a)
                cls = self._class_by_name(a.arg, a.mod)
                if fn is not None and cls is not None:
                    fn.returns_cls = cls
            elif a.directive == "alias-of":
                bound = self._bound_self_attr(a.node)
                ci = self._class_of_stmt(a)
                if bound and ci is not None and self._lock_id_known(a.arg):
                    ci.alias[bound] = a.arg
                    ci.locks.pop(bound, None)
            elif a.directive == "guarded-by":
                bound = self._bound_self_attr(a.node)
                ci = self._class_of_stmt(a)
                lid = self._resolve_lock_expr(a.arg, ci, a.mod)
                if bound and ci is not None and lid is not None:
                    self.guarded[(ci.key, bound)] = (lid, a.line)
            elif a.directive == "hb":
                self.hb_stmts.add((a.mod.relpath, a.node.lineno))
                self.hb_decls.append(a)

    def _enclosing_or_bound_def(self, a: Annotation) -> Optional[FuncInfo]:
        if isinstance(a.node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            key = self._fn_key_of_def(a.mod, a.node)
            return self.functions.get(key)
        return None

    def _class_by_name(self, name: str, mod: ModuleSource) -> Optional[str]:
        local = f"{mod_dotted(mod)}.{name}"
        if local in self.classes:
            return local
        hits = [k for k, c in self.classes.items() if c.name == name]
        return hits[0] if len(hits) == 1 else None

    def _class_of_stmt(self, a: Annotation) -> Optional[ClassInfo]:
        from ..astutil import enclosing_class

        cls = enclosing_class(a.node)
        if cls is None:
            return None
        return self.classes.get(f"{mod_dotted(a.mod)}.{cls.name}")

    def _bound_self_attr(self, node: ast.stmt) -> Optional[str]:
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            tgts = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in tgts:
                d = dotted(t)
                if d and d.startswith("self.") and len(d.split(".")) == 2:
                    return d.split(".")[1]
        return None

    def _lock_id_known(self, lid: str) -> bool:
        return lid in self.lock_ids()

    def _resolve_lock_expr(self, expr: str, ci: Optional[ClassInfo],
                           mod: ModuleSource) -> Optional[str]:
        if expr.startswith("self.") and ci is not None:
            attr = expr.split(".", 1)[1]
            if attr in ci.locks:
                return ci.locks[attr].id
            if attr in ci.alias:
                return ci.alias[attr]
            return None
        mid = f"{mod_dotted(mod)}.{expr}"
        if mid in self.module_locks:
            return mid
        return expr if self._lock_id_known(expr) else None

    # -- declaration scans --------------------------------------------------

    def _unwrap(self, mod: ModuleSource, call: ast.Call
                ) -> tuple[ast.AST, Optional[str]]:
        """See through obs.sync.maybe_wrap(inner, "name")."""
        origin = mod.imports.resolve(call.func) or ""
        if origin.split(".")[-1] in _WRAP_SUFFIXES and call.args:
            name = _const_str(call.args[1]) if len(call.args) > 1 else None
            return call.args[0], name
        return call, None

    def _value_class(self, mod: ModuleSource, node: ast.AST
                     ) -> Optional[str]:
        """Class key a constructor call resolves to, or None (memoized
        per (module, origin) — constructor origins repeat massively)."""
        if not isinstance(node, ast.Call):
            return None
        origin = mod.imports.resolve(node.func)
        if origin is None:
            return None
        memo_key = (mod.relpath, origin)
        if memo_key in self._value_class_memo:
            return self._value_class_memo[memo_key]
        name = origin.split(".")[-1]
        out = None
        resolved = self.index.resolve_symbol(origin)
        if resolved is not None:
            tmod, sym = resolved
            key = f"{mod_dotted(tmod)}.{sym}"
            if key in self.classes or any(
                    isinstance(n, ast.ClassDef) and n.name == sym
                    for n in tmod.tree.body):
                out = key
        if out is None:
            local = f"{mod_dotted(mod)}.{name}"
            if local in self.classes:
                out = local
            else:
                hits = [k for k, c in self.classes.items()
                        if c.name == name]
                out = hits[0] if len(hits) == 1 else None
        self._value_class_memo[memo_key] = out
        return out

    def _scan_module_level(self, mod: ModuleSource) -> None:
        md = mod_dotted(mod)
        vtypes = self.module_var_types.setdefault(mod.relpath, {})
        globals_assigned: dict[str, ast.AST] = {}
        for node in mod.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                globals_assigned[node.targets[0].id] = node
        # Assignments to declared globals inside functions count too
        # (sched.engine's lazily-built executor). One pass over the
        # cached flat walk: `global X` anywhere makes later `X = ...`
        # assignments module-level for typing purposes.
        gnames = {n for g in mod.walk_nodes()
                  if isinstance(g, ast.Global) for n in g.names}
        if gnames:
            for st in mod.walk_nodes():
                if isinstance(st, ast.Assign) \
                        and len(st.targets) == 1 \
                        and isinstance(st.targets[0], ast.Name) \
                        and st.targets[0].id in gnames:
                    globals_assigned.setdefault(st.targets[0].id, st)
        for name, node in globals_assigned.items():
            val = node.value
            wrap_name = None
            if isinstance(val, ast.Call):
                val, wrap_name = self._unwrap(mod, val)
            if not isinstance(val, ast.Call):
                continue
            origin = mod.imports.resolve(val.func) or ""
            kind = _LOCK_ORIGINS.get(origin)
            if kind is None and origin.split(".")[-1] in \
                    {o.split(".")[-1] for o in _LOCK_ORIGINS} \
                    and origin.startswith("threading"):
                kind = "lock"
            if kind is not None:
                self.module_locks[f"{md}.{name}"] = LockDecl(
                    id=f"{md}.{name}", kind=kind, mod=mod,
                    line=node.lineno, wrap_name=wrap_name)
                continue
            if origin.split(".")[-1] == _EXECUTOR_SUFFIX:
                self.module_executors[f"{md}.{name}"] = (mod, node.lineno)
                continue
            cls = self._value_class(mod, val)
            if cls is not None:
                vtypes[name] = cls

    def _scan_class(self, mod: ModuleSource, node: ast.ClassDef) -> None:
        md = mod_dotted(mod)
        key = f"{md}.{node.name}"
        ci = ClassInfo(key=key, name=node.name, mod=mod, node=node)
        ci.bases = [mod.imports.resolve(b) or (dotted(b) or "")
                    for b in node.bases]
        for n in node.body:
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                ci.methods[n.name] = n
            elif isinstance(n, ast.AnnAssign) \
                    and isinstance(n.target, ast.Name):
                # Dataclass fields: `done: Event = field(default_factory=
                # threading.Event)` declares a safe-typed attr; a plain
                # class-level annotation (`daemon_obj: ServeDaemon`)
                # types the attr for call/access resolution.
                if isinstance(n.value, ast.Call):
                    val = n.value
                    origin = mod.imports.resolve(val.func) or ""
                    if origin.split(".")[-1] == "field":
                        for kw in val.keywords:
                            if kw.arg == "default_factory":
                                fo = mod.imports.resolve(kw.value) or ""
                                if fo in _SAFE_ORIGINS:
                                    ci.safe_attrs.add(n.target.id)
                    elif origin in _SAFE_ORIGINS:
                        ci.safe_attrs.add(n.target.id)
                ann = n.annotation
                if isinstance(ann, (ast.Name, ast.Attribute)):
                    d = dotted(ann)
                    if d is not None:
                        self._pending_attr_ann.append(
                            (ci, n.target.id, d.split(".")[-1], mod))
        params = {a.arg for meth in ci.methods.values()
                  if meth.name == "__init__"
                  for a in meth.args.args + meth.args.kwonlyargs}
        for meth in ci.methods.values():
            for st in walk_cached(meth):
                if isinstance(st, ast.Assign):
                    targets = st.targets
                elif isinstance(st, ast.AnnAssign) \
                        and st.value is not None:
                    targets = [st.target]
                else:
                    continue
                tgt_attrs = [d.split(".")[1] for t in targets
                             for d in [dotted(t)]
                             if d and d.startswith("self.")
                             and len(d.split(".")) == 2]
                if not tgt_attrs:
                    continue
                val = st.value
                wrap_name = None
                if isinstance(val, ast.Call):
                    val, wrap_name = self._unwrap(mod, val)
                if isinstance(val, ast.Name) and meth.name == "__init__" \
                        and val.id in params:
                    # Injected dependency (obs/metrics.py's shared
                    # instrument lock): identity unknown until an
                    # alias-of annotation unifies it.
                    from ..astutil import LOCKISH_RE

                    for attr in tgt_attrs:
                        if LOCKISH_RE.search(attr):
                            ci.locks[attr] = LockDecl(
                                id=f"{key}.{attr}", kind="injected",
                                mod=mod, line=st.lineno)
                    continue
                if not isinstance(val, ast.Call):
                    continue
                origin = mod.imports.resolve(val.func) or ""
                kind = _LOCK_ORIGINS.get(origin)
                for attr in tgt_attrs:
                    if kind is not None:
                        ci.locks[attr] = LockDecl(
                            id=f"{key}.{attr}", kind=kind, mod=mod,
                            line=st.lineno, wrap_name=wrap_name)
                    elif origin in _SAFE_ORIGINS:
                        ci.safe_attrs.add(attr)
                        if origin.startswith("queue."):
                            ci.queue_attrs.add(attr)
                    elif origin in _THREAD_ORIGINS:
                        for kw in val.keywords:
                            if kw.arg == "target":
                                t = dotted(kw.value) or ""
                                if t.startswith("self."):
                                    ci.thread_attrs[attr] = \
                                        t.split(".", 1)[1]
                    elif origin.split(".")[-1] == _EXECUTOR_SUFFIX:
                        ci.executor_attrs.add(attr)
                    else:
                        cls = self._value_class(mod, val)
                        if cls is not None:
                            ci.attr_types[attr] = cls
                # Registry inserts: self._reg[k] = <ClassName(...)> or a
                # local previously typed (handled again in body pass).
            # Registry element types: `self._reg[k] = ClassName(...)`
            # directly, or through a method-local first
            # (`sess = ServeSession(...); self._sessions[sess.id] =
            # sess` — the SessionManager idiom).
            meth_locals: dict[str, str] = {}
            for st in walk_cached(meth):
                if isinstance(st, ast.Assign) and len(st.targets) == 1 \
                        and isinstance(st.targets[0], ast.Name):
                    cls = self._value_class(mod, st.value)
                    if cls is not None:
                        meth_locals[st.targets[0].id] = cls
            for st in walk_cached(meth):
                if isinstance(st, ast.Assign):
                    for t in st.targets:
                        if isinstance(t, ast.Subscript):
                            d = dotted(t.value)
                            if d and d.startswith("self.") \
                                    and len(d.split(".")) == 2:
                                cls = self._value_class(mod, st.value)
                                if cls is None and isinstance(
                                        st.value, ast.Name):
                                    cls = meth_locals.get(st.value.id)
                                if cls is not None:
                                    ci.elem_types[d.split(".")[1]] = cls
        self.classes[key] = ci
        for mname, meth in ci.methods.items():
            fk = f"{key}.{mname}"
            self.functions[fk] = FuncInfo(key=fk, mod=mod, node=meth,
                                          cls=key)

    def _mark_handlers(self) -> None:
        """Classes whose base chain reaches *RequestHandler serve each
        request on its own thread (ThreadingHTTPServer)."""
        def is_handler(key: str, seen: set) -> bool:
            ci = self.classes.get(key)
            if ci is None or key in seen:
                return False
            seen.add(key)
            for b in ci.bases:
                if b.split(".")[-1].endswith("RequestHandler"):
                    return True
                base_key = self._class_by_name(b.split(".")[-1], ci.mod)
                if base_key and is_handler(base_key, seen):
                    return True
            return False

        for key, ci in self.classes.items():
            ci.handler = is_handler(key, set())

    def _scan_functions(self, mod: ModuleSource) -> None:
        md = mod_dotted(mod)
        for node in mod.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fk = f"{md}.{node.name}"
                self.functions[fk] = FuncInfo(key=fk, mod=mod, node=node,
                                              cls=None)
        # Factory return types (one pattern each): `return ClassName(...)`
        # and `return <module var of known type>`.
        for fi in list(self.functions.values()):
            if fi.mod is not mod or fi.returns_cls is not None:
                continue
            vtypes = self.module_var_types.get(mod.relpath, {})
            for ret in fi.same_scope():
                if not isinstance(ret, ast.Return) or ret.value is None:
                    continue
                cls = self._value_class(mod, ret.value)
                if cls is None and isinstance(ret.value, ast.Name):
                    cls = vtypes.get(ret.value.id)
                if cls is not None:
                    fi.returns_cls = cls
                    break

    def _fn_key_of_def(self, mod: ModuleSource, node: ast.AST) -> str:
        from ..astutil import enclosing_class

        cls = enclosing_class(node)
        md = mod_dotted(mod)
        if cls is not None:
            return f"{md}.{cls.name}.{node.name}"
        return f"{md}.{node.name}"

    # -- roots --------------------------------------------------------------

    def _method_key(self, cls_key: str, name: str,
                    seen: Optional[set] = None) -> Optional[str]:
        """Resolve a method through the in-model base chain."""
        seen = seen or set()
        if cls_key in seen:
            return None
        seen.add(cls_key)
        ci = self.classes.get(cls_key)
        if ci is None:
            return None
        if name in ci.methods:
            return f"{cls_key}.{name}"
        for b in ci.bases:
            bk = self._class_by_name(b.split(".")[-1], ci.mod)
            if bk:
                hit = self._method_key(bk, name, seen)
                if hit:
                    return hit
        return None

    def _build_roots(self) -> None:
        for key, ci in self.classes.items():
            for attr, target in sorted(ci.thread_attrs.items()):
                entry = self._method_key(key, target)
                if entry:
                    self.roots[f"thread:{key}.{target}"] = (entry, False)
            if ci.handler:
                for mname in sorted(ci.methods):
                    if mname.startswith("do_") or mname == "handle":
                        self.roots[f"handler:{key}"] = \
                            (f"{key}.{mname}", True)
                        break
        # executor.submit(fn, ...) sites — the submitted callable runs
        # on a pool thread.
        for fi in list(self.functions.values()):
            for call in fi.same_scope():
                if not (isinstance(call, ast.Call)
                        and isinstance(call.func, ast.Attribute)
                        and call.func.attr == "submit" and call.args):
                    continue
                target = self._callable_key(fi, call.args[0])
                if target is not None:
                    self.roots[f"executor:{target}"] = (target, True)

    def _callable_key(self, fi: FuncInfo, node: ast.AST) -> Optional[str]:
        d = dotted(node)
        if d is None:
            return None
        if d.startswith("self.") and fi.cls:
            return self._method_key(fi.cls, d.split(".", 1)[1])
        md = mod_dotted(fi.mod)
        if f"{md}.{d}" in self.functions:
            return f"{md}.{d}"
        origin = fi.mod.imports.resolve(node)
        return self._resolve_function(fi.mod, origin)

    def _resolve_function(self, mod: ModuleSource,
                          origin: Optional[str],
                          depth: int = 0) -> Optional[str]:
        if not origin or depth > 4:
            return None
        resolved = self.index.resolve_symbol(origin)
        if resolved is not None:
            tmod, sym = resolved
            key = f"{mod_dotted(tmod)}.{sym}"
            if key in self.functions:
                return key
            # Re-export hops (obs/__init__ re-exports export.subscribe),
            # depth-bounded: import cycles must not recurse forever.
            hop = tmod.imports.names.get(sym)
            if hop and hop != origin:
                return self._resolve_function(tmod, hop, depth + 1)
        # Unique bare name among module-level functions (the
        # `from . import get_metrics` shape resolves to a bare name).
        bare = origin.split(".")[-1]
        hits = [k for k, f in self.functions.items()
                if f.cls is None and k.split(".")[-1] == bare]
        return hits[0] if len(hits) == 1 else None

    # -- body analysis ------------------------------------------------------

    def _lock_id_of_expr(self, fi: FuncInfo, node: ast.AST
                         ) -> Optional[str]:
        d = dotted(node)
        if d is None and isinstance(node, ast.Call):
            d = dotted(node.func)
        if d is None:
            return None
        if d.startswith("self.") and fi.cls:
            attr = d.split(".")[1]
            ci = self.classes.get(fi.cls)
            if ci is None:
                return None
            if attr in ci.alias:
                return ci.alias[attr]
            if attr in ci.locks:
                return ci.locks[attr].id
            return None
        mid = f"{mod_dotted(fi.mod)}.{d}"
        return mid if mid in self.module_locks else None

    def _held_at(self, fi: FuncInfo, node: ast.AST) -> frozenset:
        held = []
        for a in ancestors_same_scope(node):
            if isinstance(a, ast.With):
                for item in a.items:
                    lid = self._lock_id_of_expr(fi, item.context_expr)
                    if lid is not None:
                        held.append(lid)
        return frozenset(held)

    def _local_types(self, fi: FuncInfo) -> dict[str, str]:
        """Flow-insensitive local-variable class types for one function:
        constructor calls, typed factory calls, typed attrs, registry
        get/pop, iteration over typed registries, annotated params."""
        ci = self.classes.get(fi.cls) if fi.cls else None
        vtypes = self.module_var_types.get(fi.mod.relpath, {})
        out: dict[str, str] = {}

        def ann_class(ann: Optional[ast.AST]) -> Optional[str]:
            if ann is None:
                return None
            if isinstance(ann, ast.Subscript):   # list[X] / Optional[X]
                return ann_class(ann.slice)
            if isinstance(ann, (ast.Name, ast.Attribute)):
                d = dotted(ann)
                if d:
                    return self._class_by_name(d.split(".")[-1], fi.mod)
            if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
                return self._class_by_name(ann.value.split(".")[-1],
                                           fi.mod)
            return None

        args = fi.node.args
        for a in args.posonlyargs + args.args + args.kwonlyargs:
            cls = ann_class(a.annotation)
            if cls:
                out[a.arg] = cls

        def expr_type(val: ast.AST) -> Optional[str]:
            cls = self._value_class(fi.mod, val)
            if cls:
                return cls
            if isinstance(val, ast.Call):
                callee = self._resolve_call(fi, val, out)
                if callee:
                    rfi = self.functions.get(callee)
                    if rfi is not None and rfi.returns_cls:
                        return rfi.returns_cls
                # registry get/pop on a typed self attr
                if isinstance(val.func, ast.Attribute) \
                        and val.func.attr in ("get", "pop"):
                    d = dotted(val.func.value)
                    if d and d.startswith("self.") and ci is not None:
                        return ci.elem_types.get(d.split(".")[1])
            d = dotted(val)
            if d and d.startswith("self.") and ci is not None \
                    and len(d.split(".")) == 2:
                return ci.attr_types.get(d.split(".")[1])
            if isinstance(val, ast.Name):
                return vtypes.get(val.id)
            return None

        def bind_iteration(target: ast.AST, it: ast.AST) -> None:
            recv = None
            if isinstance(it, ast.Call) \
                    and isinstance(it.func, ast.Attribute) \
                    and it.func.attr in ("values", "items"):
                recv = dotted(it.func.value)
            if recv and recv.startswith("self.") and ci is not None:
                elem = ci.elem_types.get(recv.split(".")[1])
                if elem:
                    if isinstance(target, ast.Name):
                        out[target.id] = elem
                    elif isinstance(target, ast.Tuple) and target.elts \
                            and isinstance(target.elts[-1], ast.Name):
                        out[target.elts[-1].id] = elem

        for st in fi.same_scope():
            if isinstance(st, ast.Assign) and len(st.targets) == 1 \
                    and isinstance(st.targets[0], ast.Name):
                t = expr_type(st.value)
                if t:
                    out[st.targets[0].id] = t
            elif isinstance(st, (ast.ListComp, ast.SetComp,
                                 ast.GeneratorExp, ast.DictComp)):
                for gen in st.generators:
                    bind_iteration(gen.target, gen.iter)
            elif isinstance(st, (ast.For, ast.AsyncFor)):
                it = st.iter
                recv = None
                if isinstance(it, ast.Call) \
                        and isinstance(it.func, ast.Attribute) \
                        and it.func.attr in ("values", "items"):
                    recv = dotted(it.func.value)
                elif isinstance(it, ast.Call) \
                        and isinstance(it.func, ast.Name) \
                        and it.func.id == "zip" and it.args:
                    recv = None     # handled by param annotations mostly
                d = recv
                if d and d.startswith("self.") and ci is not None:
                    elem = ci.elem_types.get(d.split(".")[1])
                    if elem:
                        tgt = st.target
                        if isinstance(tgt, ast.Name):
                            out[tgt.id] = elem
                        elif isinstance(tgt, ast.Tuple) and tgt.elts \
                                and isinstance(tgt.elts[-1], ast.Name):
                            out[tgt.elts[-1].id] = elem
                # `for x in batch:` with batch: list[Cls]
                if isinstance(it, ast.Name) and it.id in out \
                        and isinstance(st.target, ast.Name):
                    out[st.target.id] = out[it.id]
                if isinstance(it, ast.Call) \
                        and isinstance(it.func, ast.Name) \
                        and it.func.id == "zip":
                    srcs = [a for a in it.args]
                    if isinstance(st.target, ast.Tuple) \
                            and len(st.target.elts) == len(srcs):
                        for tgt, src in zip(st.target.elts, srcs):
                            if isinstance(tgt, ast.Name) \
                                    and isinstance(src, ast.Name) \
                                    and src.id in out:
                                out[tgt.id] = out[src.id]
        return out

    def _resolve_call(self, fi: FuncInfo, call: ast.Call,
                      ltypes: dict[str, str]) -> Optional[str]:
        func = call.func
        # super().m()
        if isinstance(func, ast.Attribute) \
                and isinstance(func.value, ast.Call) \
                and isinstance(func.value.func, ast.Name) \
                and func.value.func.id == "super" and fi.cls:
            ci = self.classes.get(fi.cls)
            for b in (ci.bases if ci else []):
                bk = self._class_by_name(b.split(".")[-1], fi.mod)
                if bk:
                    hit = self._method_key(bk, func.attr)
                    if hit:
                        return hit
            return None
        # f(...).m(...) — chained through the inner call's return type
        if isinstance(func, ast.Attribute) \
                and isinstance(func.value, ast.Call):
            inner = self._resolve_call(fi, func.value, ltypes)
            if inner:
                rfi = self.functions.get(inner)
                if rfi is not None and rfi.returns_cls:
                    return self._method_key(rfi.returns_cls, func.attr)
            cls = self._value_class(fi.mod, func.value)
            if cls:
                return self._method_key(cls, func.attr)
            return None
        d = dotted(func)
        if d is None:
            return None
        parts = d.split(".")
        ci = self.classes.get(fi.cls) if fi.cls else None
        if parts[0] == "self" and ci is not None:
            if len(parts) == 2:
                return self._method_key(fi.cls, parts[1])
            if len(parts) == 3:
                owner = ci.attr_types.get(parts[1])
                if owner:
                    return self._method_key(owner, parts[2])
            return None
        if len(parts) == 2:
            if parts[0] in ltypes:
                return self._method_key(ltypes[parts[0]], parts[1])
            vtypes = self.module_var_types.get(fi.mod.relpath, {})
            if parts[0] in vtypes:
                return self._method_key(vtypes[parts[0]], parts[1])
        if len(parts) == 3 and parts[0] in ltypes:
            # d.sessions.open(...) — typed local, one owned-attr hop.
            mid = self.classes.get(ltypes[parts[0]])
            if mid is not None:
                owner = mid.attr_types.get(parts[1])
                if owner:
                    return self._method_key(owner, parts[2])
        # module function / constructor / imported symbol
        origin = fi.mod.imports.resolve(func)
        cls = self._value_class(fi.mod, call)
        if cls is not None:
            return self._method_key(cls, "__init__") or f"{cls}.__init__"
        md = mod_dotted(fi.mod)
        if len(parts) == 1 and f"{md}.{d}" in self.functions:
            return f"{md}.{d}"
        return self._resolve_function(fi.mod, origin)

    def _analyze_bodies(self) -> None:
        for fi in self.functions.values():
            self._analyze_one(fi)

    def _analyze_one(self, fi: FuncInfo) -> None:
        from ..astutil import statement_of

        ci = self.classes.get(fi.cls) if fi.cls else None
        ltypes = fi.ltypes = self._local_types(fi)
        in_init = fi.node.name == "__init__"
        # join line: `self.<thread attr>.join()` (or `.shutdown()`).
        for call in fi.same_scope():
            if isinstance(call, ast.Call) \
                    and isinstance(call.func, ast.Attribute) \
                    and call.func.attr in ("join", "shutdown"):
                d = dotted(call.func.value)
                if d and d.startswith("self.") and ci is not None \
                        and (d.split(".")[1] in ci.thread_attrs
                             or d.split(".")[1] in ci.executor_attrs):
                    fi.join_line = min(fi.join_line or call.lineno,
                                       call.lineno)

        def after_join(node: ast.AST) -> bool:
            return fi.join_line is not None \
                and getattr(node, "lineno", 0) > fi.join_line

        def stmt_hb(node: ast.AST) -> bool:
            st = statement_of(node)
            return (fi.mod.relpath, getattr(st, "lineno", -1)) \
                in self.hb_stmts

        def record_access(owner: Optional[str], attr: str, write: bool,
                          node: ast.AST) -> None:
            if owner is None or owner not in self.classes:
                return
            self.accesses.append(Access(
                owner=owner, attr=attr, write=write, mod=fi.mod,
                node=node, fn=fi.key, locks=self._held_at(fi, node),
                in_init=in_init, after_join=after_join(node),
                hb=stmt_hb(node)))

        def owner_of(base: ast.AST) -> tuple[Optional[str], Optional[str]]:
            """(owning class key, attr) for an attribute chain's first
            hop: self.X, typed-local.X, typed self.attr.X."""
            d = dotted(base)
            if d is None:
                return None, None
            parts = d.split(".")
            if parts[0] == "self" and fi.cls:
                if len(parts) == 2:
                    return fi.cls, parts[1]
                if len(parts) == 3 and ci is not None:
                    owner = ci.attr_types.get(parts[1])
                    if owner:
                        return owner, parts[2]
                return None, None
            if len(parts) == 2 and parts[0] in ltypes:
                return ltypes[parts[0]], parts[1]
            return None, None

        from ..rules.shared_state import _MUTATORS

        for node in fi.same_scope():
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                tgts = (node.targets if isinstance(node, ast.Assign)
                        else [node.target])
                for t in tgts:
                    base = t.value if isinstance(t, ast.Subscript) else t
                    owner, attr = owner_of(base)
                    if attr:
                        record_access(owner, attr, True, node)
                if isinstance(node, ast.AugAssign):
                    owner, attr = owner_of(node.target)
                    if attr:
                        record_access(owner, attr, True, node)
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    base = t.value if isinstance(t, ast.Subscript) else t
                    owner, attr = owner_of(base)
                    if attr:
                        record_access(owner, attr, True, node)
            elif isinstance(node, ast.Call):
                locks = self._held_at(fi, node)
                callee = self._resolve_call(fi, node, ltypes)
                if callee is not None:
                    fi.calls.append((callee, locks, after_join(node),
                                     node))
                if isinstance(node.func, ast.Attribute):
                    if node.func.attr in _MUTATORS:
                        owner, attr = owner_of(node.func.value)
                        if attr:
                            record_access(owner, attr, True, node)
            elif isinstance(node, ast.Attribute) \
                    and isinstance(getattr(node, "ctx", None), ast.Load):
                owner, attr = owner_of(node)
                if attr:
                    # Skip the method-call receiver itself: reading
                    # `self._q` to call .put on it is use, not shared-
                    # state access of a plain field (safe attrs filter
                    # later anyway); plain loads are what we want.
                    record_access(owner, attr, False, node)

    def _detect_blocking(self) -> None:
        """Second pass (needs every FuncInfo.calls populated for the
        interprocedural closure): blocking calls made while a modeled
        lock is syntactically held — JTL504's input."""
        for fi in self.functions.values():
            if isinstance(fi.node, ast.AsyncFunctionDef):
                continue
            for node in fi.same_scope():
                if not isinstance(node, ast.Call):
                    continue
                locks = self._held_at(fi, node)
                if not locks:
                    continue
                what = self._blocking_what(fi, node, fi.ltypes, locks)
                if what is not None:
                    self.blocking.append(BlockingCall(
                        fn=fi.key, mod=fi.mod, node=node, what=what,
                        locks=locks))

    def _direct_block_label(self, fi: FuncInfo, call: ast.Call,
                            locks: frozenset) -> Optional[str]:
        """Label when this call is a blocking PRIMITIVE (no call-graph
        recursion); None otherwise."""
        ci = self.classes.get(fi.cls) if fi.cls else None
        f = call.func
        origin = fi.mod.imports.resolve(f) or ""
        tail = origin.split(".")[-1]
        if tail == "urlopen" or origin.startswith("urllib.request"):
            return "urllib.request.urlopen"
        if origin in ("time.sleep",):
            return "time.sleep"
        if origin.startswith("subprocess.") \
                and tail in _BLOCKING_SUBPROC:
            return origin
        if not isinstance(f, ast.Attribute):
            return None
        d = dotted(f.value)
        attr_of_self = d.split(".")[1] if d and d.startswith("self.") \
            and len(d.split(".")) == 2 and ci is not None else None
        if f.attr == "get":
            # Only queue-typed receivers count (never dict.get, never
            # ContextVar.get); put on this codebase's unbounded queues
            # cannot block, so only the consuming side is flagged.
            if attr_of_self and attr_of_self in (ci.queue_attrs if ci
                                                 else ()):
                return "Queue.get"
            return None
        if f.attr == "wait":
            lid = self._lock_id_of_expr(fi, f.value)
            if lid is not None and lid in locks:
                return None         # Condition.wait on the held lock
            if lid is not None or (attr_of_self and ci
                                   and attr_of_self in ci.safe_attrs):
                return "Event/Condition.wait"
            return None
        if f.attr == "acquire":
            lid = self._lock_id_of_expr(fi, f.value)
            if lid is not None and lid not in locks:
                return "lock.acquire"
            return None
        if f.attr in _BLOCKING_METHODS:
            if f.attr == "result":
                return "Future.result"
            if f.attr == "join":
                # str.join is ubiquitous: require a thread-ish receiver.
                if (attr_of_self and ci
                        and (attr_of_self in ci.thread_attrs
                             or attr_of_self in ci.executor_attrs)) \
                        or (d and "thread" in d.lower()):
                    return "Thread.join"
            return None
        return None

    def _blocking_what(self, fi: FuncInfo, call: ast.Call,
                       ltypes: dict[str, str],
                       locks: frozenset) -> Optional[str]:
        """Label when this call can block (primitive or through a
        resolvable callee whose closure blocks); None otherwise."""
        label = self._direct_block_label(fi, call, locks)
        if label is not None:
            return label
        callee = self._resolve_call(fi, call, ltypes)
        if callee is not None and self._callee_blocks(callee):
            return f"{callee}() [blocks inside]"
        return None

    def _callee_blocks(self, fn_key: str, depth: int = 0,
                       seen: Optional[set] = None) -> bool:
        if depth > 3:
            return False
        memo = self._blocks_memo.get(fn_key)
        if memo is not None:
            return memo
        seen = seen or set()
        if fn_key in seen:
            return False
        seen.add(fn_key)
        fi = self.functions.get(fn_key)
        if fi is None:
            return False
        out = False
        for call in fi.same_scope():
            if isinstance(call, ast.Call) and self._direct_block_label(
                    fi, call, self._held_at(fi, call)) is not None:
                out = True
                break
        if not out:
            for callee, _locks, _aj, _node in fi.calls:
                if self._callee_blocks(callee, depth + 1, seen):
                    out = True
                    break
        if not out and depth > 0:
            # Depth-truncated negatives are not cacheable (a deeper
            # start could still find the block); positives always are.
            return out
        self._blocks_memo[fn_key] = out
        return out

    # -- interprocedural lockset credit -------------------------------------

    def _propagate_caller_locks(self) -> None:
        """A private function whose EVERY in-model call site holds lock
        L is analyzed as holding L (obs/health._transition's "caller
        holds the lock" contract). One level, write-credited into the
        recorded accesses."""
        callers: dict[str, list[frozenset]] = {}
        for fi in self.functions.values():
            for callee, locks, _aj, _node in fi.calls:
                callers.setdefault(callee, []).append(locks)
        credit: dict[str, frozenset] = {}
        for fn_key, locksets in callers.items():
            name = fn_key.split(".")[-1]
            if not name.startswith("_") or name.startswith("__"):
                continue
            common = frozenset.intersection(*locksets) if locksets \
                else frozenset()
            if common:
                credit[fn_key] = common
        if not credit:
            return
        for acc in self.accesses:
            extra = credit.get(acc.fn)
            if extra:
                acc.locks = acc.locks | extra

    # -- closures -----------------------------------------------------------

    def _build_closures(self) -> None:
        for root, (entry, _multi) in self.roots.items():
            seen: set[str] = set()
            frontier = [entry]
            while frontier:
                cur = frontier.pop()
                if cur in seen:
                    continue
                seen.add(cur)
                fi = self.functions.get(cur)
                if fi is None:
                    continue
                for callee, _locks, after_join, _node in fi.calls:
                    if not after_join:
                        frontier.append(callee)
            self.closures[root] = seen

    # -- lock order ---------------------------------------------------------

    def _acq_closure(self, fn_key: str) -> set[str]:
        return self._acq_star.get(fn_key, set())

    def _compute_acq_star(self) -> None:
        """May-acquire closure per function: fixpoint of
        acq*(f) = acquires(f) ∪ ⋃ acq*(callees) over the call graph
        (cycle-safe, whole-graph — replaces a per-call-site recursion
        that dominated the model's wall time)."""
        star = {k: set(fi.acquires) for k, fi in self.functions.items()}
        changed = True
        while changed:
            changed = False
            for k, fi in self.functions.items():
                cur = star[k]
                for callee, _locks, _aj, _node in fi.calls:
                    extra = star.get(callee)
                    if extra and not extra <= cur:
                        cur |= extra
                        changed = True
        self._acq_star = star

    def _build_order_edges(self) -> None:
        # Direct syntactic acquisitions per function.
        for fi in self.functions.values():
            for node in fi.same_scope():
                if isinstance(node, ast.With):
                    for item in node.items:
                        lid = self._lock_id_of_expr(fi, item.context_expr)
                        if lid is not None:
                            fi.acquires.add(lid)
        # with a: with b: nesting + with a, b: items.
        for fi in self.functions.values():
            for node in fi.same_scope():
                if not isinstance(node, ast.With):
                    continue
                ids = [lid for item in node.items
                       for lid in
                       [self._lock_id_of_expr(fi, item.context_expr)]
                       if lid is not None]
                for outer, inner in zip(ids, ids[1:]):
                    self.order_edges.setdefault(
                        (outer, inner), (fi.mod, node.lineno, False))
                if not ids:
                    continue
                held = self._held_at(fi, node)
                for outer in held:
                    for inner in ids:
                        self.order_edges.setdefault(
                            (outer, inner), (fi.mod, node.lineno, False))
        # Calls while holding: held x callee acquisition closure. A
        # callee in the HOLDER'S OWN class is JTL201's same-class-call
        # territory; everything else is marked via_call=True for
        # JTL502's exclusive jurisdiction.
        self._compute_acq_star()
        for fi in self.functions.values():
            for callee, locks, _aj, node in fi.calls:
                if not locks:
                    continue
                same_class = fi.cls is not None \
                    and callee.rsplit(".", 1)[0] == fi.cls
                for inner in sorted(self._acq_closure(callee)):
                    for outer in locks:
                        self.order_edges.setdefault(
                            (outer, inner),
                            (fi.mod, node.lineno, not same_class))

    # -- contract view ------------------------------------------------------

    def contract_section(self) -> dict:
        """The deterministic `sync` section for contracts.json: locks,
        thread roots, each shared structure's guarding lock + the
        threads that touch it, and the may-happen lock-order edges."""
        locks = dict(sorted(self.lock_ids().items()))
        threads = {root: entry for root, (entry, _m)
                   in sorted(self.roots.items())}
        guarded: dict[str, dict] = {}
        # ONE eligibility walk (iter_shared_attrs — the JTL501 rule's
        # exact input), so the contract can never desynchronize from
        # what the race rule actually checks.
        for owner, attr, sites in iter_shared_attrs(self):
            ci = self.classes[owner]
            decl = self.guarded.get((owner, attr))
            if decl is None and not any(a.write for a in sites):
                continue        # read-only post-init: not a structure
                                # anything needs guarding
            if decl is None and attr in ci.attr_types:
                continue        # owned-object handles: lifecycle state,
                                # not a guarded structure
            common = frozenset.intersection(*[a.locks for a in sites])
            lock = decl[0] if decl else (sorted(common)[0] if common
                                         else None)
            if lock is None:
                continue
            roots = sorted({r for a in sites for r in self.sides_of(a.fn)})
            if not roots and not decl:
                continue
            guarded[f"{owner}.{attr}"] = {"lock": lock, "threads": roots}
        order = sorted([a, b] for a, b in self.order_edges)
        return {"locks": locks, "threads": threads, "guarded": guarded,
                "order": order}


def sync_model(index: FlowIndex) -> SyncModel:
    """Extract (and memoize on the index) the concurrency model."""
    cached = getattr(index, "_sync", None)
    if cached is None:
        cached = SyncModel(index)
        index._sync = cached
    return cached


def iter_shared_attrs(model: SyncModel) -> Iterator[tuple]:
    """(owner class key, attr, non-init/non-joined/non-hb sites) for
    every plain attribute the model saw accessed — the JTL501 walk."""
    by_attr: dict[tuple[str, str], list[Access]] = {}
    for acc in model.accesses:
        by_attr.setdefault((acc.owner, acc.attr), []).append(acc)
    for (owner, attr), accs in sorted(by_attr.items()):
        ci = model.classes.get(owner)
        if ci is None or ci.handler:
            # Handler classes are instantiated per request: their attrs
            # are thread-confined by construction (the shared state a
            # handler touches lives on the daemon object, which IS
            # modeled).
            continue
        if attr in ci.locks or attr in ci.alias or attr in ci.safe_attrs \
                or attr in ci.thread_attrs or attr in ci.executor_attrs:
            continue
        sites = [a for a in accs
                 if not a.in_init and not a.after_join and not a.hb]
        if sites:
            yield owner, attr, sites
