"""`python -m jepsen_etcd_demo_tpu.analysis` -> the jtlint CLI."""

import sys

from .cli import main

sys.exit(main())
