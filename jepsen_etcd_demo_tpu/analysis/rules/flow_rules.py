"""JTL4xx — interprocedural flow rules over the jtflow contract graph.

Where the JTL1xx/2xx rules see one file at a time, these run over the
whole-program ``FlowIndex`` (analysis/flow/) and check the contracts
that *span* modules — the drift class the PR 3 PACKED_FIELDS 5→6
widening and the PR 7 ``/metrics`` family collision belong to:

  JTL401 packed-schema drift     producer/consumer column-width and
                                 annotation drift against the declared
                                 packed-result schemas
  JTL402 cross-module donation   read-after-donation through
                                 factory→_CACHE→instrument_kernel edges
                                 that cross module boundaries (the
                                 interprocedural half of JTL102)
  JTL403 sharding-axis contract  a collective's axis name absent from
                                 every mesh construction; packed-table
                                 word-width math disagreeing with the
                                 declared table-word-bits
  JTL404 resumable-carry drift   consumers touching carry fields the
                                 kernel's NamedTuple does not declare
  JTL405 metric contract         snapshot-contract keys not
                                 pre-registered; dynamic metric
                                 families colliding with plain names
                                 outside export.LABELED_FAMILIES
  JTL406 contracts-sync          contracts.json stale against the tree
                                 (regenerate-and-diff, the limits-doc
                                 discipline)
  JTL407 plan-contract           the KernelPlan registry
                                 (plan/registry.py PLAN_FAMILIES)
                                 diffed against contracts.json: every
                                 spec family must resolve to a plan
                                 entry (module / factory / donation
                                 set / packed schema / carry / mesh
                                 axes all matching) and every
                                 dispatchable family must appear in
                                 the spec

All seven are ProjectRules sharing ONE FlowIndex per lint invocation
(the engine's ProjectContext); a direct ``check_project(root)`` call
builds its own, which is how the fixture mini-projects under
tests/lint_fixtures/flow_*/ are exercised.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Iterator, Optional

from ..core import ModuleSource, PACKAGE_NAME, ProjectRule, register
from ..findings import Finding

# NamedTuple API surface that is not a field access.
_NT_API = {"_replace", "_asdict", "_fields", "_make", "count", "index"}

_PACKED_DIRECTIVES = ("packs", "unpacks", "packed", "packed-width",
                      "partials", "partials-from")
_ALL_DIRECTIVES = _PACKED_DIRECTIVES + ("mesh-axes", "table-word-bits",
                                        "metrics")


class FlowRule(ProjectRule):
    """Shared plumbing: resolve the FlowIndex/FlowFacts for a root,
    through the engine's shared context when one is provided."""

    def _facts(self, root: Path, ctx=None):
        from ..flow.facts import flow_facts
        from ..flow.index import FlowIndex

        index = None
        if ctx is not None and hasattr(ctx, "flow_index"):
            index = ctx.flow_index()
        if index is None:
            index = FlowIndex.build(Path(root))
        return flow_facts(index)

    def check_project(self, root: Path, ctx=None) -> list[Finding]:
        return list(self._check(self._facts(root, ctx)))

    def _check(self, facts) -> Iterator[Finding]:
        raise NotImplementedError


def _stack_widths(scope: ast.AST) -> list[int]:
    """Element counts of every `*.stack([...])` call under `scope`."""
    out = []
    for node in ast.walk(scope):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "stack" and node.args \
                and isinstance(node.args[0], (ast.List, ast.Tuple)):
            out.append(len(node.args[0].elts))
    return out


def _row_widths(scope: ast.AST) -> list[int]:
    """Widths of the row a statement builds: stack([...]) calls when
    present, else bare tuple literals (the wgl2 host-checkpoint shape
    `ckpt = (states, masks, valid, step)`)."""
    widths = _stack_widths(scope)
    if widths:
        return widths
    return [len(n.elts) for n in ast.walk(scope)
            if isinstance(n, (ast.Tuple, ast.List))
            and isinstance(getattr(n, "ctx", None), ast.Load) and n.elts]


def _max_trailing_index(scope: ast.AST) -> Optional[int]:
    """Max constant column index over `X[..., c]` subscripts."""
    best = None
    for node in ast.walk(scope):
        if not isinstance(node, ast.Subscript):
            continue
        sl = node.slice
        if isinstance(sl, ast.Tuple) and sl.elts \
                and isinstance(sl.elts[-1], ast.Constant) \
                and isinstance(sl.elts[-1].value, int) \
                and any(isinstance(e, ast.Constant) and e.value is Ellipsis
                        for e in sl.elts[:-1]):
            c = sl.elts[-1].value
            best = c if best is None else max(best, c)
    return best


def _stmt_int_literals(stmt: ast.stmt) -> set[int]:
    return {n.value for n in ast.walk(stmt)
            if isinstance(n, ast.Constant) and isinstance(n.value, int)
            and not isinstance(n.value, bool)}


@register
class PackedSchemaDriftRule(FlowRule):
    id = "JTL401"
    name = "packed-schema-drift"
    scopes = None
    rationale = (
        "PR 3 widened wgl3.PACKED_FIELDS from 5 to 6 columns and had to "
        "hand-patch unpack_np, parallel/dense.py, parallel/multislice.py "
        "and the __graft_entry__ shard-shape assert — a consumer "
        "unpacking a width its producer doesn't emit reads garbage "
        "columns or asserts on every launch")
    hint = ("derive widths from the schema tuple (len(PACKED_FIELDS*)) "
            "or keep the `# jtflow:` annotation's literal in step with "
            "the declared field tuple")

    def _check(self, facts) -> Iterator[Finding]:
        for a in facts.annotations:
            yield from self._check_annotation(facts, a)

    def _check_annotation(self, facts, a) -> Iterator[Finding]:
        mod: ModuleSource = a.mod
        if a.directive not in _ALL_DIRECTIVES:
            yield mod.finding(self, a.line,
                              f"unknown jtflow directive "
                              f"`{a.directive}` — the contract it meant "
                              f"to declare is not being checked")
            return
        if a.node is None:
            yield mod.finding(self, a.line,
                              f"jtflow `{a.directive}` annotation does "
                              f"not bind to a statement (stale "
                              f"annotation — nothing is verified)")
            return
        if a.directive == "table-word-bits":
            try:
                int(a.arg)
            except ValueError:
                yield mod.finding(self, a.line,
                                  f"table-word-bits needs an integer, "
                                  f"got {a.arg!r}")
            return
        if a.directive not in _PACKED_DIRECTIVES:
            return
        if a.directive == "partials":
            names = tuple(s.strip() for s in a.arg.split(",") if s.strip())
            widths = _row_widths(a.node)
            if not widths:
                yield mod.finding(self, a.line,
                                  "partials annotation binds to a "
                                  "statement without a stack([...]) or "
                                  "row tuple — nothing to verify")
            elif widths[-1] != len(names):
                yield mod.finding(
                    self, a.node,
                    f"partial-sum layout drift: {len(names)} field(s) "
                    f"declared ({', '.join(names)}) but the stacked "
                    f"accumulator has {widths[-1]} element(s)")
            return
        if a.directive == "partials-from":
            yield from self._check_partials_from(facts, a)
            return
        # packs / unpacks / packed / packed-width share a schema ref.
        parts = a.arg.split()
        ref = parts[-1] if parts else ""
        schema = facts.schemas.get(ref)
        if schema is None:
            yield mod.finding(self, a.line,
                              f"jtflow {a.directive} references unknown "
                              f"packed schema {ref!r} (known: "
                              f"{', '.join(sorted(facts.schemas)) or 'none'})")
            return
        if a.directive == "packed-width":
            try:
                lit = int(parts[0])
            except (ValueError, IndexError):
                yield mod.finding(self, a.line,
                                  f"packed-width needs `packed-width=N "
                                  f"<schema>`, got {a.arg!r}")
                return
            if lit != schema.width:
                yield mod.finding(
                    self, a.node,
                    f"packed-width drift: literal {lit} vs "
                    f"{schema.ref} = {schema.width} column(s) "
                    f"({', '.join(schema.fields)})")
            elif lit not in _stmt_int_literals(a.node):
                yield mod.finding(
                    self, a.line,
                    f"stale packed-width annotation: literal {lit} no "
                    f"longer appears in the annotated statement")
        elif a.directive == "packs":
            widths = _stack_widths(a.node)
            if not widths:
                yield mod.finding(self, a.line,
                                  f"packs annotation on "
                                  f"{getattr(a.node, 'name', 'statement')!r} "
                                  f"found no stack([...]) to verify")
            elif widths[-1] != schema.width:
                yield mod.finding(
                    self, a.node,
                    f"packed-schema drift: producer stacks "
                    f"{widths[-1]} column(s) but {schema.ref} declares "
                    f"{schema.width} ({', '.join(schema.fields)})")
        elif a.directive == "unpacks":
            top = _max_trailing_index(a.node)
            if top is None:
                yield mod.finding(self, a.line,
                                  "unpacks annotation found no "
                                  "`x[..., i]` column reads to verify")
            elif top != schema.width - 1:
                yield mod.finding(
                    self, a.node,
                    f"packed-schema drift: consumer reads column "
                    f"{top} but {schema.ref} declares {schema.width} "
                    f"column(s) ({', '.join(schema.fields)}) — max "
                    f"index {schema.width - 1}")
        # "packed" is declarative: the schema resolving is the check.

    def _check_partials_from(self, facts, a) -> Iterator[Finding]:
        mod = a.mod
        layout = facts.partial_layouts.get(a.arg)
        if layout is None:
            yield mod.finding(
                self, a.line,
                f"partials-from references {a.arg!r}, which declares no "
                f"`# jtflow: partials` layout (known: "
                f"{', '.join(sorted(facts.partial_layouts)) or 'none'})")
            return
        header = (_stack_widths(a.node) or [0])[0]
        total = header + len(layout)
        target = None
        if isinstance(a.node, ast.Assign) and len(a.node.targets) == 1 \
                and isinstance(a.node.targets[0], ast.Name):
            target = a.node.targets[0].id
        if target is None:
            return
        body = getattr(getattr(a.node, "jt_parent", None), "body", None)
        if not isinstance(body, list) or a.node not in body:
            return
        for s in body[body.index(a.node) + 1:]:
            for n in ast.walk(s):
                if isinstance(n, ast.Subscript) \
                        and isinstance(n.value, ast.Name) \
                        and n.value.id == target \
                        and isinstance(n.slice, ast.Constant) \
                        and isinstance(n.slice.value, int) \
                        and n.slice.value >= total:
                    yield mod.finding(
                        self, n,
                        f"partial-sum drift: `{target}[{n.slice.value}]` "
                        f"reads past the {total} column(s) the "
                        f"{a.arg} layout emits ({header} verdict + "
                        f"{len(layout)} partials: {', '.join(layout)})")


@register
class CrossDonationRule(FlowRule):
    id = "JTL402"
    name = "cross-module-donation"
    scopes = None
    rationale = (
        "the donating kernels live behind factories in ops/ while their "
        "carries are threaded from stream/sched/checkers — JTL102 "
        "resolves donation only inside one file, so a cross-module "
        "consumer reading a donated carry after the call (or not "
        "rebinding it in a loop) was invisible until this pass")
    hint = ("rebind the donated operand from the call's result in the "
            "same statement (`carry, part = run(carry, ...)`)")

    def _check(self, facts) -> Iterator[Finding]:
        from ..astutil import walk_same_scope
        from ..flow.facts import contract_modules
        from .donation import scan_donation_sites

        index = facts.index
        for mod in contract_modules(index):
            for fn in mod.walk_nodes():
                if not isinstance(fn, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    continue
                local: dict[str, tuple[int, ...]] = {}
                for node in walk_same_scope(fn):
                    if isinstance(node, ast.Assign) \
                            and len(node.targets) == 1 \
                            and isinstance(node.targets[0], ast.Name):
                        d = index.donates(mod, node.value)
                        if d is not None and d[1]:      # cross-module only
                            local[node.targets[0].id] = d[0]

                def expr_donates(call):
                    d = index.donates(mod, call)
                    return d[0] if d is not None and d[1] else None

                yield from scan_donation_sites(fn, mod, self, local,
                                               expr_donates)


@register
class ShardingAxisRule(FlowRule):
    id = "JTL403"
    name = "sharding-axis-contract"
    scopes = None
    rationale = (
        "a collective (psum/pmax/ppermute) naming an axis no mesh "
        "construction declares fails at trace time on the first real "
        "pod — or silently binds to the wrong axis after a mesh rename; "
        "and the packed-table word math (`1 << (K - 5)`) is duplicated "
        "across wgl3/sparse/lattice, so one module changing the word "
        "packing strands the others' shard-width arithmetic")
    hint = ("declare the axis in the mesh construction (make_mesh/"
            "Mesh/`# jtflow: mesh-axes`) or fix the collective's axis "
            "name; keep word-width shifts equal to the declared "
            "`# jtflow: table-word-bits`")

    def _check(self, facts) -> Iterator[Finding]:
        declared = set(facts.mesh_axes)
        if declared:          # no meshes at all: nothing to check against
            for use in facts.axis_uses:
                if use.axis not in declared:
                    yield use.mod.finding(
                        self, use.line,
                        f"{use.kind} uses axis {use.axis!r}, which no "
                        f"mesh construction declares (declared: "
                        f"{', '.join(sorted(declared))})")
        if facts.table_word_bits is not None:
            bits, decl_mod, decl_line = facts.table_word_bits
            for mod, line, n in facts.word_shifts:
                if n != bits:
                    yield mod.finding(
                        self, line,
                        f"packed-table word math uses `1 << (K - {n})` "
                        f"but table-word-bits={bits} is declared at "
                        f"{decl_mod}:{decl_line} — shard widths "
                        f"diverge")


@register
class CarryDriftRule(FlowRule):
    id = "JTL404"
    name = "resumable-carry-drift"
    scopes = None
    rationale = (
        "the resumable chunk kernels thread NamedTuple carries "
        "(wgl3._Carry3) through stream/sched checkpoint-restore paths "
        "in OTHER modules; a field renamed in the kernel leaves the "
        "consumer reading an attribute that no longer exists — an "
        "AttributeError mid-run at best, a stale checkpoint at worst")
    hint = ("read only fields the carry NamedTuple declares; extend the "
            "NamedTuple (and its _init_carry* factory) first when the "
            "consumer needs more state")

    def _check(self, facts) -> Iterator[Finding]:
        from ..astutil import dotted, enclosing_class, enclosing_function
        from ..flow.facts import contract_modules

        index = facts.index
        if not facts.carry_factories:
            return
        for mod in contract_modules(index):
            for node in mod.walk_nodes():
                if not (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.value, ast.Call)):
                    continue
                origin = mod.imports.resolve(node.value.func) or ""
                key = ".".join(origin.split(".")[-2:])
                carry_cls = facts.carry_factories.get(key)
                if carry_cls is None:
                    continue
                carry = facts.carries[carry_cls]
                target = dotted(node.targets[0])
                if target is None:
                    continue
                scope = (enclosing_class(node) if target.startswith("self.")
                         else enclosing_function(node)) or mod.tree
                for read in ast.walk(scope):
                    if not isinstance(read, ast.Attribute):
                        continue
                    chain = dotted(read)
                    if chain is None \
                            or not chain.startswith(target + "."):
                        continue
                    attr = chain[len(target) + 1:]
                    if "." in attr:
                        attr = attr.split(".", 1)[0]
                    if attr in carry.fields or attr in _NT_API:
                        continue
                    yield mod.finding(
                        self, read,
                        f"`{target}.{attr}` is not a field of "
                        f"{carry_cls} ({carry.module} declares: "
                        f"{', '.join(carry.fields)}) — carry contract "
                        f"drift")


@register
class MetricContractRule(FlowRule):
    id = "JTL405"
    name = "metric-contract"
    scopes = None
    rationale = (
        "the bench/web snapshot contract is 'zeros permitted, never "
        "absent': a key the stats readers fetch but no capture "
        "pre-registers vanishes from metrics.json on quiet runs; and "
        "PR 7's /metrics collision (per-kernel wgl.compile_s.<k> "
        "summaries against the plain wgl.compile_s counter) rendered "
        "one family with two TYPE lines, invalidating the whole scrape")
    hint = ("add the key to the pre-registered capture() tuples "
            "(obs/__init__.py), or register the dynamic family in "
            "obs/export.py LABELED_FAMILIES so it exports under a "
            "`_by_<label>` suffix")

    def _check(self, facts) -> Iterator[Finding]:
        prereg = set(facts.preregistered)
        if facts.prereg_modules:
            for mod, line, name in facts.snapshot_reads:
                if name not in prereg:
                    yield mod.finding(
                        self, line,
                        f"snapshot contract key {name!r} is not "
                        f"pre-registered by capture() — absent (not "
                        f"zero) on runs that never touch it")
            # Pre-registered names nothing writes: dead contract weight.
            literal_writes = {w.name for w in facts.metric_writes
                              if w.name is not None}
            families = [w.family for w in facts.metric_writes if w.family]
            for name in sorted(prereg):
                if name in literal_writes:
                    continue
                if any(name.startswith(f) for f in families):
                    continue
                decl_mod, decl_line = facts.preregistered[name]
                m = facts.index.modules.get(decl_mod)
                if m is not None:
                    yield m.finding(
                        self, decl_line,
                        f"pre-registered metric {name!r} has no writer "
                        f"anywhere in the project — stale contract "
                        f"entry")
        # The PR 7 collision class, statically: a dynamic family whose
        # prefix is also a plain metric name must be a LABELED_FAMILIES
        # member (the exporter then folds it under `_by_<label>`).
        plain = {w.name for w in facts.metric_writes if w.name is not None}
        for w in facts.metric_writes:
            if w.family and w.family in plain \
                    and w.family not in facts.labeled_families:
                yield w.mod.finding(
                    self, w.line,
                    f"dynamic metric family `{w.family}.<member>` "
                    f"collides with the plain metric {w.family!r} and "
                    f"is not in export LABELED_FAMILIES — /metrics "
                    f"would render one family with two TYPE lines "
                    f"(invalid exposition, the PR 7 incident)")


@register
class ContractsSyncRule(FlowRule):
    id = "JTL406"
    name = "contracts-sync"
    scopes = None
    rationale = (
        "contracts.json is the reviewed statement of the kernel "
        "interfaces (and the seed for ROADMAP item 5's KernelPlan); a "
        "stale copy silently re-legitimizes drift the flow rules exist "
        "to catch — regenerate-and-diff, the limits-doc discipline")
    hint = "run `jepsen-tpu lint --write-contracts` and review the diff"

    def check_project(self, root: Path, ctx=None) -> list[Finding]:
        root = Path(root)
        if not (root / PACKAGE_NAME).is_dir():
            return []        # fixture mini-projects / foreign trees
        from ..flow.contracts import CONTRACTS_FILE, contracts_in_sync

        index = None
        if ctx is not None and hasattr(ctx, "flow_index"):
            index = ctx.flow_index()
        ok, detail = contracts_in_sync(root, index=index)
        if ok:
            return []
        return [Finding(rule=self.id, path=CONTRACTS_FILE, line=1,
                        message=detail, hint=self.hint)]

    def covered_paths(self, root: Path) -> list[str]:
        from ..flow.contracts import CONTRACTS_FILE

        return [CONTRACTS_FILE]


@register
class PlanContractRule(FlowRule):
    id = "JTL407"
    name = "plan-contract"
    scopes = None
    rationale = (
        "the KernelPlan layer (plan/) was seeded FROM contracts.json; "
        "a registry family whose module/factory/donation set drifts "
        "from the spec dispatches a kernel under the wrong contract "
        "(a donated operand read back, a packed width misread), a spec "
        "family with no registry entry is a kernel the plan spine "
        "silently cannot dispatch, and a registry family outside the "
        "spec is an unreviewed backend — exactly the refactor-drift "
        "this layer's one-plan-under-every-kernel promise forbids")
    hint = ("keep plan/registry.py PLAN_FAMILIES in step with "
            "contracts.json (regenerate with `jepsen-tpu lint "
            "--write-contracts`, then mirror the kernel's entry); "
            "declared carries must exist in the contracts `carries` "
            "section and mesh axes in `meshes`")

    def check_project(self, root: Path, ctx=None) -> list[Finding]:
        import json

        root = Path(root)
        contracts_path = root / "contracts.json"
        if not contracts_path.is_file():
            return []           # JTL406 owns the missing-spec failure
        try:
            contracts = json.loads(
                contracts_path.read_text(encoding="utf-8"))
        except ValueError:
            return []           # JTL406 reports the invalid file
        facts = self._facts(root, ctx)
        found = self._find_registry(facts.index)
        if found is None:
            if (root / PACKAGE_NAME).is_dir():
                return [Finding(
                    rule=self.id, path="contracts.json", line=1,
                    message=("contracts.json declares kernel families "
                             "but no module defines a PLAN_FAMILIES "
                             "registry — the plan layer cannot "
                             "dispatch any of them"),
                    hint=self.hint)]
            return []           # foreign tree / fixture without a plan
        mod, node, families = found
        if families is None:
            return [mod.finding(
                self, node.lineno,
                "PLAN_FAMILIES is not a pure literal — JTL407 cannot "
                "verify the plan registry against contracts.json")]
        return list(self._diff(mod, node, families, contracts))

    def _find_registry(self, index):
        """(module, Dict node, {family: (entry, key line)}) of the
        first PLAN_FAMILIES pure-literal dict in the tree."""
        from ..flow.facts import _module_consts, contract_modules

        for mod in contract_modules(index):
            node = _module_consts(mod).get("PLAN_FAMILIES")
            if not isinstance(node, ast.Dict):
                continue
            try:
                value = ast.literal_eval(node)
            except (ValueError, TypeError):
                return (mod, node, None)    # non-literal: flagged below
            fams = {}
            for k in node.keys:
                if isinstance(k, ast.Constant) \
                        and isinstance(k.value, str):
                    ent = (value.get(k.value)
                           if isinstance(value, dict) else None)
                    fams[k.value] = (ent, k.lineno)
            return (mod, node, fams)
        return None

    def _diff(self, mod, node, families, contracts) -> Iterator[Finding]:
        spec = contracts.get("kernels", {})
        carries = set(contracts.get("carries", {}))
        meshes = set(contracts.get("meshes", {}))
        for fam in sorted(set(spec) - set(families)):
            yield mod.finding(
                self, node.lineno,
                f"kernel family {fam!r} is in contracts.json but has "
                f"no KernelPlan registry entry — the plan layer "
                f"cannot dispatch it")
        for fam in sorted(set(families) - set(spec)):
            yield mod.finding(
                self, families[fam][1],
                f"plan registry dispatches backend {fam!r}, which "
                f"contracts.json does not declare — dispatch target "
                f"outside the spec")
        for fam in sorted(set(spec) & set(families)):
            ent, line = families[fam]
            dec = spec[fam]
            if not isinstance(ent, dict):
                yield mod.finding(
                    self, line,
                    f"plan registry entry {fam!r} is not a pure dict "
                    f"literal — JTL407 cannot verify it against the "
                    f"spec")
                continue
            for fld in ("module", "factory"):
                if ent.get(fld) != dec.get(fld):
                    yield mod.finding(
                        self, line,
                        f"{fam}: registry {fld} {ent.get(fld)!r} != "
                        f"contracts {dec.get(fld)!r}")
            if sorted(ent.get("donates", [])) != sorted(
                    dec.get("donates", [])):
                yield mod.finding(
                    self, line,
                    f"{fam}: registry donates "
                    f"{sorted(ent.get('donates', []))} != contracts "
                    f"{sorted(dec.get('donates', []))}")
            if (ent.get("packed") or None) != dec.get("packed"):
                yield mod.finding(
                    self, line,
                    f"{fam}: registry packed {ent.get('packed')!r} != "
                    f"contracts {dec.get('packed')!r}")
            if ent.get("carry") and ent["carry"] not in carries:
                yield mod.finding(
                    self, line,
                    f"{fam}: registry carry {ent['carry']!r} is not a "
                    f"contracts carries entry ({sorted(carries)})")
            for ax in ent.get("axes", []):
                if ax not in meshes:
                    yield mod.finding(
                        self, line,
                        f"{fam}: registry mesh axis {ax!r} is not "
                        f"declared by any mesh construction "
                        f"(contracts meshes: {sorted(meshes)})")

    def covered_paths(self, root: Path) -> list[str]:
        return ["contracts.json"]
