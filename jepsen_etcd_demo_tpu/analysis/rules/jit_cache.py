"""JTL101 jit-cache-key: recompile storms from unstable jit caching.

The corpus engine's whole speedup (PR 2: 5.18 s -> 0.36 s) is kernel
reuse; one call site that re-jits per invocation or keys a kernel cache
on per-run data silently re-traces/re-compiles every launch and the
regression only shows up as wall clock. Three statically visible
shapes:

  * ``jax.jit(f)(x)`` — jit-and-call in one expression: the compiled
    callable is discarded, so every execution pays tracing (and, cache
    miss permitting, XLA compilation) again.
  * a kernel-cache store (``_CACHE[key] = ...``) whose key contains
    ``id(...)`` / ``time.*`` / ``random.*`` — per-process, per-run or
    colliding-after-GC identities; the persistent compile cache can
    never hit across processes on such keys.
  * ``static_argnums``/``static_argnames`` passed a computed (non-
    literal) value — the static set itself varying per call site is a
    retrace hazard and defeats review of WHAT is being baked into the
    compiled program.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from ..astutil import (CACHE_NAME_RE, call_args_source,
                       enclosing_function)
from ..core import KERNEL_SCOPES, ModuleSource, Rule, register
from ..findings import Finding

_BAD_KEY_ORIGINS = ("time.", "random.")


def _is_literalish(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, (ast.Tuple, ast.List)):
        return all(_is_literalish(e) for e in node.elts)
    if isinstance(node, ast.UnaryOp):
        return _is_literalish(node.operand)
    return False


@register
class JitCacheKeyRule(Rule):
    id = "JTL101"
    name = "jit-cache-key"
    scopes = KERNEL_SCOPES
    rationale = (
        "Recompile storms: PR 2's throughput win is kernel reuse; an "
        "unstable jit-cache key or a jit-and-call re-traces per launch "
        "and only shows up as wall clock.")
    hint = ("cache the jitted callable (module _CACHE keyed on "
            "(model.cache_key(), cfg, shapes) or functools.lru_cache); "
            "keys must be content-derived, never id()/time/random; "
            "static_argnums must be a literal")

    def check(self, mod: ModuleSource) -> Iterator[Finding]:
        for node in mod.walk_nodes():
            if not isinstance(node, ast.Call):
                continue
            # jax.jit(f)(...) — immediately-invoked jit.
            if isinstance(node.func, ast.Call) \
                    and mod.imports.is_call_to(node.func, "jax.jit"):
                yield mod.finding(
                    self, node,
                    "jax.jit created and called in one expression — the "
                    "compiled callable is discarded, every call pays "
                    "tracing/compilation again")
            if mod.imports.is_call_to(node, "jax.jit"):
                for kw in node.keywords:
                    if kw.arg in ("static_argnums", "static_argnames") \
                            and not _is_literalish(kw.value):
                        yield mod.finding(
                            self, node,
                            f"{kw.arg} is a computed expression "
                            f"({call_args_source(kw.value, mod.text) or 'non-literal'}) "
                            f"— per-call static sets are a retrace "
                            f"hazard; spell the static argument "
                            f"positions as a literal")
        yield from self._cache_key_stores(mod)

    def _cache_key_stores(self, mod: ModuleSource) -> Iterator[Finding]:
        for node in mod.walk_nodes():
            if not isinstance(node, ast.Assign):
                continue
            for tgt in node.targets:
                if not (isinstance(tgt, ast.Subscript)
                        and isinstance(tgt.value, ast.Name)
                        and CACHE_NAME_RE.search(tgt.value.id)):
                    continue
                key_expr = self._key_expr(tgt.slice, node, mod)
                for bad in self._unstable_parts(key_expr, mod):
                    yield mod.finding(
                        self, node,
                        f"kernel cache {tgt.value.id} keyed on "
                        f"{bad} — a per-run/per-process identity: the "
                        f"cache can never hit across runs and may "
                        f"collide after GC")

    def _key_expr(self, key: ast.AST, store: ast.Assign,
                  mod: ModuleSource) -> ast.AST:
        """The key expression, following one level of local
        `key = (...)` indirection — the repo's idiom."""
        if not isinstance(key, ast.Name):
            return key
        fn = enclosing_function(store)
        body = fn.body if fn is not None else mod.tree.body
        best = None
        for stmt in body:
            if stmt.lineno >= store.lineno:
                break
            if isinstance(stmt, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == key.id
                    for t in stmt.targets):
                best = stmt.value
        return best if best is not None else key

    def _unstable_parts(self, expr: ast.AST,
                        mod: ModuleSource) -> Iterator[str]:
        for n in ast.walk(expr):
            if not isinstance(n, ast.Call):
                continue
            origin = mod.imports.resolve(n.func)
            if origin == "id":
                yield "id(...)"
            elif origin and any(origin.startswith(p)
                                for p in _BAD_KEY_ORIGINS):
                yield f"{origin}(...)"
