"""JTL203 unlocked-shared-state: thread/worker races on mutable attrs.

A class that spawns ``threading.Thread(target=self._x)`` (the stream
consumer, the recorder listener's downstream) has two sides mutating
``self``: the thread body and the caller-facing methods. An attribute
MUTATED on both sides without a lock is a data race — dict/list ops
are atomic-ish under the GIL until they aren't (check-then-act,
read-modify-write, iteration during mutation).

Scope is deliberately mutation-vs-mutation: one side mutating while
the other only reads is the GIL-tolerated pattern this codebase uses
knowingly (StreamSession._falsified) and flagging reads would bury the
signal. Recognized synchronization, per attribute:

  * attr initialized to a thread-safe type (queue.*, threading.Event/
    Lock/Condition/Semaphore, collections.deque) — exempt;
  * every mutation (both sides) under a ``with <lock>:`` — exempt;
  * mutation after ``self.<thread>.join()`` in the same method — the
    thread is dead, exempt (StreamSession.finalize's shape).
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from ..astutil import LOCKISH_RE, ancestors, dotted
from ..core import CONCURRENCY_SCOPES, ModuleSource, Rule, register
from ..findings import Finding

_SAFE_TYPES = {"queue.Queue", "queue.SimpleQueue", "queue.LifoQueue",
               "queue.PriorityQueue", "threading.Event", "threading.Lock",
               "threading.RLock", "threading.Condition",
               "threading.Semaphore", "threading.BoundedSemaphore",
               "collections.deque"}
_MUTATORS = {"append", "appendleft", "add", "update", "pop", "popitem",
             "popleft", "remove", "discard", "extend", "insert", "clear",
             "setdefault", "__setitem__"}


def _self_attr(node: ast.AST) -> Optional[str]:
    """`self.X...` -> "X" (the first attribute after self)."""
    d = dotted(node)
    if d and d.startswith("self.") and len(d.split(".")) >= 2:
        return d.split(".")[1]
    return None


class _ClassInfo:
    def __init__(self, cls: ast.ClassDef, mod: ModuleSource):
        self.cls = cls
        self.mod = mod
        self.methods = {n.name: n for n in cls.body
                        if isinstance(n, (ast.FunctionDef,
                                          ast.AsyncFunctionDef))}
        self.safe_attrs: set[str] = set()
        self.thread_attrs: set[str] = set()     # self.X = Thread(...)
        self.thread_targets: set[str] = set()   # method names
        self._scan_init_and_threads()

    def _scan_init_and_threads(self):
        for meth in self.methods.values():
            for node in ast.walk(meth):
                if not (isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Call)):
                    continue
                origin = self.mod.imports.resolve(node.value.func) or ""
                tgt_attrs = [a for t in node.targets
                             for a in [_self_attr(t)] if a]
                if origin in _SAFE_TYPES:
                    self.safe_attrs.update(tgt_attrs)
                if origin in ("threading.Thread", "Thread"):
                    self.thread_attrs.update(tgt_attrs)
                    for kw in node.value.keywords:
                        if kw.arg == "target":
                            m = _self_attr(kw.value)
                            if m:
                                self.thread_targets.add(m)

    def thread_side_methods(self) -> set[str]:
        """Transitive closure of self.* calls from the thread targets."""
        out = set(self.thread_targets)
        frontier = list(out)
        while frontier:
            name = frontier.pop()
            meth = self.methods.get(name)
            if meth is None:
                continue
            for node in ast.walk(meth):
                if isinstance(node, ast.Call):
                    callee = _self_attr(node.func)
                    if callee in self.methods and callee not in out:
                        out.add(callee)
                        frontier.append(callee)
        return out

    def mutations(self, meth) -> list[tuple[str, ast.AST, bool, bool]]:
        """(attr, node, under_lock, after_join) per self-attr mutation."""
        join_line = None
        for node in ast.walk(meth):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "join" \
                    and _self_attr(node.func.value) in self.thread_attrs:
                join_line = min(join_line or node.lineno, node.lineno)
        out = []

        def emit(attr: Optional[str], node: ast.AST):
            if attr is None or attr in self.safe_attrs \
                    or attr in self.thread_attrs:
                return
            under_lock = any(
                isinstance(a, (ast.With, ast.AsyncWith)) and any(
                    LOCKISH_RE.search((dotted(i.context_expr) or "")
                                    .split(".")[-1])
                    for i in a.items)
                for a in ancestors(node))
            after_join = join_line is not None and node.lineno > join_line
            out.append((attr, node, under_lock, after_join))

        for node in ast.walk(meth):
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                tgts = (node.targets if isinstance(node, ast.Assign)
                        else [node.target])
                for t in tgts:
                    base = t.value if isinstance(
                        t, (ast.Subscript,)) else t
                    emit(_self_attr(base), node)
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _MUTATORS:
                emit(_self_attr(node.func.value), node)
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    base = t.value if isinstance(t, ast.Subscript) else t
                    emit(_self_attr(base), node)
        return out


@register
class UnlockedSharedStateRule(Rule):
    id = "JTL203"
    name = "unlocked-shared-state"
    scopes = CONCURRENCY_SCOPES
    rationale = (
        "The recorder listener / StreamSession consumer share one "
        "process with the event-loop workers; an attribute mutated on "
        "both sides without a lock is a data race the GIL only "
        "sometimes hides.")
    hint = ("guard both sides with one threading.Lock, hand the data "
            "across on a queue.Queue, or confine mutation to one side "
            "(join() the thread before touching its state)")

    def check(self, mod: ModuleSource) -> Iterator[Finding]:
        for node in mod.walk_nodes():
            if isinstance(node, ast.ClassDef):
                yield from self._check_class(node, mod)

    def _check_class(self, cls: ast.ClassDef,
                     mod: ModuleSource) -> Iterator[Finding]:
        info = _ClassInfo(cls, mod)
        if not info.thread_targets:
            return
        thread_side = info.thread_side_methods()
        t_mut: dict[str, list] = {}
        o_mut: dict[str, list] = {}
        for name, meth in info.methods.items():
            if name == "__init__":
                continue
            bucket = t_mut if name in thread_side else o_mut
            for attr, n, locked, after_join in info.mutations(meth):
                if after_join:
                    continue
                bucket.setdefault(attr, []).append((n, locked, name))
        for attr in sorted(set(t_mut) & set(o_mut)):
            both = t_mut[attr] + o_mut[attr]
            if all(locked for _, locked, _ in both):
                continue
            node, _, meth = o_mut[attr][0]
            t_meth = t_mut[attr][0][2]
            yield mod.finding(
                self, node,
                f"{cls.name}.{attr} mutated by worker-facing "
                f"{meth}() AND by thread-side {t_meth}() (thread "
                f"target: {', '.join(sorted(info.thread_targets))}) "
                f"without a lock — a cross-thread data race")
