"""JTL104 traced-branch: Python control flow on traced values.

``if jnp.any(x):`` inside a jitted function raises a
ConcretizationTypeError at trace time — the friendly failure. The
nasty variants are OUTSIDE jit: the branch silently forces a blocking
device fetch per evaluation (a host sync the profiler attributes to
nothing), and under ``vmap``/``shard_map`` tracing it fails only on
the first data-dependent path. The WGL kernels express data-dependent
control as ``lax.cond``/``jnp.where`` masks for exactly this reason
(ops/wgl3.py's step functions).

Heuristic: an ``if``/``while`` test that mentions a ``jax.numpy``
name. Static configuration branches (``if cfg.k_slots > 16``) don't
match; a genuinely wanted host branch on a fetched value should fetch
explicitly (``bool(np.asarray(x))``) — which names the sync and falls
under JTL103's bounded-fetch discipline instead.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..core import KERNEL_SCOPES, ModuleSource, Rule, register
from ..findings import Finding


@register
class TracedBranchRule(Rule):
    id = "JTL104"
    name = "traced-branch"
    scopes = KERNEL_SCOPES
    rationale = (
        "Python if/while on a traced value either breaks under jit "
        "(ConcretizationTypeError) or silently host-syncs per "
        "evaluation outside it; kernel code expresses data-dependent "
        "control as lax.cond/where masks.")
    hint = ("inside kernels use lax.cond / lax.while_loop / jnp.where "
            "masks; on the host, fetch explicitly first "
            "(bool(np.asarray(x))) so the sync is visible and bounded")

    def check(self, mod: ModuleSource) -> Iterator[Finding]:
        for node in mod.walk_nodes():
            if not isinstance(node, (ast.If, ast.While)):
                continue
            jnp_name = self._jnp_use(node.test, mod)
            if jnp_name:
                kind = "if" if isinstance(node, ast.If) else "while"
                yield mod.finding(
                    self, node,
                    f"Python `{kind}` branches on a jax.numpy value "
                    f"({jnp_name}) — trace-time error under jit, "
                    f"hidden per-evaluation host sync outside it")

    def _jnp_use(self, test: ast.AST, mod: ModuleSource) -> str:
        for n in ast.walk(test):
            if isinstance(n, (ast.Name, ast.Attribute)):
                origin = mod.imports.resolve(n)
                if origin and (origin == "jax.numpy"
                               or origin.startswith("jax.numpy.")):
                    return origin
        return ""
