"""JTL202 loop-bound-primitive: asyncio primitives crossing event loops.

The ADVICE r5 bug class: an ``asyncio.Lock`` binds to the event loop
that first awaits it; ``--test-count >= 2`` runs each test under its
own ``asyncio.run``, so any primitive that SURVIVES a run (module
global, cached in a long-lived dict, attribute of a long-lived object)
raises ``"... is bound to a different event loop"`` in the second run
— the EtcdDB install-lock / PORT_MAP incident. The shipped fix keys
the cache by ``asyncio.get_running_loop()`` (db/etcd.py
``_install_lock``), which this rule recognizes and accepts.

Flagged: an asyncio primitive constructed OUTSIDE an async function
(module level, ``__init__``, sync helpers) and stored somewhere that
can outlive a loop — unless the store is a container keyed by the
running loop. Construction inside an async function is accepted (the
instance belongs to the loop that is running it). A primitive on a
strictly per-run object is safe in practice — suppress with the
lifetime argument inline (clients/fake_kv.py, runner/core.py do).
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..astutil import (ancestors, enclosing_function, statement_of,
                       walk_cached)
from ..core import CONCURRENCY_SCOPES, ModuleSource, Rule, register
from ..findings import Finding

_PRIMITIVES = {"asyncio.Lock", "asyncio.Event", "asyncio.Condition",
               "asyncio.Semaphore", "asyncio.BoundedSemaphore",
               "asyncio.Queue", "asyncio.LifoQueue",
               "asyncio.PriorityQueue"}
_LOOP_GETTERS = ("get_running_loop", "get_event_loop")


@register
class LoopBoundPrimitiveRule(Rule):
    id = "JTL202"
    name = "loop-bound-primitive"
    scopes = CONCURRENCY_SCOPES
    rationale = (
        "ADVICE r5 (EtcdDB install lock / PORT_MAP): an asyncio "
        "primitive binds to the loop that first awaits it; surviving "
        "into a second asyncio.run raises 'bound to a different event "
        "loop' mid-test.")
    hint = ("create the primitive inside the running loop, or key the "
            "cache by asyncio.get_running_loop() (db/etcd.py "
            "_install_lock); a strictly per-run instance may suppress "
            "with its lifetime argument")

    def check(self, mod: ModuleSource) -> Iterator[Finding]:
        for node in mod.walk_nodes():
            if not (isinstance(node, ast.Call)
                    and mod.imports.resolve(node.func) in _PRIMITIVES):
                continue
            fn = enclosing_function(node)
            if isinstance(fn, ast.AsyncFunctionDef):
                continue          # created under the running loop
            prim = mod.imports.resolve(node.func)
            if self._loop_keyed_store(node, fn, mod):
                continue
            where = (f"in sync function {fn.name}()" if fn is not None
                     else "at module scope")
            yield mod.finding(
                self, node,
                f"{prim}() created {where} — binds to whichever loop "
                f"first awaits it; if this object survives into a "
                f"second asyncio.run it raises 'bound to a different "
                f"event loop' (ADVICE r5 bug class)")

    def _loop_keyed_store(self, prim: ast.Call, fn,
                          mod: ModuleSource) -> bool:
        """True when the primitive is stored into a container under a
        key derived from the running loop — the sanctioned cache shape
        (db/etcd.py _install_lock), or via .setdefault(loop, ...)."""
        loop_names = self._loop_names(fn, mod)
        stmt = statement_of(prim)
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                if isinstance(t, ast.Subscript) \
                        and self._loop_derived(t.slice, loop_names, mod):
                    return True
        for a in ancestors(prim):
            if isinstance(a, ast.Call) \
                    and isinstance(a.func, ast.Attribute) \
                    and a.func.attr == "setdefault" and a.args \
                    and self._loop_derived(a.args[0], loop_names, mod):
                return True
            if isinstance(a, ast.stmt):
                break
        return False

    def _loop_names(self, fn, mod: ModuleSource) -> set[str]:
        """Names bound from asyncio.get_running_loop()/get_event_loop()
        in the enclosing function."""
        out: set[str] = set()
        if fn is None:
            return out
        for node in walk_cached(fn):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call):
                origin = mod.imports.resolve(node.value.func) or ""
                if origin.rsplit(".", 1)[-1] in _LOOP_GETTERS:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            out.add(t.id)
        return out

    def _loop_derived(self, key: ast.AST, loop_names: set[str],
                      mod: ModuleSource) -> bool:
        for n in ast.walk(key):
            if isinstance(n, ast.Name) and n.id in loop_names:
                return True
            if isinstance(n, ast.Call):
                origin = mod.imports.resolve(n.func) or ""
                if origin.rsplit(".", 1)[-1] in _LOOP_GETTERS:
                    return True
        return False
