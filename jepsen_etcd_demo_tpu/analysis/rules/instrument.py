"""JTL105 uninstrumented-kernel: every jit cache wears obs.instrument_kernel.

The PR 1 invariant — every jit-compiled kernel the harness caches is
wrapped in ``obs.instrument_kernel`` so compile-vs-execute attribution
is never a blind spot (BENCH_r05's wedged-tunnel diagnosis ran entirely
on this attribution). Until ISSUE 7 it was enforced by convention only,
and PR 3's lattice kernels (parallel/lattice.py) shipped uninstrumented
— exactly the drift this rule exists to stop.

Accepted shapes:

  * ``instrument_kernel("name", jax.jit(...))`` anywhere in the
    statement — wrapped at the jit site;
  * ``return jax.jit(...)`` from a PLAIN factory function — the repo's
    ``_chunk_fn`` idiom, where the CALLER wraps at its cache store
    (that store is itself checked: a bare ``_CACHE[...] = jax.jit(...)``
    flags). A factory decorated with ``functools.lru_cache`` gets no
    such exemption: the lru_cache IS the kernel cache, there is no
    later wrap point.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..astutil import CACHE_NAME_RE, ancestors, decorator_names, \
    enclosing_function, walk_same_scope
from ..core import KERNEL_SCOPES, ModuleSource, Rule, register
from ..findings import Finding

_LRU_DECOS = ("functools.lru_cache", "functools.cache", "lru_cache",
              "cache")


@register
class UninstrumentedKernelRule(Rule):
    id = "JTL105"
    name = "uninstrumented-kernel"
    scopes = KERNEL_SCOPES
    rationale = (
        "PR 1 invariant: every cached jit kernel is wrapped in "
        "obs.instrument_kernel for compile/execute attribution; "
        "parallel/lattice.py (PR 3) shipped without it — a telemetry "
        "blind spot this rule would have caught.")
    hint = ("wrap the jitted callable: obs.instrument_kernel(\"<kernel-"
            "name>\", jax.jit(...)) — same signature, near-zero cost "
            "outside a capture")

    def check(self, mod: ModuleSource) -> Iterator[Finding]:
        for node in mod.walk_nodes():
            if not (isinstance(node, ast.Call)
                    and mod.imports.is_call_to(node, "jax.jit")):
                continue
            if self._wrapped(node, mod):
                continue
            fn = enclosing_function(node)
            in_return = any(isinstance(a, ast.Return)
                            for a in ancestors(node))
            if in_return and fn is not None:
                decos = decorator_names(fn, mod.imports)
                if not any(d == want or d.endswith("." + want)
                           for d in decos for want in _LRU_DECOS):
                    continue   # plain factory: caller's store is checked
                yield mod.finding(
                    self, node,
                    f"jit kernel cached by functools.lru_cache on "
                    f"{fn.name}() but not wrapped in "
                    f"obs.instrument_kernel — the lru_cache IS the "
                    f"kernel cache, there is no later wrap point")
                continue
            yield mod.finding(
                self, node,
                "jit-compiled kernel not wrapped in "
                "obs.instrument_kernel — compile/execute attribution "
                "blind spot (the PR 1 invariant)")
        yield from self._factory_stores(mod)

    def _factory_stores(self, mod: ModuleSource) -> Iterator[Finding]:
        """The caller half of the plain-factory exemption: a cache
        store of a LOCAL factory's result (`_CACHE[k] = make_fn(...)`)
        flags when the factory's returns contain a bare jax.jit — the
        exact pre-fix parallel/lattice.py shape (factory + separate
        cached_* store, neither wrapping)."""
        # Resolve factories by name only when the name is UNIQUE in the
        # module: with duplicates (nested `measure`/`build` defs recur)
        # a bare name could consult the wrong def — stay conservative.
        all_fns = [n for n in mod.walk_nodes()
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))]
        counts: dict[str, int] = {}
        for n in all_fns:
            counts[n.name] = counts.get(n.name, 0) + 1
        fns = {n.name: n for n in all_fns if counts[n.name] == 1}
        for node in mod.walk_nodes():
            if not isinstance(node, ast.Assign):
                continue
            for tgt in node.targets:
                if not (isinstance(tgt, ast.Subscript)
                        and isinstance(tgt.value, ast.Name)
                        and CACHE_NAME_RE.search(tgt.value.id)):
                    continue
                val = node.value
                if self._contains_instrument(val, mod):
                    continue
                if isinstance(val, ast.Call) \
                        and isinstance(val.func, ast.Name) \
                        and val.func.id in fns \
                        and self._returns_bare_jit(fns[val.func.id], mod):
                    yield mod.finding(
                        self, node,
                        f"cache store of {val.func.id}()'s result: the "
                        f"factory returns a bare jax.jit and nothing "
                        f"wraps it in obs.instrument_kernel — the "
                        f"pre-fix parallel/lattice.py blind spot")

    def _returns_bare_jit(self, fn, mod: ModuleSource) -> bool:
        for node in walk_same_scope(fn):
            if isinstance(node, ast.Return) and node.value is not None:
                for c in ast.walk(node.value):
                    if isinstance(c, ast.Call) \
                            and mod.imports.is_call_to(c, "jax.jit") \
                            and not self._wrapped(c, mod):
                        return True
        return False

    def _contains_instrument(self, expr: ast.AST,
                             mod: ModuleSource) -> bool:
        return any(isinstance(c, ast.Call) and mod.imports.is_call_to(
            c, "instrument_kernel", "obs.instrument_kernel")
            for c in ast.walk(expr))

    def _wrapped(self, jit_call: ast.Call, mod: ModuleSource) -> bool:
        for a in ancestors(jit_call):
            if isinstance(a, ast.Call) and mod.imports.is_call_to(
                    a, "instrument_kernel", "obs.instrument_kernel"):
                return True
            if isinstance(a, ast.stmt):
                break
        return False
