"""JTL301 limits-doc: KernelLimits fields documented, tagged, ranged.

The refactored core of ``tools/check_limits_doc.py`` (which remains as
a thin CLI shim): every ``KernelLimits`` field must appear in
doc/perf.md's "KernelLimits reference" table with its
``[worker]/[arch]/[tunable]`` provenance tag and its ``lo..hi`` safe
range, both MATCHING ``ops/limits.py field_meta()`` — the autotuner's
search bounds are the documented bounds, enforced (ISSUE 4; now ISSUE
7 moves it onto the shared rule-runner so doc lint and code lint share
one findings format and one baseline mechanism).

This is a :class:`~..core.ProjectRule`: it runs once per lint
invocation against the repo root, not per Python module. It imports
``ops.limits`` (dataclass metadata only — no jax), keeping the tier-1
lint path fast.
"""

from __future__ import annotations

from pathlib import Path
from typing import Optional

from ..core import PACKAGE_NAME, ProjectRule, register
from ..findings import Finding


def field_metadata() -> dict[str, dict]:
    from ...ops.limits import field_meta

    return field_meta()


def range_text(meta: dict) -> str:
    lo, hi = meta["range"]
    return f"{lo}..{hi}"


def doc_problems(doc_path: Path) -> list[tuple[str, Optional[int], str]]:
    """Every documentation problem as (field, doc line or None, message).
    Message text is the tools/check_limits_doc.py contract — stable
    wording, substring-matched by tests."""
    text = Path(doc_path).read_text(encoding="utf-8")
    lines = text.splitlines()
    problems: list[tuple[str, Optional[int], str]] = []
    for name, meta in field_metadata().items():
        span = f"`{name}`"
        rows = [(i, ln) for i, ln in enumerate(lines, start=1)
                if span in ln and ln.lstrip().startswith("|")]
        if span not in text or not rows:
            problems.append((name, None,
                             f"{name}: no table row in doc/perf.md "
                             f"(env JEPSEN_TPU_LIMIT_{name.upper()})"))
            continue
        # A field may appear in several tables (the probe-group map, the
        # reference); it passes when SOME row carries both its tag and
        # its range — the reference row. The range must fill a WHOLE
        # table cell: a bare substring test would let `1..80` satisfy a
        # wanted `1..8` (prefix drift the lint exists to catch).
        want_tag = f"[{meta['kind']}]"
        want_cell = f"| {range_text(meta)} |"
        cells = [(i, " ".join(r.split())) for i, r in rows]
        if any(want_tag in r and want_cell in r for _, r in cells):
            continue
        line0 = rows[0][0]
        has_tag = any(want_tag in r for _, r in cells)
        has_cell = any(want_cell in r for _, r in cells)
        if not has_tag:
            problems.append((name, line0,
                             f"{name}: no table row carries its "
                             f"provenance tag {want_tag} (tags: "
                             f"[worker]/[arch]/[tunable])"))
        if not has_cell:
            problems.append((name, line0,
                             f"{name}: no table row carries its safe "
                             f"range `{range_text(meta)}` as a whole "
                             f"cell (ops/limits.py field_meta is the "
                             f"source of truth)"))
        if has_tag and has_cell:
            problems.append((name, line0,
                             f"{name}: tag {want_tag} and range "
                             f"`{range_text(meta)}` never appear in "
                             f"the SAME row"))
    return problems


def missing_fields(doc_path: Path) -> list[str]:
    """KernelLimits field names not mentioned (as `field` code spans) in
    the perf doc."""
    text = Path(doc_path).read_text(encoding="utf-8")
    return [name for name in field_metadata() if f"`{name}`" not in text]


def doc_errors(doc_path: Path) -> list[str]:
    """Every problem as a human-readable string (the historic
    tools/check_limits_doc.py API)."""
    return [msg for _, _, msg in doc_problems(doc_path)]


@register
class LimitsDocRule(ProjectRule):
    id = "JTL301"
    name = "limits-doc"
    scopes = None
    rationale = (
        "ISSUE 4: the autotuner searches each KernelLimits field "
        "inside its documented safe range — a doc row missing or "
        "contradicting ops/limits.py field_meta drifts the enforced "
        "bounds from the documented ones.")
    hint = ("fix the 'KernelLimits reference' table in doc/perf.md: "
            "every field needs a row with its [worker]/[arch]/"
            "[tunable] tag and its lo..hi safe range")
    doc_relpath = "doc/perf.md"

    def _applicable(self, root: Path) -> bool:
        """This rule is about THIS repo's doc: linting a foreign tree
        (`lint /tmp/scratch/f.py` — root resolves outside the harness
        repo) must not manufacture a 'doc not found' failure."""
        return (Path(root) / self.doc_relpath).is_file() \
            or (Path(root) / PACKAGE_NAME).is_dir()

    def covered_paths(self, root: Path) -> list[str]:
        return [self.doc_relpath] if self._applicable(root) else []

    def check_project(self, root: Path, ctx=None) -> list[Finding]:
        if not self._applicable(root):
            return []
        doc = Path(root) / self.doc_relpath
        if not doc.is_file():
            return [Finding(rule=self.id, path=self.doc_relpath, line=1,
                            message=f"{self.doc_relpath} not found under "
                                    f"{root} — the KernelLimits "
                                    f"reference table lives there",
                            hint=self.hint)]
        lines = doc.read_text(encoding="utf-8").splitlines()
        out = []
        for _field, line, msg in doc_problems(doc):
            ln = line or 1
            out.append(Finding(
                rule=self.id, path=self.doc_relpath, line=ln,
                message=msg, hint=self.hint,
                snippet=lines[ln - 1] if 0 < ln <= len(lines) else ""))
        return out
