"""jtlint rule suite: importing this package registers every rule.

Rule id blocks (doc/analysis.md has the full reference):
  JTL1xx — JAX kernel hygiene (ops/, parallel/, sched/, stream/, tune/)
  JTL2xx — concurrency discipline (runner/, stream/, sched/, db/, web/,
           clients/, control/)
  JTL3xx — project-level lints (doc consistency)
  JTL4xx — interprocedural flow rules over the jtflow contract graph
           (packed schemas, cross-module donation, sharding axes,
           resumable carries, metric contracts, contracts.json sync)
  JTL5xx — jtsan: interprocedural happens-before / lock-set concurrency
           analysis (lockset races, cross-module lock order,
           check-then-act, blocking under lock, thread lifecycles,
           sync contracts) cross-validated by the runtime sanitizer
           (obs/sync.py)
  JTL000 — reserved: unparseable file (emitted by the engine itself)

Adding a rule = one module here with a ``@register``-ed Rule subclass,
an import below, a fixture pair in tests/lint_fixtures/, and a doc
section in doc/analysis.md (tests/test_lint.py enforces the last two).
"""

from . import donation          # noqa: F401
from . import env_limits        # noqa: F401
from . import event_loop        # noqa: F401
from . import flow_rules        # noqa: F401
from . import host_sync         # noqa: F401
from . import instrument        # noqa: F401
from . import jit_cache         # noqa: F401
from . import limits_doc        # noqa: F401
from . import lock_order        # noqa: F401
from . import metric_name       # noqa: F401
from . import shared_state      # noqa: F401
from . import sync_rules        # noqa: F401
from . import traced_branch     # noqa: F401
