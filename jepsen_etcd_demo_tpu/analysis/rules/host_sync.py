"""JTL103 host-sync-in-loop: device fetches hiding inside chunk loops.

The chunked sweeps stay fast because dispatch is asynchronous: the host
loop enqueues chunk N+1 while the device runs chunk N (PR 2's
pipelining; PR 5's streaming overlap). One ``.item()`` /
``np.asarray(carry...)`` / ``block_until_ready()`` inside such a loop
serializes the whole pipeline — every iteration round-trips the
device. BENCH rounds attribute multi-second regressions to exactly
this shape on the tunneled backend, where a fetch costs ~100 ms.

Deliberate bounded fetches exist (the death polls every
``long_scan_poll`` chunks — the fail-fast contract) and must carry an
inline suppression WITH justification; the suppression is the
documentation.

Heuristics (documented in doc/analysis.md): ``.block_until_ready()``
always flags in a loop; ``np.asarray`` / ``np.array`` / ``bool/int/
float`` / ``.item()`` flag only when their operand source mentions a
device-carry hint (``carry``/``dead``/``overflow``/``jnp``) — plain
numpy post-processing loops stay silent.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from ..astutil import call_args_source, in_loop
from ..core import KERNEL_SCOPES, ModuleSource, Rule, register
from ..findings import Finding

_DEVICE_HINT = re.compile(r"\bcarry\b|\bdead\b|\boverflow\b|\bjnp\b")
_NP_FETCHES = ("numpy.asarray", "numpy.array")
_CAST_BUILTINS = ("bool", "int", "float")


@register
class HostSyncInLoopRule(Rule):
    id = "JTL103"
    name = "host-sync-in-loop"
    scopes = KERNEL_SCOPES
    rationale = (
        "Async dispatch is the chunk pipeline's whole win (PR 2/PR 5); "
        "a per-iteration host fetch serializes it — ~100 ms per chunk "
        "on the tunneled backend the BENCH records measure.")
    hint = ("hoist the fetch out of the loop, batch it (one packed "
            "fetch at the end), or bound it (poll every N chunks) and "
            "suppress with the justification inline")

    def check(self, mod: ModuleSource) -> Iterator[Finding]:
        for node in mod.walk_nodes():
            if not (isinstance(node, ast.Call) and in_loop(node)):
                continue
            if isinstance(node.func, ast.Attribute):
                if node.func.attr == "block_until_ready":
                    yield mod.finding(
                        self, node,
                        "block_until_ready() inside a loop — "
                        "serializes every iteration on the device")
                    continue
                if node.func.attr == "item" and _DEVICE_HINT.search(
                        call_args_source(node.func.value, mod.text)):
                    yield mod.finding(
                        self, node,
                        ".item() on a device value inside a loop — a "
                        "blocking per-iteration D2H fetch")
                    continue
            origin = mod.imports.resolve(node.func)
            if origin is None:
                continue
            arg_src = " ".join(call_args_source(a, mod.text)
                               for a in node.args)
            if origin in _NP_FETCHES and _DEVICE_HINT.search(arg_src):
                yield mod.finding(
                    self, node,
                    f"np.{origin.rsplit('.', 1)[-1]}(...) on a device "
                    f"value inside a loop — a blocking per-iteration "
                    f"D2H fetch")
            elif origin in _CAST_BUILTINS and _DEVICE_HINT.search(arg_src) \
                    and not any(isinstance(a, ast.Call)
                                for a in node.args):
                # bool(np.asarray(x)) reports at the inner call only.
                yield mod.finding(
                    self, node,
                    f"{origin}() on a device value inside a loop — a "
                    f"blocking per-iteration D2H fetch")
