"""JTL201 lock-order: lock-acquisition-order cycles (deadlock shapes).

Two code paths acquiring the same two locks in opposite orders is the
classic static deadlock; with the recorder listener thread, the stream
consumer, and the obs capture lock all live in one process (and the
ROADMAP daemon multiplying threads), acquisition order is worth
machine-checking.

Per module: every ``with <lock>:`` nesting adds an edge outer->inner
(``with a, b:`` adds a->b); a method calling a same-class sibling while
holding a lock adds edges to the sibling's locks. Lock identity is the
expression text qualified by the owning class (``StreamSession.self.
_lock``) so two classes' unrelated ``self._lock`` attributes never
alias. A cycle in the resulting graph — including a self-edge, which
is a self-deadlock on a non-reentrant ``threading.Lock`` — is a
finding naming the full cycle.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, Optional

from ..astutil import (LOCKISH_RE, ancestors_same_scope, dotted,
                       enclosing_class, walk_same_scope)
from ..core import CONCURRENCY_SCOPES, ModuleSource, Rule, register
from ..findings import Finding



def _lock_id(expr: ast.AST, mod: ModuleSource) -> Optional[str]:
    d = dotted(expr)
    if d is None and isinstance(expr, ast.Call):
        d = dotted(expr.func)     # with self._lock() factory style
    if d is None or not LOCKISH_RE.search(d.split(".")[-1]):
        return None
    cls = enclosing_class(expr)
    return f"{cls.name}.{d}" if cls is not None and d.startswith("self.") \
        else d


@register
class LockOrderRule(Rule):
    id = "JTL201"
    name = "lock-order"
    scopes = CONCURRENCY_SCOPES
    rationale = (
        "Opposite acquisition orders across threads deadlock; the "
        "listener thread + stream consumer + obs capture lock already "
        "share a process, and the ROADMAP daemon multiplies threads.")
    hint = ("pick one global acquisition order and restructure the "
            "out-of-order path (release-then-reacquire, or lift the "
            "inner acquisition out)")

    def check(self, mod: ModuleSource) -> Iterator[Finding]:
        edges: dict[tuple[str, str], ast.AST] = {}
        class_locks: dict[tuple[str, str], set[str]] = {}  # (cls,meth)->locks
        for node in mod.walk_nodes():
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                cls = enclosing_class(node)
                if cls is not None:
                    # Same-scope only: a with-lock inside a nested def
                    # belongs to that callable, not to this method.
                    class_locks[(cls.name, node.name)] = {
                        lid for w in walk_same_scope(node)
                        if isinstance(w, (ast.With, ast.AsyncWith))
                        for item in w.items
                        for lid in [_lock_id(item.context_expr, mod)]
                        if lid is not None}
        for node in mod.walk_nodes():
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            ids = [(_lock_id(i.context_expr, mod), i.context_expr)
                   for i in node.items]
            ids = [(lid, e) for lid, e in ids if lid is not None]
            # with a, b: -> a->b
            for (outer, _), (inner, e) in zip(ids, ids[1:]):
                edges.setdefault((outer, inner), e)
            if not ids:
                continue
            # Held = enclosing withs in the SAME scope: a with inside a
            # nested def is not under the outer function's locks (the
            # callback runs later, possibly with nothing held).
            held = [lid for a in ancestors_same_scope(node)
                    if isinstance(a, (ast.With, ast.AsyncWith))
                    for item in a.items
                    for lid in [_lock_id(item.context_expr, mod)]
                    if lid is not None]
            for outer in held:
                for inner, e in ids:
                    edges.setdefault((outer, inner), e)
            # same-class calls made while holding these locks
            cls = enclosing_class(node)
            if cls is None:
                continue
            for call in walk_same_scope(node):
                if not isinstance(call, ast.Call):
                    continue
                cd = dotted(call.func)
                if cd is None or not cd.startswith("self."):
                    continue
                callee = cd.split(".", 1)[1]
                for inner in class_locks.get((cls.name, callee), ()):
                    for outer, _ in ids:
                        # outer == inner IS the finding: a helper
                        # re-acquiring the caller's non-reentrant lock.
                        edges.setdefault((outer, inner), call)
        yield from self._cycles(edges, mod)

    def _cycles(self, edges: dict, mod: ModuleSource) -> Iterator[Finding]:
        graph: dict[str, set[str]] = {}
        for a, b in edges:
            graph.setdefault(a, set()).add(b)
        reported: set[tuple] = set()
        for (a, b), site in sorted(edges.items(),
                                   key=lambda kv: kv[1].lineno):
            if a == b:
                key = (a,)
                if key not in reported:
                    reported.add(key)
                    yield mod.finding(
                        self, site,
                        f"lock {a} acquired while already held — "
                        f"self-deadlock on a non-reentrant lock")
                continue
            path = self._find_path(graph, b, a)
            if path is None:
                continue
            cycle = [a] + path          # path runs b..a, closing the loop
            key = tuple(sorted(set(cycle)))
            if key in reported:
                continue
            reported.add(key)
            yield mod.finding(
                self, site,
                "lock acquisition order cycle: " + " -> ".join(cycle)
                + " — two threads taking opposite ends deadlock")

    def _find_path(self, graph: dict, src: str, dst: str
                   ) -> Optional[list[str]]:
        stack = [(src, [src])]
        seen = {src}
        while stack:
            cur, path = stack.pop()
            if cur == dst:
                return path
            for nxt in sorted(graph.get(cur, ())):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None
