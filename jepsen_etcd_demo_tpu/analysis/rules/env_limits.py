"""JTL106 raw-limit-env: JEPSEN_TPU_LIMIT_* reads outside ops/limits.py.

``ops/limits.py`` is the single resolution point for every kernel knob
(env > set_limits > tuned profile > default, with validation — PR 4's
LimitsEnvError work). A raw ``os.environ["JEPSEN_TPU_LIMIT_..."]``
anywhere else bypasses the whole ladder: no range validation, no tuned
profile, no provenance, and the doc lint (JTL301) can't see it.
Computed env-var names built via ``limits.env_var(field)`` are the
sanctioned escape hatch (cli/main.py's --sweep-mode) and don't match.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from ..core import ModuleSource, Rule, register
from ..findings import Finding

_PREFIX = "JEPSEN_TPU_LIMIT"


def _literal_env_key(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str) \
            and node.value.startswith(_PREFIX):
        return node.value
    return None


@register
class RawLimitEnvRule(Rule):
    id = "JTL106"
    name = "raw-limit-env"
    scopes = None          # whole package; limits.py itself is exempt
    rationale = (
        "ops/limits.py is the one resolution point for kernel knobs "
        "(validated env > set_limits > tuned profile > default, PR 4); "
        "a raw env read bypasses validation, tuning and provenance.")
    hint = ("read limits().<field> (ops/limits.py) instead; to pin a "
            "field programmatically use set_limits(), to pin it for "
            "subprocesses set the env via limits.env_var(field)")

    def check(self, mod: ModuleSource) -> Iterator[Finding]:
        if mod.relpath.endswith("ops/limits.py"):
            return
        for node in mod.walk_nodes():
            key, write = None, False
            if isinstance(node, ast.Subscript):
                base = mod.imports.resolve(node.value)
                if base in ("os.environ",):
                    key = _literal_env_key(node.slice)
                    write = not isinstance(node.ctx, ast.Load)
            elif isinstance(node, ast.Call):
                origin = mod.imports.resolve(node.func)
                if origin in ("os.getenv", "os.environ.get") and node.args:
                    key = _literal_env_key(node.args[0])
            if key is None:
                continue
            if write:
                yield mod.finding(
                    self, node,
                    f"raw write of {key} with a hardcoded var name — "
                    f"unvalidated, and the name silently desyncs if "
                    f"the field is renamed",
                    hint="compute the name via limits.env_var(field) "
                         "(subprocess pins) or use set_limits() "
                         "in-process — both stay on the resolution "
                         "ladder")
            else:
                yield mod.finding(
                    self, node,
                    f"raw read of {key} outside ops/limits.py — "
                    f"bypasses the limits resolution ladder "
                    f"(validation, tuned profile, provenance)")
