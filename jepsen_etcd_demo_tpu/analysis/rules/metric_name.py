"""JTL107 computed-metric-name: metric names must be string literals.

The obs registry happily creates an instrument per distinct name, and
PR 8's Prometheus exporter (obs/export.py) turns every name into a
scrape-visible series — so a name BUILT at the call site
(``m.counter(f"runner.ops_{op.value}")``) is a label-cardinality
explosion waiting for the first unbounded value: registry memory grows
with workload data, /metrics output grows without bound, and the
pre-registration contract ("zeros permitted, never absent") can't
cover names that don't exist until traffic invents them.

Legitimate *bounded* families (per-kernel histograms where the member
set is the fixed set of instrument_kernel call sites, per-knob tune
gauges) carry a justified inline suppression — the justification must
make the boundedness argument — and the exporter folds them into ONE
labeled Prometheus family (export.LABELED_FAMILIES) rather than N
names.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..core import ModuleSource, Rule, register
from ..findings import Finding

_METHODS = ("counter", "gauge", "histogram")


def _builder_kind(node: ast.AST) -> str:
    """Non-empty iff the name is BUILT at the call site. A plain Name /
    constant passes: iterating a module-level literal tuple (the
    capture() pre-registration loops) is bounded by construction, and
    the builder shapes are the ones that splice workload data in."""
    if isinstance(node, ast.JoinedStr):
        return "an f-string"
    if isinstance(node, ast.BinOp):
        return "string concatenation/formatting"
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
            and node.func.attr == "format":
        return "a .format() call"
    return ""


@register
class ComputedMetricNameRule(Rule):
    id = "JTL107"
    name = "computed-metric-name"
    scopes = None          # metrics are emitted from every layer
    rationale = (
        "a metric name built at the call site (f-string / + / .format) "
        "is unbounded cardinality: the registry allocates per distinct "
        "name and the Prometheus exporter (obs/export.py) publishes "
        "every one as a scrape series — one unbounded interpolated "
        "value and /metrics grows with workload data")
    hint = ("use a string-literal metric name; for a genuinely BOUNDED "
            "family (fixed kernel/knob sets) suppress with the "
            "boundedness argument and register the family in "
            "obs/export.py LABELED_FAMILIES so it exports as one "
            "labeled series")

    def check(self, mod: ModuleSource) -> Iterator[Finding]:
        for node in mod.walk_nodes():
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _METHODS and node.args):
                continue
            recv = node.func.value
            if isinstance(recv, ast.Name) and recv.id in mod.imports.names:
                # A module-level function that happens to share a method
                # name (np.histogram(...)) — not a registry instrument.
                continue
            kind = _builder_kind(node.args[0])
            if not kind:
                continue
            yield mod.finding(
                self, node,
                f".{node.func.attr}() name built from {kind} — metric "
                f"names must be string literals (unbounded series "
                f"cardinality otherwise)")
