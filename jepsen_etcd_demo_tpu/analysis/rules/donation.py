"""JTL102 donation-read: donated buffers read after the donating call.

``donate_argnums`` lets XLA alias an operand's buffer into the output
(the chunked sweeps' frontier carry and the pallas resumable table ride
on this — PR 2/PR 5). After the call the donated array is DELETED:
touching it raises on strict backends and silently reads reused memory
on others. Until ISSUE 7 the donation call sites were hand-audited
per PR; this rule keeps them audited.

Intra-module resolution (documented limit: cross-module donating
callables — e.g. stream/engine.py calling wgl3's factory — resolve
only in wgl3's own file):

  * ``run = jax.jit(f, donate_argnums=(0,))`` — direct binding;
  * factories: a function whose return resolves to a donating jit —
    through ``instrument_kernel(...)`` wraps, nested ``def`` s,
    ``_CACHE[key]`` stores, and one level of factory-calls-factory;
  * call sites: ``run(carry, ...)`` and ``factory(...)(carry, ...)``.

Flagged shapes: a donated operand read in a LATER statement before
being rebound, and a donated operand inside a loop that the call
statement does not rebind (the next iteration would pass a deleted
buffer). The repo idiom — ``carry, part = run(carry, ...)`` — rebinds
in the same statement and is clean.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from ..astutil import (ancestors, assigned_names, dotted, statement_of,
                       walk_cached, walk_same_scope)
from ..core import KERNEL_SCOPES, ModuleSource, Rule, register
from ..findings import Finding


def _donate_indices(call: ast.Call, mod: ModuleSource
                    ) -> Optional[tuple[int, ...]]:
    """The literal donate_argnums of a jax.jit call, else None."""
    if not mod.imports.is_call_to(call, "jax.jit"):
        return None
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return (v.value,)
            if isinstance(v, (ast.Tuple, ast.List)):
                out = []
                for e in v.elts:
                    if isinstance(e, ast.Constant) \
                            and isinstance(e.value, int):
                        out.append(e.value)
                return tuple(out) or None
    return None


class _Resolver:
    """Resolves expressions / function names to donated positions."""

    def __init__(self, mod: ModuleSource):
        self.mod = mod
        # EVERY def gets scanned (fn_nodes); name-based RESOLUTION only
        # trusts unique names — with duplicates (nested `run`/`launch`
        # defs recur across factories, e.g. ops/wgl3_pallas.py) a bare
        # name is ambiguous and resolving the wrong one would flag or
        # clear the wrong call sites.
        self.fn_nodes: list[ast.AST] = [
            n for n in mod.walk_nodes()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        counts: dict[str, int] = {}
        for n in self.fn_nodes:
            counts[n.name] = counts.get(n.name, 0) + 1
        self.fns: dict[str, ast.AST] = {
            n.name: n for n in self.fn_nodes if counts[n.name] == 1}
        self._memo: dict[str, Optional[tuple[int, ...]]] = {}

    def expr(self, node: ast.AST, depth: int = 0
             ) -> Optional[tuple[int, ...]]:
        if depth > 6 or node is None:
            return None
        if isinstance(node, ast.Call):
            d = _donate_indices(node, self.mod)
            if d is not None:
                return d
            if self.mod.imports.is_call_to(
                    node, "instrument_kernel", "obs.instrument_kernel") \
                    and node.args:
                return self.expr(node.args[-1], depth + 1)
            # factory(...) — a call to a function that returns donating
            if isinstance(node.func, ast.Name):
                return self.function(node.func.id, depth + 1)
            return None
        if isinstance(node, ast.Name) and node.id in self.fns:
            # a returned inner def
            return self.function(node.id, depth + 1)
        return None

    def function(self, name: str, depth: int = 0
                 ) -> Optional[tuple[int, ...]]:
        if name in self._memo:
            return self._memo[name]
        self._memo[name] = None          # cycle guard
        fn = self.fns.get(name)
        if fn is None or depth > 6:
            return None
        result: Optional[tuple[int, ...]] = None
        for node in walk_cached(fn):
            if isinstance(node, ast.Return) and node.value is not None:
                val = node.value
                if isinstance(val, ast.Subscript):
                    val = self._cache_store_value(fn, val) or val
                result = self.expr(val, depth + 1)
                if result is not None:
                    break
        self._memo[name] = result
        return result

    def _cache_store_value(self, fn, sub: ast.Subscript
                           ) -> Optional[ast.AST]:
        """`return _CACHE[key]` -> the value some `_CACHE[...] = X`
        in the same function stored."""
        base = dotted(sub.value)
        if base is None:
            return None
        for node in walk_cached(fn):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Subscript) \
                            and dotted(t.value) == base:
                        return node.value
        return None


def scan_donation_sites(fn, mod: ModuleSource, rule: Rule,
                        local: dict, expr_donates) -> Iterator[Finding]:
    """The donated-call-site check, shared by the intra-module rule
    (JTL102) and the interprocedural flow rule (JTL402 —
    analysis/rules/flow_rules.py). `local` maps binding names to donated
    positions; `expr_donates(call_expr)` resolves ``factory(...)(carry)``
    immediate-call shapes. Same-scope walk only: nested defs get their
    own pass."""
    for node in walk_same_scope(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        indices = None
        if isinstance(f, ast.Name):
            indices = local.get(f.id)
        elif isinstance(f, ast.Call):
            indices = expr_donates(f)
        if not indices:
            continue
        stmt = statement_of(node)
        rebound = assigned_names(ast.Tuple(
            elts=list(getattr(stmt, "targets", []))
            if isinstance(stmt, ast.Assign) else [], ctx=ast.Store()))
        for i in indices:
            if i >= len(node.args):
                continue
            name = dotted(node.args[i])
            if name is None:
                continue   # a fresh expression: nothing to re-read
            if name in rebound:
                continue
            if _in_loop_stmt(stmt, fn):
                yield mod.finding(
                    rule, node,
                    f"donated operand `{name}` (position {i}) is "
                    f"not rebound by the call statement inside a "
                    f"loop — the next iteration passes a deleted "
                    f"buffer")
                continue
            read = _later_read(stmt, name, fn)
            if read is not None:
                yield mod.finding(
                    rule, read,
                    f"donated operand `{name}` (donated at line "
                    f"{node.lineno}) read after the donating call "
                    f"— the buffer no longer exists")


def _in_loop_stmt(stmt: ast.stmt, fn) -> bool:
    for a in ancestors(stmt):
        if a is fn:
            return False
        if isinstance(a, (ast.For, ast.AsyncFor, ast.While)):
            return True
    return False


def _later_read(stmt: ast.stmt, name: str, fn) -> Optional[ast.AST]:
    """First Load of `name` in a statement after `stmt` in the same
    (innermost) body list, before any rebinding statement."""
    p = getattr(stmt, "jt_parent", None)
    body = getattr(p, "body", None)
    if not isinstance(body, list) or stmt not in body:
        return None
    after = body[body.index(stmt) + 1:]
    for s in after:
        for n in ast.walk(s):
            if isinstance(n, (ast.Name, ast.Attribute)) \
                    and isinstance(getattr(n, "ctx", None), ast.Load) \
                    and dotted(n) == name:
                return n
        if isinstance(s, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            tgts = (s.targets if isinstance(s, ast.Assign)
                    else [s.target])
            if any(name in assigned_names(t) for t in tgts):
                return None
    return None


@register
class DonationReadRule(Rule):
    id = "JTL102"
    name = "donation-read"
    scopes = KERNEL_SCOPES
    rationale = (
        "donate_argnums deletes the operand's buffer at the call; a "
        "later read raises (strict backends) or reads reused memory "
        "(silent corruption). The PR 2/PR 5 donation paths were "
        "hand-audited; this keeps them audited.")
    hint = ("rebind the donated operand from the call's result in the "
            "same statement (`carry, part = run(carry, ...)`); if the "
            "old buffer is genuinely needed, drop the donation or copy "
            "first")

    def check(self, mod: ModuleSource) -> Iterator[Finding]:
        resolver = _Resolver(mod)
        for fn in resolver.fn_nodes:
            yield from self._check_function(fn, resolver, mod)

    def _check_function(self, fn, resolver: _Resolver,
                        mod: ModuleSource) -> Iterator[Finding]:
        # Same-scope walks only: nested defs are in resolver.fns and get
        # their OWN pass — descending here would report their call
        # sites twice under two fingerprints. (Known limit: a donating
        # binding captured by closure into a nested def is not tracked.)
        # Local donating bindings: run = <donating expr>
        local: dict[str, tuple[int, ...]] = {}
        for node in walk_same_scope(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                d = resolver.expr(node.value)
                if d is not None:
                    local[node.targets[0].id] = d
        yield from scan_donation_sites(fn, mod, self, local, resolver.expr)
