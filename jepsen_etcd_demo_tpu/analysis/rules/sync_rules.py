"""JTL5xx — jtsan: interprocedural happens-before / lock-set analysis.

Where JTL201/203 see one class in one file, these rules run over the
whole-program ``SyncModel`` (analysis/flow/sync.py): thread spawn sites,
executor submissions and HTTP handler classes become roots; the call
graph carries lock-sets and reachability across modules; ``join()``
and ``# jtsan:`` annotations contribute happens-before edges. The serve
daemon (PR 13) is the motivating subject — a web of handler threads,
one dispatch thread, stream consumer threads, and the obs pump sharing
a dozen locks across six packages.

  JTL501 lockset-race        a shared attribute whose access sites'
                             lock-sets have an empty intersection — the
                             Eraser discipline, compositional across
                             modules (RacerD's ownership idiom via the
                             "callers always hold" credit)
  JTL502 cross-lock-order    lock-order cycles THROUGH call chains
                             spanning modules (JTL201 only sees
                             same-class nesting)
  JTL503 check-then-act      read under a lock, decide, write under a
                             LATER acquisition without re-validating —
                             the admission/registry double-insert shape
  JTL504 blocking-under-lock blocking primitives (Queue.get,
                             future.result, Thread.join, HTTP waits)
                             while holding a modeled lock, resolved
                             through the call graph
  JTL505 thread-lifecycle    a thread/executor-owning class (directly
                             or through owned instances/registries)
                             whose shutdown path never reaches a
                             join/close for some source
  JTL506 sync-contract       the ``# jtsan:`` annotation grammar and
                             sanitizer wrap-names VERIFIED against the
                             model; contracts.json must carry the
                             ``sync`` section (content drift rides the
                             JTL406 regenerate-and-diff gate)

The runtime counterpart (obs/sync.py) records witnessed acquisition
orders under JEPSEN_TPU_SYNC_TRACE=1; tests/test_jtsan.py cross-
validates them against JTL502's edge set.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Iterator, Optional

from ..astutil import dotted, walk_same_scope
from ..core import PACKAGE_NAME, ProjectRule, register
from ..findings import Finding
from .shared_state import _MUTATORS


class SyncRule(ProjectRule):
    """Shared plumbing: one SyncModel per lint invocation, through the
    engine's shared FlowIndex when provided."""

    def _model(self, root: Path, ctx=None):
        from ..flow.index import FlowIndex
        from ..flow.sync import sync_model

        index = None
        if ctx is not None and hasattr(ctx, "flow_index"):
            index = ctx.flow_index()
        if index is None:
            index = FlowIndex.build(Path(root))
        return sync_model(index)

    def check_project(self, root: Path, ctx=None) -> list[Finding]:
        return list(self._check(self._model(root, ctx)))

    def _check(self, model) -> Iterator[Finding]:
        raise NotImplementedError


def _fmt_locks(locks) -> str:
    return ", ".join(sorted(locks)) if locks else "no lock"


def _fmt_sides(sides) -> str:
    return ", ".join(sorted(sides)) if sides else "caller threads"


@register
class LocksetRaceRule(SyncRule):
    id = "JTL501"
    name = "lockset-race"
    scopes = None
    rationale = (
        "PR 13 turned the harness into one process full of handler "
        "threads, a dispatch thread, stream consumers and the obs pump; "
        "JTL203 only sees a single class spawning its own thread. An "
        "attribute reachable from two threads whose access sites share "
        "no lock (and no happens-before edge) is a data race — the "
        "Eraser lock-set discipline, applied across modules")
    hint = ("hold the structure's one guarding lock at every access "
            "site (route reads through a locked stats()/snapshot "
            "reader), hand the data across on a queue, or order the "
            "sides with an Event/join and annotate it (# jtsan: hb=)")

    def _check(self, model) -> Iterator[Finding]:
        from ..flow.sync import iter_shared_attrs

        for owner, attr, sites in iter_shared_attrs(model):
            ci = model.classes[owner]
            decl = model.guarded.get((owner, attr))
            if decl is not None:
                lid, _line = decl
                bad = sorted((s for s in sites if lid not in s.locks),
                             key=lambda s: (s.mod.relpath,
                                            s.node.lineno))
                if bad:
                    s = bad[0]
                    yield s.mod.finding(
                        self, s.node,
                        f"{ci.name}.{attr} is annotated "
                        f"`# jtsan: guarded-by={lid.split('.')[-1]}` "
                        f"but {s.fn.split('.')[-1]}() "
                        f"{'writes' if s.write else 'reads'} it holding "
                        f"{_fmt_locks(s.locks)} — the declared guard is "
                        f"broken")
                continue
            writes = [s for s in sites if s.write]
            if not writes:
                continue
            side_of = {id(s): model.sides_of(s.fn) for s in sites}
            all_sides = set().union(*side_of.values())
            outside = [s for s in sites if not side_of[id(s)]]
            if not all_sides:
                continue
            if len(all_sides) == 1 and not outside:
                continue            # single-threaded closure
            common = frozenset.intersection(*[s.locks for s in sites])
            if common:
                continue
            locked = [s for s in sites if s.locks]
            if locked:
                bad = sorted((s for s in sites if not s.locks),
                             key=lambda s: (not s.write,
                                            s.mod.relpath,
                                            s.node.lineno))
                if not bad:
                    # Divergent but every site locked: report the first
                    # write (two disjoint locks guard nothing).
                    bad = sorted(writes, key=lambda s: (s.mod.relpath,
                                                        s.node.lineno))
                s = bad[0]
                others = sorted({lk for o in sites if o.locks
                                 for lk in o.locks})
                yield s.mod.finding(
                    self, s.node,
                    f"{ci.name}.{attr} is guarded by "
                    f"{', '.join(others)} on other paths, but "
                    f"{s.fn.split('.')[-1]}() "
                    f"{'writes' if s.write else 'reads'} it holding "
                    f"{_fmt_locks(s.locks)} (threads: "
                    f"{_fmt_sides(all_sides)}) — no common lock-set, a "
                    f"cross-thread race")
            else:
                write_roots = set().union(
                    *[side_of[id(s)] for s in writes])
                if len(write_roots) < 2:
                    continue        # caller-vs-own-thread is JTL203's
                s = sorted(writes, key=lambda x: (x.mod.relpath,
                                                  x.node.lineno))[0]
                yield s.mod.finding(
                    self, s.node,
                    f"{ci.name}.{attr} is mutated from two threads "
                    f"({_fmt_sides(write_roots)}) with no lock at any "
                    f"site and no happens-before edge — a cross-module "
                    f"data race")


@register
class CrossLockOrderRule(SyncRule):
    id = "JTL502"
    name = "cross-lock-order"
    scopes = None
    rationale = (
        "JTL201 sees with-nesting inside one class; the serve->sched->"
        "obs call paths hold one module's lock while acquiring "
        "another's, which is exactly where an acquisition-order cycle "
        "would hide — two threads taking opposite ends deadlock the "
        "daemon, and nothing in-process can recover it")
    hint = ("pick one global acquisition order (document it in the "
            "contracts sync section) and restructure the out-of-order "
            "path — release before calling across modules, or snapshot "
            "under the inner lock first")

    def _check(self, model) -> Iterator[Finding]:
        graph: dict[str, set[str]] = {}
        for a, b in model.order_edges:
            graph.setdefault(a, set()).add(b)
        lock_mods = model.lock_modules()
        reported: set[tuple] = set()
        for (a, b), (mod, line, via_call) in sorted(
                model.order_edges.items(),
                key=lambda kv: (kv[1][0].relpath, kv[1][1])):
            if a == b:
                # Nest/same-class self-edges are JTL201's
                # self-deadlock finding; a re-acquisition through a
                # call CHAIN (any other class or module) is ours —
                # JTL201 cannot follow the call.
                if not via_call:
                    continue
                if (a,) not in reported:
                    reported.add((a,))
                    yield mod.finding(
                        self, line,
                        f"lock {a} re-acquired through a call chain "
                        f"while already held — self-deadlock on a "
                        f"non-reentrant lock")
                continue
            path = self._find_path(graph, b, a)
            if path is None:
                continue
            cycle = [a] + path
            key = tuple(sorted(set(cycle)))
            if key in reported:
                continue
            # JTL201's jurisdiction: a cycle made ONLY of direct/
            # same-class nesting whose locks all live in one module
            # (the declaring modules — parsing them back out of the id
            # would mis-split module-level lock ids). Anything with a
            # call-chain edge, or spanning modules, is ours.
            edges = list(zip(cycle, cycle[1:]))
            any_call = any(model.order_edges[e][2] for e in edges
                           if e in model.order_edges)
            mods = {lock_mods.get(lid, lid) for lid in key}
            if not any_call and len(mods) <= 1:
                continue
            reported.add(key)
            yield mod.finding(
                self, line,
                "lock acquisition order cycle through call chains: "
                + " -> ".join(cycle)
                + " — two threads taking opposite ends deadlock")

    def _find_path(self, graph, src, dst) -> Optional[list]:
        stack = [(src, [src])]
        seen = {src}
        while stack:
            cur, path = stack.pop()
            if cur == dst:
                return path
            for nxt in sorted(graph.get(cur, ())):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None


def _self_attr_reads(scope: ast.AST) -> set[str]:
    out = set()
    for n in ast.walk(scope):
        if isinstance(n, ast.Attribute) \
                and isinstance(getattr(n, "ctx", None), ast.Load):
            d = dotted(n)
            if d and d.startswith("self.") and len(d.split(".")) == 2:
                out.add(d.split(".")[1])
    return out


def _self_attr_writes(scope: ast.AST) -> set[str]:
    out = set()
    for n in ast.walk(scope):
        if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            tgts = n.targets if isinstance(n, ast.Assign) else [n.target]
            for t in tgts:
                base = t.value if isinstance(t, ast.Subscript) else t
                d = dotted(base)
                if d and d.startswith("self.") and len(d.split(".")) == 2:
                    out.add(d.split(".")[1])
        elif isinstance(n, ast.Call) \
                and isinstance(n.func, ast.Attribute) \
                and n.func.attr in _MUTATORS:
            d = dotted(n.func.value)
            if d and d.startswith("self.") and len(d.split(".")) == 2:
                out.add(d.split(".")[1])
    return out


def _bound_names(scope: ast.AST) -> set[str]:
    out = set()
    for n in ast.walk(scope):
        if isinstance(n, ast.Assign):
            for t in n.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


def _revalidates(scope: ast.AST, attr: str) -> bool:
    """True when the second critical section re-reads the structure
    into a binding (the `x = d.setdefault(...)` / re-get idiom) —
    the decision is re-derived under the lock, not trusted stale."""
    for n in ast.walk(scope):
        if isinstance(n, ast.Assign):
            for sub in ast.walk(n.value):
                d = dotted(sub)
                if d == f"self.{attr}":
                    return True
    return False


@register
class CheckThenActRule(SyncRule):
    id = "JTL503"
    name = "check-then-act"
    scopes = None
    rationale = (
        "the serve admission path reads a counter/registry under the "
        "lock, decides, then applies the decision under a LATER "
        "acquisition — between the two, another thread changed the "
        "state (two tenants double-insert a model; an inflight counter "
        "admits past its bound). Atomicity violations survive every "
        "individual-access lock discipline")
    hint = ("do the read-decide-write in ONE critical section, or "
            "re-validate under the second acquisition and bind the "
            "result (`x = d.setdefault(k, x)` — use what the structure "
            "actually holds)")

    def _check(self, model) -> Iterator[Finding]:
        for key in sorted(model.functions):
            fi = model.functions[key]
            if isinstance(fi.node, ast.AsyncFunctionDef):
                continue
            withs = []
            for node in walk_same_scope(fi.node):
                if isinstance(node, ast.With):
                    ids = {lid for item in node.items for lid in
                           [model._lock_id_of_expr(fi,
                                                   item.context_expr)]
                           if lid is not None}
                    if ids:
                        withs.append((node, ids))
            withs.sort(key=lambda w: w[0].lineno)
            for i, (w1, ids1) in enumerate(withs):
                reads1 = _self_attr_reads(w1) - _self_attr_writes(w1)
                bound1 = _bound_names(w1)
                if not reads1 or not bound1:
                    continue
                for w2, ids2 in withs[i + 1:]:
                    if not (ids1 & ids2):
                        continue
                    inter = reads1 & _self_attr_writes(w2)
                    for attr in sorted(inter):
                        if not self._gated(fi, w1, w2, bound1):
                            continue
                        if _revalidates(w2, attr):
                            continue
                        yield fi.mod.finding(
                            self, w2,
                            f"check-then-act: self.{attr} was read "
                            f"under {', '.join(sorted(ids1 & ids2))} "
                            f"in an earlier critical section of "
                            f"{fi.node.name}(), the decision taken "
                            f"between acquisitions, and the write here "
                            f"trusts the stale read — re-validate "
                            f"under this lock and bind the result")

    def _gated(self, fi, w1, w2, bound1: set[str]) -> bool:
        """An If/While between the sections (or enclosing the second)
        whose test uses a name the first section bound — the 'decide'
        step."""
        from ..astutil import ancestors_same_scope

        candidates = [a for a in ancestors_same_scope(w2)
                      if isinstance(a, (ast.If, ast.While))]
        for node in walk_same_scope(fi.node):
            if isinstance(node, (ast.If, ast.While)) \
                    and w1.lineno <= node.lineno <= w2.lineno:
                candidates.append(node)
        for c in candidates:
            for n in ast.walk(c.test):
                if isinstance(n, ast.Name) and n.id in bound1:
                    return True
        return False


@register
class BlockingUnderLockRule(SyncRule):
    id = "JTL504"
    name = "blocking-under-lock"
    scopes = None
    rationale = (
        "a blocking call (Queue.get, future.result, Thread.join, an "
        "HTTP wait) made while holding a lock turns every sibling of "
        "that lock into a convoy — the /metrics scrape and the stats "
        "readers take the same locks, so one stalled dispatch freezes "
        "the whole observability plane (and a join under the lock the "
        "joined thread wants is a deadlock)")
    hint = ("move the blocking call outside the critical section: "
            "snapshot the state under the lock, release, then block "
            "(serve/sessions.py's close() shape)")

    def _check(self, model) -> Iterator[Finding]:
        seen: set[tuple] = set()
        for b in sorted(model.blocking,
                        key=lambda b: (b.mod.relpath, b.node.lineno)):
            key = (b.mod.relpath, b.node.lineno, b.what)
            if key in seen:
                continue
            seen.add(key)
            yield b.mod.finding(
                self, b.node,
                f"{b.what} while holding {_fmt_locks(b.locks)} in "
                f"{b.fn.split('.')[-1]}() — every thread needing "
                f"{'that lock' if len(b.locks) == 1 else 'those locks'} "
                f"convoys behind this block")


@register
class ThreadLifecycleRule(SyncRule):
    id = "JTL505"
    name = "thread-lifecycle"
    scopes = None
    rationale = (
        "the serve daemon owns threads transitively — scheduler "
        "dispatch thread, per-session stream consumers, the obs pump; "
        "a shutdown path that misses one source leaks the thread past "
        "close(), which in a long-running daemon means encoder state "
        "and device handles held forever (and joins that never happen "
        "hide latent crashes)")
    hint = ("give every thread/executor source a release on the "
            "owner's shutdown path: join the thread, shutdown the "
            "executor, close owned instances (SessionManager."
            "close_all's shape), and call it from the owning close()")

    def _check(self, model) -> Iterator[Finding]:
        owning = self._thread_owning(model)
        releasing = self._releasing(model, owning)
        for key in sorted(owning):
            ci = model.classes.get(key)
            if ci is None or ci.handler:
                continue
            sources = self._sources(model, ci, owning)
            if not sources:
                continue            # owning only transitively via elems
            released = {attr for (cls, attr) in releasing if cls == key}
            missing = [a for a in sorted(sources) if a not in released]
            if not missing:
                continue
            if not released:
                yield ci.mod.finding(
                    self, ci.node,
                    f"{ci.name} owns thread source(s) "
                    f"{', '.join(sorted(sources))} but no method ever "
                    f"joins/shuts them down — the threads outlive "
                    f"every shutdown path")
            else:
                for attr in missing:
                    yield ci.mod.finding(
                        self, ci.node,
                        f"{ci.name}.{attr} owns threads "
                        f"(via {sources[attr]}) but {ci.name}'s "
                        f"shutdown path never releases it — joined "
                        f"sources: {', '.join(sorted(released))}")
        # Module-level executors with no shutdown anywhere.
        for name, (mod, line) in sorted(model.module_executors.items()):
            if self._module_has_shutdown(model, mod, name):
                continue
            yield mod.finding(
                self, line,
                f"module executor {name} is never shut down — its "
                f"worker threads live for the process")

    def _sources(self, model, ci, owning) -> dict[str, str]:
        out = {}
        for attr in ci.thread_attrs:
            out[attr] = "threading.Thread"
        for attr in ci.executor_attrs:
            out[attr] = "ThreadPoolExecutor"
        for attr, cls in ci.attr_types.items():
            if cls in owning:
                out[attr] = cls
        for attr, cls in ci.elem_types.items():
            if cls in owning:
                out[attr] = f"registry of {cls}"
        return out

    def _thread_owning(self, model) -> set[str]:
        owning = {k for k, ci in model.classes.items()
                  if ci.thread_attrs or ci.executor_attrs}
        changed = True
        while changed:
            changed = False
            for k, ci in model.classes.items():
                if k in owning:
                    continue
                if any(c in owning for c in ci.attr_types.values()) \
                        or any(c in owning
                               for c in ci.elem_types.values()):
                    owning.add(k)
                    changed = True
        return owning

    def _releasing(self, model, owning) -> set[tuple[str, str]]:
        """(class key, source attr) pairs some method of the class
        releases — join/shutdown for direct sources, a call into a
        releasing method of the owned class for indirect ones."""
        released: set[tuple[str, str]] = set()
        # method keys that release ANY source of their class
        rel_methods: set[str] = set()
        changed = True
        while changed:
            changed = False
            for key, ci in model.classes.items():
                if key not in owning:
                    continue
                for mname in ci.methods:
                    fk = f"{key}.{mname}"
                    fi = model.functions.get(fk)
                    if fi is None:
                        continue
                    for call in walk_same_scope(fi.node):
                        if not isinstance(call, ast.Call):
                            continue
                        if isinstance(call.func, ast.Attribute) \
                                and call.func.attr in ("join",
                                                       "shutdown"):
                            d = dotted(call.func.value)
                            a = d.split(".")[1] if d \
                                and d.startswith("self.") \
                                and len(d.split(".")) == 2 else None
                            if a and (a in ci.thread_attrs
                                      or a in ci.executor_attrs):
                                if (key, a) not in released:
                                    released.add((key, a))
                                    rel_methods.add(fk)
                                    changed = True
                    for callee, _locks, _aj, node in fi.calls:
                        if callee not in rel_methods:
                            continue
                        tcls = callee.rsplit(".", 1)[0]
                        if tcls == key:
                            # Delegation within the class: close_all()
                            # calling close() is as releasing as close.
                            if fk not in rel_methods:
                                rel_methods.add(fk)
                                changed = True
                            continue
                        d = dotted(node.func) or ""
                        # self.<attr>.<m>() on a typed owned attr
                        if d.startswith("self.") \
                                and len(d.split(".")) == 3:
                            a = d.split(".")[1]
                            if ci.attr_types.get(a) == tcls \
                                    and (key, a) not in released:
                                released.add((key, a))
                                rel_methods.add(fk)
                                changed = True
                            continue
                        # element of a typed registry (popped/iterated)
                        for a, ecls in ci.elem_types.items():
                            if ecls == tcls and (key, a) not in released:
                                released.add((key, a))
                                rel_methods.add(fk)
                                changed = True
        return released

    def _module_has_shutdown(self, model, mod, name: str) -> bool:
        bare = name.split(".")[-1]
        for n in mod.walk_nodes():
            if isinstance(n, ast.Call) \
                    and isinstance(n.func, ast.Attribute) \
                    and n.func.attr == "shutdown":
                d = dotted(n.func.value) or ""
                if d.split(".")[-1] == bare:
                    return True
        return False


@register
class SyncContractRule(SyncRule):
    id = "JTL506"
    name = "sync-contract"
    scopes = None
    rationale = (
        "the model's extra facts arrive via `# jtsan:` annotations and "
        "the sanitizer's wrap-name literals; trusted unverified, a "
        "stale annotation silently re-legitimizes the race it once "
        "excused and a renamed lock breaks the witnessed-vs-modeled "
        "comparison — so every declaration is checked against the "
        "tree, and contracts.json must carry the sync section the "
        "model regenerates (content drift rides the JTL406 gate)")
    hint = ("fix or remove the stale annotation; wrap-name literals "
            "must equal the model's canonical lock id "
            "(<module>.<Class>.<attr>); regenerate contracts.json with "
            "`jepsen-tpu lint --write-contracts`")

    def _check(self, model) -> Iterator[Finding]:
        from ..flow.sync import _DIRECTIVES

        for a in sorted(model.annotations,
                        key=lambda a: (a.mod.relpath, a.line)):
            if a.directive not in _DIRECTIVES:
                yield a.mod.finding(
                    self, a.line,
                    f"unknown jtsan directive `{a.directive}` — the "
                    f"contract it meant to declare is not being checked")
                continue
            if a.node is None:
                yield a.mod.finding(
                    self, a.line,
                    f"jtsan `{a.directive}` annotation does not bind to "
                    f"a statement (stale annotation — nothing is "
                    f"verified)")
                continue
            yield from self._verify_one(model, a)
        # Sanitizer wrap names must equal the canonical lock id.
        decls = list(model.module_locks.values()) + [
            d for ci in model.classes.values() for d in ci.locks.values()]
        for d in sorted(decls, key=lambda d: (d.mod.relpath, d.line)):
            if d.wrap_name is not None and d.wrap_name != d.id:
                yield d.mod.finding(
                    self, d.line,
                    f"sanitizer wrap name {d.wrap_name!r} != the "
                    f"model's canonical lock id {d.id!r} — witnessed "
                    f"edges would not match the static model")

    def _verify_one(self, model, a) -> Iterator[Finding]:
        if a.directive == "returns":
            fn = model._enclosing_or_bound_def(a)
            if fn is None:
                yield a.mod.finding(
                    self, a.line,
                    "jtsan returns= must annotate a def")
                return
            if model._class_by_name(a.arg, a.mod) is None:
                yield a.mod.finding(
                    self, a.line,
                    f"jtsan returns= names unknown class {a.arg!r}")
        elif a.directive == "alias-of":
            bound = model._bound_self_attr(a.node)
            ci = model._class_of_stmt(a)
            if bound is None or ci is None:
                yield a.mod.finding(
                    self, a.line,
                    "jtsan alias-of= must annotate a `self.X = ...` "
                    "assignment inside a class")
                return
            if not model._lock_id_known(a.arg):
                yield a.mod.finding(
                    self, a.line,
                    f"jtsan alias-of= names unknown lock {a.arg!r}")
        elif a.directive == "guarded-by":
            bound = model._bound_self_attr(a.node)
            ci = model._class_of_stmt(a)
            if bound is None or ci is None \
                    or model._resolve_lock_expr(a.arg, ci,
                                                a.mod) is None:
                yield a.mod.finding(
                    self, a.line,
                    f"jtsan guarded-by={a.arg!r} does not resolve to a "
                    f"known lock on an attr-initializing statement")
        elif a.directive == "hb":
            ci = model._class_of_stmt(a)
            ok = False
            if a.arg.startswith("self.") and ci is not None:
                attr = a.arg.split(".", 1)[1]
                ok = attr in ci.safe_attrs or attr in ci.thread_attrs
            if not ok:
                yield a.mod.finding(
                    self, a.line,
                    f"jtsan hb={a.arg!r} must name an Event/Thread "
                    f"attr of the enclosing class — no ordering edge "
                    f"exists to justify the exemption")

    def check_project(self, root: Path, ctx=None) -> list[Finding]:
        import json

        out = list(self._check(self._model(root, ctx)))
        root = Path(root)
        contracts_path = root / "contracts.json"
        if (root / PACKAGE_NAME).is_dir() and contracts_path.is_file():
            try:
                contracts = json.loads(
                    contracts_path.read_text(encoding="utf-8"))
            except ValueError:
                return out          # JTL406 reports the invalid file
            if "sync" not in contracts:
                out.append(Finding(
                    rule=self.id, path="contracts.json", line=1,
                    message=("contracts.json has no `sync` section — "
                             "the concurrency contract is undeclared; "
                             "regenerate with `jepsen-tpu lint "
                             "--write-contracts`"),
                    hint=self.hint))
        return out

    def covered_paths(self, root: Path) -> list[str]:
        return ["contracts.json"]
