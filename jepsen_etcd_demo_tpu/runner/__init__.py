"""The core run loop — jepsen.core/run! equivalent.

Orchestrates: node setup (OS + DB), concurrent client workers + nemesis
interpreting the generator, history recording, phased shutdown, teardown,
checking, and store persistence (reference flow: SURVEY.md §3.1).
"""

from .history import HistoryRecorder  # noqa: F401
from .core import run_test, interpret_generators  # noqa: F401
